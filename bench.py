"""Benchmark: training throughput (img/sec/chip) vs the north star
(BASELINE.json: >= 2000 img/s/chip @ 256^2 pix2pix on TPU).

Headline metric: the full jitted pix2pix train step (U-Net G + 70x70
PatchGAN D + L1, the 'facades'/'edges2shoes' preset family) on 256x256
synthetic pairs. BENCH_PRESET selects any other preset (e.g. 'reference'
for the heavy ExpandNetwork + multiscale-D + VGG workload).

Timing methodology (tunneled-TPU safe): K train steps run inside ONE
jitted ``lax.scan`` dispatch (build_multi_train_step) so per-call host/
tunnel overhead amortizes away; calls are CHAINED (each consumes the
previous state) and a single host fetch of the final loss forces the whole
chain — ``jax.block_until_ready`` does not reliably fence on the tunneled
'axon' platform, and per-step fetches would bill one tunnel round-trip per
step. The RTT of a trivial fetch is measured separately and subtracted.
The mechanics live in ``p2p_tpu.obs.timing`` (``StepTimer.chain`` +
``measure_rtt``), so this file, the train loop, and the metrics stream all
share ONE fenced img/sec/chip definition.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Env knobs: BENCH_PRESET, BENCH_BS (per-chip batch), BENCH_STEPS, BENCH_IMG;
BENCH_JSONL=<path> additionally appends the record (kind="bench") to that
metrics stream through the obs registry.

``--sweep`` runs the ten BASELINE.md contract rows (headline, bs=1,
edges2shoes int8-delayed, cityscapes, pix2pixhd, vid2vid, the round-6
int8-multiscale-D and pallas-fusion rows, and the round-7 open-loop
serving row) and diffs each against the
last-recorded band, exiting nonzero on a >3% regression below the band
floor — the standing perf-regression gate (VERDICT r5 #7). New rows carry
``band: None`` until their first on-TPU recording lands in BASELINE.md.
``--sweep --dry-run`` shrinks every row to toy dims and skips the band
check: a CPU-able plumbing test that each contract config still builds,
steps, and reports (CI runs it).

Every image-preset record additionally carries a fenced per-net ``phases``
breakdown (``_phase_breakdown``: G/D/C fwd+bwd ms via ``StepTimer.chain``,
one dispatch per net, outside the headline timing) so a lever's win — or
the remaining gap to the 2000 img/s north star — is attributable to its
net rather than only the headline number. ``BENCH_BREAKDOWN=0`` skips it.

``--infer`` is the standing INFERENCE headline row: the serving engine
(p2p_tpu.serve — AOT bucket-batched generator inference with pipelined
PNG output) on synthetic data, reported with the fenced breakdown
(end-to-end img/s, device img/s, encode overlap, compiles-per-bucket).
``--infer --dry-run`` is its CPU-able CI plumbing row.

``--chaos [SPEC]`` arms the fault-injection layer
(p2p_tpu.resilience.chaos) for the run. With ``--infer`` (default spec
``serve_write:1.0x2``) the first two output writes fail (then the seam
goes quiet), so the row measures throughput WITH the retry/recovery
machinery firing; ``chaos_injected``/``retries`` land in the record. The
resilience contract this mode stands guard over: injected faults at the
wrapped seams must cost retries, never correctness — the row must still
satisfy the bucket-compile contract and stay in band. (Probabilistic
specs like ``serve_write:0.2`` measure sustained-fault throughput but CAN
legitimately exhaust the 3-attempt retry budget on an unlucky streak —
that's the give-up-eventually contract, not a bug.)

``--chaos`` WITHOUT ``--infer`` (default spec ``nan@3x2``) is the
standing SENTINEL row: the train headline with the divergence sentinel
(p2p_tpu.resilience.health) classifying every step inside the timed
region at the trainer's exact delayed-read cost model, and the ``nan``
seam poisoning the targeted observations. The contract: the sentinel's
healthy-path overhead stays within the BASELINE.md headline band (<1%) —
``sentinel`` {steps, spikes, nonfinite} lands in the record as proof the
path actually ran.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys


def _phase_breakdown(cfg, state, host_batch, dtype, scan_k, rtt) -> dict:
    """Fenced per-net (G/D/C) fwd+bwd timings — the attribution layer the
    sweep records carry so a lever's win (int8-D, Pallas fusion, ...) shows
    up against ITS net, not just the headline number (BENCH_r06+).

    Each net gets its own jitted ``lax.scan`` of ``scan_k`` value_and_grad
    iterations (chained through the carry so XLA cannot hoist the loop
    body), timed with the same ``StepTimer.chain`` + RTT methodology as the
    headline — one fenced dispatch per net. Numbers are ms per iteration:
    ONE forward+backward of that net alone (the D figure is one D pass;
    the train step runs two — fake and real). They are attribution
    weights, not an additive decomposition of the step (the real step
    fuses cross-net work the isolated programs cannot)."""
    import jax
    import jax.numpy as jnp

    from p2p_tpu.obs import StepTimer, span
    from p2p_tpu.train.state import build_models
    from p2p_tpu.utils.images import ingest

    g, d, c = build_models(cfg, dtype)
    real_a = ingest(jnp.asarray(host_batch["input"]), dtype)
    real_b = ingest(jnp.asarray(host_batch["target"]), dtype)
    use_quant = cfg.model.int8_delayed

    g_vars = {"params": 0, "batch_stats": state.batch_stats_g}
    if use_quant:
        g_vars["quant"] = state.quant_g

    def g_loss(params, x):
        vars_ = dict(g_vars, params=params)
        out = g.apply(vars_, x, False)
        return jnp.mean(jnp.square(out.astype(jnp.float32)))

    d_vars = {"spectral": state.spectral_d}
    if use_quant:
        d_vars["quant"] = state.quant_d
    if cfg.model.split_d_pairs:
        pair = (real_a, real_b)
    else:
        pair = jnp.concatenate([real_a, real_b], axis=-1)

    def d_loss(params, x):
        preds = d.apply({"params": params, **d_vars}, x)
        return sum(jnp.mean(jnp.square(p.astype(jnp.float32)))
                   for p in jax.tree_util.tree_leaves(preds))

    c_vars = {"batch_stats": state.batch_stats_c}
    if use_quant and state.quant_c is not None:
        # net_c on the delayed-int8 path (int8_compression) reads its
        # stored scales like G/D do
        c_vars["quant"] = state.quant_c

    def c_loss(params, x):
        out = c.apply({"params": params, **c_vars}, x, False)
        return jnp.mean(jnp.square(out.astype(jnp.float32)))

    def perturb(x, eps):
        # thread the scan carry into the input so the loop body genuinely
        # depends on the previous iteration (XLA would hoist an invariant
        # body out of the while loop and time nothing)
        if isinstance(x, tuple):
            return (x[0] + eps.astype(x[0].dtype), x[1])
        return x + eps.astype(x.dtype)

    def timed_ms(name, loss_fn, params, x):
        # params/x enter as jit ARGUMENTS (not closure constants): the
        # program is value-independent, so it can hit the persistent XLA
        # cache across runs and never embeds weight blobs in the HLO
        def prog_fn(p, xx):
            def body(carry, _):
                val, grads = jax.value_and_grad(loss_fn)(
                    p, perturb(xx, carry * 1e-30))
                leaf = jax.tree_util.tree_leaves(grads)[0]
                return (val + leaf.reshape(-1)[0].astype(jnp.float32) * 0.0,
                        None)

            return jax.lax.scan(body, jnp.zeros((), jnp.float32), None,
                                length=scan_k)

        prog = jax.jit(prog_fn)
        with span(f"bench_phase_{name}_warmup"):
            out, _ = prog(params, x)
            float(out)                      # compile + fence
        t = StepTimer(batch_size=1)
        with span(f"bench_phase_{name}"), t.chain(steps=scan_k,
                                                  rtt=rtt) as ch:
            out, _ = prog(params, x)
            ch.fence(out)
        return round(t.elapsed / scan_k * 1000.0, 3)

    phases = {"g_ms": timed_ms("g", g_loss, state.params_g, real_a),
              "d_ms": timed_ms("d", d_loss, state.params_d, pair)}
    if cfg.model.use_compression_net:
        phases["c_ms"] = timed_ms("c", c_loss, state.params_c, real_b)
    return phases


def run_single(tiny: bool = False, with_sentinel: bool = False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.synthetic import synthetic_batch
    from p2p_tpu.models.vgg import load_vgg19_params
    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_multi_train_step

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # Default headline: the int8-discriminator QAT step with DELAYED
    # (stored-scale) activation quantization — identical architecture/
    # losses to 'facades' (the bf16 number is one BENCH_PRESET=facades
    # away); trained-quality evidence for THIS path is the decayed
    # 40-epoch real-photo run metrics_facades_int8_decay.jsonl (README
    # "Round 3": final 22.21 dB / 0.769 SSIM / 0.63 VFID, best-in-decay
    # 23.75 / 0.794 / 0.398 — at the dynamic-path peak level).
    preset = os.environ.get("BENCH_PRESET", "facades_int8")
    cfg = get_preset(preset)
    facades_like = preset in ("facades", "facades_int8",
                              "facades_int8_full")
    # BENCH_IMG overrides to a square size; otherwise non-default presets
    # bench at their NATIVE dims (e.g. pix2pixhd 1024×512), facades at 256².
    if tiny:
        # --sweep --dry-run: toy dims proving the config builds and steps
        # (keep a rectangular extent when the preset has one — the HD
        # generators assume W > H)
        img, wid = 32, (64 if cfg.data.image_width else None)
    elif "BENCH_IMG" in os.environ or facades_like or not on_tpu:
        img = int(os.environ.get("BENCH_IMG", "256" if on_tpu else "64"))
        wid = None
    else:
        img, wid = cfg.data.image_size, cfg.data.image_width
    bs = int(os.environ.get("BENCH_BS", ("128" if facades_like else
                                         str(cfg.data.batch_size)) if on_tpu
                            else "2"))
    scan_k = int(os.environ.get("BENCH_SCAN", "8" if on_tpu else "2"))
    n_calls = int(os.environ.get("BENCH_STEPS", "64" if on_tpu else "4")) // scan_k
    n_calls = max(n_calls, 2)
    if tiny:
        bs, scan_k, n_calls = 1, 2, 2
        cfg = cfg.replace(
            model=dataclasses.replace(
                cfg.model, ngf=8, ndf=8, num_D=min(cfg.model.num_D, 2),
                n_layers_D=2, n_blocks=min(cfg.model.n_blocks, 2)),
            data=dataclasses.replace(
                cfg.data, n_frames=min(cfg.data.n_frames, 2)),
            loss=dataclasses.replace(cfg.loss, lambda_vgg=0.0),
        )

    cfg = cfg.replace(
        data=dataclasses.replace(
            cfg.data, batch_size=bs, image_size=img, image_width=wid
        )
    )
    bench_int8 = os.environ.get("BENCH_INT8", "").lower()
    if bench_int8 in ("1", "d", "true", "on", "g"):
        # int8 discriminator on any preset; BENCH_INT8=g also quantizes
        # the generator trunk (ResNet families / U-Net encoder)
        both = bench_int8 == "g"
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, int8=True, int8_generator=both))
        preset = preset + ("_i8gd" if both else "_i8d")
    if (os.environ.get("BENCH_DELAYED", "") == "1"
            and not cfg.model.int8_delayed):
        # delayed (stored-scale) activation quantization, ops/int8.py
        # (no-op suffix-skip when the preset already ships delayed)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, int8_delayed=True))
        preset = preset + "_ds"
    if os.environ.get("BENCH_THIN", "") == "1":
        # U-Net image head as the subpixel form (ModelConfig.thin_head)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, thin_head=True))
        preset = preset + "_th"
    if os.environ.get("BENCH_STEM", "") == "1":
        # U-Net k4-s2 stem as strided patches (ModelConfig.thin_stem)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, thin_stem=True))
        preset = preset + "_st"
    if os.environ.get("BENCH_HPAL", "") == "1":
        # thin head through the Pallas fused kernel (bypass the Mosaic
        # gate so runtime upgrades get re-probed — ops/conv.py)
        os.environ["P2P_HPAL_FORCE"] = "1"
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, thin_head=True, head_pallas=True))
        preset = preset.removesuffix("_th") + "_hp"
    if os.environ.get("BENCH_SPLITD", ""):
        # feed D unconcatenated (a,b) pairs (ModelConfig.split_d_pairs) —
        # BENCH_SPLITD=0 forces concat on presets that default split
        split_on = os.environ["BENCH_SPLITD"] == "1"
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, split_d_pairs=split_on))
        preset = preset + ("_splitd" if split_on else "_concatd")
    if os.environ.get("BENCH_MOM", ""):
        # low-precision Adam moment storage (OptimConfig.moment_dtype),
        # e.g. BENCH_MOM=bfloat16 — the bs=1 parameter-traffic lever
        cfg = cfg.replace(optim=dataclasses.replace(
            cfg.optim, moment_dtype=os.environ["BENCH_MOM"]))
        preset = preset + "_mom16"
    if os.environ.get("BENCH_UPSAMPLE", ""):
        # override the U-Net decoder upsample family (deconv|subpixel|resize)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, upsample_mode=os.environ["BENCH_UPSAMPLE"]))
        preset = preset + "_" + os.environ["BENCH_UPSAMPLE"]
    if os.environ.get("BENCH_I8DEC", "") == "1":
        # quantized subpixel decoder for the U-Net (QuantSubpixelDeconv)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, int8=True, int8_generator=True, int8_decoder=True))
        preset = preset + "_i8dec"
    if os.environ.get("BENCH_NORM", ""):
        # generator norm override — BENCH_NORM=pallas_instance routes the
        # norm→act(→residual) chains through the fused Pallas epilogue
        # (ops/pallas/norm_act.py; lax fallback off-TPU)
        val = os.environ["BENCH_NORM"]
        cfg = cfg.replace(model=dataclasses.replace(cfg.model, norm=val))
        preset = preset + {"pallas_instance": "_pnorm",
                           "instance": "_inorm"}.get(val, "_" + val)
    if os.environ.get("BENCH_NORMD", ""):
        # discriminator-side norm (ModelConfig.norm_d — pix2pixHD-paper D
        # layout; pallas_instance = fused norm+LeakyReLU epilogue)
        val = os.environ["BENCH_NORMD"]
        cfg = cfg.replace(model=dataclasses.replace(cfg.model, norm_d=val))
        preset = preset + {"pallas_instance": "_pnormd",
                           "instance": "_inormd"}.get(val, "_" + val + "d")
    dtype = jnp.bfloat16 if cfg.train.mixed_precision else None

    n_frames = cfg.data.n_frames
    # BENCH_U8=0 opts out of the uint8 batch contract (default ON — the
    # real pipeline ships uint8 and the steps normalize on device, so the
    # HBM-resident scan batches are uint8 too: 4× less input read traffic
    # per step; numerics pinned identical in tests/test_train.py)
    bench_u8 = os.environ.get("BENCH_U8", "1") == "1"
    host = synthetic_batch(batch_size=bs * max(n_frames, 1), size=img,
                           bits=cfg.model.quant_bits, width=wid,
                           dtype="uint8" if bench_u8 else "float32")
    if n_frames > 1:
        # video presets: NTHWC clips through the video step (the img/s
        # figure counts FRAMES — the per-chip pixel-throughput analogue)
        host = {k: v.reshape(bs, n_frames, *v.shape[1:])
                for k, v in host.items()}
    single = {k: jnp.asarray(v) for k, v in host.items()}
    batches = {
        k: jnp.asarray(np.broadcast_to(v, (scan_k,) + v.shape).copy())
        for k, v in host.items()
    }

    vgg_params = None
    if cfg.loss.lambda_vgg > 0:
        vgg_params = load_vgg19_params(
            jnp.bfloat16 if dtype is not None else jnp.float32
        )
    if n_frames > 1:
        from p2p_tpu.train.video_step import (
            build_multi_video_train_step,
            create_video_train_state,
        )

        state = create_video_train_state(cfg, jax.random.key(0), single,
                                         train_dtype=dtype)
        step = build_multi_video_train_step(
            cfg, vgg_params, train_dtype=dtype,
            unroll=int(os.environ.get("BENCH_UNROLL", "1")))
    else:
        state = create_train_state(cfg, jax.random.key(0), single,
                                   train_dtype=dtype)
        # BENCH_UNROLL: lax.scan unroll factor (default 1); >1 trades
        # compile time/code size for cross-step scheduling freedom
        step = build_multi_train_step(
            cfg, vgg_params, train_dtype=dtype,
            unroll=int(os.environ.get("BENCH_UNROLL", "1")))

    from p2p_tpu.obs import StepTimer, measure_rtt, span

    # tunnel round-trip cost of one trivial fetch
    rtt = measure_rtt()

    # warmup (compile) + fence
    with span("bench_warmup"):
        state, metrics = step(state, batches)
        float(metrics["loss_g"][-1])

    # --chaos: exercise the divergence sentinel at the trainer's exact
    # cost model — the PREVIOUS dispatch's per-step metrics are fetched
    # and classified while the next one runs (train/loop.py's delayed
    # read), INSIDE the timed region, so the row measures the healthy-
    # path overhead the BASELINE.md band check stands guard over. The
    # 'nan' chaos seam poisons observations here exactly like the loop.
    sentinel = None
    sentinel_stats = {"steps": 0, "spikes": 0, "nonfinite": 0}
    if with_sentinel:
        from p2p_tpu.resilience.health import (
            DivergenceSentinel,
            poison_nan_observation,
        )

        sentinel = DivergenceSentinel()

        def sentinel_feed(metrics_dev):
            host = jax.device_get(metrics_dev)
            for i in range(scan_k):
                sentinel_stats["steps"] += 1
                # step = OBSERVED step count (1-based, warmup excluded):
                # the default nan@3x2 spec targets the first fetched
                # dispatch at every scan_k, not a train-step number that
                # would shift past the range at BENCH_SCAN=8
                m = poison_nan_observation(
                    sentinel_stats["steps"],
                    {k: float(v[i]) for k, v in host.items()})
                status = sentinel.classify(m)
                if status != "healthy":
                    key = ("nonfinite" if status == "diverged" else "spikes")
                    sentinel_stats[key] += 1

    # the chained fenced interval, minus RTT — StepTimer.chain is the
    # same accumulator the per-step tick() path feeds, so this number and
    # the train loop's are the one img/sec/chip definition
    timer = StepTimer(batch_size=bs * max(n_frames, 1))
    with span("bench_timed"), timer.chain(
            steps=scan_k * n_calls, rtt=rtt) as ch:
        pend = None
        for _ in range(n_calls):
            state, metrics = step(state, batches)
            if sentinel is not None:
                if pend is not None:
                    sentinel_feed(pend)
                pend = metrics
        if sentinel is not None and pend is not None:
            sentinel_feed(pend)
        ch.fence(metrics["loss_g"][-1])  # forces the whole chained sequence

    # per-net attribution breakdown (OUTSIDE the timed headline chain, so
    # the headline number is untouched); BENCH_BREAKDOWN=0 skips it. Video
    # presets keep headline-only records (their nets differ per step).
    phases = None
    if os.environ.get("BENCH_BREAKDOWN", "1") == "1" and n_frames == 1:
        phases = _phase_breakdown(cfg, state, host, dtype, scan_k, rtt)
        phases["step_ms"] = round(
            timer.elapsed / max(timer.intervals, 1) * 1000.0, 3)

    img_per_sec = timer.images_per_sec
    baseline = 2000.0  # BASELINE.json north_star: img/s/chip @ 256^2 pix2pix
    comparable = on_tpu and img == 256 and preset in (
        "facades", "facades_int8", "edges2shoes_dp",
        # suffix order as generated above: INT8 → DELAYED → THIN → I8DEC
        "facades_int8_ds", "facades_int8_i8gd", "facades_int8_i8gd_ds",
        "facades_int8_i8dec", "facades_int8_ds_i8dec",
        "facades_int8_ds_th", "facades_int8_th", "facades_int8_hp",
    )
    dims = f"{img}x{wid}" if wid else f"{img}px"
    record = {
        "metric": f"train_throughput_{preset}_{platform}_{dims}_bs{bs}",
        "value": round(img_per_sec, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_per_sec / baseline, 4) if comparable else 0.0,
    }
    if sentinel is not None:
        record["sentinel"] = dict(sentinel_stats)
    if phases is not None:
        record["phases"] = phases
    if comparable:
        # context: the 2000 img/s north star was set for TPU v4 (275 bf16
        # peak TF/s); this driver measures whatever chip the tunnel exposes.
        # Roofline for THIS step on v5e (XLA cost analysis: 10.45 TF +
        # 38 GB/step): ~2413 img/s at 100% utilization.
        kind = jax.devices()[0].device_kind
        record["chip"] = kind
        if "v5 lite" in kind.lower() or "v5e" in kind.lower():
            record["v4_equiv_at_same_efficiency"] = round(
                img_per_sec * 275.0 / 197.0, 2)
    if os.environ.get("BENCH_JSONL"):
        # mirror the result into a metrics stream (same record, kind-tagged)
        from p2p_tpu.obs import JSONLSink, MetricsRegistry

        reg = MetricsRegistry()
        sink = JSONLSink(os.environ["BENCH_JSONL"])
        reg.add_sink(sink)
        reg.record({"kind": "bench", "rtt_sec": round(rtt, 6), **record},
                   force=True)
        sink.close()
    return record


# ---------------------------------------------------------------------------
# --infer: the standing inference headline row (docs/SERVING.md)
# ---------------------------------------------------------------------------

def run_infer(tiny: bool = False) -> dict:
    """Serving-engine throughput: AOT bucket-batched generator inference
    with pipelined PNG output (p2p_tpu.serve.InferenceEngine), reported
    with the fenced StepTimer breakdown — img/s end-to-end, device-only
    img/s, encode overlap, and compiles-per-bucket (must equal the bucket
    count: the bucketing contract this row stands guard over).

    Env knobs: BENCH_PRESET (default facades_int8 — same generator as the
    train headline), BENCH_BS (default 64 on TPU), BENCH_IMG, BENCH_STEPS
    (number of full batches; a half-size tail batch is always appended to
    exercise the bucket router), BENCH_INFER_DTYPE (bf16|f32, default
    bf16), BENCH_INFER_SAVE=0 to skip PNG output (pure device number).
    """
    import tempfile

    import jax

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.synthetic import synthetic_batch
    from p2p_tpu.serve import InferenceEngine
    from p2p_tpu.train.state import create_infer_state

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    preset = os.environ.get("BENCH_PRESET", "facades_int8")
    cfg = get_preset(preset)
    facades_like = preset in ("facades", "facades_int8",
                              "facades_int8_full")
    if tiny:
        img, wid = 32, (64 if cfg.data.image_width else None)
        bs, n_batches = 2, 2
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, ngf=8, ndf=8, num_D=min(cfg.model.num_D, 2),
            n_layers_D=2, n_blocks=min(cfg.model.n_blocks, 2)))
    else:
        # same shape rule as run_single: BENCH_IMG forces square,
        # otherwise non-default presets serve at their NATIVE dims
        # (pix2pixhd 1024×512 — the HD generators assume W > H)
        if "BENCH_IMG" in os.environ or facades_like or not on_tpu:
            img = int(os.environ.get("BENCH_IMG", "256" if on_tpu else "64"))
            wid = None
        else:
            img, wid = cfg.data.image_size, cfg.data.image_width
        bs = int(os.environ.get("BENCH_BS", "64" if on_tpu else "2"))
        n_batches = int(os.environ.get("BENCH_STEPS",
                                       "32" if on_tpu else "4"))
    dtype = os.environ.get("BENCH_INFER_DTYPE", "bf16")
    save = os.environ.get("BENCH_INFER_SAVE", "1") == "1"
    cfg = cfg.replace(data=dataclasses.replace(
        cfg.data, test_batch_size=bs, image_size=img, image_width=wid))

    tail = max(1, bs // 2)
    buckets = tuple(sorted({tail, bs}))
    u8 = cfg.data.uint8_pipeline
    host = synthetic_batch(batch_size=bs, size=img,
                           bits=cfg.model.quant_bits, width=wid,
                           dtype="uint8" if u8 else "float32")
    state = create_infer_state(cfg, jax.random.key(0), host)
    engine = InferenceEngine(cfg, state, buckets=buckets, dtype=dtype,
                             with_metrics=False)

    def batches():
        for _ in range(n_batches):
            yield host
        # the tail batch: routes to the smaller bucket, never a recompile
        yield {k: v[:tail] for k, v in host.items()}

    out_dir = tempfile.mkdtemp(prefix="bench_infer_") if save else None
    from p2p_tpu.obs import span

    with span("bench_infer"):
        stats, _ = engine.run(batches(), out_dir=out_dir)
    dims = f"{img}x{wid}" if wid else f"{img}px"
    record = {
        "metric": f"infer_throughput_{preset}_{dtype}_{platform}_{dims}_bs{bs}",
        "value": round(stats.img_per_sec, 2),
        "unit": "img/sec/chip",
        **stats.as_dict(),
    }
    # contract gate BEFORE the metrics mirror: a run that recompiled
    # mid-serve must not append its (broken) row to the standing stream —
    # and must fail under `python -O` too, so no bare assert
    if stats.n_compiles != len(buckets):
        raise RuntimeError(
            f"bucket contract broken: {stats.n_compiles} compiles for "
            f"{len(buckets)} buckets")
    if os.environ.get("BENCH_JSONL"):
        from p2p_tpu.obs import JSONLSink, MetricsRegistry

        reg = MetricsRegistry()
        sink = JSONLSink(os.environ["BENCH_JSONL"])
        reg.add_sink(sink)
        reg.record({"kind": "bench_infer", **record}, force=True)
        sink.close()
    return record


# ---------------------------------------------------------------------------
# --serve: the open-loop serving-latency row (docs/SERVING.md "HTTP API")
# ---------------------------------------------------------------------------

def run_serve(tiny: bool = False) -> dict:
    """Open-loop serving latency: synthetic clients submit requests on a
    FIXED arrival schedule (independent of completions — the open-loop
    discipline that exposes queueing delay closed-loop benchmarks hide)
    against the continuous batcher + shared dispatch loop + AOT bucket
    engine (p2p_tpu.serve.batcher/frontend — the exact serving stack
    behind the HTTP frontend, minus the socket so the row measures
    batching + inference, not urllib). Reports p50/p99 request latency
    (admission → response bytes ready), served img/sec, and the bucket
    occupancy the continuous batcher achieved — plus the standing
    compile contract (n_compiles == len(buckets), zero mid-serve).

    Env knobs: BENCH_PRESET (default facades_int8), BENCH_BS (largest
    bucket / group cap), BENCH_IMG, BENCH_SERVE_N (total requests),
    BENCH_SERVE_RATE (arrivals/sec; 0 = as-fast-as-possible burst),
    BENCH_INFER_DTYPE (bf16|f32).
    """
    import threading
    import time

    import jax
    import numpy as np

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.synthetic import synthetic_batch
    from p2p_tpu.obs import MetricsRegistry
    from p2p_tpu.resilience.queue import BoundedRequestQueue
    from p2p_tpu.serve import (
        ContinuousBatcher,
        DispatchLoop,
        InferenceEngine,
        default_buckets,
    )
    from p2p_tpu.train.state import create_infer_state

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    preset = os.environ.get("BENCH_PRESET", "facades_int8")
    cfg = get_preset(preset)
    if tiny:
        img, bs, n_req, rate = 32, 4, 24, 0.0
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, ngf=8, ndf=8, num_D=min(cfg.model.num_D, 2),
            n_layers_D=2, n_blocks=min(cfg.model.n_blocks, 2)))
    else:
        img = int(os.environ.get("BENCH_IMG", "256" if on_tpu else "64"))
        bs = int(os.environ.get("BENCH_BS", "64" if on_tpu else "4"))
        n_req = int(os.environ.get("BENCH_SERVE_N",
                                   "1024" if on_tpu else "64"))
        rate = float(os.environ.get("BENCH_SERVE_RATE", "0"))
    dtype = os.environ.get("BENCH_INFER_DTYPE", "bf16")
    cfg = cfg.replace(data=dataclasses.replace(
        cfg.data, test_batch_size=bs, image_size=img, image_width=None))
    buckets = default_buckets(bs)
    u8 = cfg.data.uint8_pipeline
    host = synthetic_batch(batch_size=1, size=img,
                           bits=cfg.model.quant_bits,
                           dtype="uint8" if u8 else "float32")
    state = create_infer_state(cfg, jax.random.key(0), host)
    engine = InferenceEngine(cfg, state, buckets=buckets, dtype=dtype,
                             with_metrics=False)
    engine.warmup()

    reg = MetricsRegistry()
    queue = BoundedRequestQueue(max_depth=max(4 * bs, n_req),
                                registry=reg, tenant="bench")
    batcher = ContinuousBatcher(queue, buckets, group_cap=bs,
                                linger_s=0.002)
    payload = host["input"][0]
    latencies = []
    done = threading.Event()

    def deliver(reqs, pred, n_real):
        # the response isn't served until the bytes are host-side: one
        # batch D2H here makes the latency honest, like the HTTP
        # responder's fetch (PNG encode excluded — that's --infer's
        # encode_sec story)
        np.asarray(pred)
        now = time.monotonic()
        for r in reqs:
            latencies.append(now - r.enqueued_at)
        if len(latencies) >= n_req:
            done.set()

    loop = DispatchLoop(
        engine, batcher, decode=lambda req: req.payload, deliver=deliver,
        on_poison=lambda req, exc: None, registry=reg, tenant="bench",
        group_cap=bs)

    consumer_exc = []

    def consume():
        try:
            while not done.is_set():
                ready, _ = batcher.next_group(timeout=0.05)
                if ready:
                    loop.dispatch(ready)
        except BaseException as e:  # surface, don't stall done.wait(600)
            consumer_exc.append(e)
            done.set()

    consumer = threading.Thread(target=consume, name="bench-serve",
                                daemon=True)
    consumer.start()
    t0 = time.monotonic()
    for i in range(n_req):
        if rate > 0:
            target = t0 + i / rate
            while True:
                lag = target - time.monotonic()
                if lag <= 0:
                    break
                time.sleep(min(lag, 0.002))
        while batcher.submit(f"r{i}", payload=payload) is None:
            time.sleep(0.001)  # queue sized for n_req; near-unreachable
    if not done.wait(600):
        raise RuntimeError(
            f"serve bench stalled: {len(latencies)}/{n_req} completed")
    wall = max(time.monotonic() - t0, 1e-9)
    batcher.close()
    consumer.join(timeout=5.0)
    if consumer_exc:
        raise consumer_exc[0]

    if engine.n_compiles != len(buckets):
        raise RuntimeError(
            f"bucket contract broken: {engine.n_compiles} compiles for "
            f"{len(buckets)} buckets")
    lat_ms = np.asarray(latencies) * 1e3
    record = {
        "metric": f"serve_openloop_{preset}_{dtype}_{platform}_"
                  f"{img}px_bs{bs}",
        "value": round(n_req / wall, 2),
        "unit": "img/sec/chip",
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "n_requests": n_req,
        "rate": rate,
        "wall_sec": round(wall, 4),
        "occupancy_mean": round(loop.occupancy_mean, 4),
        "padded_images": loop.padded_images,
        "n_compiles": engine.n_compiles,
        "buckets": list(buckets),
    }
    if os.environ.get("BENCH_JSONL"):
        from p2p_tpu.obs import JSONLSink

        sink = JSONLSink(os.environ["BENCH_JSONL"])
        reg.add_sink(sink)
        reg.record({"kind": "bench_serve", **record}, force=True)
        sink.close()
    return record


# ---------------------------------------------------------------------------
# --sweep: the standing perf-regression gate (VERDICT r5 #7)
# ---------------------------------------------------------------------------

# The eight contract rows with BASELINE.md's last-recorded bands
# (img/s/chip; round-5 ledger + session-2 final-tree regression sweep).
# A row regresses when it lands >3% below its band FLOOR — the band width
# itself is documented tunnel/day drift, not regression. ``band: None`` =
# a new row whose band is pending its first on-TPU recording (BASELINE.md
# "adding a band"): the row runs and reports, the regression gate arms
# once the measured band is written here.
SWEEP_ROWS = [
    {"name": "headline_facades_int8_bs128", "env": {},
     "band": (1684.4, 1717.2)},
    {"name": "facades_int8_bs1", "env": {"BENCH_BS": "1"},
     "band": (217.0, 228.7)},
    {"name": "edges2shoes_int8_delayed",
     "env": {"BENCH_PRESET": "edges2shoes_dp", "BENCH_INT8": "1",
             "BENCH_DELAYED": "1"},
     "band": (1364.7, 1371.6)},
    {"name": "cityscapes_spatial",
     "env": {"BENCH_PRESET": "cityscapes_spatial"}, "band": (37.5, 37.9)},
    {"name": "pix2pixhd", "env": {"BENCH_PRESET": "pix2pixhd"},
     "band": (8.77, 8.81)},
    {"name": "vid2vid_temporal",
     "env": {"BENCH_PRESET": "vid2vid_temporal"}, "band": (200.3, 203.5)},
    # round-6 rows (ISSUE 6): int8 over the FULL 3-scale spectral-norm
    # multiscale D (the reference workload's D, delayed scales), and the
    # fused Pallas norm+act chains on the instance-norm ResNet family
    {"name": "reference_int8_multiD",
     "env": {"BENCH_PRESET": "reference", "BENCH_INT8": "1",
             "BENCH_DELAYED": "1"},
     "band": None},
    {"name": "cityscapes_pallas_fused",
     "env": {"BENCH_PRESET": "cityscapes_spatial",
             "BENCH_NORM": "pallas_instance"},
     "band": None},
    # round-8 row (ISSUE 14): FULL-model delayed int8 on the headline
    # facades config — the drained-worklist coverage set, now a FIRST-
    # CLASS preset (ISSUE 15: the former BENCH_INT8_FULL opt-out env
    # gate is gone, the measurement of record for the ROADMAP item-2
    # band decision rides every default sweep). Band-pending until
    # measured on-chip; the lint's train_step[facades_int8_full]
    # roofline row is its static twin.
    {"name": "facades_int8_full",
     "env": {"BENCH_PRESET": "facades_int8_full"}, "band": None},
    # round-7 row (ISSUE 12): the open-loop serving-latency row — the
    # continuous-batching stack behind the HTTP frontend (run_serve);
    # value is served img/sec, the record carries p50/p99 request latency
    {"name": "serve_openloop_continuous_batch", "env": {},
     "mode": "serve", "band": None},
]

REGRESSION_TOLERANCE = 0.03


def run_sweep(dry_run: bool = False) -> int:
    """Run every contract row; return a nonzero exit code naming each row
    that lands >3% under its band floor. ``dry_run`` shrinks the rows to
    toy dims (CPU-able) and checks plumbing only."""
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    check_bands = on_tpu and not dry_run
    if not check_bands and not dry_run:
        print("note: not on TPU — values are not comparable to the "
              "BASELINE.md bands; band check skipped", file=sys.stderr)
    # the sweep owns these knobs; a stray env override would silently
    # bench a different contract than the bands record
    owned = ("BENCH_PRESET", "BENCH_BS", "BENCH_INT8", "BENCH_DELAYED",
             "BENCH_IMG", "BENCH_NORM", "BENCH_NORMD", "BENCH_BREAKDOWN")
    saved = {k: os.environ.pop(k) for k in owned if k in os.environ}
    if saved:
        print(f"note: ignoring {sorted(saved)} for --sweep",
              file=sys.stderr)
    from p2p_tpu.analysis.hlo_cost import roofline_row_for

    def sweep_roofline(row):
        """The perf_budget.json row statically modeling this sweep row's
        program, None when the traced set doesn't cover it. Keys on the
        FULL row env, not just the preset: BENCH_INT8 switches the U-Net
        family to the delayed-int8 program, and the plain cityscapes row
        runs the reference norm — only its BENCH_NORM=pallas_instance
        variant matches the fused traced row."""
        if row.get("mode") == "serve":
            return None          # the traced set models train/eval steps
        env = row["env"]
        preset = env.get("BENCH_PRESET", "facades_int8")
        if env.get("BENCH_INT8"):
            return (roofline_row_for("facades_int8")
                    if preset in ("facades", "edges2shoes_dp") else None)
        if preset == "cityscapes_spatial" and not env.get("BENCH_NORM"):
            return None          # reference-norm program, not the fused one
        return roofline_row_for(preset)

    regressions = []
    results = []
    try:
        for row in SWEEP_ROWS:
            os.environ.update(row["env"])
            runner = (run_serve if row.get("mode") == "serve"
                      else run_single)
            try:
                rec = runner(tiny=dry_run)
            finally:
                for k in row["env"]:
                    os.environ.pop(k, None)
            band = row["band"]
            status = "ok" if band is not None else "ok (band pending)"
            if not (rec["value"] > 0):
                status = "failed"
                regressions.append((row["name"], rec["value"],
                                    band[0] if band else 0.0))
            elif check_bands and band is not None:
                lo = band[0]
                floor = lo * (1 - REGRESSION_TOLERANCE)
                if rec["value"] < floor:
                    status = f"REGRESSION (<{floor:.1f})"
                    regressions.append((row["name"], rec["value"], lo))
            entry = {"row": row["name"], "value": rec["value"],
                     "band": list(band) if band is not None else None,
                     "status": status, "metric": rec["metric"],
                     # the perf_budget.json row statically modeling this
                     # config's program family (ISSUE 13): the measured
                     # number and its cost-model bound travel together
                     "roofline": sweep_roofline(row)}
            if "p50_ms" in rec:
                # the serving row's latency tail rides the sweep record
                entry["latency_ms"] = {"p50": rec["p50_ms"],
                                       "p99": rec["p99_ms"]}
            if "phases" in rec:
                # the per-net attribution breakdown rides every sweep row
                # (ISSUE 6 satellite — see _phase_breakdown)
                entry["phases"] = rec["phases"]
            results.append(entry)
            print(json.dumps(results[-1]), flush=True)
    finally:
        os.environ.update(saved)
    print(json.dumps({
        "kind": "bench_sweep", "dry_run": dry_run,
        "bands_checked": check_bands, "rows": len(results),
        "regressions": [r[0] for r in regressions],
    }))
    if regressions:
        for name, val, lo in regressions:
            print(f"REGRESSION: {name} = {val} vs band floor {lo} "
                  f"(-{(1 - val / lo) * 100:.1f}%)", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", action="store_true",
                    help="run all ten BASELINE.md contract rows and fail "
                         "on >3% regression below the recorded band "
                         "(band-less rows report without gating)")
    ap.add_argument("--infer", action="store_true",
                    help="bench the serving engine instead of the train "
                         "step: AOT bucket-batched inference + pipelined "
                         "PNG output, fenced breakdown (docs/SERVING.md)")
    ap.add_argument("--serve", action="store_true",
                    help="bench the SERVING STACK open-loop: continuous "
                         "batcher + dispatch loop + engine under a fixed "
                         "arrival schedule; reports p50/p99 request "
                         "latency + served img/sec (docs/SERVING.md)")
    ap.add_argument("--chaos", nargs="?", const="__default__",
                    default=None, metavar="SPEC",
                    help="arm fault injection for the run. With --infer "
                         "(default spec 'serve_write:1.0x2') the row "
                         "measures throughput with retries firing; alone "
                         "(default spec 'nan@3x2') it runs the TRAIN "
                         "headline with the divergence sentinel classifying "
                         "every step at the trainer's delayed-read cost "
                         "model — the standing sentinel-overhead row "
                         "(docs/RESILIENCE.md)")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --sweep/--infer/--chaos: toy dims, plumbing "
                         "check only (CPU-able; no band comparison)")
    args = ap.parse_args(argv)
    chaos_counts = None
    if args.chaos:
        from p2p_tpu.resilience import ChaosMonkey, install_chaos

        spec = args.chaos
        if spec == "__default__":
            spec = "serve_write:1.0x2" if args.infer else "nan@3x2"
        monkey = ChaosMonkey.from_spec(spec)
        install_chaos(monkey)
        chaos_counts = monkey.counts
    if args.serve:
        rec = run_serve(tiny=args.dry_run)
        if chaos_counts is not None:
            rec["chaos_injected"] = chaos_counts()
        print(json.dumps(rec))
        return 0
    if args.infer:
        rec = run_infer(tiny=args.dry_run)
        if chaos_counts is not None:
            from p2p_tpu.obs import get_registry

            rec["chaos_injected"] = chaos_counts()
            rec["retries"] = int(
                get_registry().total("retry_attempts_total"))
        print(json.dumps(rec))
        return 0
    if args.sweep:
        return run_sweep(dry_run=args.dry_run)
    # plain train row; --chaos additionally runs the sentinel at the
    # trainer's cost model and reports what it classified/injected
    rec = run_single(tiny=args.dry_run and chaos_counts is not None,
                     with_sentinel=chaos_counts is not None)
    if chaos_counts is not None:
        rec["chaos_injected"] = chaos_counts()
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
