"""Benchmark: training throughput (img/sec/chip) vs the north star
(BASELINE.json: >= 2000 img/s/chip @ 256^2 pix2pix on TPU).

Headline metric: the full jitted pix2pix train step (U-Net G + 70x70
PatchGAN D + L1, the 'facades'/'edges2shoes' preset family) on 256x256
synthetic pairs. BENCH_PRESET selects any other preset (e.g. 'reference'
for the heavy ExpandNetwork + multiscale-D + VGG workload).

Timing methodology (tunneled-TPU safe): K train steps run inside ONE
jitted ``lax.scan`` dispatch (build_multi_train_step) so per-call host/
tunnel overhead amortizes away; calls are CHAINED (each consumes the
previous state) and a single host fetch of the final loss forces the whole
chain — ``jax.block_until_ready`` does not reliably fence on the tunneled
'axon' platform, and per-step fetches would bill one tunnel round-trip per
step. The RTT of a trivial fetch is measured separately and subtracted.
The mechanics live in ``p2p_tpu.obs.timing`` (``StepTimer.chain`` +
``measure_rtt``), so this file, the train loop, and the metrics stream all
share ONE fenced img/sec/chip definition.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Env knobs: BENCH_PRESET, BENCH_BS (per-chip batch), BENCH_STEPS, BENCH_IMG;
BENCH_JSONL=<path> additionally appends the record (kind="bench") to that
metrics stream through the obs registry.
"""

from __future__ import annotations

import dataclasses
import json
import os


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.synthetic import synthetic_batch
    from p2p_tpu.models.vgg import load_vgg19_params
    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_multi_train_step

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # Default headline: the int8-discriminator QAT step with DELAYED
    # (stored-scale) activation quantization — identical architecture/
    # losses to 'facades' (the bf16 number is one BENCH_PRESET=facades
    # away); trained-quality evidence for THIS path is the decayed
    # 40-epoch real-photo run metrics_facades_int8_decay.jsonl (README
    # "Round 3": final 22.21 dB / 0.769 SSIM / 0.63 VFID, best-in-decay
    # 23.75 / 0.794 / 0.398 — at the dynamic-path peak level).
    preset = os.environ.get("BENCH_PRESET", "facades_int8")
    cfg = get_preset(preset)
    facades_like = preset in ("facades", "facades_int8")
    # BENCH_IMG overrides to a square size; otherwise non-default presets
    # bench at their NATIVE dims (e.g. pix2pixhd 1024×512), facades at 256².
    if "BENCH_IMG" in os.environ or facades_like or not on_tpu:
        img = int(os.environ.get("BENCH_IMG", "256" if on_tpu else "64"))
        wid = None
    else:
        img, wid = cfg.data.image_size, cfg.data.image_width
    bs = int(os.environ.get("BENCH_BS", ("128" if facades_like else
                                         str(cfg.data.batch_size)) if on_tpu
                            else "2"))
    scan_k = int(os.environ.get("BENCH_SCAN", "8" if on_tpu else "2"))
    n_calls = int(os.environ.get("BENCH_STEPS", "64" if on_tpu else "4")) // scan_k
    n_calls = max(n_calls, 2)

    cfg = cfg.replace(
        data=dataclasses.replace(
            cfg.data, batch_size=bs, image_size=img, image_width=wid
        )
    )
    bench_int8 = os.environ.get("BENCH_INT8", "").lower()
    if bench_int8 in ("1", "d", "true", "on", "g"):
        # int8 discriminator on any preset; BENCH_INT8=g also quantizes
        # the generator trunk (ResNet families / U-Net encoder)
        both = bench_int8 == "g"
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, int8=True, int8_generator=both))
        preset = preset + ("_i8gd" if both else "_i8d")
    if (os.environ.get("BENCH_DELAYED", "") == "1"
            and not cfg.model.int8_delayed):
        # delayed (stored-scale) activation quantization, ops/int8.py
        # (no-op suffix-skip when the preset already ships delayed)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, int8_delayed=True))
        preset = preset + "_ds"
    if os.environ.get("BENCH_THIN", "") == "1":
        # U-Net image head as the subpixel form (ModelConfig.thin_head)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, thin_head=True))
        preset = preset + "_th"
    if os.environ.get("BENCH_STEM", "") == "1":
        # U-Net k4-s2 stem as strided patches (ModelConfig.thin_stem)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, thin_stem=True))
        preset = preset + "_st"
    if os.environ.get("BENCH_HPAL", "") == "1":
        # thin head through the Pallas fused kernel (bypass the Mosaic
        # gate so runtime upgrades get re-probed — ops/conv.py)
        os.environ["P2P_HPAL_FORCE"] = "1"
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, thin_head=True, head_pallas=True))
        preset = preset.removesuffix("_th") + "_hp"
    if os.environ.get("BENCH_SPLITD", ""):
        # feed D unconcatenated (a,b) pairs (ModelConfig.split_d_pairs) —
        # BENCH_SPLITD=0 forces concat on presets that default split
        split_on = os.environ["BENCH_SPLITD"] == "1"
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, split_d_pairs=split_on))
        preset = preset + ("_splitd" if split_on else "_concatd")
    if os.environ.get("BENCH_MOM", ""):
        # low-precision Adam moment storage (OptimConfig.moment_dtype),
        # e.g. BENCH_MOM=bfloat16 — the bs=1 parameter-traffic lever
        cfg = cfg.replace(optim=dataclasses.replace(
            cfg.optim, moment_dtype=os.environ["BENCH_MOM"]))
        preset = preset + "_mom16"
    if os.environ.get("BENCH_UPSAMPLE", ""):
        # override the U-Net decoder upsample family (deconv|subpixel|resize)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, upsample_mode=os.environ["BENCH_UPSAMPLE"]))
        preset = preset + "_" + os.environ["BENCH_UPSAMPLE"]
    if os.environ.get("BENCH_I8DEC", "") == "1":
        # quantized subpixel decoder for the U-Net (QuantSubpixelDeconv)
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, int8=True, int8_generator=True, int8_decoder=True))
        preset = preset + "_i8dec"
    dtype = jnp.bfloat16 if cfg.train.mixed_precision else None

    n_frames = cfg.data.n_frames
    # BENCH_U8=0 opts out of the uint8 batch contract (default ON — the
    # real pipeline ships uint8 and the steps normalize on device, so the
    # HBM-resident scan batches are uint8 too: 4× less input read traffic
    # per step; numerics pinned identical in tests/test_train.py)
    bench_u8 = os.environ.get("BENCH_U8", "1") == "1"
    host = synthetic_batch(batch_size=bs * max(n_frames, 1), size=img,
                           bits=cfg.model.quant_bits, width=wid,
                           dtype="uint8" if bench_u8 else "float32")
    if n_frames > 1:
        # video presets: NTHWC clips through the video step (the img/s
        # figure counts FRAMES — the per-chip pixel-throughput analogue)
        host = {k: v.reshape(bs, n_frames, *v.shape[1:])
                for k, v in host.items()}
    single = {k: jnp.asarray(v) for k, v in host.items()}
    batches = {
        k: jnp.asarray(np.broadcast_to(v, (scan_k,) + v.shape).copy())
        for k, v in host.items()
    }

    vgg_params = None
    if cfg.loss.lambda_vgg > 0:
        vgg_params = load_vgg19_params(
            jnp.bfloat16 if dtype is not None else jnp.float32
        )
    if n_frames > 1:
        from p2p_tpu.train.video_step import (
            build_multi_video_train_step,
            create_video_train_state,
        )

        state = create_video_train_state(cfg, jax.random.key(0), single,
                                         train_dtype=dtype)
        step = build_multi_video_train_step(
            cfg, vgg_params, train_dtype=dtype,
            unroll=int(os.environ.get("BENCH_UNROLL", "1")))
    else:
        state = create_train_state(cfg, jax.random.key(0), single,
                                   train_dtype=dtype)
        # BENCH_UNROLL: lax.scan unroll factor (default 1); >1 trades
        # compile time/code size for cross-step scheduling freedom
        step = build_multi_train_step(
            cfg, vgg_params, train_dtype=dtype,
            unroll=int(os.environ.get("BENCH_UNROLL", "1")))

    from p2p_tpu.obs import StepTimer, measure_rtt, span

    # tunnel round-trip cost of one trivial fetch
    rtt = measure_rtt()

    # warmup (compile) + fence
    with span("bench_warmup"):
        state, metrics = step(state, batches)
        float(metrics["loss_g"][-1])

    # the chained fenced interval, minus RTT — StepTimer.chain is the
    # same accumulator the per-step tick() path feeds, so this number and
    # the train loop's are the one img/sec/chip definition
    timer = StepTimer(batch_size=bs * max(n_frames, 1))
    with span("bench_timed"), timer.chain(
            steps=scan_k * n_calls, rtt=rtt) as ch:
        for _ in range(n_calls):
            state, metrics = step(state, batches)
        ch.fence(metrics["loss_g"][-1])  # forces the whole chained sequence

    img_per_sec = timer.images_per_sec
    baseline = 2000.0  # BASELINE.json north_star: img/s/chip @ 256^2 pix2pix
    comparable = on_tpu and img == 256 and preset in (
        "facades", "facades_int8", "edges2shoes_dp",
        # suffix order as generated above: INT8 → DELAYED → THIN → I8DEC
        "facades_int8_ds", "facades_int8_i8gd", "facades_int8_i8gd_ds",
        "facades_int8_i8dec", "facades_int8_ds_i8dec",
        "facades_int8_ds_th", "facades_int8_th", "facades_int8_hp",
    )
    dims = f"{img}x{wid}" if wid else f"{img}px"
    record = {
        "metric": f"train_throughput_{preset}_{platform}_{dims}_bs{bs}",
        "value": round(img_per_sec, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_per_sec / baseline, 4) if comparable else 0.0,
    }
    if comparable:
        # context: the 2000 img/s north star was set for TPU v4 (275 bf16
        # peak TF/s); this driver measures whatever chip the tunnel exposes.
        # Roofline for THIS step on v5e (XLA cost analysis: 10.45 TF +
        # 38 GB/step): ~2413 img/s at 100% utilization.
        kind = jax.devices()[0].device_kind
        record["chip"] = kind
        if "v5 lite" in kind.lower() or "v5e" in kind.lower():
            record["v4_equiv_at_same_efficiency"] = round(
                img_per_sec * 275.0 / 197.0, 2)
    if os.environ.get("BENCH_JSONL"):
        # mirror the result into a metrics stream (same record, kind-tagged)
        from p2p_tpu.obs import JSONLSink, MetricsRegistry

        reg = MetricsRegistry()
        sink = JSONLSink(os.environ["BENCH_JSONL"])
        reg.add_sink(sink)
        reg.record({"kind": "bench", "rtt_sec": round(rtt, 6), **record},
                   force=True)
        sink.close()
    print(json.dumps(record))


if __name__ == "__main__":
    main()
