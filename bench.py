"""Benchmark: training throughput (img/sec/chip) on the flagship config.

Runs the full jitted alternating-GAN train step (G+D+C updates, LSGAN +
feature-matching + VGG19-perceptual + TV losses, STE quantizer, spectral
norm) on 256x256 synthetic pairs — the reference's workload (train.py hot
loop, SURVEY §3.1) at the north-star metric: images/sec/chip vs the
BASELINE.json target of 2000 img/s/chip on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Env knobs: BENCH_BS (per-chip batch), BENCH_STEPS, BENCH_IMG (image size).
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.synthetic import synthetic_batch
    from p2p_tpu.models.vgg import load_vgg19_params
    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_train_step

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    img = int(os.environ.get("BENCH_IMG", "256" if on_tpu else "64"))
    bs = int(os.environ.get("BENCH_BS", "8" if on_tpu else "2"))
    n_steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    warmup = max(2, n_steps // 10)

    import dataclasses

    cfg = get_preset("reference")
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, batch_size=bs, image_size=img)
    )
    dtype = jnp.bfloat16 if cfg.train.mixed_precision else None

    host = synthetic_batch(batch_size=bs, size=img, bits=cfg.model.quant_bits)
    batch = {k: jnp.asarray(v, jnp.float32) for k, v in host.items()}

    state = create_train_state(cfg, jax.random.key(0), batch, train_dtype=dtype)
    vgg_params = load_vgg19_params(jnp.bfloat16 if dtype is not None else jnp.float32)
    step = build_train_step(cfg, vgg_params, train_dtype=dtype)

    for _ in range(warmup):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - t0

    img_per_sec = bs * n_steps / elapsed
    baseline = 2000.0  # BASELINE.json north_star: img/s/chip @ 256^2 on TPU
    # only a real-TPU 256^2 run is comparable to the baseline number
    comparable = on_tpu and img == 256
    print(json.dumps({
        "metric": f"train_throughput_{platform}_{img}px_bs{bs}",
        "value": round(img_per_sec, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_per_sec / baseline, 4) if comparable else 0.0,
    }))


if __name__ == "__main__":
    main()
