"""p2p_tpu — a TPU-native (JAX/XLA/Pallas) paired-image conditional-GAN framework.

A ground-up reimplementation of the capability surface of the reference
``Dev-Vault-Archived/p2p-pytorch`` repo (learned bit-depth compression + GAN
restoration, pix2pix family), designed TPU-first:

- NHWC layouts, bf16 compute / fp32 params, static shapes, everything jitted.
- One compiled train step containing all network updates (G, D, C).
- Parallelism via ``jax.sharding.Mesh`` axes ``(data, spatial, time)``:
  data-parallel, GSPMD spatial sharding with conv halo exchange, and
  temporal sequence parallelism — collectives ride ICI, inserted by XLA or
  written explicitly in ``shard_map`` regions.
- Pallas kernels for ops where XLA's defaults are weak (fused InstanceNorm).

Subpackages:
    core      mesh / config / dtype policy / rng
    ops       quantizer (STE), pixel (un)shuffle, convs, norms, spectral norm
    models    generators, discriminators, VGG feature extractor
    losses    GAN / feature-matching / perceptual / metrics
    data      dataset generation + input pipeline
    train     train state, jitted step, schedules, checkpointing, loop
    parallel  sharding rules, halo exchange, collectives
    infer     batched generator inference
    analysis  static analysis: sharding audit, jaxpr/HLO lint, AST rules
"""

__version__ = "0.1.0"
