"""Static-analysis subsystem — the standing correctness gate.

Three analyzers over one structured-findings format
(:mod:`p2p_tpu.analysis.findings`; waivable in-source via
``# p2p-lint: disable=<rule> -- reason``):

- :mod:`p2p_tpu.analysis.sharding_audit` — statically verify a
  partition-rule table against an ``eval_shape``-built state tree: dead/
  shadowed rules, unknown mesh axes, indivisible shards, plus the
  ``tp``-diff migration worklist (ROADMAP item 3).
- :mod:`p2p_tpu.analysis.jaxpr_lint` — the reusable jaxpr/HLO structural
  pin library (collective census, scan-carry ppermute, activation-gather
  bounds, host-callback and f32-leak detectors). tests/test_pp.py and
  tests/test_ops.py import their pins from here.
- :mod:`p2p_tpu.analysis.ast_rules` — project AST lints over ``p2p_tpu/``
  (traced randomness, ``jax.debug`` outside obs, hot-loop host syncs,
  CLI↔config flag drift).

Frontend: ``python -m p2p_tpu.cli.lint --strict`` (the CI gate) —
docs/STATIC_ANALYSIS.md has the rule catalog and waiver policy. Every
analyzer is ``eval_shape``/trace/text-based: zero device compute, CPU-safe.
"""

from p2p_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Finding,
    Report,
    apply_pragma_waivers,
    parse_pragmas,
)
