"""Static-analysis subsystem — the standing correctness+performance gate.

Eight analyzers over one structured-findings format
(:mod:`p2p_tpu.analysis.findings`; waivable in-source via
``# p2p-lint: disable=<rule> -- reason``):

- :mod:`p2p_tpu.analysis.sharding_audit` — statically verify a
  partition-rule table (predicate rules included) against an
  ``eval_shape``-built state tree: dead/shadowed rules, unknown mesh
  axes, indivisible shards, plus the ``tp``-diff migration worklist
  (ROADMAP item 3; the facades family is drained —
  ``parallel/rules.tp_equivalence_rules``).
- :mod:`p2p_tpu.analysis.collective_consistency` — the multi-host-hang
  lint: host-side collectives reachable under per-host-divergent
  predicates or after divergent early exits, plus collectives under
  ``lax.cond`` in traced programs.
- :mod:`p2p_tpu.analysis.memory_audit` — per-device HBM budget table
  (state bytes under the live layout law + traced liveness activation
  peak), buffer-donation markers on lowered train steps, and the
  serving dead-restore check.
- :mod:`p2p_tpu.analysis.concurrency_lint` — host-concurrency races:
  signal-handler reentrancy, unlocked shared-state mutation in
  lock-owning classes, atexit-vs-thread shutdown ordering.
- :mod:`p2p_tpu.analysis.jaxpr_lint` — the reusable jaxpr/HLO structural
  pin library (collective census, scan-carry ppermute, activation-gather
  bounds, host-callback detector with partial resolution, f32-leak
  detector). tests/test_pp.py and tests/test_ops.py import their pins
  from here.
- :mod:`p2p_tpu.analysis.ast_rules` — project AST lints over ``p2p_tpu/``
  (traced randomness, ``jax.debug`` outside obs, hot-loop host syncs,
  CLI↔config flag drift).
- :mod:`p2p_tpu.analysis.hlo_cost` — the static roofline cost model:
  per-program FLOPs / bytes-moved / arithmetic intensity over the traced
  set, published as the ``perf_budget.json`` artifact with canonical-row
  bounds asserted.
- :mod:`p2p_tpu.analysis.perf_audit` — performance lints: the fusion-gap
  lint (``perf-unfused-norm-chain``), the collective-overlap audit
  (``perf-serialized-collective``), and the delayed-int8 coverage
  worklist (``--int8-diff``, ROADMAP item 2).

Frontend: ``python -m p2p_tpu.cli.lint --strict`` (the CI gate) —
docs/STATIC_ANALYSIS.md has the rule catalog and waiver policy. Every
analyzer is ``eval_shape``/trace/lowering-text-based: zero device
compute, CPU-safe.
"""

from p2p_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Finding,
    Report,
    apply_pragma_waivers,
    parse_pragmas,
)
