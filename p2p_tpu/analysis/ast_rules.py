"""Project AST lints — the traps this repo has already been bitten by.

Four rules, each scoped to the zone of ``p2p_tpu/`` where the trap is
real (a blanket rule would drown the signal — host-side data/chaos code
legitimately uses ``np.random``):

- ``ast-traced-randomness`` (error, traced zone: models/ ops/ losses/
  parallel/ train/step.py train/video_step.py): ``np.random.*`` /
  ``random.*`` calls in modules whose code runs under ``jit``. Python
  randomness inside a traced fn bakes ONE sample into the compiled
  program — the classic silent-determinism bug; use ``jax.random`` with a
  threaded key.
- ``ast-debug-outside-obs`` (error, everywhere except obs/):
  ``jax.debug.*`` belongs behind the p2p_tpu/obs seams (taps.py's
  sentinel, spans) where cost and cadence are managed; a stray
  ``jax.debug.print`` in a model fences every dispatch.
- ``ast-host-sync-hot-loop`` (warning, hot loop zone: train/loop.py
  train/video_loop.py serve/engine.py): ``.item()`` /
  ``jax.device_get(...)`` force a device→host sync at the call site; the
  loop's contract is delayed, batched reads (queue_health_observation,
  AsyncImageWriter's batched D2H).
- ``ast-cli-flag-drift`` (error, cli/): (a) an ``add_argument`` flag whose
  ``args.<dest>`` is never read — parsed-but-dead surface area; (b) an
  ``apply_overrides``/``over`` keyword that names no field on any
  core.config dataclass — the flag would raise (or worse, silently stop
  applying) after a config refactor.

Findings are waivable in-source: ``# p2p-lint: disable=<rule> -- reason``
on the line or the line above (p2p_tpu/analysis/findings.py).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set

from p2p_tpu.analysis.findings import (
    ERROR,
    WARNING,
    Finding,
    Report,
    apply_pragma_waivers,
)

RULE_RANDOMNESS = "ast-traced-randomness"
RULE_DEBUG = "ast-debug-outside-obs"
RULE_HOST_SYNC = "ast-host-sync-hot-loop"
RULE_FLAG_DRIFT = "ast-cli-flag-drift"

#: module zones (package-relative, '/'-separated)
TRACED_ZONE = ("models/", "ops/", "losses/", "parallel/")
TRACED_FILES = ("train/step.py", "train/video_step.py")
HOT_LOOP_FILES = ("train/loop.py", "train/video_loop.py", "serve/engine.py")
OBS_ZONE = ("obs/",)
CLI_ZONE = ("cli/",)

_HOST_SYNC_CALLS = {"jax.device_get"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for an Attribute/Name chain, else None — shared by every
    AST-family analyzer (collective_consistency, concurrency_lint)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_dotted = dotted_name


def _in_zone(relpath: str, dirs: Sequence[str] = (),
             files: Sequence[str] = ()) -> bool:
    return relpath in files or any(relpath.startswith(d) for d in dirs)


def config_field_names() -> Set[str]:
    """Union of field names over every dataclass in core.config (plus
    MeshSpec) — the legal keyword surface of ``apply_overrides``."""
    import dataclasses

    from p2p_tpu.core import config as config_mod
    from p2p_tpu.core.mesh import MeshSpec

    names: Set[str] = set()
    for obj in list(vars(config_mod).values()) + [MeshSpec]:
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            names.update(f.name for f in dataclasses.fields(obj))
    return names


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str, imports_random: bool):
        self.relpath = relpath
        self.imports_random = imports_random
        self.findings: List[Finding] = []
        # cli-flag-drift accounting
        self.arg_defs: Dict[str, int] = {}      # dest -> line
        self.attr_reads: Set[str] = set()       # args.<x>
        self.str_consts: Set[str] = set()       # any string constant
        self.over_kwargs: List = []             # (kwarg, line)

    # ---- generic collection -------------------------------------------
    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str):
            self.str_consts.add(node.value)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "args" \
                and isinstance(node.ctx, ast.Load):
            self.attr_reads.add(node.attr)
        self.generic_visit(node)

    # ---- the rules -----------------------------------------------------
    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func)
        if dotted:
            self._check_randomness(node, dotted)
            self._check_debug(node, dotted)
            self._check_host_sync(node, dotted)
        self._collect_cli(node, dotted)
        self.generic_visit(node)

    def _check_randomness(self, node, dotted: str):
        if not _in_zone(self.relpath, TRACED_ZONE, TRACED_FILES):
            return
        hit = (dotted.startswith("np.random.")
               or dotted.startswith("numpy.random.")
               or (self.imports_random and dotted.startswith("random.")))
        if hit:
            self.findings.append(Finding(
                rule=RULE_RANDOMNESS, severity=ERROR,
                file=self.relpath, line=node.lineno,
                message=f"{dotted}() in a traced module: Python/numpy "
                        "randomness bakes one sample into the compiled "
                        "program — thread a jax.random key instead",
            ))

    def _check_debug(self, node, dotted: str):
        if _in_zone(self.relpath, OBS_ZONE):
            return
        if dotted.startswith("jax.debug."):
            self.findings.append(Finding(
                rule=RULE_DEBUG, severity=ERROR,
                file=self.relpath, line=node.lineno,
                message=f"{dotted}() outside the p2p_tpu/obs seams — "
                        "telemetry/debug taps route through obs (taps.py, "
                        "spans.py) where cost and cadence are managed",
            ))

    def _check_host_sync(self, node, dotted: str):
        if not _in_zone(self.relpath, files=HOT_LOOP_FILES):
            return
        is_item = (isinstance(node.func, ast.Attribute)
                   and node.func.attr == "item" and not node.args
                   and not node.keywords)
        if is_item or dotted in _HOST_SYNC_CALLS:
            what = dotted if dotted in _HOST_SYNC_CALLS else ".item()"
            self.findings.append(Finding(
                rule=RULE_HOST_SYNC, severity=WARNING,
                file=self.relpath, line=node.lineno,
                message=f"{what} in a hot loop forces a device→host sync "
                        "at the call site — batch/delay the read "
                        "(queue_health_observation, AsyncImageWriter)",
            ))

    def _collect_cli(self, node: ast.Call, dotted: Optional[str]):
        if not _in_zone(self.relpath, CLI_ZONE):
            return
        func = node.func
        # X.add_argument("--flag", ...) — any receiver
        if isinstance(func, ast.Attribute) and func.attr == "add_argument" \
                and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str) \
                    and first.value.startswith("-"):
                dest = first.value.lstrip("-").replace("-", "_")
                for kw in node.keywords:
                    if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                        dest = str(kw.value.value)
                self.arg_defs[dest] = node.lineno
        # getattr(args, "name"[, default]) counts as a read
        if isinstance(func, ast.Name) and func.id == "getattr" and node.args:
            recv = node.args[0]
            if isinstance(recv, ast.Name) and recv.id == "args" \
                    and len(node.args) > 1 \
                    and isinstance(node.args[1], ast.Constant):
                self.attr_reads.add(str(node.args[1].value))
        # over(cfg_block, field=...) / apply_overrides(...)
        name = dotted or ""
        if name in ("over", "apply_overrides") \
                or name.endswith(".apply_overrides"):
            for kw in node.keywords:
                if kw.arg is not None:
                    self.over_kwargs.append((kw.arg, node.lineno))

    def finish(self) -> List[Finding]:
        if _in_zone(self.relpath, CLI_ZONE):
            referenced = self.attr_reads | self.str_consts
            for dest, line in sorted(self.arg_defs.items()):
                if dest not in referenced:
                    self.findings.append(Finding(
                        rule=RULE_FLAG_DRIFT, severity=ERROR,
                        file=self.relpath, line=line,
                        message=f"flag --{dest} is parsed but args.{dest} "
                                "is never read — dead CLI surface (wire it "
                                "or drop it)",
                    ))
            if self.over_kwargs:
                try:
                    fields = config_field_names()
                except Exception:
                    fields = set()   # config unimportable: skip, don't lie
                for kwarg, line in self.over_kwargs:
                    if fields and kwarg not in fields:
                        self.findings.append(Finding(
                            rule=RULE_FLAG_DRIFT, severity=ERROR,
                            file=self.relpath, line=line,
                            message=f"apply_overrides keyword {kwarg!r} "
                                    "names no field on any core.config "
                                    "dataclass — cfg↔flag drift",
                        ))
        return self.findings


def lint_source(relpath: str, text: str,
                tree: Optional[ast.AST] = None) -> List[Finding]:
    """All findings for one module, pragmas applied. ``relpath`` is the
    package-relative path ('/'-separated, e.g. ``train/step.py``);
    ``tree`` lets a caller share one parse across the AST-family
    analyzers."""
    if tree is None:
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            return [Finding(rule="ast-syntax-error", severity=ERROR,
                            file=relpath, line=e.lineno or 1,
                            message=f"unparseable module: {e.msg}")]
    imports_random = any(
        (isinstance(n, ast.Import)
         and any(a.name == "random" for a in n.names))
        for n in ast.walk(tree))
    v = _Visitor(relpath, imports_random)
    v.visit(tree)
    return apply_pragma_waivers(v.finish(), sources={relpath: text})


def lint_package(pkg_root: Optional[str] = None) -> Report:
    """Run the AST pass over every module of ``p2p_tpu/`` (default: the
    installed package directory). Findings keep package-relative paths;
    pragma waivers are resolved against the real files."""
    from p2p_tpu.analysis.findings import iter_package_sources

    report = Report()
    for rel, text, err in iter_package_sources(pkg_root):
        if text is None:
            report.add(Finding(rule="ast-unreadable", severity=ERROR,
                               file=rel, message=str(err)))
            continue
        report.extend(lint_source(rel, text))
    return report
