"""Collective-consistency checker — the multi-host-hang lint.

Every host of a multi-process run must issue the SAME collectives in the
SAME order; one host branching away from (or bailing out before) a
collective leaves every other host blocked in it forever — the classic
multi-host hang, and exactly the failure mode the elastic seam (PR 7) and
the recovery ladder (PR 5) are most exposed to: both sit between a LOCAL
observation (a signal flag, a health verdict, an injected fault) and a
cross-host agreement point.

Two AST rules over the host-side control flow of ``p2p_tpu/`` plus one
jaxpr rule over the traced step programs:

- ``collective-divergent-branch`` (error): a collective call lexically
  inside an ``if``/``while`` whose predicate the analyzer cannot prove
  host-uniform, or inside an ``except`` handler (one host's exception is
  the canonical divergent predicate). Host-uniform means: built only from
  constants and ``jax.process_count()`` (including names assigned from
  them in the same function). ``jax.process_index()`` is deliberately NOT
  uniform — it is the per-host value.
- ``collective-after-divergent-exit`` (error): a collective call in a
  function where a lexically-earlier ``return``/``raise``/``break``/
  ``continue`` sits under a non-uniform predicate (or in an ``except``
  handler). Hosts taking that early exit skip the collective the others
  enter — the same hang with the branch inverted.
- ``jaxpr-collective-under-cond`` (warning): a collective primitive inside
  a ``lax.cond`` branch of a traced program. The repo's in-graph guards
  use ``where``-selects precisely so every device executes the same
  collective schedule; a psum under a data-dependent cond re-introduces
  the divergence in-graph.

What counts as a collective: the raw ``jax.experimental.multihost_utils``
entry points, plus the repo's own documented collective-bearing helpers
(``PreemptionGuard.should_stop``, ``poll_preempt``,
``combine_process_metric_stats``, ``MetricsRegistry.aggregate``) — the
curated list below. The analyzer is intentionally conservative: a site it
cannot prove uniform is a finding; provably-aligned protocols (e.g. the
preemption guard's poll-counter cadence) carry an in-source waiver pragma
stating the alignment argument — the waiver IS the documentation.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from p2p_tpu.analysis.ast_rules import dotted_name as _dotted
from p2p_tpu.analysis.findings import (
    ERROR,
    WARNING,
    Finding,
    apply_pragma_waivers,
)

RULE_DIVERGENT_BRANCH = "collective-divergent-branch"
RULE_DIVERGENT_EXIT = "collective-after-divergent-exit"
RULE_COND_COLLECTIVE = "jaxpr-collective-under-cond"

#: raw multi-host collective entry points (matched on the final dotted
#: segment, so ``multihost_utils.process_allgather`` and a bare import
#: both hit)
COLLECTIVE_CALLS = frozenset({
    "process_allgather",
    "sync_global_devices",
    "broadcast_one_to_all",
})

#: repo functions/methods documented to enter collectives on >1 process
#: (their OWN bodies are linted too; calling them inherits the hazard)
COLLECTIVE_BEARING = frozenset({
    "should_stop",                   # PreemptionGuard agreement allgather
    "poll_preempt",                  # train loops' step-boundary poll
    "combine_process_metric_stats",  # eval stats allgather
    "aggregate",                     # MetricsRegistry cross-host reduce
    # elastic restore path (resilience/reshape.py): the plan decides —
    # and elastic_restore executes — a cross-host Orbax load plus the
    # `migrate` verdict's restore-time transform chain (batch_rebase /
    # pp_restructure / tp_amax_recalibrate / dtype_cast, see
    # reshape.RESHAPE_TRANSFORMS); a host that skips either call (or
    # reaches it with a different plan) strands every other host's
    # restore collectives
    "plan_elastic_restore",
    "elastic_restore",
})

#: calls whose value is identical on every host
_UNIFORM_CALLS = frozenset({"jax.process_count", "process_count"})


def _collective_name(call: ast.Call) -> Optional[str]:
    """The collective a Call enters, or None."""
    func = call.func
    name = None
    if isinstance(func, ast.Attribute):
        name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    if name in COLLECTIVE_CALLS or name in COLLECTIVE_BEARING:
        return name
    return None


def _uniform_expr(node: ast.AST, uniform_names: Set[str]) -> bool:
    """True iff the analyzer can PROVE the expression is host-uniform."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in uniform_names
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func)
        return (dotted in _UNIFORM_CALLS
                or (dotted or "").endswith(".process_count")) \
            and not node.args and not node.keywords
    if isinstance(node, ast.Compare):
        return (_uniform_expr(node.left, uniform_names)
                and all(_uniform_expr(c, uniform_names)
                        for c in node.comparators))
    if isinstance(node, ast.BoolOp):
        return all(_uniform_expr(v, uniform_names) for v in node.values)
    if isinstance(node, ast.BinOp):
        return (_uniform_expr(node.left, uniform_names)
                and _uniform_expr(node.right, uniform_names))
    if isinstance(node, ast.UnaryOp):
        return _uniform_expr(node.operand, uniform_names)
    return False


_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_EXITS = (ast.Return, ast.Raise, ast.Break, ast.Continue)


def _collect_uniform_names(fn: ast.AST) -> Set[str]:
    """Names provably host-uniform EVERYWHERE in the function: every
    binding must be a direct assignment from a uniform expression — a
    name with ANY other binding (a later ``n = self._requested``, a loop
    target, an augmented assign) is demoted, or the flow-insensitive
    const-prop would bless a divergent predicate through its earlier
    uniform assignment."""
    tainted: Set[str] = set()
    assigns = []   # (name, value) for single-Name plain assignments

    def taint_targets(target_node):
        for t in ast.walk(target_node):
            if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
                tainted.add(t.id)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                assigns.append((node.targets[0].id, node.value))
            else:
                for t in node.targets:   # tuple-unpack / multi-target
                    taint_targets(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                               ast.NamedExpr)):
            taint_targets(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            taint_targets(node.target)
        elif isinstance(node, ast.comprehension):
            taint_targets(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    taint_targets(item.optional_vars)
    # optimistic greatest fixpoint: start from every non-tainted assigned
    # name, then repeatedly DROP any name with an assignment that is not
    # uniform under the current set — uniform-from-uniform chains
    # (``world = n`` after ``n = jax.process_count()``) survive, while a
    # later ``n = self._requested`` demotes ``n`` AND everything derived
    # from it, in as many rounds as the chain is deep
    by_name: Dict[str, List[ast.AST]] = {}
    for name, value in assigns:
        by_name.setdefault(name, []).append(value)
    uniform = {n for n in by_name if n not in tainted}
    for _ in range(len(by_name) + 1):
        dropped = {
            n for n in uniform
            if not all(_uniform_expr(v, uniform) for v in by_name[n])
        }
        if not dropped:
            break
        uniform -= dropped
    return uniform


def _calls_in(node: ast.AST) -> List[ast.Call]:
    """Call nodes in a statement, NOT descending into nested functions
    (their bodies run at call time, under their own analysis)."""
    out: List[ast.Call] = []
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, _FN_NODES) and n is not node:
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


class _FunctionPass:
    def __init__(self, relpath: str, fn, uniform_names: Set[str]):
        self.relpath = relpath
        self.fn = fn
        self.uniform = uniform_names
        self.findings: List[Finding] = []
        # (line, why) of the first divergent early exit seen so far
        self.divergent_exit: Optional[Tuple[int, str]] = None

    def run(self) -> List[Finding]:
        self._walk(self.fn.body, divergent=None)
        return self.findings

    # -- statement walk (source order) ----------------------------------
    def _walk(self, stmts: Sequence[ast.stmt], divergent: Optional[str]):
        for st in stmts:
            if isinstance(st, _EXITS) and divergent is not None \
                    and self.divergent_exit is None:
                self.divergent_exit = (st.lineno, divergent)
            self._scan_calls(st, divergent)
            self._recurse(st, divergent)

    def _scan_calls(self, st: ast.stmt, divergent: Optional[str]):
        # only this statement's own expressions — compound bodies recurse
        # with their own divergence context (_shallow strips them)
        for call in _calls_in(_shallow(st)):
            name = _collective_name(call)
            if name is None:
                continue
            if divergent is not None:
                self.findings.append(Finding(
                    rule=RULE_DIVERGENT_BRANCH, severity=ERROR,
                    file=self.relpath, line=call.lineno,
                    message=f"collective {name!r} reachable only under a "
                            f"per-host-divergent predicate ({divergent}) — "
                            "a host that skips it hangs every other host's "
                            "next collective",
                ))
            elif self.divergent_exit is not None:
                line, why = self.divergent_exit
                self.findings.append(Finding(
                    rule=RULE_DIVERGENT_EXIT, severity=ERROR,
                    file=self.relpath, line=call.lineno,
                    message=f"collective {name!r} follows a divergent "
                            f"early exit at line {line} ({why}) — hosts "
                            "taking that exit never enter this collective "
                            "while the rest block in it",
                ))

    def _recurse(self, st: ast.stmt, divergent: Optional[str]):
        if isinstance(st, (ast.If, ast.While)):
            test_div = divergent
            if test_div is None \
                    and not _uniform_expr(st.test, self.uniform):
                src = ast.unparse(st.test) if hasattr(ast, "unparse") \
                    else "<predicate>"
                test_div = f"branch on {src!r} at line {st.lineno}"
            self._walk(st.body, test_div)
            self._walk(st.orelse, test_div)
        elif isinstance(st, ast.Try):
            self._walk(st.body, divergent)
            for h in st.handlers:
                why = divergent or (
                    f"except handler at line {h.lineno} — an exception "
                    "raised on one host only")
                self._walk(h.body, why)
            self._walk(st.orelse, divergent)
            self._walk(st.finalbody, divergent)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._walk(st.body, divergent)
            self._walk(st.orelse, divergent)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            self._walk(st.body, divergent)
        # nested function definitions get their own _FunctionPass


def _shallow(st: ast.stmt) -> ast.stmt:
    """A copy-free view of a statement excluding compound bodies (which
    the walk visits with their own divergence context)."""
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                       ast.ClassDef)):
        # defining is not calling: the body runs at CALL time, under its
        # own _FunctionPass — scanning it here would flag a collective in
        # a helper merely DEFINED inside a divergent branch
        return ast.Pass()
    if isinstance(st, (ast.If, ast.While)):
        return st.test
    if isinstance(st, (ast.For, ast.AsyncFor)):
        return st.iter
    if isinstance(st, ast.Try):
        return ast.Pass()   # everything interesting is in the bodies
    if isinstance(st, (ast.With, ast.AsyncWith)):
        # context-manager expressions execute unconditionally at entry
        return ast.Tuple(elts=[i.context_expr for i in st.items],
                         ctx=ast.Load())
    return st


def lint_collective_source(relpath: str, text: str,
                           tree: Optional[ast.Module] = None,
                           ) -> List[Finding]:
    """All collective-consistency findings for one module (pragmas
    applied). ``tree`` lets a caller share one parse across the
    AST-family analyzers (cli/lint.py's single package walk)."""
    if tree is None:
        try:
            tree = ast.parse(text)
        except SyntaxError:
            return []   # the AST pass reports unparseable modules already
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            uniform = _collect_uniform_names(node)
            findings.extend(
                _FunctionPass(relpath, node, uniform).run())
    return apply_pragma_waivers(findings, sources={relpath: text})


def lint_package_collectives(pkg_root: Optional[str] = None) -> List[Finding]:
    """The collective-consistency pass over every module of ``p2p_tpu/``."""
    from p2p_tpu.analysis.findings import iter_package_sources

    out: List[Finding] = []
    for rel, text, _err in iter_package_sources(pkg_root):
        if text is not None:   # ast_rules reports unreadable modules
            out.extend(lint_collective_source(rel, text))
    return out


# ------------------------------------------------------ traced programs


def collectives_under_cond(jaxpr, tag: str = "program") -> List[Finding]:
    """Findings for collective primitives inside ``lax.cond`` branches of
    a traced program — the in-graph twin of the AST rules: a collective
    whose execution depends on a traced predicate diverges the device
    collective schedule exactly like a host branch diverges the host one.
    (The repo's in-jit guards use ``where``-selects, never cond, for this
    reason — resilience/health.py.)"""
    from p2p_tpu.analysis.jaxpr_lint import (
        COLLECTIVE_PRIMITIVES,
        eqn_location,
        iter_eqns,
        normalize_primitive,
        sub_jaxprs,
    )

    out: List[Finding] = []

    def branch_collectives(jx):
        for eqn in iter_eqns(jx):
            name = normalize_primitive(eqn.primitive.name)
            if name in COLLECTIVE_PRIMITIVES:
                yield name, eqn

    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    stack = [jaxpr]
    while stack:
        jx = stack.pop()
        for eqn in jx.eqns:
            if eqn.primitive.name == "cond":
                for br in eqn.params.get("branches", ()):
                    for name, inner in branch_collectives(br):
                        fname, line = eqn_location(inner)
                        out.append(Finding(
                            rule=RULE_COND_COLLECTIVE, severity=WARNING,
                            file=fname, line=line,
                            path=None if fname else tag,
                            message=f"collective {name!r} inside a "
                                    f"lax.cond branch of {tag!r} — a "
                                    "data-dependent predicate diverges "
                                    "the collective schedule; use a "
                                    "where-select over the collective's "
                                    "result instead",
                        ))
            else:
                stack.extend(sub_jaxprs(eqn.params))
    return out
