"""Host-concurrency race lint — the threaded surface's standing gate.

The host side of this trainer is genuinely concurrent: SIGTERM handlers
interrupt the main thread between bytecodes (resilience/preempt.py), the
obs registry fans records out from sentinel-callback and signal-flush
threads (obs/registry.py), the serve writer moves D2H+encode onto a pool
(serve/io.py), and everything registers atexit hooks that run during
interpreter shutdown. Three AST rules, scoped to what is statically
checkable:

- ``conc-signal-handler-unsafe`` (error): inside a function installed via
  ``signal.signal(...)``, a call into locking / buffered-IO / allocating
  machinery (``.acquire``/``.flush``/``.write``/``.log``/``.record``/
  ``.inc``/``.observe``/``.export``, ``print``, ``open``, ``logging.*``,
  or a ``with <...lock...>`` block). A handler runs ON the interrupted
  main thread, possibly while that thread holds the very lock the call
  needs — the self-deadlock preempt.py's deferral-thread pattern exists
  to avoid. The safe pattern: set a flag, hand side effects to a helper
  thread.
- ``conc-unlocked-shared-mutation``: in a class that owns a
  ``threading.Lock`` (assigned in ``__init__``), a mutation of shared
  state outside a ``with self.<lock>`` block — (a) container attrs
  initialized to a list/dict/set literal (error), (b) attrs mutated
  under the lock in one method and without it in another (error — the
  inconsistent-discipline smell), (c) augmented assignment on a plain
  attr (warning: ``+=`` is a read-modify-write; lost updates under
  concurrent callers). ``__init__`` itself is exempt (pre-sharing).
- ``conc-atexit-thread-join`` (warning): an ``atexit``-registered
  callable (resolved within the module) whose body joins threads
  (``.join()`` / ``shutdown(wait=True)``). atexit runs during
  interpreter shutdown after non-daemon threads were already joined;
  blocking there wedges exit when a worker is stuck on a lock the dying
  main thread holds.

Like every analyzer here, provably-safe sites carry in-source waivers
stating the safety argument (e.g. the serve writer's futures list is
touched by the single dispatch thread only — the waiver documents the
contract the next refactor must keep).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from p2p_tpu.analysis.ast_rules import dotted_name as _dotted
from p2p_tpu.analysis.findings import (
    ERROR,
    WARNING,
    Finding,
    apply_pragma_waivers,
)

RULE_SIGNAL_UNSAFE = "conc-signal-handler-unsafe"
RULE_UNLOCKED_MUTATION = "conc-unlocked-shared-mutation"
RULE_ATEXIT_JOIN = "conc-atexit-thread-join"

#: attribute-call suffixes that take locks / touch buffered IO — unsafe
#: on a signal path
_UNSAFE_HANDLER_CALLS = frozenset({
    "acquire", "flush", "write", "log", "record", "inc", "observe",
    "export", "put",
})
_UNSAFE_HANDLER_FUNCS = frozenset({"print", "open"})


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for a ``self.X`` attribute access, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


# ------------------------------------------------ signal-handler rule


def _signal_calls(scope: ast.AST):
    for c in ast.walk(scope):
        if isinstance(c, ast.Call) \
                and (_dotted(c.func) or "").endswith("signal.signal") \
                and len(c.args) == 2:
            yield c


def _signal_handler_nodes(tree: ast.Module) -> Set[int]:
    """ids of the FunctionDef nodes registered via ``signal.signal(sig,
    h)``. Resolution is SCOPED like the atexit rule's: a ``self.X``
    handler resolves to the ENCLOSING class's method X — two classes
    sharing a method name must not get each other's bodies audited."""
    module_fns = {n.name: n for n in tree.body
                  if isinstance(n, ast.FunctionDef)}
    out: Set[int] = set()
    seen: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {n.name: n for n in node.body
                   if isinstance(n, ast.FunctionDef)}
        for c in _signal_calls(node):
            if id(c) in seen:
                continue
            seen.add(id(c))
            h = c.args[1]
            name = _self_attr(h) or (
                h.id if isinstance(h, ast.Name) else None) or (
                h.attr if isinstance(h, ast.Attribute) else None)
            target = methods.get(name or "") or module_fns.get(name or "")
            if target is not None:
                out.add(id(target))
    for c in _signal_calls(tree):   # module-level installs
        if id(c) in seen:
            continue
        seen.add(id(c))
        h = c.args[1]
        name = (h.id if isinstance(h, ast.Name) else None) or (
            h.attr if isinstance(h, ast.Attribute) else None)
        target = module_fns.get(name or "")
        if target is not None:
            out.add(id(target))
    return out


def _handler_findings(relpath: str, fn: ast.FunctionDef) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                src = ast.unparse(item.context_expr) \
                    if hasattr(ast, "unparse") else ""
                if "lock" in src.lower():
                    out.append(Finding(
                        rule=RULE_SIGNAL_UNSAFE, severity=ERROR,
                        file=relpath, line=node.lineno,
                        message=f"signal handler {fn.name!r} acquires "
                                f"{src!r}: the interrupted main thread "
                                "may already hold it — self-deadlock; "
                                "defer to a helper thread",
                    ))
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func) or ""
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        bad = (attr in _UNSAFE_HANDLER_CALLS
               or dotted in _UNSAFE_HANDLER_FUNCS
               or dotted.startswith("logging."))
        if bad:
            out.append(Finding(
                rule=RULE_SIGNAL_UNSAFE, severity=ERROR,
                file=relpath, line=node.lineno,
                message=f"signal handler {fn.name!r} calls "
                        f"{dotted or attr!r} — locking/buffered-IO "
                        "machinery on the interrupted main thread can "
                        "self-deadlock; set a flag and defer side "
                        "effects to a helper thread",
            ))
    return out


# ------------------------------------------- unlocked-mutation rule


_MUTATOR_METHODS = frozenset({
    "append", "extend", "remove", "insert", "pop", "clear", "update",
    "add", "discard", "setdefault", "popitem",
})


def _stmt_exprs(st: ast.stmt) -> List[ast.AST]:
    """The expression roots a statement evaluates ITSELF — compound
    bodies excluded (the class scan recurses into them with their own
    with-lock context)."""
    if isinstance(st, (ast.If, ast.While)):
        return [st.test]
    if isinstance(st, (ast.For, ast.AsyncFor)):
        return [st.iter]
    if isinstance(st, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in st.items]
    if isinstance(st, ast.Try):
        return []
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
        return []   # defining is not executing
    return [st]


class _ClassScan:
    """Per-class accounting for the unlocked-shared-mutation rule."""

    def __init__(self, relpath: str, cls: ast.ClassDef):
        self.relpath = relpath
        self.cls = cls
        self.lock_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        # attr -> [(line, in_lock, in_init, kind)]
        self.mutations: List[Tuple[str, int, bool, bool, str]] = []

    def scan(self) -> List[Finding]:
        for node in self.cls.body:
            if isinstance(node, ast.FunctionDef):
                if node.name == "__init__":
                    self._scan_init(node)
        if not self.lock_attrs:
            return []
        for node in self.cls.body:
            if isinstance(node, ast.FunctionDef):
                self._scan_method(node)
        return self._findings()

    def _scan_init(self, fn: ast.FunctionDef):
        for node in ast.walk(fn):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target = node.target   # self._sinks: List[Any] = []
            if target is None:
                continue
            attr = _self_attr(target)
            if attr is not None:
                v = node.value
                if isinstance(v, ast.Call):
                    dotted = _dotted(v.func) or ""
                    if dotted.endswith("Lock"):   # Lock AND RLock
                        self.lock_attrs.add(attr)
                    if dotted in ("list", "dict", "set"):
                        self.container_attrs.add(attr)
                if isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                  ast.DictComp, ast.SetComp)):
                    self.container_attrs.add(attr)

    def _with_locks(self, node: ast.With) -> bool:
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.lock_attrs:
                return True
        return False

    def _scan_method(self, fn: ast.FunctionDef):
        in_init = fn.name == "__init__"

        def walk(stmts: Sequence[ast.stmt], locked: bool):
            for st in stmts:
                self._scan_stmt(st, locked, in_init)
                if isinstance(st, ast.With):
                    walk(st.body, locked or self._with_locks(st))
                elif isinstance(st, (ast.If, ast.While)):
                    walk(st.body, locked)
                    walk(st.orelse, locked)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    walk(st.body, locked)
                    walk(st.orelse, locked)
                elif isinstance(st, ast.Try):
                    walk(st.body, locked)
                    for h in st.handlers:
                        walk(h.body, locked)
                    walk(st.orelse, locked)
                    walk(st.finalbody, locked)

        walk(fn.body, False)

    def _scan_stmt(self, st: ast.stmt, locked: bool, in_init: bool):
        def note(attr, line, kind):
            self.mutations.append((attr, line, locked, in_init, kind))

        if isinstance(st, ast.Assign):
            for t in st.targets:
                attr = _self_attr(t)
                if attr is not None:
                    note(attr, st.lineno, "assign")
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        note(attr, st.lineno, "setitem")
        elif isinstance(st, ast.AugAssign):
            attr = _self_attr(st.target)
            if attr is not None:
                note(attr, st.lineno, "augassign")
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        note(attr, st.lineno, "delitem")
        # mutator-method calls ANYWHERE in the statement's own
        # expressions — `x = self._q.pop(0)` / `if self._q.pop():` /
        # `return self._q.pop()` are the common pop-and-use race shapes,
        # not just bare `self._q.append(...)` statements. Compound
        # bodies are excluded (they recurse with their own lock context).
        for root in _stmt_exprs(st):
            stack = [root]
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.Lambda, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    continue   # runs at call time, not here
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _MUTATOR_METHODS:
                    attr = _self_attr(n.func.value)
                    if attr is not None:
                        note(attr, n.lineno, f".{n.func.attr}()")
                stack.extend(ast.iter_child_nodes(n))

    def _findings(self) -> List[Finding]:
        locked_attrs = {a for a, _, lk, ini, _ in self.mutations
                        if lk and not ini}
        out: List[Finding] = []
        for attr, line, locked, in_init, kind in self.mutations:
            if locked or in_init or attr in self.lock_attrs:
                continue
            cls = self.cls.name
            if attr in self.container_attrs:
                out.append(Finding(
                    rule=RULE_UNLOCKED_MUTATION, severity=ERROR,
                    file=self.relpath, line=line,
                    message=f"{cls}.{attr} ({kind}) mutated outside "
                            f"the class's lock — {cls} owns "
                            f"{sorted(self.lock_attrs)} precisely because "
                            "it is shared across threads; lock the "
                            "mutation (and iterate over snapshots)",
                ))
            elif attr in locked_attrs:
                out.append(Finding(
                    rule=RULE_UNLOCKED_MUTATION, severity=ERROR,
                    file=self.relpath, line=line,
                    message=f"{cls}.{attr} ({kind}) mutated WITHOUT the "
                            "lock here but WITH it elsewhere in the "
                            "class — inconsistent locking discipline",
                ))
            elif kind == "augassign":
                out.append(Finding(
                    rule=RULE_UNLOCKED_MUTATION, severity=WARNING,
                    file=self.relpath, line=line,
                    message=f"{cls}.{attr} += outside the class's lock: "
                            "read-modify-write races lose updates under "
                            "concurrent callers",
                ))
        return out


# --------------------------------------------------- atexit-join rule


def _atexit_findings(relpath: str, tree: ast.Module) -> List[Finding]:
    # Handler resolution is SCOPED: a ``self.X`` handler resolves to the
    # method X of the ENCLOSING class (a module with five ``close``
    # methods must not audit the first one for every registration —
    # both false negatives and phantom repeats); bare-name handlers
    # resolve module-level.
    module_fns: Dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            module_fns.setdefault(node.name, node)

    def class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
        return {n.name: n for n in cls.body
                if isinstance(n, ast.FunctionDef)}

    # (register-call, resolver dict) pairs in their resolution scope
    sites: List[Tuple[ast.Call, Dict[str, ast.FunctionDef]]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods = class_methods(node)
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) \
                        and (_dotted(inner.func) or "").endswith(
                            "atexit.register") and inner.args:
                    sites.append((inner, methods))
        elif isinstance(node, ast.Call) \
                and (_dotted(node.func) or "").endswith("atexit.register") \
                and node.args:
            sites.append((node, module_fns))
    # class-scoped register calls were collected twice (ast.walk visits
    # them at module level too) — keep the class-scoped resolution
    seen_calls = set()
    out: List[Finding] = []
    for call, scope in sites:
        if id(call) in seen_calls:
            continue
        seen_calls.add(id(call))
        h = call.args[0]
        name = _self_attr(h) or (h.id if isinstance(h, ast.Name) else None) \
            or (h.attr if isinstance(h, ast.Attribute) else None)
        target = scope.get(name or "") or (
            module_fns.get(name or "") if scope is not module_fns else None)
        if target is None:
            continue
        for inner in ast.walk(target):
            if isinstance(inner, ast.Call) \
                    and isinstance(inner.func, ast.Attribute):
                is_join = inner.func.attr == "join" and not inner.args
                is_shutdown = inner.func.attr == "shutdown" and any(
                    kw.arg == "wait" and isinstance(kw.value, ast.Constant)
                    and kw.value.value for kw in inner.keywords)
                if is_join or is_shutdown:
                    out.append(Finding(
                        rule=RULE_ATEXIT_JOIN, severity=WARNING,
                        file=relpath, line=inner.lineno,
                        message=f"atexit-registered {name!r} blocks on "
                                f"thread {'join' if is_join else 'shutdown(wait=True)'} "
                                "— atexit runs during interpreter "
                                "shutdown; a stuck worker wedges process "
                                "exit",
                    ))
    return out


# --------------------------------------------------------- entry points


def lint_concurrency_source(relpath: str, text: str,
                            tree: Optional[ast.Module] = None,
                            ) -> List[Finding]:
    if tree is None:
        try:
            tree = ast.parse(text)
        except SyntaxError:
            return []   # ast_rules reports unparseable modules
    findings: List[Finding] = []
    handlers = _signal_handler_nodes(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and id(node) in handlers:
            findings.extend(_handler_findings(relpath, node))
        if isinstance(node, ast.ClassDef):
            findings.extend(_ClassScan(relpath, node).scan())
    findings.extend(_atexit_findings(relpath, tree))
    return apply_pragma_waivers(findings, sources={relpath: text})


def lint_package_concurrency(pkg_root: Optional[str] = None) -> List[Finding]:
    from p2p_tpu.analysis.findings import iter_package_sources

    out: List[Finding] = []
    for rel, text, _err in iter_package_sources(pkg_root):
        if text is not None:   # ast_rules reports unreadable modules
            out.extend(lint_concurrency_source(rel, text))
    return out
