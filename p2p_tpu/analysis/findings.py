"""Structured findings — the ONE report format every analyzer emits.

A finding is ``(rule id, severity, location, message)``; locations are
either ``file:line`` (AST rules, jaxpr eqn source info) or a tree leaf
path (sharding audit). Any finding with a ``file:line`` location is
waivable in-source with the pragma

    # p2p-lint: disable=<rule>[,<rule>...] -- <reason>

on the offending line or on the line directly above it. ``disable=all``
waives every rule at that location. The ``-- <reason>`` tail is REQUIRED
policy-wise (CI reports the waiver count; a waiver without a reason is
itself a finding) — see docs/STATIC_ANALYSIS.md.

Severity semantics:

- ``error``   — a structural claim is violated now; fails the lint gate.
- ``warning`` — latent hazard (e.g. a dead sharding rule); fails under
  ``--strict`` (the CI mode).
- ``info``    — informational, never fails. The sharding auditor's
  ``tp``-diff migration worklist rides this level.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

#: the in-source waiver pragma; reason tail after ``--`` is kept verbatim.
PRAGMA_RE = re.compile(
    r"#\s*p2p-lint:\s*disable=([A-Za-z0-9_,\-]+)(?:\s+--\s*(.+?))?\s*$")

RULE_BAD_WAIVER = "lint-waiver-without-reason"


def waiver_summary_line(n_waived: int) -> str:
    """The ONE formatter for the waiver-count summary — the same pattern
    as ``obs.prometheus_exposition`` (one formatter behind every scrape
    surface): the lint CLI's OK and FAIL status lines both embed this
    string, so the phrase CI greps (``waiver(s) carried with reasons``)
    appears EXACTLY once per run regardless of outcome, and the two
    print paths cannot drift apart."""
    return f"{int(n_waived)} waiver(s) carried with reasons"


@dataclasses.dataclass
class Finding:
    rule: str
    message: str
    severity: str = ERROR
    file: Optional[str] = None      # repo-relative or absolute path
    line: Optional[int] = None      # 1-indexed
    path: Optional[str] = None      # tree leaf path (sharding findings)
    waived: bool = False
    waive_reason: Optional[str] = None

    @property
    def location(self) -> str:
        if self.file is not None:
            return f"{self.file}:{self.line}" if self.line else self.file
        return self.path or "<global>"

    def format(self) -> str:
        tail = f"  [waived: {self.waive_reason or 'no reason'}]" \
            if self.waived else ""
        return (f"{self.severity.upper():7s} {self.rule:28s} "
                f"{self.location}: {self.message}{tail}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_pragmas(text: str) -> Dict[int, Tuple[Set[str], str]]:
    """1-indexed line → (waived rule ids, reason). ``all`` waives any rule."""
    out: Dict[int, Tuple[Set[str], str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out[i] = (rules, (m.group(2) or "").strip())
    return out


def _pragma_for(pragmas: Dict[int, Tuple[Set[str], str]],
                rule: str, line: int):
    """A pragma waives the finding's own line or the line directly above."""
    for ln in (line, line - 1):
        hit = pragmas.get(ln)
        if hit and (rule in hit[0] or "all" in hit[0]):
            return hit
    return None


def apply_pragma_waivers(
    findings: Sequence[Finding],
    sources: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Mark file-located findings waived where a pragma covers them, and
    APPEND a ``lint-waiver-without-reason`` finding for reasonless pragmas
    that fired (a waiver must say why — docs/STATIC_ANALYSIS.md).

    ``sources`` maps file path → text; missing entries are read from disk
    (unreadable files simply leave the finding unwaived).
    """
    sources = dict(sources or {})
    cache: Dict[str, Optional[Dict[int, Tuple[Set[str], str]]]] = {}
    out = list(findings)
    # bad-waiver findings collect SEPARATELY and append after the loop:
    # appending mid-iteration would feed them back through the pragma
    # match, where a reasonless `disable=all` waives the complaint about
    # itself and spawns another, forever
    bad: List[Finding] = []
    seen_bad: Set[Tuple[str, int]] = set()
    for f in out:
        if f.file is None or f.line is None or f.waived:
            continue
        if f.file not in cache:
            text = sources.get(f.file)
            if text is None:
                try:
                    with open(f.file, encoding="utf-8") as fh:
                        text = fh.read()
                except OSError:
                    text = None
            cache[f.file] = parse_pragmas(text) if text is not None else None
        pragmas = cache[f.file]
        if not pragmas:
            continue
        hit = _pragma_for(pragmas, f.rule, f.line)
        if hit is not None:
            f.waived = True
            f.waive_reason = hit[1] or None
            if not hit[1] and (f.file, f.line) not in seen_bad:
                seen_bad.add((f.file, f.line))
                bad.append(Finding(
                    rule=RULE_BAD_WAIVER, severity=WARNING,
                    file=f.file, line=f.line,
                    message=f"pragma waives {f.rule!r} without a "
                            "'-- <reason>' tail",
                ))
    return out + bad


def iter_package_sources(pkg_root: Optional[str] = None):
    """Yield ``(relpath, text, error)`` for every ``.py`` module of
    ``p2p_tpu/`` (default: the installed package directory) — the ONE
    walk every AST-family analyzer shares. ``text`` is None exactly when
    ``error`` holds the read failure; ``relpath`` is package-relative,
    '/'-separated."""
    import os

    if pkg_root is None:
        import p2p_tpu

        pkg_root = os.path.dirname(os.path.abspath(p2p_tpu.__file__))
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, pkg_root).replace(os.sep, "/")
            try:
                with open(full, encoding="utf-8") as fh:
                    yield rel, fh.read(), None
            except OSError as e:
                yield rel, None, e


class Report:
    """An ordered finding collection with the gate semantics baked in."""

    def __init__(self, findings: Sequence[Finding] = ()):
        self.findings: List[Finding] = list(findings)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    def failing(self, strict: bool = True) -> List[Finding]:
        """Unwaived findings that fail the gate: errors always, warnings
        under ``--strict``; info never fails."""
        levels = (ERROR, WARNING) if strict else (ERROR,)
        return [f for f in self.active if f.severity in levels]

    def sorted(self) -> List[Finding]:
        return sorted(
            self.findings,
            key=lambda f: (_SEVERITY_ORDER.get(f.severity, 9), f.rule,
                           f.location),
        )

    def counts(self) -> Dict[str, int]:
        c = {ERROR: 0, WARNING: 0, INFO: 0, "waived": 0}
        for f in self.findings:
            if f.waived:
                c["waived"] += 1
            else:
                c[f.severity] = c.get(f.severity, 0) + 1
        return c

    def summary(self) -> str:
        c = self.counts()
        return (f"{c[ERROR]} errors, {c[WARNING]} warnings, {c[INFO]} info, "
                f"{c['waived']} waived")

    def render(self, include_info: bool = True) -> str:
        lines = [f.format() for f in self.sorted()
                 if include_info or f.severity != INFO or f.waived]
        lines.append(self.summary())
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "findings": [f.as_dict() for f in self.sorted()],
            "counts": self.counts(),
        }, indent=2)
