"""Static roofline cost model over traced programs (ISSUE 13 tentpole).

The paper's pipeline is a fixed-shape, kernel-dominated GAN step, so its
cost is statically computable: every ``conv_general_dilated`` /
``dot_general`` eqn's FLOPs follow from its shapes, every operand's HBM
bytes from its dtype, and the ratio — arithmetic intensity — says which
side of the chip's roofline a program sits on *before it ever runs*.
This module walks a traced jaxpr (``jax.make_jaxpr`` over
``ShapeDtypeStruct`` args — zero device compute, the CI contract shared
with every other analyzer here) and produces:

- :func:`eqn_cost` — per-eqn ``(kind class, flops, bytes, dtype key)``;
  MXU ops (conv/dot) get exact contraction FLOPs, elementwise/reduce ops
  count one VPU flop per element, movement ops (pad/slice/concat/...)
  count bytes only, collectives count ICI bytes. ``pallas_call`` is
  atomic: operands + results once — the hand-fused kernels' streaming
  contract is exactly "one read + one write per tensor" and their
  interior ref ops must not be double-counted.
- :func:`program_cost` — the per-program aggregate: total/per-class
  FLOPs and bytes, arithmetic intensity, MXU dtype split (the int8
  lever's denominator), per-source-line hotspots. ``lax.scan`` bodies
  multiply by trip count (the PP tick loop and ``scan_steps`` are real
  cost, not one iteration's).
- :func:`roofline_summary` — time bounds against a chip model
  (:data:`CHIP_MODEL`, v5e-class planning numbers): ``t_compute`` =
  Σ flops/peak-at-dtype, ``t_memory`` = bytes/BW, and the bound class
  (``compute-bound`` / ``memory-bound``). A *static* bound — XLA fuses
  below the byte count — but one that moves with the model, so
  regressions (an f32 leak doubling operand traffic, a lost int8 conv
  halving MXU rate) show as table diffs.
- :func:`perf_budget_rows` — the ``perf_budget.json`` artifact
  (``memory_budget.json``'s twin): one row per traced program of the
  lint CLI's set, with declared bounds (:data:`PERF_BOUNDS`) asserted on
  canonical rows — ``perf-roofline-out-of-bounds`` (warning) when a row
  leaves its band, info summary rows otherwise.

The numbers are a COST MODEL, not a measurement: bands are pinned on the
fixed tiny-config trace shapes (deterministic — jaxpr-based, immune to
XLA version drift), and their job is to catch structural regressions,
not to predict img/sec. BENCH rows remain the measurement of record;
``bench.py --sweep`` records link here via :func:`roofline_row_for`.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from p2p_tpu.analysis.findings import INFO, WARNING, Finding

RULE_ROOFLINE_BOUNDS = "perf-roofline-out-of-bounds"
#: the per-row info summary rides its OWN rule id so a grep (or waiver)
#: for the violation rule never matches a clean run's summary lines
RULE_ROOFLINE_ROW = "perf-roofline-row"

#: v5e-class planning numbers (SNIPPETS retrieval brief / ops/int8.py
#: header): peak MXU rate per operand dtype and HBM bandwidth. Planning
#: constants for the static bound, not a measurement — override the HBM
#: figure with ``P2P_HBM_GBPS`` for other parts.
CHIP_MODEL: Dict[str, Any] = {
    "name": "v5e-class",
    "peak_flops": {
        "int8": 394e12,        # s8×s8→s32 MXU rate (2× bf16)
        "bfloat16": 197e12,
        "float32": 49e12,      # f32 runs at the slow full-precision path
    },
    "hbm_gbps": 819.0,
}

#: eqn kind classes the aggregate reports
MXU, VPU, MEM, ICI = "mxu", "vpu", "mem", "ici"

#: movement primitives: bytes in + bytes out, zero flops
_MOVEMENT = frozenset({
    "broadcast_in_dim", "concatenate", "pad", "slice", "dynamic_slice",
    "dynamic_update_slice", "gather", "scatter", "rev", "transpose",
    "convert_element_type", "select_n", "iota", "copy",
    "device_put", "squeeze", "expand_dims",
})

#: metadata-only primitives: free at run time (bitcasts / aliasing views)
_FREE = frozenset({
    "reshape", "stop_gradient", "bitcast_convert_type",
    "sharding_constraint", "split", "pvary",
})

_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pbroadcast",
})

_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "reduce_window_sum",
    "reduce_window_max", "reduce_window_min", "cumsum", "cummax", "cummin",
    "cumprod", "reduce", "reduce_precision",
})


def _aval_nbytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    try:
        item = np.dtype(aval.dtype).itemsize
    except TypeError:
        item = 4                     # extended dtypes (PRNG keys)
    n = int(np.prod(aval.shape, dtype=np.int64)) if len(aval.shape) else 1
    return n * item


def _aval_numel(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) if len(shape) else 1


def _io_bytes(eqn) -> int:
    return (sum(_aval_nbytes(v) for v in eqn.invars)
            + sum(_aval_nbytes(v) for v in eqn.outvars))


def _mxu_dtype_key(eqn) -> str:
    """The roofline rate bucket an MXU eqn runs at: int8 when BOTH
    contraction operands are int8 (the s8×s8→s32 path), else the widest
    float operand (an f32 operand forces the full-precision path —
    the same law ``jaxpr-f32-leak`` enforces as a finding)."""
    dts = [str(getattr(getattr(v, "aval", None), "dtype", "?"))
           for v in eqn.invars[:2]]
    if all(d == "int8" for d in dts):
        return "int8"
    if any(d == "float32" for d in dts):
        return "float32"
    return "bfloat16"


def conv_flops(eqn) -> int:
    """Exact MACs×2 of a ``conv_general_dilated`` eqn from its shapes:
    ``2 · out_numel · KH·KW · C_in_per_group`` — the closed form every
    conv roofline uses (independent of stride/padding/dilation, which the
    out shape already encodes; the kernel's in-feature dim is already
    per-group in XLA's rhs layout)."""
    dn = eqn.params["dimension_numbers"]
    rhs_shape = tuple(eqn.invars[1].aval.shape)
    spatial = [rhs_shape[d] for d in dn.rhs_spec[2:]]
    c_in = rhs_shape[dn.rhs_spec[1]]
    out_numel = _aval_numel(eqn.outvars[0])
    return 2 * out_numel * int(np.prod(spatial, dtype=np.int64)) * c_in


def dot_flops(eqn) -> int:
    """``2 · out_numel · prod(contract dims)`` for a ``dot_general``."""
    (lc, _), _ = eqn.params["dimension_numbers"]
    lhs_shape = tuple(eqn.invars[0].aval.shape)
    k = int(np.prod([lhs_shape[d] for d in lc], dtype=np.int64)) if lc else 1
    return 2 * _aval_numel(eqn.outvars[0]) * k


def eqn_cost(eqn) -> Optional[Tuple[str, int, int, Optional[str]]]:
    """``(kind class, flops, bytes, mxu dtype key)`` for one eqn, or None
    for structural/free eqns. Control-flow eqns return None — the walk
    (:func:`program_cost`) descends into their bodies itself so scan trip
    counts multiply correctly."""
    name = eqn.primitive.name
    if name == "conv_general_dilated":
        return MXU, conv_flops(eqn), _io_bytes(eqn), _mxu_dtype_key(eqn)
    if name == "dot_general":
        return MXU, dot_flops(eqn), _io_bytes(eqn), _mxu_dtype_key(eqn)
    if name == "pallas_call":
        # atomic: the hand-fused kernels' contract is one streaming pass
        # over operands + results; interior ref ops must not double-count
        return MEM, 0, _io_bytes(eqn), None
    from p2p_tpu.analysis.jaxpr_lint import normalize_primitive

    base = normalize_primitive(name)
    if base in _COLLECTIVES:
        return ICI, 0, sum(_aval_nbytes(v) for v in eqn.invars), None
    if name in _FREE:
        return None
    if name in _MOVEMENT:
        return MEM, 0, _io_bytes(eqn), None
    if name in _REDUCTIONS or name.startswith("reduce_"):
        return VPU, sum(_aval_numel(v) for v in eqn.invars), \
            _io_bytes(eqn), None
    if any(hasattr(q, "eqns") or hasattr(q, "jaxpr")
           for p in eqn.params.values()
           for q in (p if isinstance(p, (list, tuple)) else [p])):
        return None                   # control flow: the walk descends
    # everything else is elementwise-ish VPU work: one flop per output
    # element, operands + results moved
    return VPU, sum(_aval_numel(v) for v in eqn.outvars), _io_bytes(eqn), \
        None


def _src_key(eqn) -> str:
    from p2p_tpu.analysis.jaxpr_lint import eqn_location

    fname, line = eqn_location(eqn)
    return f"{fname}:{line}" if fname else "<?>"


def program_cost(jaxpr, top_k: int = 5) -> Dict[str, Any]:
    """Aggregate cost of a traced program: total / per-class flops and
    bytes, arithmetic intensity, the MXU dtype split, and the ``top_k``
    hottest source lines by flops. ``scan`` bodies multiply by trip
    count; ``cond``/``while`` branches count once (documented
    approximation — the repo's in-jit guards are `where`-selects, so
    traced conds are rare and tiny)."""
    from p2p_tpu.analysis.jaxpr_lint import sub_jaxprs

    flops_by_class: Dict[str, int] = defaultdict(int)
    bytes_by_class: Dict[str, int] = defaultdict(int)
    mxu_flops_by_dtype: Dict[str, int] = defaultdict(int)
    by_line: Dict[Tuple[str, str], List[int]] = defaultdict(lambda: [0, 0])
    n_eqns = 0

    def walk(jx, mult: int):
        nonlocal n_eqns
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                walk(eqn.params["jaxpr"], mult * length)
                continue
            cost = eqn_cost(eqn)
            if cost is None:          # structural/free: descend instead
                for sub in sub_jaxprs(eqn.params):
                    walk(sub, mult)
                continue
            n_eqns += 1
            cls, fl, by, dtk = cost
            flops_by_class[cls] += fl * mult
            bytes_by_class[cls] += by * mult
            if dtk is not None:
                mxu_flops_by_dtype[dtk] += fl * mult
            if fl:
                entry = by_line[(name, _src_key(eqn))]
                entry[0] += fl * mult
                entry[1] += by * mult

    walk(jaxpr, 1)
    flops = sum(flops_by_class.values())
    nbytes = sum(bytes_by_class.values())
    top = sorted(by_line.items(), key=lambda kv: -kv[1][0])[:top_k]
    return {
        "flops": int(flops),
        "bytes": int(nbytes),
        "arith_intensity": round(flops / nbytes, 4) if nbytes else 0.0,
        "flops_by_class": {k: int(v) for k, v in flops_by_class.items()},
        "bytes_by_class": {k: int(v) for k, v in bytes_by_class.items()},
        "mxu_flops_by_dtype": {k: int(v)
                               for k, v in mxu_flops_by_dtype.items()},
        "counted_eqns": n_eqns,
        "top_lines": [{"op": op, "src": src, "flops": int(f),
                       "bytes": int(b)}
                      for (op, src), (f, b) in top],
    }


def roofline_summary(cost: Dict[str, Any],
                     chip: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Static time bounds for one :func:`program_cost` result against a
    chip model: ``t_compute`` sums each MXU dtype bucket at its own peak
    rate (+ VPU flops at the bf16 rate), ``t_memory`` is total bytes over
    HBM bandwidth; the larger bound names the program's roofline side."""
    import os

    chip = chip or CHIP_MODEL
    peaks = chip["peak_flops"]
    bw = float(os.environ.get("P2P_HBM_GBPS", chip["hbm_gbps"])) * 1e9
    t_c = sum(fl / peaks.get(dt, peaks["bfloat16"])
              for dt, fl in cost["mxu_flops_by_dtype"].items())
    t_c += cost["flops_by_class"].get(VPU, 0) / peaks["bfloat16"]
    t_m = cost["bytes"] / bw
    mxu = sum(cost["mxu_flops_by_dtype"].values())
    return {
        "chip": chip["name"],
        "t_compute_us": round(t_c * 1e6, 3),
        "t_memory_us": round(t_m * 1e6, 3),
        "bound": "compute-bound" if t_c >= t_m else "memory-bound",
        "mxu_flops_fraction": round(mxu / cost["flops"], 4)
        if cost["flops"] else 0.0,
        "int8_mxu_fraction": round(
            cost["mxu_flops_by_dtype"].get("int8", 0) / mxu, 4)
        if mxu else 0.0,
    }


# ------------------------------------------------- the budget artifact


#: Canonical-row bounds for ``perf_budget.json`` (the CI-asserted twin of
#: the memory table's ``fits``). Pinned on the lint CLI's FIXED tiny-config
#: trace shapes — deterministic, so the bands are tight-ish (±~40% around
#: the recorded value) and a structural regression (f32 operand doubling
#: bytes, a de-quantized conv zeroing the int8 share, a lost fusion
#: inflating VPU traffic) trips them. Re-pin deliberately when the traced
#: set or the models change — the CI diff of perf_budget.json is the
#: review surface.
PERF_BOUNDS: Dict[str, Dict[str, float]] = {
    # recorded values (tiny-config traces, this tree): ai 2.5717
    "eval_forward[facades]": {
        "min_arith_intensity": 1.6, "max_arith_intensity": 4.0,
        "min_mxu_flops_fraction": 0.9,
    },
    # ai 1.0059, mxu 0.926
    "train_step[facades]": {
        "min_arith_intensity": 0.65, "max_arith_intensity": 1.6,
        "min_mxu_flops_fraction": 0.85,
    },
    # ai 0.734, int8 MXU share 0.4784 — the SHIPPING preset's program
    # (the headline bench row): D + stems-off generator coverage. Floor
    # raised 0.30 → 0.40 post-ISSUE-14 (the recorded value is the
    # drained state for this config; losing any quantized family drops
    # below it).
    "train_step[facades_int8]": {
        "min_arith_intensity": 0.45, "max_arith_intensity": 1.2,
        "min_mxu_flops_fraction": 0.85,
        "min_int8_mxu_fraction": 0.40,
    },
    # ai 1.6768, int8 MXU share 0.9012 — the FULL-COVERAGE program
    # (core.config.int8_full_coverage; the --int8-diff audit subject and
    # the facades_int8_full band-pending sweep row). The 0.80 floor is the
    # post-drain contract: a coverage regression (a de-quantized conv
    # family, a new unknobbed layer) fails CI as out-of-bounds here even
    # before its worklist line is noticed.
    "train_step[facades_int8_full]": {
        "min_arith_intensity": 1.0, "max_arith_intensity": 2.7,
        "min_mxu_flops_fraction": 0.9,
        "min_int8_mxu_fraction": 0.80,
    },
    # ai 5.1726 (the fused chains keep the epilogues out of the byte
    # count — a lost fusion inflates bytes and drops intensity out the
    # bottom of this band)
    "train_step[cityscapes_pallas]": {
        "min_arith_intensity": 3.2, "max_arith_intensity": 8.0,
        "min_mxu_flops_fraction": 0.9,
    },
    # ai 0.9956
    "video_train_step[vid2vid_temporal]": {
        "min_arith_intensity": 0.6, "max_arith_intensity": 1.6,
        "min_mxu_flops_fraction": 0.85,
    },
    # ai 2.62 (the overlap schedule; scan trip counts multiplied in)
    "pp_train_step[reference]": {
        "min_arith_intensity": 1.6, "max_arith_intensity": 4.2,
        "min_mxu_flops_fraction": 0.9,
    },
}

#: sweep-preset → canonical budget row (bench.py links each sweep record
#: to the roofline row that models its config; None = not yet traced)
_SWEEP_ROOFLINE = {
    "facades": "train_step[facades]",
    "facades_int8": "train_step[facades_int8]",
    # the facades_int8_full sweep row's key (a first-class preset on the
    # facades_int8 preset — core.config.int8_full_coverage)
    "facades_int8_full": "train_step[facades_int8_full]",
    "edges2shoes_dp": "train_step[facades]",     # same U-Net family
    "cityscapes_spatial": "train_step[cityscapes_pallas]",
    "pix2pixhd": "train_step[cityscapes_pallas]",  # same fused family
    "vid2vid_temporal": "video_train_step[vid2vid_temporal]",
}


def roofline_row_for(preset: str) -> Optional[str]:
    """The ``perf_budget.json`` row name modeling ``preset``'s program
    family, or None when the traced set does not cover it yet."""
    return _SWEEP_ROOFLINE.get(preset)


def _bounds_violations(row: Dict[str, Any],
                       bounds: Dict[str, float]) -> List[str]:
    out = []
    ai = row["cost"]["arith_intensity"]
    if ai < bounds.get("min_arith_intensity", 0.0):
        out.append(f"arith_intensity {ai} < "
                   f"{bounds['min_arith_intensity']}")
    if ai > bounds.get("max_arith_intensity", float("inf")):
        out.append(f"arith_intensity {ai} > "
                   f"{bounds['max_arith_intensity']}")
    mf = row["roofline"]["mxu_flops_fraction"]
    if mf < bounds.get("min_mxu_flops_fraction", 0.0):
        out.append(f"mxu_flops_fraction {mf} < "
                   f"{bounds['min_mxu_flops_fraction']}")
    i8 = row["roofline"]["int8_mxu_fraction"]
    if i8 < bounds.get("min_int8_mxu_fraction", 0.0):
        out.append(f"int8_mxu_fraction {i8} < "
                   f"{bounds['min_int8_mxu_fraction']}")
    return out


def perf_budget_rows(programs: Sequence[Tuple[str, Any]],
                     ) -> Tuple[List[dict], List[Finding]]:
    """Rows + findings for the ``perf_budget.json`` artifact.

    ``programs`` is ``(name, jaxpr)`` per traced program (the lint CLI's
    set). Every row carries the cost aggregate, the roofline summary and
    its declared bounds; a canonical row outside its bounds emits
    ``perf-roofline-out-of-bounds`` (warning — strict CI fails it), every
    row also reports an info summary line so the gate output shows the
    table at a glance."""
    rows: List[dict] = []
    findings: List[Finding] = []
    for name, jaxpr in programs:
        cost = program_cost(jaxpr)
        roof = roofline_summary(cost)
        bounds = PERF_BOUNDS.get(name, {})
        row = {
            "program": name,
            "canonical": name in PERF_BOUNDS,
            "cost": cost,
            "roofline": roof,
            "bounds": bounds,
        }
        bad = _bounds_violations(row, bounds) if bounds else []
        row["within_bounds"] = not bad
        rows.append(row)
        if bad:
            findings.append(Finding(
                rule=RULE_ROOFLINE_BOUNDS, severity=WARNING, path=name,
                message=f"roofline row outside its declared band: "
                        f"{'; '.join(bad)} — a structural cost regression "
                        "(or a deliberate change that must re-pin "
                        "analysis/hlo_cost.PERF_BOUNDS)",
            ))
        else:
            findings.append(Finding(
                rule=RULE_ROOFLINE_ROW, severity=INFO, path=name,
                message=f"{cost['flops'] / 1e6:.1f} MFLOP, "
                        f"{cost['bytes'] / 1e6:.2f} MB moved, "
                        f"intensity {cost['arith_intensity']}, "
                        f"{roof['bound']}, int8 MXU share "
                        f"{roof['int8_mxu_fraction']}",
            ))
    return rows, findings
