"""jaxpr/HLO structural lint library — the reusable form of the test pins.

tests/test_pp.py and tests/test_ops.py grew hand-rolled jaxpr walkers
(``_sub_jaxprs``, the scan-carry ppermute check) and compiled-text
all-gather greps; every new sharding/perf PR re-invented them. This module
is the single source of truth those tests now import, plus the two checks
the lint CLI runs as a standing gate:

- **collective census** — :func:`collect_collectives` over a jaxpr (traced
  primitive names, normalized: ``psum2`` → ``psum``) or compiled HLO text
  (``all-gather``/``collective-permute``/... opcodes, async ``-start``
  forms counted once), with :func:`assert_no_collective` /
  :func:`assert_collective_count` as the pin forms.
- **activation-gather bound** — :func:`assert_no_collective_as_large_as`:
  no ``all-gather`` (or any chosen collective) operand/result shape on the
  compiled text may reach the full-activation element count. This is the
  exact check both HLO pins hand-rolled.
- **scan-carry ppermute** — :func:`scan_ppermute_carry_flags`: for every
  ``ppermute`` directly inside a ``lax.scan`` body, True iff its operand
  is a scan CARRY invar (structurally independent of the tick's compute —
  the latency-hiding schedule pin of docs/PARALLELISM.md).
- **host-callback census** — :func:`host_callback_findings`: callbacks
  (``pure_callback``/``io_callback``/``debug_callback``/``debug_print``)
  inside a program that is supposed to be a hot path.
- **f32-leak detector** — :func:`f32_leak_findings`: walks every
  ``dot_general``/``conv_general_dilated`` eqn's operand dtypes under a
  declared bf16 policy; an f32 operand is compute the policy says should
  not exist. Findings carry the eqn's source ``file:line`` (via jax source
  info), so deliberate f32 islands are waivable in-source with the
  ``# p2p-lint: disable=...`` pragma.

Everything here is trace/text-based: ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` args and ``.lower().compile().as_text()`` — zero
device compute, CPU-safe (the CI contract).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable, Iterator, List, Optional, Tuple, Union

import numpy as np

from p2p_tpu.analysis.findings import ERROR, Finding

RULE_HOST_CALLBACK = "jaxpr-host-callback"
RULE_F32_LEAK = "jaxpr-f32-leak"

#: traced collective primitives (normalized names — see normalize_primitive)
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pbroadcast", "pgather",
})

#: compiled-HLO collective opcodes (async forms appear as ``<op>-start``)
HLO_COLLECTIVES = (
    "all-gather", "all-reduce", "collective-permute", "all-to-all",
    "reduce-scatter", "collective-broadcast",
)

# an HLO instruction is `%name = <shape> <opcode>(...)`; async collectives
# carry TUPLE result shapes `(f32[..], f32[..])`, so the shape matcher must
# accept both forms or -start lines silently drop out of the census
_HLO_OP_RE = re.compile(
    r"=\s+(?:\([^)]*\)|\S+)\s+(" + "|".join(HLO_COLLECTIVES)
    + r")(-start)?\(")
_HLO_SHAPE_RE = re.compile(r"\w+\[([\d,]+)\]")
_CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "host_callback", "outside_call",
})


def normalize_primitive(name: str) -> str:
    """Strip jax's versioning suffix from a primitive name (``psum2`` →
    ``psum``) so call sites pin semantics, not jax-internal renames."""
    return name.rstrip("0123456789")


def sub_jaxprs(params) -> Iterator:
    """Yield every (Closed)Jaxpr hiding in an eqn's params dict — the
    recursion step shared by every structural walk (scan/cond/pjit/
    shard_map/custom_vjp bodies)."""
    for p in params.values():
        vals = p if isinstance(p, (list, tuple)) else [p]
        for q in vals:
            if hasattr(q, "eqns"):
                yield q
            elif hasattr(q, "jaxpr") and hasattr(q.jaxpr, "eqns"):
                yield q.jaxpr


def iter_eqns(jaxpr) -> Iterator:
    """Depth-first over EVERY eqn of a jaxpr, descending into sub-jaxprs.
    Accepts a Jaxpr or ClosedJaxpr."""
    if hasattr(jaxpr, "jaxpr"):        # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def eqn_location(eqn) -> Tuple[Optional[str], Optional[int]]:
    """(file, line) of the user frame that created an eqn, or (None, None).
    Best-effort over jax's private source-info API — a jax upgrade that
    moves it degrades findings to location-less, never crashes the lint."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, int(frame.start_line)
    except Exception:
        pass
    return None, None


# ------------------------------------------------------------ collectives


def collect_collectives(obj: Union[str, object]) -> Counter:
    """Collective census of a jaxpr (traced primitive names) or compiled
    HLO text (opcode names). Async HLO forms (``all-gather-start``) count
    once under the base opcode; ``-done`` lines are not instructions that
    move data and are ignored."""
    if isinstance(obj, str):
        counts: Counter = Counter()
        for m in _HLO_OP_RE.finditer(obj):
            counts[m.group(1)] += 1
        return counts
    return Counter(
        normalize_primitive(e.primitive.name) for e in iter_eqns(obj)
        if normalize_primitive(e.primitive.name) in COLLECTIVE_PRIMITIVES
    )


def assert_no_collective(obj, kinds: Optional[Iterable[str]] = None) -> None:
    """Pin: the program contains NO collectives (or none of ``kinds``)."""
    found = collect_collectives(obj)
    if kinds is not None:
        found = Counter({k: v for k, v in found.items() if k in set(kinds)})
    assert not found, f"unexpected collectives in program: {dict(found)}"


def assert_collective_count(obj, kind: str, expected: int) -> None:
    """Pin: exactly ``expected`` instances of one collective kind."""
    got = collect_collectives(obj)[kind]
    assert got == expected, (
        f"expected {expected} x {kind!r}, found {got} "
        f"(census: {dict(collect_collectives(obj))})")


def assert_collective_present(obj, kind: str) -> None:
    """Pin: at least one instance of ``kind`` survives in the program
    (e.g. the lowered ppermute was not optimized away on a fake mesh)."""
    got = collect_collectives(obj)[kind]
    assert got >= 1, (
        f"no {kind!r} in program (census: {dict(collect_collectives(obj))})")


def hlo_collective_shapes(text: str,
                          kind: str = "all-gather") -> List[Tuple[int, str]]:
    """Every (element count, line) for shapes on compiled-text lines that
    mention ``kind``. Matches EVERY shape on the line — async forms carry
    tuple shapes, and missing those would pass vacuously (the lesson both
    hand-rolled greps encode)."""
    out: List[Tuple[int, str]] = []
    for ln in text.splitlines():
        if kind not in ln:
            continue
        for m in _HLO_SHAPE_RE.finditer(ln):
            dims = [int(d) for d in m.group(1).split(",") if d]
            out.append((int(np.prod(dims)) if dims else 0, ln))
    return out


def assert_no_collective_as_large_as(text: str, numel: int,
                                     kind: str = "all-gather") -> None:
    """Pin: no ``kind`` line in the compiled text touches a shape with
    >= ``numel`` elements — the "no full-activation all-gather" contract
    (docs/PARALLELISM.md)."""
    for n, ln in hlo_collective_shapes(text, kind):
        assert n < numel, (
            f"{kind} as large as the pinned bound ({n} >= {numel}): {ln}")


# -------------------------------------------------- scan-carry ppermute


def scan_ppermute_carry_flags(jaxpr) -> List[bool]:
    """For every ``ppermute`` directly inside a ``lax.scan`` body: True iff
    its operand is a scan CARRY invar (the transfer consumes the previous
    tick's value and has no data dependence on this tick's compute — the
    latency-hiding schedule's structural property)."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    out: List[bool] = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
                carry = set(map(id, body.invars[nc:nc + nk]))
                for e2 in body.eqns:
                    if normalize_primitive(e2.primitive.name) == "ppermute":
                        out.append(id(e2.invars[0]) in carry)
                walk(body)
            else:
                for sub in sub_jaxprs(eqn.params):
                    walk(sub)

    walk(jaxpr)
    return out


# ------------------------------------------------------- lint findings


def resolve_callback_target(eqn) -> Optional[str]:
    """The USER function behind a callback eqn, or None.

    ``jax.debug.callback`` wraps the user callable in a ``_flat_callback``
    closure, and the repo's obs taps bind theirs through
    ``functools.partial`` (obs/taps.py ``nan_sentinel``) — so the raw
    ``eqn.params['callback']`` never names the function a human would
    recognize. Resolution: look through the jax flat-callback closure,
    then through ONE level of ``functools.partial`` (the repo's binding
    idiom; deeper nesting stays anonymous on purpose — resolve it when a
    real tap needs it)."""
    import functools

    cb = eqn.params.get("callback")
    if cb is None:
        return None
    if getattr(cb, "__name__", "") == "_flat_callback" \
            and getattr(cb, "__closure__", None):
        for cell in cb.__closure__:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if callable(v):
                cb = v
                break
    if isinstance(cb, functools.partial):
        cb = cb.func
    return getattr(cb, "__name__", None) or type(cb).__name__


def host_callback_findings(jaxpr, tag: str = "program",
                           allow: Iterable[str] = ()) -> List[Finding]:
    """Findings for host callbacks inside a supposedly-hot program.

    ``allow`` exempts PRIMITIVE names (``debug_callback`` — every debug
    callback passes) or RESOLVED target function names (``_on_counts`` —
    only the obs sentinel's own callback passes, anything else still
    flags; see :func:`resolve_callback_target`)."""
    allowed = {normalize_primitive(a) for a in allow} | set(allow)
    out: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        name = normalize_primitive(eqn.primitive.name)
        if name not in _CALLBACK_PRIMITIVES:
            continue
        target = resolve_callback_target(eqn)
        if name in allowed or (target is not None and target in allowed):
            continue
        fname, line = eqn_location(eqn)
        what = f"{name}->{target}" if target else name
        out.append(Finding(
            rule=RULE_HOST_CALLBACK, severity=ERROR,
            file=fname, line=line, path=None if fname else tag,
            message=f"host callback {what!r} in hot path {tag!r} — "
                    "route telemetry through p2p_tpu/obs seams or keep "
                    "it out of the jitted step",
        ))
    return out


def f32_leak_findings(jaxpr, tag: str = "program",
                      policy: str = "bfloat16") -> List[Finding]:
    """Findings for ``dot_general``/``conv_general_dilated`` eqns with a
    float32 operand under a declared low-precision compute policy.

    The check is on OPERANDS (not outputs): f32 accumulation via
    ``preferred_element_type`` is the policy-conformant pattern, an f32
    input tensor is a leak — it forces the full-precision MXU path and
    doubles the operand's HBM traffic.

    Findings dedupe per source location: one line of model code expands
    to many eqns (taps, fwd + transpose instances, microbatches) but is
    ONE policy decision — the finding carries the eqn count instead of
    repeating per eqn (which would also let a single waived line inflate
    the waiver-count metric by hundreds)."""
    seen: dict = {}
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name not in ("dot_general", "conv_general_dilated"):
            continue
        dtypes = []
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            dtypes.append(str(getattr(aval, "dtype", "?")))
        if any(d == "float32" for d in dtypes):
            fname, line = eqn_location(eqn)
            key = (fname, line, eqn.primitive.name, tuple(dtypes))
            if key in seen:
                seen[key] = (seen[key][0], seen[key][1] + 1)
            else:
                seen[key] = (Finding(
                    rule=RULE_F32_LEAK, severity=ERROR,
                    file=fname, line=line, path=None if fname else tag,
                    message=f"{eqn.primitive.name} with float32 operand "
                            f"{tuple(dtypes)} under declared {policy} "
                            f"policy in {tag!r}",
                ), 1)
    out: List[Finding] = []
    for f, n in seen.values():
        if n > 1:
            f.message += f" (x{n} eqns at this line)"
        out.append(f)
    return out
