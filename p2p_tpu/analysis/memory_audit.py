"""Static per-device HBM budgeting + buffer-donation audit.

Three capabilities, all ``eval_shape``/trace/lowering-text based — zero
device compute, so a 1024×512 preset budgets on a 1-CPU CI runner:

1. **State budget** (:func:`state_budget`): per-device bytes of the full
   TrainState — params / optimizer moments / EMA / quant scales / other —
   for a named config × mesh (plain ``{axis: size}`` dicts, no devices).
   Layout comes from THE live partitioner
   (``parallel/rules.trainstate_rules``): Megatron TP pair shards when
   the mesh has a real model axis, ZeRO optimizer/EMA (± param) shards
   when it has a real fsdp axis, replicated otherwise — i.e. the budget
   reflects exactly what the trainers place. Every fsdp row additionally
   carries ``opt_ema_reduction`` vs its fsdp=1 twin, and
   ``memory-fsdp-shortfall`` (error) fires when the sharded
   optimizer+EMA bytes fail the ZeRO arithmetic — at least
   (axis−1)/axis of the replicated bytes must vanish (small slack for
   the indivisible leaves: Adam count scalars, odd-width heads).
2. **Activation peak** (:func:`traced_peak_bytes`): a linear liveness scan
   over the traced train-step jaxpr — allocate each eqn's outputs, free
   every value after its last use, track the high-water mark. An UPPER
   BOUND (XLA fuses/donates/rematerializes below it), but a static one
   that moves with the model, so regressions show as table diffs.
   :func:`memory_budget_table` combines 1+2 into the per-config×mesh
   table the lint CLI publishes as ``memory_budget.json``.
3. **Donation audit** (:func:`donation_findings`): parses the LOWERED
   program text for per-parameter donation markers — single-device
   lowerings resolve donation to ``tf.aliasing_output = N``, multi-device
   lowerings carry the ``jax.buffer_donor`` request — and flags any
   sizeable state leaf with NEITHER on a program that declares
   ``donate_argnums``: that leaf is silently copied instead of donated,
   and the step holds 2× its bytes at peak. ``memory-donation-missing``
   fires when a supposedly-donating program shows no markers at all.

Plus the serving-restore check (:func:`dead_restore_findings`):
``memory-dead-restore`` flags a serving restore template that reads
subtrees the engine immediately discards (the EMA-serving case: restoring
``params_g`` just to swap in ``ema_g`` doubles the generator restore
bytes). It audits the LIVE template helper
(:func:`p2p_tpu.serve.engine.serving_restore_template`), so the gate
holds as the serving path evolves.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2p_tpu.analysis.findings import ERROR, INFO, WARNING, Finding

RULE_DONATION_MISSING = "memory-donation-missing"
RULE_DONATION_DEFEATED = "memory-donation-defeated"
RULE_DEAD_RESTORE = "memory-dead-restore"
RULE_OVER_HBM = "memory-over-hbm"
RULE_FSDP_SHORTFALL = "memory-fsdp-shortfall"

#: tolerated shortfall from the ideal 1/axis optimizer+EMA bytes: the
#: leaves the fsdp spec builder legally replicates (Adam count scalars,
#: inject_hyperparams scalars, dims no axis divides) are a fixed few
#: hundred bytes — 2% covers them on every checked-in config
FSDP_REDUCTION_SLACK = 0.02

#: default per-device HBM budget (v5e-class chip), overridable via
#: ``P2P_HBM_GB`` for other parts
DEFAULT_HBM_GB = 16.0

#: the config × mesh matrix the budget table covers. The FIRST mesh of
#: each preset is its canonical topology (over-budget there is a warning;
#: hypothetical rows report at info level via the table only).
MEMORY_MATRIX: Tuple[Tuple[str, Tuple[Dict[str, int], ...]], ...] = (
    ("facades", ({"data": 1}, {"data": 1, "model": 2},
                 # ISSUE 15 canonical fsdp rows: the ZeRO optimizer+EMA
                 # shard — CI asserts each row's opt_ema_reduction ≥
                 # (axis−1)/axis − slack vs its fsdp=1 twin
                 {"data": 1, "fsdp": 4})),
    ("facades_int8", ({"data": 1}, {"data": 1, "fsdp": 2})),
    ("edges2shoes_dp", ({"data": 8}, {"data": 4, "model": 2},
                        {"data": 2, "fsdp": 4})),
    ("cityscapes_spatial", ({"data": 2, "spatial": 2},)),
    ("pix2pixhd", ({"data": 1, "spatial": 2},
                   {"data": 1, "spatial": 2, "model": 2},
                   {"data": 1, "spatial": 2, "fsdp": 2})),
)


def leaf_nbytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dt = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dt.itemsize if shape \
        else dt.itemsize


def _component(name: str) -> str:
    head = name.split("/", 1)[0]
    if head.startswith("params_") or head == "pp_stages":
        return "params"   # the PP stage stack IS generator params
    if head.startswith("opt_"):
        return "opt"
    if head == "ema_g":
        return "ema"
    if head.startswith("quant_"):
        return "quant"
    return "other"


def state_budget(cfg, mesh_sizes: Dict[str, int],
                 tp_min_ch: int = 512,
                 fsdp_params: bool = False) -> Dict[str, int]:
    """Per-device TrainState bytes by component for ``cfg`` on a
    hypothetical mesh. The layout law IS the live partitioner
    (``parallel/rules.trainstate_rules`` resolved per leaf): TP channel
    shards when ``model > 1``, ZeRO optimizer/EMA (± param under
    ``fsdp_params``) shards when ``fsdp > 1``, everything else
    replicated — data/spatial/time axes still do NOT divide state
    bytes."""
    import jax

    from p2p_tpu.analysis.sharding_audit import abstract_train_state
    from p2p_tpu.parallel.rules import (
        leaf_path_name,
        match_partition_rules,
        trainstate_rules,
    )

    sizes = {str(k): int(v) for k, v in mesh_sizes.items()}
    rules = trainstate_rules(sizes, tp_min_ch=tp_min_ch,
                             fsdp_params=fsdp_params)
    out: Dict[str, int] = {"params": 0, "opt": 0, "ema": 0, "quant": 0,
                           "other": 0}
    from jax.sharding import PartitionSpec as P

    state = abstract_train_state(cfg)
    specs = match_partition_rules(rules, state)
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    # P may subclass tuple on this jax — is_leaf keeps each spec atomic
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    for (path, leaf), spec in zip(flat, flat_specs):
        name = leaf_path_name(path)
        nbytes = leaf_nbytes(leaf)
        shard = 1
        for entry in tuple(spec or ()):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            for a in axes:
                shard *= sizes.get(str(a), 1)
        out[_component(name)] += nbytes // max(1, shard)
    out["state_total"] = sum(out.values())
    return out


# ------------------------------------------------------- liveness peak


def traced_peak_bytes(jaxpr) -> int:
    """High-water-mark bytes of a traced program under a linear
    allocate-at-def / free-after-last-use scan of its top-level eqns.
    Sub-jaxprs (scan bodies, custom-vjp branches) are treated as atomic:
    their operands and results count, their internals don't — a
    documented under-approximation inside scans, an over-approximation
    everywhere XLA fuses."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr

    def nbytes(v) -> int:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            return 0
        try:
            item = np.dtype(aval.dtype).itemsize
        except TypeError:
            item = 4   # extended dtypes (PRNG keys): count the key words
        return int(np.prod(aval.shape, dtype=np.int64)) * item \
            if len(aval.shape) else item

    is_var = lambda v: type(v).__name__ == "Var"  # noqa: E731
    # Literals are unhashable — key everything by id (vars are unique
    # objects within one jaxpr)
    last_use: Dict[int, int] = {}
    size: Dict[int, int] = {}
    n = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if is_var(v):
                last_use[id(v)] = i
                size[id(v)] = nbytes(v)
    for v in jaxpr.outvars:
        if is_var(v):
            last_use[id(v)] = n
            size[id(v)] = nbytes(v)
    # DropVar outputs (discarded results of multi-output eqns — scan
    # residual slots, unused grads) are materialized at the eqn and dead
    # immediately after: count them toward THIS eqn's peak only, never
    # into the running live set (they have no uses, so the last-use map
    # would otherwise keep their bytes resident forever).
    is_drop = lambda v: type(v).__name__ == "DropVar"  # noqa: E731
    live = sum(nbytes(v) for v in list(jaxpr.invars) + list(jaxpr.constvars))
    peak = live
    for i, eqn in enumerate(jaxpr.eqns):
        dropped = sum(nbytes(v) for v in eqn.outvars if is_drop(v))
        live += sum(nbytes(v) for v in eqn.outvars if not is_drop(v))
        peak = max(peak, live + dropped)
        dead = {id(v) for v in list(eqn.invars) + list(eqn.outvars)
                if is_var(v) and last_use.get(id(v), n + 1) <= i}
        for vid in dead:
            live -= size.get(vid, 0)
    return int(peak)


def activation_peak_bytes(cfg, local_batch: int, train_dtype=None) -> int:
    """Liveness peak of the preset's traced train step at ``local_batch``,
    MINUS the resident state bytes — the activations+workspace share of
    the budget. Pure tracing (``jax.make_jaxpr`` over ShapeDtypeStructs)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from p2p_tpu.analysis.sharding_audit import abstract_train_state
    from p2p_tpu.train.step import build_train_step

    if train_dtype is None and cfg.train.mixed_precision:
        train_dtype = jnp.bfloat16
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data,
                                      batch_size=max(1, int(local_batch))))
    state = abstract_train_state(cfg, batch_size=cfg.data.batch_size,
                                 train_dtype=train_dtype)
    sds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)
    h, w = cfg.image_hw
    dt = np.uint8 if cfg.data.uint8_pipeline else np.float32
    batch = {
        "input": jax.ShapeDtypeStruct(
            (cfg.data.batch_size, h, w, cfg.model.input_nc), dt),
        "target": jax.ShapeDtypeStruct(
            (cfg.data.batch_size, h, w, cfg.model.output_nc), dt),
    }
    step = build_train_step(cfg, train_dtype=train_dtype, jit=False)
    jx = jax.make_jaxpr(step)(sds, batch)
    state_bytes = sum(leaf_nbytes(l) for l in jax.tree_util.tree_leaves(sds))
    return max(0, traced_peak_bytes(jx) - state_bytes)


def memory_budget_table(hbm_gb: Optional[float] = None,
                        matrix=MEMORY_MATRIX,
                        ) -> Tuple[List[dict], List[Finding]]:
    """The per-config×mesh HBM budget table (the ``memory_budget.json``
    artifact) plus findings: ``memory-over-hbm`` (warning) when a preset's
    CANONICAL mesh row exceeds the budget; hypothetical rows only report
    in the table (``fits`` flag)."""
    import os

    from p2p_tpu.core.config import get_preset

    if hbm_gb is None:
        hbm_gb = float(os.environ.get("P2P_HBM_GB", DEFAULT_HBM_GB))
    budget = int(hbm_gb * (1 << 30))
    rows: List[dict] = []
    findings: List[Finding] = []
    for preset, meshes in matrix:
        cfg = get_preset(preset)
        # trace once per preset at local batch 1, scale linearly in the
        # per-device batch and inversely in the activation-sharding axes
        act1 = activation_peak_bytes(cfg, 1)
        for j, mesh in enumerate(meshes):
            # batches shard over data AND fsdp (core/mesh.BATCH_AXES)
            data = int(mesh.get("data", 1)) * int(mesh.get("fsdp", 1))
            act_shard = int(mesh.get("spatial", 1)) * int(mesh.get("time", 1))
            local_bs = max(1, cfg.data.batch_size // max(1, data))
            state = state_budget(cfg, mesh,
                                 tp_min_ch=cfg.parallel.tp_min_ch)
            act = act1 * local_bs // max(1, act_shard)
            total = state["state_total"] + act
            row = {
                "preset": preset,
                "mesh": dict(mesh),
                "canonical": j == 0,
                "local_batch": local_bs,
                "bytes": {**{k: int(v) for k, v in state.items()},
                          "activation_peak": int(act),
                          "total": int(total)},
                "hbm_budget_bytes": budget,
                "fits": total <= budget,
            }
            fsdp = int(mesh.get("fsdp", 1))
            if fsdp > 1:
                # the ZeRO arithmetic, CI-asserted: vs the same config on
                # the fsdp=1 twin mesh, per-device optimizer+EMA bytes
                # must drop by at least (axis-1)/axis (minus the slack
                # the indivisible leaves cost)
                twin = state_budget(cfg, {**mesh, "fsdp": 1},
                                    tp_min_ch=cfg.parallel.tp_min_ch)
                rep = twin["opt"] + twin["ema"]
                shd = state["opt"] + state["ema"]
                reduction = 1.0 - (shd / rep) if rep else 0.0
                row["opt_ema_reduction"] = round(reduction, 4)
                row["fsdp_axis"] = fsdp
                floor = (fsdp - 1) / fsdp - FSDP_REDUCTION_SLACK
                if reduction < floor:
                    findings.append(Finding(
                        rule=RULE_FSDP_SHORTFALL, severity=ERROR,
                        path=f"{preset}×{mesh}",
                        message=f"fsdp={fsdp} sharded optimizer+EMA bytes "
                                f"{shd} vs replicated {rep}: reduction "
                                f"{reduction:.3f} < required "
                                f"{floor:.3f} — the ZeRO rules stopped "
                                "sharding this state (dead rule? pattern "
                                "drift?)",
                    ))
            rows.append(row)
            if j == 0 and not row["fits"]:
                findings.append(Finding(
                    rule=RULE_OVER_HBM, severity=WARNING,
                    path=f"{preset}×{mesh}",
                    message=f"projected per-device HBM "
                            f"{total / (1 << 30):.2f} GiB exceeds the "
                            f"{hbm_gb:.0f} GiB budget on the preset's "
                            "canonical mesh (static bound: state + "
                            "liveness activation peak, no donation/remat "
                            "credit) — shard state (FSDP), enable remat, "
                            "or shrink the local batch",
                ))
            else:
                findings.append(Finding(
                    rule=RULE_OVER_HBM, severity=INFO,
                    path=f"{preset}×{mesh}",
                    message=f"per-device HBM {total / (1 << 30):.2f} GiB "
                            f"of {hbm_gb:.0f} GiB "
                            f"({'fits' if row['fits'] else 'OVER'})",
                ))
    return rows, findings


# ------------------------------------------------------ donation audit


_MAIN_SIG_RE = re.compile(
    r"func\.func public @main\((.*?)\)\s*->", re.S)


def lowered_donation_markers(lowered_text: str) -> Optional[List[bool]]:
    """Per-argument donation marker flags from a lowered program's text:
    True where the arg carries ``tf.aliasing_output`` (single-device
    lowering: donation RESOLVED to an output) or ``jax.buffer_donor``
    (multi-device lowering: donation requested, XLA resolves at compile).
    None when the main signature cannot be parsed."""
    m = _MAIN_SIG_RE.search(lowered_text)
    if m is None:
        return None
    entries = re.split(r",\s*(?=%arg\d+)", m.group(1))
    return [("tf.aliasing_output" in e or "jax.buffer_donor" in e)
            for e in entries]


def _jaxpr_used_invars(jaxpr) -> List[bool]:
    """Per-invar used flags for a (Closed)Jaxpr — an invar feeding no eqn
    and no output is pruned from the lowered main signature
    (``jit``'s default ``keep_unused=False``)."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    used = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            used.add(id(v))
    for v in jaxpr.outvars:
        used.add(id(v))
    return [id(v) in used for v in jaxpr.invars]


def donation_findings(lowered_text: str, donated_tree: Any, tag: str,
                      min_bytes: int = 1024, jaxpr=None) -> List[Finding]:
    """Findings for a jitted program that declares ``donate_argnums=0``:
    ``donated_tree`` is the (abstract) first argument; a leaf of at least
    ``min_bytes`` whose lowered parameter carries no donation marker is
    copied instead of donated — the program holds 2× its bytes at peak.

    ``jaxpr`` (the SAME trace the lowering came from) aligns the lowered
    parameter list with the flattened tree: ``jit`` prunes UNUSED args
    from the main signature (``keep_unused=False``), so a positional map
    would attribute flags to the wrong leaves the moment a state leaf
    goes unread — pass it whenever available. Pruned (unused) leaves are
    skipped: no buffer is consumed, so there is nothing to donate."""
    import jax

    flags = lowered_donation_markers(lowered_text)
    if flags is None:
        return [Finding(
            rule=RULE_DONATION_MISSING, severity=ERROR, path=tag,
            message="could not parse the lowered program's main signature "
                    "— donation audit impossible (jax lowering format "
                    "change?)")]
    flat, _ = jax.tree_util.tree_flatten_with_path(donated_tree)
    if jaxpr is not None:
        used = _jaxpr_used_invars(jaxpr)
        if len(used) < len(flat) or sum(used) != len(flags):
            return [Finding(
                rule=RULE_DONATION_MISSING, severity=ERROR, path=tag,
                message=f"argument mapping failed: jaxpr has "
                        f"{len(used)} invars ({sum(used)} used) vs "
                        f"{len(flat)} donated leaves and {len(flags)} "
                        "lowered parameters")]
        leaf_flags: List[Optional[bool]] = []
        pos = 0
        for i in range(len(flat)):
            if used[i]:
                leaf_flags.append(flags[pos])
                pos += 1
            else:
                leaf_flags.append(None)   # pruned: nothing to donate
    else:
        if len(flags) < len(flat):
            return [Finding(
                rule=RULE_DONATION_MISSING, severity=ERROR, path=tag,
                message=f"lowered program has {len(flags)} parameters "
                        f"but the donated tree has {len(flat)} leaves — "
                        "argument mapping failed (pass jaxpr= for "
                        "pruned-arg alignment)")]
        leaf_flags = list(flags[: len(flat)])
    live = [f for f in leaf_flags if f is not None]
    if live and not any(live):
        return [Finding(
            rule=RULE_DONATION_MISSING, severity=ERROR, path=tag,
            message="no donation marker on ANY state parameter — the "
                    "program copies the whole state every step (is "
                    "donate_argnums missing on the jit?)")]
    out: List[Finding] = []
    for i, (path, leaf) in enumerate(flat):
        if leaf_flags[i] is not False:
            continue
        nbytes = leaf_nbytes(leaf)
        if nbytes < min_bytes:
            continue
        out.append(Finding(
            rule=RULE_DONATION_DEFEATED, severity=ERROR,
            path=f"{tag}:{jax.tree_util.keystr(path)}",
            message=f"state leaf ({nbytes} B) declared donated but "
                    "carries no aliasing/donor marker in the lowered "
                    "program — it is copied, not donated (shape/dtype "
                    "changed between input and output?)",
        ))
    return out


# -------------------------------------------------- serving dead restore


def template_dead_restore_findings(template, tag: str) -> List[Finding]:
    """The template-level check behind :func:`dead_restore_findings`: an
    EMA-serving template carrying BOTH ``params_g`` and ``ema_g`` restores
    a generator tree it immediately discards."""
    import jax

    has_ema = bool(jax.tree_util.tree_leaves(template.ema_g))
    has_params = bool(jax.tree_util.tree_leaves(template.params_g))
    if not (has_ema and has_params):
        return []
    nbytes = sum(leaf_nbytes(l) for l in
                 jax.tree_util.tree_leaves(template.params_g))
    return [Finding(
        rule=RULE_DEAD_RESTORE, severity=ERROR, path=tag,
        message=f"EMA-serving template restores BOTH params_g "
                f"({nbytes} B) and ema_g, then discards params_g — 2× "
                "generator restore traffic and transient memory; prune "
                "params_g from the template",
    )]


def dead_restore_findings(presets: Sequence[str] = ("facades",),
                          ) -> List[Finding]:
    """Audit the LIVE serving restore template: any top-level subtree the
    engine restores and then immediately discards is dead restore traffic
    (and transient 2× memory at engine construction). The EMA-serving
    template is the known case: it must prune ``params_g`` and restore
    only the smoothed tree (p2p_tpu/serve/engine.py
    ``serving_restore_template``)."""
    import dataclasses as dc

    import jax

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.serve.engine import serving_restore_template

    out: List[Finding] = []
    for preset in presets:
        cfg = get_preset(preset)
        # the EMA variant is where the dead restore can creep in
        cfg = dc.replace(cfg, health=dc.replace(cfg.health, ema_decay=0.999))
        h, w = cfg.image_hw
        sample = {
            "input": np.zeros((1, h, w, cfg.model.input_nc), np.uint8),
            "target": np.zeros((1, h, w, cfg.model.output_nc), np.uint8),
        }
        template = jax.eval_shape(
            lambda c=cfg, s=sample: serving_restore_template(c, s))
        out.extend(template_dead_restore_findings(
            template, tag=f"serving_restore_template[{preset}+ema]"))
    return out
