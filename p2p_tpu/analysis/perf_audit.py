"""Performance lints over traced programs (ISSUE 13 tentpole, parts b-d).

Three rules, all structural walks of jaxprs traced from
``ShapeDtypeStruct`` args (zero device compute), all following the
findings/waiver conventions of docs/STATIC_ANALYSIS.md:

- **Fusion-gap lint** (``perf-unfused-norm-chain``,
  :func:`unfused_norm_chain_findings`): in a program whose config says
  the InstanceNorm+activation(+residual) epilogues fuse through
  ``ops/pallas/norm_act`` (``norm="pallas_instance"``), any REFERENCE
  instance-norm chain — the ``rsqrt`` over per-(sample,channel) stat
  tiles multiplied back into the full activation — is a chain that did
  NOT reach the kernel: either the dispatch seam silently fell back to
  the lax reference on TPU, or new model code never routed through
  ``ops/norm.make_norm_act``. The walk does not descend into
  ``pallas_call`` bodies (the kernel's interior rsqrt is the FUSED
  path), so the detector is purely structural and backend-independent;
  the lint CLI traces the fused program with ``P2P_TPU_FORCE_PALLAS=1``
  so the kernel appears in the jaxpr even on a CPU runner. Findings
  carry the chain's ``file:line`` via jax source info — a deliberate
  reference island waives in source.

- **Collective-overlap audit** (``perf-serialized-collective``,
  :func:`serialized_collective_findings`): the schedule rule
  generalizing ``jaxpr_lint.scan_ppermute_carry_flags`` into a finding:
  every in-``scan`` collective's operand is classified *carried/invar*
  (available when the tick starts — the transfer can run under the
  tick's compute, the latency-hiding property ``pp_overlap`` buys) vs
  *tick-computed* (produced by the tick body — the ICI hop serializes
  behind stage compute). Tick-computed operands flag at warning
  severity naming the overlap lever.

- **int8-coverage worklist** (``perf-int8-coverage-gap``,
  :func:`int8_coverage`): in a program whose config enables the
  delayed-int8 path, every ``conv_general_dilated`` / ``dot_general``
  still contracting in bf16/f32 is unconverted MXU work. ISSUE 14
  DRAINED the worklist: the lint CLI audits the full-coverage program
  (``core.config.int8_full_coverage``), where every site is either
  quantized (U-Net encoder+decoder, all D inner convs, the kn2row D
  head, net_c) or carries a dated in-source waiver stating its verdict
  (measured-rejected HBM-bound stems and the U-Net image head; the
  per-form dispatch table's bf16 backward contractions, which jax
  attributes to the custom-VJP call sites). Waived sites leave the
  worklist, so CLI ``--int8-diff`` prints 0 and CI asserts emptiness —
  any NEW bf16/f32 contraction in the program is a live line again.
  Info severity, deduped per source line like ``jaxpr-f32-leak``.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterable, List, Tuple

from p2p_tpu.analysis.findings import INFO, WARNING, Finding
from p2p_tpu.analysis.jaxpr_lint import (
    COLLECTIVE_PRIMITIVES,
    eqn_location,
    normalize_primitive,
    sub_jaxprs,
)

RULE_UNFUSED_NORM = "perf-unfused-norm-chain"
RULE_SERIALIZED = "perf-serialized-collective"
RULE_INT8_GAP = "perf-int8-coverage-gap"

#: elementwise-ish links a norm chain may pass through between the
#: stat-rsqrt and the full-size multiply
_CHAIN_LINKS = frozenset({
    "mul", "add", "sub", "convert_element_type", "broadcast_in_dim",
    "reshape", "max", "min",
})


from p2p_tpu.analysis.hlo_cost import _aval_numel as _numel


def _is_stat_shaped(v) -> bool:
    """The instance-norm statistic signature: rank >= 3 with the spatial
    dims reduced to 1 (``(N, 1, 1, C)`` after a keepdims mean/var over
    H, W). BatchNorm stats are rank-1 ``(C,)`` and never match — the
    rule is specifically about the per-sample norm the Pallas kernel
    fuses."""
    shape = getattr(getattr(v, "aval", None), "shape", None)
    if shape is None or len(shape) < 3:
        return False
    unit = sum(1 for d in shape[1:-1] if d == 1)
    return unit >= 1 and unit == len(shape) - 2


def _feeds_full_multiply(start_var, consumers, depth: int = 6) -> bool:
    """True when ``start_var`` (a stat-shaped tensor) reaches, through a
    short elementwise chain, a ``mul`` against a tensor with strictly
    more elements — the normalize step applying rsqrt(var) to the full
    activation."""
    seen = set()
    frontier = [(start_var, 0)]
    while frontier:
        v, d = frontier.pop()
        if d > depth or id(v) in seen:
            continue
        seen.add(id(v))
        for eqn in consumers.get(id(v), ()):
            name = eqn.primitive.name
            if name == "mul":
                others = [o for o in eqn.invars if id(o) != id(v)]
                if any(_numel(o) > max(1, _numel(v)) * 3 for o in others):
                    return True
            if name in _CHAIN_LINKS:
                for ov in eqn.outvars:
                    frontier.append((ov, d + 1))
    return False


def unfused_norm_chain_findings(jaxpr, tag: str = "program",
                                ) -> List[Finding]:
    """Findings for reference instance-norm(+act) chains in a program
    that was supposed to route them through ``ops/pallas/norm_act``.
    One finding per source line (a model reuses the same norm call site
    across blocks/microbatches — one policy decision, one finding)."""
    seen: Dict[Tuple, Finding] = {}
    counts: Dict[Tuple, int] = defaultdict(int)

    def scan(jx):
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        consumers: Dict[int, List] = defaultdict(list)
        for eqn in jx.eqns:
            for v in eqn.invars:
                if type(v).__name__ == "Var":
                    consumers[id(v)].append(eqn)
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                continue          # the kernel interior IS the fused path
            if eqn.primitive.name == "rsqrt" \
                    and _is_stat_shaped(eqn.outvars[0]) \
                    and _feeds_full_multiply(eqn.outvars[0], consumers):
                fname, line = eqn_location(eqn)
                key = (fname, line)
                counts[key] += 1
                if key not in seen:
                    seen[key] = Finding(
                        rule=RULE_UNFUSED_NORM, severity=WARNING,
                        file=fname, line=line,
                        path=None if fname else tag,
                        message=f"InstanceNorm(+act) chain in {tag!r} "
                                "lowered as reference XLA ops instead of "
                                "the fused ops/pallas/norm_act kernel — "
                                "silent fallback of the dispatch seam, or "
                                "model code not routed through "
                                "ops/norm.make_norm_act",
                    )
                continue
            for sub in sub_jaxprs(eqn.params):
                scan(sub)

    scan(jaxpr)
    out = []
    for key, f in seen.items():
        if counts[key] > 1:
            f.message += f" (x{counts[key]} chains at this line)"
        out.append(f)
    return out


# ---------------------------------------------- collective overlap (c)


def classify_scan_collectives(jaxpr, kinds: Iterable[str] = ("ppermute",),
                              ) -> List[Dict[str, Any]]:
    """For every collective of ``kinds`` directly inside a ``lax.scan``
    body: ``{"kind", "operand": "carry"|"invar"|"computed", "eqn"}``.

    - ``carry``    — a scan carry invar: the previous tick's value; the
      transfer is structurally independent of this tick's compute (the
      overlapped schedule's pin).
    - ``invar``    — a body const/xs invar: available when the tick
      starts; the transfer can still issue ahead of compute.
    - ``computed`` — produced by the tick body before the collective:
      the ICI hop cannot start until that compute finishes — serialized.
    """
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    kinds = {normalize_primitive(k) for k in kinds}
    out: List[Dict[str, Any]] = []

    def classify_body(jx, env):
        """Classify collectives of a scan body against ``env`` (var id →
        carry/invar), following them INTO wrapper sub-jaxprs (remat,
        pjit, custom_vjp) whose invars align positionally with the
        wrapping eqn's — a checkpointed stage function must not hide a
        serialized hop from the audit. Unalignable wrappers are skipped
        (no classification beats a false positive); inner scans get
        their own context from the outer walk."""
        for eqn in jx.eqns:
            name = normalize_primitive(eqn.primitive.name)
            if name in kinds and name in COLLECTIVE_PRIMITIVES:
                op = eqn.invars[0]
                out.append({"kind": name,
                            "operand": env.get(id(op), "computed"),
                            "eqn": eqn})
                continue
            if eqn.primitive.name == "scan":
                continue
            for sub in sub_jaxprs(eqn.params):
                sj = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                if len(sj.invars) != len(eqn.invars):
                    continue
                inner = {id(iv): env[id(ov)]
                         for iv, ov in zip(sj.invars, eqn.invars)
                         if id(ov) in env}
                classify_body(sj, inner)

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
                env = {}
                for i, v in enumerate(body.invars):
                    env[id(v)] = ("carry" if nc <= i < nc + nk
                                  else "invar")
                for v in getattr(body, "constvars", ()):
                    env[id(v)] = "invar"   # loop-invariant closure
                classify_body(body, env)
                walk(body)
            else:
                for sub in sub_jaxprs(eqn.params):
                    walk(sub)

    walk(jaxpr)
    return out


def serialized_collective_findings(jaxpr, tag: str = "program",
                                   kinds: Iterable[str] = ("ppermute",),
                                   ) -> List[Finding]:
    """``perf-serialized-collective`` findings for every tick-computed
    in-scan collective operand (see :func:`classify_scan_collectives`)."""
    out: List[Finding] = []
    for rec in classify_scan_collectives(jaxpr, kinds=kinds):
        if rec["operand"] != "computed":
            continue
        fname, line = eqn_location(rec["eqn"])
        out.append(Finding(
            rule=RULE_SERIALIZED, severity=WARNING,
            file=fname, line=line, path=None if fname else tag,
            message=f"in-scan {rec['kind']} in {tag!r} consumes a value "
                    "computed by the SAME tick — the ICI hop serializes "
                    "behind stage compute; route the previous tick's "
                    "output through the scan carry instead "
                    "(ParallelConfig.pp_overlap / --pp_overlap, "
                    "docs/PARALLELISM.md latency-hiding schedule)",
        ))
    return out


# ------------------------------------------------- int8 coverage (d)


def int8_coverage(jaxpr, tag: str = "program",
                  ) -> Tuple[List[dict], List[Finding]]:
    """``(worklist, findings)`` enumerating conv/dot eqns still
    contracting in bf16/f32 inside a delayed-int8 program. Info severity
    — the ROADMAP item-2 worklist, drained by ISSUE 14: the caller runs
    the findings through ``apply_pragma_waivers`` and drops waived sites
    from the worklist (a dated waiver IS a drained verdict). Entries
    carry op, operand dtypes, shapes and ``file:line``; one entry per
    source line with an eqn count."""
    agg: Dict[Tuple, dict] = {}
    # descend everything EXCEPT pallas_call kernels (block-shaped refs)
    def walk(jx):
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                continue
            if eqn.primitive.name in ("conv_general_dilated",
                                      "dot_general"):
                dts = tuple(
                    str(getattr(getattr(v, "aval", None), "dtype", "?"))
                    for v in eqn.invars[:2])
                # covered = BOTH contraction operands int8 (the s8×s8→s32
                # MXU path — the same law hlo_cost._mxu_dtype_key books
                # the doubled rate under); a half-quantized site is
                # still unconverted MXU work and stays on the worklist
                if all(d == "int8" for d in dts):
                    continue
                fname, line = eqn_location(eqn)
                key = (fname, line, eqn.primitive.name, dts)
                if key in agg:
                    agg[key]["eqns"] += 1
                else:
                    agg[key] = {
                        "program": tag,
                        "op": eqn.primitive.name,
                        "dtypes": list(dts),
                        "out_shape": list(getattr(
                            eqn.outvars[0].aval, "shape", ())),
                        "file": fname, "line": line, "eqns": 1,
                    }
                continue
            for sub in sub_jaxprs(eqn.params):
                walk(sub)

    walk(jaxpr)
    worklist = list(agg.values())
    findings = [Finding(
        rule=RULE_INT8_GAP, severity=INFO,
        file=w["file"], line=w["line"], path=None if w["file"] else tag,
        message=f"{w['op']} still contracts in {tuple(w['dtypes'])} in "
                f"delayed-int8 program {tag!r} (out {tuple(w['out_shape'])}"
                f", x{w['eqns']} eqns) — unconverted MXU work for the "
                "ROADMAP item-2 int8 lever",
    ) for w in worklist]
    return worklist, findings
