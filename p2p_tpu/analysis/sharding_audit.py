"""Sharding-rule auditor — static verification of a partition-rule table
against a state tree, with zero device memory.

:func:`p2p_tpu.parallel.rules.match_partition_rules` raises on an
UNMATCHED leaf, but that is the only failure it can see. This auditor
detects what first-match-wins semantics silently absorb:

- **dead rules** that fire on no leaf at all (typo'd pattern, stale path
  after a model rename) — the rule table claims coverage it doesn't have;
- **shadowed rules**: every leaf a rule matches is claimed by an EARLIER
  pattern, so the rule can never fire — the classic silent layout bug
  when a specific rule lands after a broad one;
- **specs naming mesh axes that don't exist** on the target mesh;
- **indivisible shards**: a spec's sharded axis product does not divide
  the leaf dimension (GSPMD would pad or error at run time — the audit
  says so at lint time);
- spec **rank overflow** (more partitioned dims than the leaf has).

State trees come from ``jax.eval_shape`` over the real constructors
(:func:`abstract_train_state`) — shapes and paths only, no allocation, so
the full-size preset states audit on a CPU CI runner.

The ``tp``-diff mode (:func:`tp_rule_gaps`) diffs the reference
shape-conditional TP assignment (:func:`p2p_tpu.parallel.tp.tp_leaf_spec`)
against a declarative rule table and reports exactly which leaves the
table cannot express. The worklist is DRAINED and the hand-built tree is
retired to a shim (ISSUE 15): the live layouts run from
``parallel/rules.py`` alone, and this diff is the standing proof the
tables still reproduce the reference assignment.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from p2p_tpu.analysis.findings import ERROR, INFO, WARNING, Finding

RULE_UNMATCHED = "sharding-unmatched-leaf"
RULE_DEAD = "sharding-dead-rule"
RULE_SHADOWED = "sharding-shadowed-rule"
RULE_UNKNOWN_AXIS = "sharding-unknown-axis"
RULE_INDIVISIBLE = "sharding-indivisible"
RULE_RANK = "sharding-spec-rank"
RULE_TP_GAP = "sharding-tp-rule-gap"

#: patterns treated as an intentional replicate-everything catch-all —
#: exempt from dead/shadow accounting (a catch-all SHOULD be unreachable
#: when earlier rules cover the tree).
_CATCH_ALL = {r".*", r"^.*$", r"(.*)"}

MeshLike = Union[None, Dict[str, int], Any]  # dict of axis sizes or a Mesh


def mesh_axis_sizes(mesh: MeshLike) -> Optional[Dict[str, int]]:
    """Axis-name → size view of a ``jax.sharding.Mesh`` OR a plain dict —
    the audit never needs devices, so a hypothetical topology ({"data": 8,
    "model": 4}) works on a 1-CPU runner."""
    if mesh is None:
        return None
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    shape = getattr(mesh, "shape", None)  # Mesh.shape is an axis->size map
    if shape is not None:
        return {str(k): int(v) for k, v in dict(shape).items()}
    raise TypeError(f"mesh must be a Mesh or {{axis: size}} dict, "
                    f"got {type(mesh).__name__}")


def named_leaves(tree: Any) -> List[Tuple[str, str, Tuple[int, ...]]]:
    """(slash-joined rule path, keystr path, shape) for every array-like
    leaf of ``tree`` — works on concrete arrays and on the
    ``ShapeDtypeStruct`` leaves :func:`abstract_train_state` produces."""
    import jax

    from p2p_tpu.parallel.rules import leaf_path_name

    out = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            shape = np.shape(leaf)
        out.append((leaf_path_name(path), jax.tree_util.keystr(path),
                    tuple(int(d) for d in shape)))
    return out


def _spec_partitions(spec) -> List[Tuple[int, Tuple[str, ...]]]:
    """(dim index, axis names) for every partitioned dim of a
    PartitionSpec; a dim entry may be one axis or a tuple of axes."""
    out = []
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        out.append((d, tuple(str(a) for a in axes)))
    return out


def _is_scalar(shape: Tuple[int, ...]) -> bool:
    # the universal floor rule: scalars / 1-element leaves never partition
    return len(shape) == 0 or int(np.prod(shape)) == 1


def _table_axis_findings(compiled, sizes: Dict[str, int]) -> List[Finding]:
    """Unknown-axis check runs TABLE-level, once per rule, so a dead or
    shadowed rule's bogus axis is still reported (per-leaf checking would
    mask it — the rule never fires on anything). Spec-BUILDER rules
    (callable specs, the fsdp table) have no table-level spec to inspect
    — ``audit_rules`` collects the axes their per-leaf resolutions
    actually name and reports through the same rule id."""
    out: List[Finding] = []
    for idx, (_, pat, spec, _pred) in enumerate(compiled):
        if callable(spec):
            continue
        missing = sorted({a for _, axes in _spec_partitions(spec)
                          for a in axes if a not in sizes})
        if missing:
            out.append(Finding(
                rule=RULE_UNKNOWN_AXIS, severity=ERROR, path=f"rule[{idx}]",
                message=f"rule[{idx}] {pat!r} spec {spec} names mesh "
                        f"ax{'es' if len(missing) > 1 else 'is'} "
                        f"{missing} absent from the target mesh "
                        f"(have {sorted(sizes)})",
            ))
    return out


def _spec_findings(spec, name: str, shape: Tuple[int, ...],
                   sizes: Optional[Dict[str, int]],
                   rule_label: str) -> List[Finding]:
    out: List[Finding] = []
    parts = _spec_partitions(spec)
    if parts and max(d for d, _ in parts) >= len(shape):
        out.append(Finding(
            rule=RULE_RANK, severity=ERROR, path=name,
            message=f"spec {spec} from {rule_label} partitions dim "
                    f"{max(d for d, _ in parts)} of a rank-{len(shape)} "
                    f"leaf (shape {shape})",
        ))
        return out
    for d, axes in parts:
        if sizes is not None:
            if any(a not in sizes for a in axes):
                continue  # reported once, table-level (_table_axis_findings)
            total = int(np.prod([sizes[a] for a in axes]))
            if total > 1 and shape[d] % total != 0:
                out.append(Finding(
                    rule=RULE_INDIVISIBLE, severity=ERROR, path=name,
                    message=f"spec {spec} from {rule_label} shards dim "
                            f"{d} (={shape[d]}) over {axes} "
                            f"(size {total}), which does not divide it",
                ))
    return out


def audit_rules(rules: Sequence[Tuple[str, Any]], tree: Any,
                mesh: MeshLike = None) -> List[Finding]:
    """Statically verify a rule table against a state tree (and optionally
    a mesh topology). Returns findings; an empty list is the audit's
    "every leaf matches, every rule earns its place" certificate."""
    from p2p_tpu.parallel.rules import resolve_spec, rule_parts

    sizes = mesh_axis_sizes(mesh)
    leaves = named_leaves(tree)
    compiled = []
    for rule in rules:
        pat, spec, pred = rule_parts(rule)
        compiled.append((re.compile(pat), pat, spec, pred))
    findings: List[Finding] = []
    if sizes is not None:
        findings.extend(_table_axis_findings(compiled, sizes))
    fired = [0] * len(compiled)
    claimed_by: Dict[str, int] = {}
    #: rule idx -> axes its spec-BUILDER resolutions named (callable
    #: specs have no table-level view — the unknown-axis check runs on
    #: this union after the leaf walk)
    builder_axes: Dict[int, set] = {}

    for name, _, shape in leaves:
        if _is_scalar(shape):
            continue  # the scalar floor never consults the table
        for idx, (cre, pat, spec, pred) in enumerate(compiled):
            if cre.search(name) is not None \
                    and (pred is None or pred(tuple(shape))):
                fired[idx] += 1
                claimed_by[name] = idx
                leaf_spec = resolve_spec(spec, shape)
                if callable(spec):
                    builder_axes.setdefault(idx, set()).update(
                        a for _, axes in _spec_partitions(leaf_spec)
                        for a in axes)
                findings.extend(_spec_findings(
                    leaf_spec, name, shape, sizes,
                    rule_label=f"rule[{idx}] {pat!r}"))
                break
        else:
            findings.append(Finding(
                rule=RULE_UNMATCHED, severity=ERROR, path=name,
                message=f"no rule matches leaf (shape {shape}); tried "
                        f"{len(compiled)} rules — add a catch-all "
                        f"(\".*\", P())",
            ))

    for idx, (cre, pat, spec, pred) in enumerate(compiled):
        if fired[idx] or pat in _CATCH_ALL:
            continue
        # a predicate rule "matches" a leaf only when its predicate also
        # accepts the shape — a regex-hit/predicate-miss leaf is neither
        # claimed nor shadow evidence
        shadow_hits = [(name, claimed_by[name])
                       for name, _, shape in leaves
                       if not _is_scalar(shape) and name in claimed_by
                       and cre.search(name) is not None
                       and (pred is None or pred(tuple(shape)))]
        if shadow_hits:
            name0, by = min(shadow_hits, key=lambda t: t[1])
            by_pat = compiled[by][1]
            findings.append(Finding(
                rule=RULE_SHADOWED, severity=ERROR, path=f"rule[{idx}]",
                message=f"rule[{idx}] {pat!r} matches "
                        f"{len(shadow_hits)} leaves (e.g. {name0!r}) but "
                        f"every one is claimed by the earlier rule[{by}] "
                        f"{by_pat!r} — it can never fire",
            ))
        else:
            findings.append(Finding(
                rule=RULE_DEAD, severity=WARNING, path=f"rule[{idx}]",
                message=f"rule[{idx}] {pat!r} fires on no leaf of the "
                        "audited tree — stale path or typo'd pattern",
            ))
    if sizes is not None:
        for idx, axes in sorted(builder_axes.items()):
            missing = sorted(a for a in axes if a not in sizes)
            if missing:
                findings.append(Finding(
                    rule=RULE_UNKNOWN_AXIS, severity=ERROR,
                    path=f"rule[{idx}]",
                    message=f"rule[{idx}] {compiled[idx][1]!r} "
                            f"(spec builder) resolved specs naming mesh "
                            f"ax{'es' if len(missing) > 1 else 'is'} "
                            f"{missing} absent from the target mesh "
                            f"(have {sorted(sizes)})",
                ))
    return findings


# -------------------------------------------------------- tp-diff mode


def tp_rule_gaps(tree: Any, rules: Optional[Sequence[Tuple[str, Any]]] = None,
                 axis_size: int = 2, min_ch: int = 512,
                 ) -> Tuple[List[dict], List[Finding]]:
    """Diff the shape-conditional TP assignment against a declarative rule
    table, leaf by leaf.

    Returns ``(worklist, findings)``: each worklist entry names a leaf the
    regex table gets WRONG relative to ``tp_leaf_spec`` (either the table
    replicates what TP shards — the common gap, needing a predicate rule —
    or the table shards what TP replicates, e.g. a width gate the regex
    cannot express). This is the ROADMAP item-3 migration worklist; the
    findings mirror it at ``info`` severity so the lint gate reports
    without failing on it.
    """
    from jax.sharding import PartitionSpec as P

    from p2p_tpu.parallel.rules import (
        REPLICATED_RULES,
        resolve_spec,
        rule_parts,
    )
    from p2p_tpu.parallel.tp import tp_leaf_spec

    rules = REPLICATED_RULES if rules is None else rules
    compiled = []
    for rule in rules:
        pat, spec, pred = rule_parts(rule)
        compiled.append((re.compile(pat), spec, pred))
    worklist: List[dict] = []
    findings: List[Finding] = []
    for name, keystr, shape in named_leaves(tree):
        if _is_scalar(shape):
            continue
        tp_spec = tp_leaf_spec(keystr, shape, axis_size, min_ch)
        rule_spec = None
        for cre, spec, pred in compiled:
            if cre.search(name) is not None \
                    and (pred is None or pred(tuple(shape))):
                rule_spec = resolve_spec(spec, shape)
                break
        if rule_spec is None or tuple(tp_spec) == tuple(rule_spec):
            continue  # unmatched leaves are audit_rules' finding, not a gap
        direction = ("needs-predicate-rule" if tuple(rule_spec) == ()
                     or rule_spec == P() else "table-overshards")
        worklist.append({
            "leaf": name, "shape": shape, "tp_spec": str(tp_spec),
            "rule_spec": str(rule_spec), "direction": direction,
        })
        findings.append(Finding(
            rule=RULE_TP_GAP, severity=INFO, path=name,
            message=f"tp_leaf_spec says {tp_spec}, rule table says "
                    f"{rule_spec} (shape {shape}) — {direction}",
        ))
    return worklist, findings


# --------------------------------------------------- shape-only states


def abstract_train_state(cfg, batch_size: Optional[int] = None,
                         train_dtype=None):
    """The preset's full TrainState as a ShapeDtypeStruct tree via
    ``jax.eval_shape`` — real constructors, real paths, ZERO device
    memory, so a 1024×512 preset audits on a laptop CPU."""
    import jax

    from p2p_tpu.train.state import create_train_state

    h, w = cfg.image_hw
    bs = batch_size or cfg.data.batch_size
    dt = np.uint8 if cfg.data.uint8_pipeline else np.float32
    nc_in, nc_out = cfg.model.input_nc, cfg.model.output_nc
    sample = {"input": np.zeros((bs, h, w, nc_in), dt),
              "target": np.zeros((bs, h, w, nc_out), dt)}
    return jax.eval_shape(
        lambda: create_train_state(cfg, jax.random.key(0), sample,
                                   train_dtype=train_dtype))
