"""Command-line drivers.

- ``python -m p2p_tpu.cli.train`` — training (reference train.py:133-157
  flag parity + TPU mesh/preset knobs).
- ``python -m p2p_tpu.cli.infer`` — batched inference from a checkpoint
  through the serving engine (replaces reference test.py, which could not
  load train.py's checkpoints — SURVEY Q5).
- ``python -m p2p_tpu.cli.serve`` — micro-batching serving frontend
  (directory-driven requests → bucket-batched predictions; docs/SERVING.md).
- ``python -m p2p_tpu.cli.generate_dataset`` — offline paired-dataset
  generation (reference generate_dataset.py:150-165 flag parity).
- ``python -m p2p_tpu.cli.lint`` — static-analysis gate over the repo
  (p2p_tpu.analysis: sharding audit, jaxpr/HLO lint, AST rules;
  docs/STATIC_ANALYSIS.md). ``--strict`` is the CI mode.
"""

import dataclasses


def apply_overrides(obj, **kw):
    """dataclasses.replace with None-valued (unset flag) entries dropped —
    the shared preset-override rule for every CLI."""
    kw = {k: v for k, v in kw.items() if v is not None}
    return dataclasses.replace(obj, **kw) if kw else obj
