"""Offline paired-dataset generation CLI.

Flag parity with reference generate_dataset.py:150-165 (same names):
--target_dataset_folder / --dataset_path / --bit_size / --max_patches /
--pool_size / --crop_size / --img_format / --upsampling. The reference's
commented-out multiprocessing pool (generate_dataset.py:130,139-147) is
live here via --pool_size workers.
"""

from __future__ import annotations

import argparse
import sys

from p2p_tpu.data.generate import generate_dataset


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu dataset generation")
    p.add_argument("--target_dataset_folder", type=str, required=True,
                   help="output dataset root (train/{a,b} written under it)")
    p.add_argument("--dataset_path", type=str, required=True,
                   help="source image folder")
    p.add_argument("--split", type=str, default="train", help="train or test")
    p.add_argument("--bit_size", type=int, default=3,
                   help="quantizer bit depth for the b/ images")
    p.add_argument("--max_patches", type=int, default=100)
    p.add_argument("--pool_size", type=int, default=0,
                   help="parallel decode workers (0 = inline)")
    p.add_argument("--crop_size", type=int, default=256,
                   help="tile size; -1 disables tiling (whole images)")
    p.add_argument("--crop_width", type=int, default=0,
                   help="rectangular tile width (0 = square crop_size); "
                        "e.g. --crop_size 512 --crop_width 1024 for "
                        "pix2pixHD-shaped frames (TPU extension; the "
                        "reference datagen is square-only)")
    # p2p-lint: disable=ast-cli-flag-drift -- reference-parity flag (generate_dataset.py:150-165), accepted but deliberately ignored: outputs are always png
    p.add_argument("--img_format", type=str, default="png",
                   help="accepted for parity; outputs are always png")
    p.add_argument("--min_std", type=float, default=0.0,
                   help="drop near-constant patches (uint8 std below this); "
                        "flat tiles blow up per-sample-norm backward passes")
    p.add_argument("--upsampling", type=int, default=0,
                   help="nearest-upsample every source by this factor (>0)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    n = generate_dataset(
        src_dir=args.dataset_path,
        out_dir=args.target_dataset_folder,
        split=args.split,
        crop_size=args.crop_size if args.crop_size > 0 else None,
        max_patches=args.max_patches,
        bits=args.bit_size,
        upsample=args.upsampling,
        workers=args.pool_size,
        min_std=args.min_std,
        crop_width=args.crop_width if args.crop_width > 0 else None,
    )
    print(f"wrote {n} paired patches to {args.target_dataset_folder}/{args.split}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
