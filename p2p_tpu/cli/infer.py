"""Inference CLI — batched generator inference from a training checkpoint.

Replaces the reference's test.py (test.py:1-46), which loads a pickled
module file train.py never writes (SURVEY Q5). Inference restores from the
SAME Orbax checkpoint the trainer saves — but through the serving engine
(p2p_tpu.serve): a params-only subtree restore (never materializing the
discriminator or optimizer state), a small set of AOT-compiled batch
buckets (the final partial batch pads up to a bucket instead of
recompiling), and thread-pooled PNG encoding that overlaps device compute.

Flag parity with test.py (--dataset/--direction/--cuda) plus checkpoint
addressing by step (--step, default latest). ``--ndf``/``--pool_size`` are
accepted-but-ignored (like --cuda): the params-only restore no longer needs
discriminator/pool hyperparameters to rebuild a checkpoint template.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu inference")
    p.add_argument("--preset", type=str, default="reference")
    p.add_argument("--name", type=str, default=None,
                   help="training name (checkpoint subdir; default preset name)")
    p.add_argument("--dataset", type=str, default=None, help="facades")
    p.add_argument("--direction", type=str, default=None, help="a2b or b2a")
    p.add_argument("--cuda", action="store_true",
                   help="accepted for parity; ignored (always TPU/XLA)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to load (default: latest)")
    p.add_argument("--data_root", type=str, default=None)
    p.add_argument("--workdir", type=str, default=".")
    p.add_argument("--out", type=str, default=None,
                   help="output dir (default <workdir>/result/<dataset>)")
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--image_size", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--ndf", type=int, default=None,
                   help="image presets: accepted-but-ignored (params-only "
                        "restore never rebuilds the discriminator); video "
                        "presets still restore the FULL state and need "
                        "the trained value")
    p.add_argument("--n_blocks", type=int, default=None)
    p.add_argument("--upsample_mode", type=str, default=None,
                   choices=["deconv", "resize"])
    p.add_argument("--metrics", action="store_true",
                   help="also print mean/max PSNR+SSIM vs the targets")
    p.add_argument("--ema_decay", type=float, default=None,
                   help="the checkpoint was trained with --ema_decay: "
                        "restore the EMA generator weights too and serve "
                        "the SMOOTHED G (bitwise == raw at decay 0)")
    p.add_argument("--pool_size", type=int, default=None,
                   help="image presets: accepted-but-ignored (params-only "
                        "restore never rebuilds the fake pool); video "
                        "presets still restore the FULL state and need "
                        "the trained value")
    # --- serving-engine knobs (p2p_tpu.serve; docs/SERVING.md) -----------
    p.add_argument("--buckets", type=str, default=None,
                   help="comma-separated batch buckets AOT-compiled at "
                        "startup (default: the test batch size; the tail "
                        "batch pads up to the smallest covering bucket)")
    p.add_argument("--dtype", type=str, default="bf16",
                   choices=["bf16", "f32"],
                   help="inference compute dtype policy (params stay f32; "
                        "delayed-int8 checkpoints additionally serve with "
                        "frozen activation scales)")
    p.add_argument("--mesh", type=str, default=None,
                   help="serving mesh: positional 'data,spatial,time"
                        "[,model]' or named 'axis=size,...'; model>1 "
                        "shards the generator tensor-parallel "
                        "(parallel/rules.py)")
    p.add_argument("--tp_min_ch", type=int, default=None,
                   help="smallest channel count the TP rule shards")
    p.add_argument("--io_threads", type=int, default=4,
                   help="PNG encode worker threads (overlap device compute)")
    p.add_argument("--compilation_cache", type=str, default=None,
                   metavar="DIR",
                   help="persistent XLA compilation cache dir: cold starts "
                        "load compiled bucket programs from disk")
    p.add_argument("--stats", action="store_true",
                   help="print the engine's fenced timing breakdown as a "
                        "JSON line (img/s, infer/encode/wall sec, compiles)")
    return p


def _parse_mesh(arg):
    if arg is None:
        return None
    from p2p_tpu.core.mesh import make_mesh, parse_mesh_arg

    try:
        spec = parse_mesh_arg(arg)
    except ValueError as e:
        raise SystemExit(
            f"--mesh must be 'data,spatial,time[,model[,pipe]]' "
            f"comma-separated ints or named 'axis=size,...' (got "
            f"{arg!r}: {e})")
    return make_mesh(spec)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cuda:
        print("note: --cuda accepted for parity but ignored (TPU/XLA build)",
              file=sys.stderr)

    import dataclasses

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.pipeline import PairedImageDataset, make_loader
    from p2p_tpu.serve import engine_from_checkpoint

    from p2p_tpu.cli import apply_overrides as over

    cfg = get_preset(args.preset)
    data = over(cfg.data, dataset=args.dataset, direction=args.direction,
                test_batch_size=args.batch_size, image_size=args.image_size)
    model = over(cfg.model, ngf=args.ngf, n_blocks=args.n_blocks,
                 upsample_mode=args.upsample_mode)
    health = over(cfg.health, ema_decay=args.ema_decay)
    cfg = dataclasses.replace(cfg, data=data, model=model, health=health,
                              name=args.name or cfg.name)
    if cfg.data.n_frames > 1:
        # the video path restores the FULL TrainState (its own pytree), so
        # the template-rebuild knobs stay live there
        model = over(cfg.model, ndf=args.ndf)
        train = over(cfg.train, pool_size=args.pool_size)
        return _video_main(args, dataclasses.replace(cfg, model=model,
                                                     train=train))
    for flag in ("ndf", "pool_size"):
        if getattr(args, flag) is not None:
            print(f"note: --{flag} accepted for parity but ignored — "
                  "params-only restore needs no checkpoint template "
                  "beyond the generator", file=sys.stderr)

    root = args.data_root or os.path.join(cfg.data.root, cfg.data.dataset)
    ds_dtype = "uint8" if cfg.data.uint8_pipeline else "float32"
    try:
        ds = PairedImageDataset(
            root, "test", cfg.data.direction, cfg.data.image_size,
            cfg.data.image_width, dtype=ds_dtype,
        )
    except (RuntimeError, FileNotFoundError) as e:
        print(f"no test images under {root}: {e}", file=sys.stderr)
        return 1

    ckpt_dir = os.path.join(
        args.workdir, cfg.train.checkpoint_dir, cfg.data.dataset, cfg.name
    )
    bs = cfg.data.test_batch_size
    sample = ds[0]
    sample_batch = {
        k: np.broadcast_to(v, (bs,) + v.shape).copy() for k, v in sample.items()
    }
    buckets = ([int(b) for b in args.buckets.split(",")] if args.buckets
               else None)
    try:
        engine, step = engine_from_checkpoint(
            cfg, ckpt_dir, sample_batch, step=args.step,
            buckets=buckets or (bs,), dtype=args.dtype,
            mesh=_parse_mesh(args.mesh), tp_min_ch=args.tp_min_ch,
            # only compile the PSNR/SSIM tail into the bucket programs
            # when asked — metrics-off serving must not pay for them
            with_metrics=args.metrics,
            compilation_cache_dir=args.compilation_cache,
            io_workers=args.io_threads,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1

    out_dir = args.out or os.path.join(
        args.workdir, cfg.train.result_dir, cfg.data.dataset
    )
    os.makedirs(out_dir, exist_ok=True)

    # drop_remainder=False: EVERY test image gets a prediction — the final
    # partial batch pads up to a compiled bucket (no tail recompile) and
    # its padding rows are masked out of files and metrics
    loader = make_loader(ds, bs, shuffle=False, num_epochs=1,
                         drop_remainder=False)
    stats, metrics = engine.run(
        loader, names=ds.names, out_dir=out_dir,
        collect_metrics=args.metrics,
    )
    print(f"wrote {stats.n_images} predictions (checkpoint step {step}) "
          f"to {out_dir}")
    if args.metrics and metrics.get("psnr"):
        psnrs, ssims = metrics["psnr"], metrics["ssim"]
        print(f"psnr_mean={np.mean(psnrs):.4f} psnr_max={np.max(psnrs):.4f} "
              f"ssim_mean={np.mean(ssims):.4f} ssim_max={np.max(ssims):.4f}")
    if args.stats:
        print(json.dumps({"kind": "serve_stats", **stats.as_dict()}))
    return 0


def _video_main(args, cfg) -> int:
    """Clip inference: per-frame predictions written as
    <out>/<video>_<frame>.png (video configs, n_frames>1). Stays on the
    full-state restore path — the video TrainState has its own structure;
    engine coverage is image presets (docs/SERVING.md)."""
    import jax

    from p2p_tpu.data.pipeline import make_loader
    from p2p_tpu.data.video import VideoClipDataset
    from p2p_tpu.train.checkpoint import CheckpointManager
    from p2p_tpu.train.video_loop import build_video_eval_step
    from p2p_tpu.train.video_step import create_video_train_state
    from p2p_tpu.utils.images import save_img

    root = args.data_root or os.path.join(cfg.data.root, cfg.data.dataset)
    try:
        ds = VideoClipDataset(
            root, "test", cfg.data.direction, cfg.data.image_size,
            cfg.data.image_width, n_frames=cfg.data.n_frames,
        )
    except (RuntimeError, FileNotFoundError) as e:
        print(f"no test clips under {root}: {e}", file=sys.stderr)
        return 1

    ckpt_dir = os.path.join(
        args.workdir, cfg.train.checkpoint_dir, cfg.data.dataset, cfg.name
    )
    ckpt = CheckpointManager(ckpt_dir)
    step = args.step if args.step is not None else ckpt.latest_step()
    if step is None:
        print(f"no checkpoint found under {ckpt_dir}", file=sys.stderr)
        return 1

    bs = cfg.data.test_batch_size
    sample = ds[0]
    sample_batch = {
        k: np.broadcast_to(v, (bs,) + v.shape).copy() for k, v in sample.items()
    }
    state = create_video_train_state(cfg, jax.random.key(0), sample_batch)
    state = ckpt.restore(state, step)
    eval_step = build_video_eval_step(cfg)

    out_dir = args.out or os.path.join(
        args.workdir, cfg.train.result_dir, cfg.data.dataset
    )
    os.makedirs(out_dir, exist_ok=True)

    n_clip = 0
    n_frames = 0
    psnrs, ssims = [], []
    for batch in make_loader(ds, bs, shuffle=False, num_epochs=1,
                             drop_remainder=False):
        pred, metrics = eval_step(state, batch)
        pred = np.asarray(pred, np.float32)
        if args.metrics:
            psnrs.extend(np.asarray(metrics["psnr"]).ravel().tolist())
            ssims.extend(np.asarray(metrics["ssim"]).ravel().tolist())
        for i in range(pred.shape[0]):
            if n_clip >= len(ds):
                break
            vid, frames = ds.windows[n_clip]
            for t, fname in enumerate(frames):
                stem = os.path.splitext(fname)[0]
                save_img(pred[i, t], os.path.join(out_dir, f"{vid}_{stem}.png"))
                n_frames += 1
            n_clip += 1
    print(f"wrote {n_frames} frames / {n_clip} clips "
          f"(checkpoint step {step}) to {out_dir}")
    if args.metrics and psnrs:
        print(f"psnr_mean={np.mean(psnrs):.4f} psnr_max={np.max(psnrs):.4f} "
              f"ssim_mean={np.mean(ssims):.4f} ssim_max={np.max(ssims):.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
