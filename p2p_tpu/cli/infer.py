"""Inference CLI — batched generator inference from a training checkpoint.

Replaces the reference's test.py (test.py:1-46), which loads a pickled
module file train.py never writes (SURVEY Q5). Here inference restores the
SAME Orbax checkpoint the trainer saves, rebuilds the generator from the
SAME config preset, and runs the eval path (compression net + quantizer
when the preset has one, plain G otherwise) over the test split, saving
predictions to ``result/<dataset>/`` exactly like the reference driver.

Flag parity with test.py (--dataset/--direction/--cuda) plus checkpoint
addressing by step (--step, default latest).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu inference")
    p.add_argument("--preset", type=str, default="reference")
    p.add_argument("--name", type=str, default=None,
                   help="training name (checkpoint subdir; default preset name)")
    p.add_argument("--dataset", type=str, default=None, help="facades")
    p.add_argument("--direction", type=str, default=None, help="a2b or b2a")
    p.add_argument("--cuda", action="store_true",
                   help="accepted for parity; ignored (always TPU/XLA)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to load (default: latest)")
    p.add_argument("--data_root", type=str, default=None)
    p.add_argument("--workdir", type=str, default=".")
    p.add_argument("--out", type=str, default=None,
                   help="output dir (default <workdir>/result/<dataset>)")
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--image_size", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--ndf", type=int, default=None,
                   help="discriminator width — needed to rebuild the "
                        "checkpoint template for full-state restore")
    p.add_argument("--n_blocks", type=int, default=None)
    p.add_argument("--upsample_mode", type=str, default=None,
                   choices=["deconv", "resize"])
    p.add_argument("--metrics", action="store_true",
                   help="also print mean/max PSNR+SSIM vs the targets")
    p.add_argument("--pool_size", type=int, default=None,
                   help="pool size the checkpoint was TRAINED with — needed "
                        "to rebuild the state template for full-state "
                        "restore (like --ndf)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cuda:
        print("note: --cuda accepted for parity but ignored (TPU/XLA build)",
              file=sys.stderr)

    import dataclasses

    import jax

    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.pipeline import PairedImageDataset, make_loader
    from p2p_tpu.train.checkpoint import CheckpointManager
    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_eval_step
    from p2p_tpu.utils.images import save_img

    from p2p_tpu.cli import apply_overrides as over

    cfg = get_preset(args.preset)
    data = over(cfg.data, dataset=args.dataset, direction=args.direction,
                test_batch_size=args.batch_size, image_size=args.image_size)
    model = over(cfg.model, ngf=args.ngf, ndf=args.ndf,
                 n_blocks=args.n_blocks, upsample_mode=args.upsample_mode)
    train = over(cfg.train, pool_size=args.pool_size)
    cfg = dataclasses.replace(cfg, data=data, model=model, train=train,
                              name=args.name or cfg.name)
    if cfg.data.n_frames > 1:
        return _video_main(args, cfg)

    root = args.data_root or os.path.join(cfg.data.root, cfg.data.dataset)
    try:
        ds = PairedImageDataset(
            root, "test", cfg.data.direction, cfg.data.image_size,
            cfg.data.image_width,
        )
    except (RuntimeError, FileNotFoundError) as e:
        print(f"no test images under {root}: {e}", file=sys.stderr)
        return 1

    ckpt_dir = os.path.join(
        args.workdir, cfg.train.checkpoint_dir, cfg.data.dataset, cfg.name
    )
    ckpt = CheckpointManager(ckpt_dir)
    step = args.step if args.step is not None else ckpt.latest_step()
    if step is None:
        print(f"no checkpoint found under {ckpt_dir}", file=sys.stderr)
        return 1

    sample = ds[0]
    bs = cfg.data.test_batch_size
    sample_batch = {
        k: np.broadcast_to(v, (bs,) + v.shape).copy() for k, v in sample.items()
    }
    state = create_train_state(cfg, jax.random.key(0), sample_batch)
    state = ckpt.restore(state, step)
    eval_step = build_eval_step(cfg)

    out_dir = args.out or os.path.join(
        args.workdir, cfg.train.result_dir, cfg.data.dataset
    )
    os.makedirs(out_dir, exist_ok=True)

    n_saved = 0
    psnrs, ssims = [], []
    # drop_remainder=False: EVERY test image gets a prediction (the final
    # partial batch costs one extra compile at its smaller shape)
    for batch in make_loader(ds, bs, shuffle=False, num_epochs=1,
                             drop_remainder=False):
        pred, metrics = eval_step(state, batch)
        pred = np.asarray(pred, np.float32)
        if args.metrics:
            psnrs.extend(np.asarray(metrics["psnr"]).ravel().tolist())
            ssims.extend(np.asarray(metrics["ssim"]).ravel().tolist())
        for i in range(pred.shape[0]):
            name = ds.names[n_saved] if n_saved < len(ds.names) else f"{n_saved}.png"
            save_img(pred[i], os.path.join(out_dir, name))
            n_saved += 1
            if n_saved >= len(ds):
                break
        if n_saved >= len(ds):
            break
    print(f"wrote {n_saved} predictions (checkpoint step {step}) to {out_dir}")
    if args.metrics and psnrs:
        print(f"psnr_mean={np.mean(psnrs):.4f} psnr_max={np.max(psnrs):.4f} "
              f"ssim_mean={np.mean(ssims):.4f} ssim_max={np.max(ssims):.4f}")
    return 0


def _video_main(args, cfg) -> int:
    """Clip inference: per-frame predictions written as
    <out>/<video>_<frame>.png (video configs, n_frames>1)."""
    import jax
    import numpy as np

    from p2p_tpu.data.pipeline import make_loader
    from p2p_tpu.data.video import VideoClipDataset
    from p2p_tpu.train.checkpoint import CheckpointManager
    from p2p_tpu.train.video_loop import build_video_eval_step
    from p2p_tpu.train.video_step import create_video_train_state
    from p2p_tpu.utils.images import save_img

    root = args.data_root or os.path.join(cfg.data.root, cfg.data.dataset)
    try:
        ds = VideoClipDataset(
            root, "test", cfg.data.direction, cfg.data.image_size,
            cfg.data.image_width, n_frames=cfg.data.n_frames,
        )
    except (RuntimeError, FileNotFoundError) as e:
        print(f"no test clips under {root}: {e}", file=sys.stderr)
        return 1

    ckpt_dir = os.path.join(
        args.workdir, cfg.train.checkpoint_dir, cfg.data.dataset, cfg.name
    )
    ckpt = CheckpointManager(ckpt_dir)
    step = args.step if args.step is not None else ckpt.latest_step()
    if step is None:
        print(f"no checkpoint found under {ckpt_dir}", file=sys.stderr)
        return 1

    bs = cfg.data.test_batch_size
    sample = ds[0]
    sample_batch = {
        k: np.broadcast_to(v, (bs,) + v.shape).copy() for k, v in sample.items()
    }
    state = create_video_train_state(cfg, jax.random.key(0), sample_batch)
    state = ckpt.restore(state, step)
    eval_step = build_video_eval_step(cfg)

    out_dir = args.out or os.path.join(
        args.workdir, cfg.train.result_dir, cfg.data.dataset
    )
    os.makedirs(out_dir, exist_ok=True)

    n_clip = 0
    n_frames = 0
    psnrs, ssims = [], []
    for batch in make_loader(ds, bs, shuffle=False, num_epochs=1,
                             drop_remainder=False):
        pred, metrics = eval_step(state, batch)
        pred = np.asarray(pred, np.float32)
        if args.metrics:
            psnrs.extend(np.asarray(metrics["psnr"]).ravel().tolist())
            ssims.extend(np.asarray(metrics["ssim"]).ravel().tolist())
        for i in range(pred.shape[0]):
            if n_clip >= len(ds):
                break
            vid, frames = ds.windows[n_clip]
            for t, fname in enumerate(frames):
                stem = os.path.splitext(fname)[0]
                save_img(pred[i, t], os.path.join(out_dir, f"{vid}_{stem}.png"))
                n_frames += 1
            n_clip += 1
    print(f"wrote {n_frames} frames / {n_clip} clips "
          f"(checkpoint step {step}) to {out_dir}")
    if args.metrics and psnrs:
        print(f"psnr_mean={np.mean(psnrs):.4f} psnr_max={np.max(psnrs):.4f} "
              f"ssim_mean={np.mean(ssims):.4f} ssim_max={np.max(ssims):.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
