"""Static-analysis frontend — ``python -m p2p_tpu.cli.lint --strict``.

The standing CI correctness gate (docs/STATIC_ANALYSIS.md). Six analyzers
share one findings format and fail the gate on any unwaived finding:

1. **AST rules** over every module of ``p2p_tpu/`` (traced randomness,
   ``jax.debug`` outside obs, hot-loop host syncs, CLI↔config flag drift).
2. **Collective-consistency checker** (analysis/collective_consistency):
   host-side collectives (the preempt-agreement allgather, eval stat
   combines, registry aggregation) reachable under per-host-divergent
   predicates or after divergent early exits — the multi-host-hang lint.
3. **Concurrency race lint** (analysis/concurrency_lint): signal-handler
   reentrancy, unlocked shared-state mutation in lock-owning classes,
   atexit-vs-thread shutdown ordering.
4. **Sharding audit**: the declarative rule tables (parallel/rules.py)
   statically verified against full-size preset TrainStates built
   shape-only via ``jax.eval_shape``. The facades family audits against
   its PREDICATE-rule TP table (zero tp-diff gaps — drained); the
   remaining families still diff against the replicated table, feeding
   the ROADMAP item-3 worklist (info severity).
5. **Memory audit** (analysis/memory_audit): donation markers on the
   lowered train steps (a declared-donated leaf with no alias/donor
   marker is copied, not donated), the serving dead-restore check, and —
   with ``--memory-budget PATH`` — the per-config×mesh HBM budget table
   written as a JSON artifact (CI uploads it).
6. **jaxpr lint**: the traced-program set — tiny-config eval forward,
   GAN train step (plus a sentinel-enabled variant exercising the
   resolved-callback allow list), the video trainer step, and (given ≥2
   devices) the pipelined ``build_pp_train_step`` program — walked for
   host callbacks, f32 dot/conv leaks under the declared bf16 policy,
   and collectives under ``lax.cond``.

Waivers: ``# p2p-lint: disable=<rule> -- reason`` in source (findings
carry eqn source locations, so even jaxpr findings waive in-source); the
waiver COUNT is printed in the summary — CI logs it on every run, and
tests pin a ceiling so it can only go down.

Exit codes: 0 clean (waived-only), 1 unwaived findings, 2 analyzer crash.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import traceback


def _ensure_fake_devices() -> None:
    """Give the CPU platform 8 fake devices BEFORE jax initializes, so
    the mesh-bearing traced programs (PP) lint everywhere the CLI runs.
    A no-op when jax is already imported (tests set this in conftest)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu static-analysis gate")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings too (the CI mode); default "
                        "fails on errors only")
    p.add_argument("--format", type=str, default="text",
                   choices=["text", "json"],
                   help="findings output format")
    p.add_argument("--tp-diff", action="store_true", dest="tp_diff",
                   help="also print the sharding auditor's tp-vs-rule-"
                        "table migration worklist (ROADMAP item 3), one "
                        "line per leaf")
    p.add_argument("--skip-jaxpr", action="store_true",
                   help="skip the (slower) traced-program analyses — "
                        "jaxpr walks AND the donation audit; AST + "
                        "sharding + dead-restore (+ budget table) only")
    p.add_argument("--memory-budget", type=str, default=None,
                   dest="memory_budget", metavar="PATH",
                   help="ALSO compute the per-config×mesh HBM budget "
                        "table (trace-heavy, ~30 s) and write it to PATH "
                        "as JSON — the CI artifact; its over-budget "
                        "findings join the report")
    p.add_argument("--tp-axis-size", type=int, default=2,
                   help="hypothetical model-axis width for the tp diff")
    p.add_argument("--tp-min-ch", type=int, default=512,
                   help="TP pair-rule channel floor for the tp diff")
    return p


def _tiny_cfg(preset: str = "facades", **model_kw):
    """A preset shrunk to trace-size: same code paths, seconds to trace."""
    from p2p_tpu.core.config import get_preset

    cfg = get_preset(preset)
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, ngf=8, ndf=8, **model_kw),
        data=dataclasses.replace(cfg.data, image_size=16, batch_size=2),
    )


def _sds_tree(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _tiny_batch(cfg, frames: int = 0):
    import jax
    import numpy as np

    bs, (h, w) = cfg.data.batch_size, cfg.image_hw
    lead = (bs, frames) if frames else (bs,)
    return {
        "input": jax.ShapeDtypeStruct(
            lead + (h, w, cfg.model.input_nc), np.uint8),
        "target": jax.ShapeDtypeStruct(
            lead + (h, w, cfg.model.output_nc), np.uint8),
    }


#: the sharding-audit preset set: the facades family audits (and diffs)
#: against its predicate-rule TP table — zero gaps is the drained state —
#: while the ResNet family still diffs against REPLICATED_RULES, feeding
#: the item-3 worklist.
AUDIT_PRESETS = ("facades", "facades_int8", "edges2shoes_dp",
                 "cityscapes_spatial")


def run_sharding_audit(report, tp_axis_size: int, tp_min_ch: int):
    """Audit each preset against ITS rule table (family TP tables where
    drained, replicated elsewhere); returns the remaining tp-diff
    worklist."""
    from p2p_tpu.analysis.sharding_audit import (
        abstract_train_state,
        audit_rules,
        tp_rule_gaps,
    )
    from p2p_tpu.core.config import get_preset
    from p2p_tpu.parallel.rules import (
        REPLICATED_RULES,
        tp_equivalence_rules,
    )

    # the hypothetical target topology: every axis the mesh vocabulary
    # names, sized so divisibility is actually exercised (no devices)
    mesh = {"data": 8, "spatial": 2, "time": 1,
            "model": tp_axis_size, "pipe": 2}
    worklist = []
    for preset in AUDIT_PRESETS:
        cfg = get_preset(preset)
        rules = tp_equivalence_rules(cfg, tp_axis_size, tp_min_ch) \
            or REPLICATED_RULES
        state = abstract_train_state(cfg)
        report.extend(audit_rules(rules, state, mesh))
        wl, findings = tp_rule_gaps(state, rules=rules,
                                    axis_size=tp_axis_size,
                                    min_ch=tp_min_ch)
        for entry in wl:
            entry["preset"] = preset
        worklist.extend(wl)
        report.extend(findings)
    return worklist


def _image_setup():
    """(cfg, abstract state, abstract batch) for the tiny image trainer —
    the ONE construction site shared by the traced analyses."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.train.state import create_train_state

    cfg = _tiny_cfg()
    batch = _tiny_batch(cfg)
    ts = jax.eval_shape(lambda: create_train_state(
        cfg, jax.random.key(0),
        {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()},
        train_dtype=jnp.bfloat16))
    return cfg, _sds_tree(ts), batch


def _video_setup():
    """The video-trainer twin of :func:`_image_setup`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.train.video_step import create_video_train_state

    vcfg = _tiny_cfg("vid2vid_temporal")
    vcfg = dataclasses.replace(
        vcfg, data=dataclasses.replace(vcfg.data, batch_size=1, n_frames=2))
    vbatch = _tiny_batch(vcfg, frames=2)
    vs = jax.eval_shape(lambda: create_video_train_state(
        vcfg, jax.random.key(0),
        {k: np.zeros(v.shape, v.dtype) for k, v in vbatch.items()},
        train_dtype=jnp.bfloat16))
    return vcfg, _sds_tree(vs), vbatch


def run_memory_audit(report, budget_path=None):
    """The trace-free memory checks: the serving dead-restore audit and —
    with ``budget_path`` — the HBM budget table (written as the JSON
    artifact). The donation audit lives with the traced analyses
    (:func:`run_traced_analyses`), where it shares each program's single
    trace."""
    from p2p_tpu.analysis.memory_audit import (
        dead_restore_findings,
        memory_budget_table,
    )

    report.extend(dead_restore_findings())

    if budget_path:
        import json

        rows, findings = memory_budget_table()
        report.extend(findings)
        with open(budget_path, "w") as fh:
            json.dump({"rows": rows}, fh, indent=2)
        print(f"memory budget table: {len(rows)} config×mesh rows -> "
              f"{budget_path}", file=sys.stderr)


def _pp_program():
    """The pipelined train step's jaxpr on a tiny 2-stage mesh, or None
    when fewer than 2 devices are visible (the CLI forces 8 fake CPU
    devices when it owns jax initialization)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    if len(jax.devices()) < 2:
        return None
    from p2p_tpu.parallel.pp import pp_split_state
    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_pp_train_step

    cfg = _tiny_cfg("reference", n_blocks=4)
    bs, (h, w) = cfg.data.batch_size, cfg.image_hw
    sample = {
        "input": np.zeros((bs, h, w, cfg.model.input_nc), np.uint8),
        "target": np.zeros((bs, h, w, cfg.model.output_nc), np.uint8),
    }
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2),
                ("data", "pipe"))
    # pp_split_state stacks + places the trunk: a (tiny) concrete state
    state = create_train_state(cfg, jax.random.key(0), sample,
                               train_dtype=jnp.bfloat16)
    pp_state = pp_split_state(state, cfg, mesh)
    step = build_pp_train_step(cfg, mesh, n_micro=2,
                               train_dtype=jnp.bfloat16, jit=False)
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in sample.items()}
    return jax.make_jaxpr(step)(_sds_tree(pp_state), batch)


def run_traced_analyses(report):
    """The traced-program analyses: jaxpr walks (host callbacks, f32
    leaks under the declared bf16 policy, collectives under ``lax.cond``)
    AND the donation-marker audit — each train-step program is traced
    ONCE (``jit(...).trace``) and both the jaxpr and the lowering come
    from that single trace."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.analysis.collective_consistency import (
        collectives_under_cond,
    )
    from p2p_tpu.analysis.findings import apply_pragma_waivers
    from p2p_tpu.analysis.jaxpr_lint import (
        f32_leak_findings,
        host_callback_findings,
    )
    from p2p_tpu.analysis.memory_audit import donation_findings
    from p2p_tpu.train.state import create_infer_state
    from p2p_tpu.train.step import build_train_step, make_infer_forward

    findings = []

    def walk(jx, tag, allow=()):
        findings.extend(host_callback_findings(jx, tag=tag, allow=allow))
        findings.extend(f32_leak_findings(jx, tag=tag))
        findings.extend(collectives_under_cond(jx, tag=tag))

    cfg, sds, batch = _image_setup()
    sample = {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()}

    # eval/serving forward (metrics tail included — its f32 quality convs
    # are the known, pragma-waived island in losses/metrics.py)
    ist = jax.eval_shape(lambda: create_infer_state(
        cfg, jax.random.key(0), sample, jnp.bfloat16))
    walk(jax.make_jaxpr(make_infer_forward(cfg, jnp.bfloat16))(
        _sds_tree(ist), batch), tag="eval_forward")

    # the full alternating-GAN train step (debug taps at their defaults:
    # a host callback here would fence every training dispatch) — ONE
    # trace of the jitted, donating step serves walks AND donation audit
    tr = build_train_step(cfg, train_dtype=jnp.bfloat16).trace(sds, batch)
    walk(tr.jaxpr, tag="train_step")
    report.extend(donation_findings(tr.lower().as_text(), sds,
                                    tag="train_step", jaxpr=tr.jaxpr))

    # the sentinel-enabled variant: the obs tap's debug_callback is the
    # ONE sanctioned callback — allowed by its RESOLVED target function
    # (obs/taps._on_counts through jax's flat-callback closure and one
    # functools.partial level), so any OTHER callback still flags
    scfg = dataclasses.replace(
        cfg, debug=dataclasses.replace(cfg.debug, nan_sentinel=True))
    walk(jax.make_jaxpr(build_train_step(scfg, train_dtype=jnp.bfloat16,
                                         jit=False))(sds, batch),
         tag="train_step+sentinel", allow=("_on_counts",))

    # the video trainer step (satellite: trace-coverage gap — the video
    # loop's hot path was previously unlinted); same shared-trace shape
    from p2p_tpu.train.video_step import build_video_train_step

    vcfg, vsds, vbatch = _video_setup()
    vtr = build_video_train_step(
        vcfg, train_dtype=jnp.bfloat16).trace(vsds, vbatch)
    walk(vtr.jaxpr, tag="video_train_step")
    report.extend(donation_findings(vtr.lower().as_text(), vsds,
                                    tag="video_train_step",
                                    jaxpr=vtr.jaxpr))

    # the pipelined program (needs >= 2 devices for a real pipe axis)
    pp = _pp_program()
    if pp is not None:
        walk(pp, tag="pp_train_step")
    else:
        print("lint: skipping pp_train_step trace (<2 devices — run with "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              file=sys.stderr)

    report.extend(apply_pragma_waivers(findings))


def run_ast_passes(report):
    """The three AST-family analyzers over ONE package walk and ONE
    parse per module (each lint_package_* entry point re-walks on its
    own — fine for tests, 3× the IO/parse cost for the gate)."""
    import ast

    from p2p_tpu.analysis.ast_rules import lint_source
    from p2p_tpu.analysis.collective_consistency import (
        lint_collective_source,
    )
    from p2p_tpu.analysis.concurrency_lint import lint_concurrency_source
    from p2p_tpu.analysis.findings import (
        ERROR,
        Finding,
        iter_package_sources,
    )

    for rel, text, err in iter_package_sources():
        if text is None:
            report.add(Finding(rule="ast-unreadable", severity=ERROR,
                               file=rel, message=str(err)))
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError:
            report.extend(lint_source(rel, text))  # emits ast-syntax-error
            continue
        report.extend(lint_source(rel, text, tree=tree))
        report.extend(lint_collective_source(rel, text, tree=tree))
        report.extend(lint_concurrency_source(rel, text, tree=tree))


def main(argv=None) -> int:
    _ensure_fake_devices()
    args = build_parser().parse_args(argv)

    from p2p_tpu.analysis.findings import Report

    try:
        report = Report()
        run_ast_passes(report)
        worklist = run_sharding_audit(report, args.tp_axis_size,
                                      args.tp_min_ch)
        run_memory_audit(report, budget_path=args.memory_budget)
        if not args.skip_jaxpr:
            run_traced_analyses(report)
    except Exception:
        traceback.print_exc()
        print("lint: analyzer crashed (exit 2)", file=sys.stderr)
        return 2

    if args.format == "json":
        import json

        payload = json.loads(report.to_json())
        if args.tp_diff:
            # the machine-readable form of the item-3 worklist — the text
            # branch's per-leaf lines, with shapes/specs as fields
            payload["tp_worklist"] = worklist
        print(json.dumps(payload, indent=2))
    else:
        print(report.render())
        if args.tp_diff:
            print(f"\ntp-diff migration worklist ({len(worklist)} leaves "
                  "still need predicate rules — ROADMAP item 3):")
            for entry in worklist:
                print(f"  [{entry['preset']}] {entry['leaf']} "
                      f"shape={entry['shape']} tp={entry['tp_spec']} "
                      f"table={entry['rule_spec']} ({entry['direction']})")
    failing = report.failing(strict=args.strict)
    waived = len(report.waived)
    mode = "strict" if args.strict else "default"
    # json mode keeps stdout machine-parseable: the status line goes to
    # stderr there, stdout in text mode (the CI log greps it)
    status_stream = sys.stderr if args.format == "json" else sys.stdout
    if failing:
        print(f"lint: FAIL ({mode}) — {len(failing)} unwaived finding(s), "
              f"{waived} waiver(s)", file=sys.stderr)
        return 1
    print(f"lint: OK ({mode}) — 0 unwaived findings, {waived} waiver(s) "
          f"carried with reasons, tp worklist {len(worklist)} leaves",
          file=status_stream)
    return 0


if __name__ == "__main__":
    sys.exit(main())
