"""Static-analysis frontend — ``python -m p2p_tpu.cli.lint --strict``.

The standing CI correctness gate (docs/STATIC_ANALYSIS.md). Runs the three
:mod:`p2p_tpu.analysis` analyzers and fails on any unwaived finding:

1. **AST rules** over every module of ``p2p_tpu/`` (traced randomness,
   ``jax.debug`` outside obs, hot-loop host syncs, CLI↔config flag drift).
2. **Sharding audit**: the declarative rule tables (parallel/rules.py)
   statically verified against full-size preset TrainStates built
   shape-only via ``jax.eval_shape`` — dead/shadowed rules, unknown mesh
   axes, indivisible shards. The ``tp``-diff mode additionally reports
   the leaves the regex table cannot yet express vs the hand-built TP
   assignment: the ROADMAP item-3 migration worklist (info severity —
   reported, never failing).
3. **jaxpr lint**: the tiny-config eval forward and full GAN train step
   traced with ``jax.make_jaxpr`` over ``ShapeDtypeStruct`` args (no
   device compute) and walked for host callbacks and f32 dot/conv leaks
   under the declared bf16 policy.

Waivers: ``# p2p-lint: disable=<rule> -- reason`` in source (findings
carry eqn source locations, so even jaxpr findings waive in-source); the
waiver COUNT is printed in the summary — CI logs it on every run.

Exit codes: 0 clean (waived-only), 1 unwaived findings, 2 analyzer crash.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import traceback


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu static-analysis gate")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings too (the CI mode); default "
                        "fails on errors only")
    p.add_argument("--format", type=str, default="text",
                   choices=["text", "json"],
                   help="findings output format")
    p.add_argument("--tp-diff", action="store_true", dest="tp_diff",
                   help="also print the sharding auditor's tp-vs-rule-"
                        "table migration worklist (ROADMAP item 3), one "
                        "line per leaf")
    p.add_argument("--skip-jaxpr", action="store_true",
                   help="skip the (slower) traced-program lint — AST + "
                        "sharding audit only")
    p.add_argument("--tp-axis-size", type=int, default=2,
                   help="hypothetical model-axis width for the tp diff")
    p.add_argument("--tp-min-ch", type=int, default=512,
                   help="TP pair-rule channel floor for the tp diff")
    return p


def _tiny_cfg():
    """facades shrunk to trace-size: same code paths, seconds to trace."""
    from p2p_tpu.core.config import get_preset

    cfg = get_preset("facades")
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, ngf=8, ndf=8),
        data=dataclasses.replace(cfg.data, image_size=16, batch_size=2),
    )


def _sds_tree(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def run_sharding_audit(report, tp_axis_size: int, tp_min_ch: int):
    """Audit the repo's live rule tables against full-size preset states
    (shape-only); returns the tp-diff worklist."""
    from p2p_tpu.analysis.sharding_audit import (
        abstract_train_state,
        audit_rules,
        tp_rule_gaps,
    )
    from p2p_tpu.core.config import get_preset
    from p2p_tpu.parallel.rules import REPLICATED_RULES

    # the hypothetical target topology: every axis the mesh vocabulary
    # names, sized so divisibility is actually exercised (no devices)
    mesh = {"data": 8, "spatial": 2, "time": 1,
            "model": tp_axis_size, "pipe": 2}
    worklist = []
    for preset in ("facades", "cityscapes_spatial"):
        state = abstract_train_state(get_preset(preset))
        report.extend(audit_rules(REPLICATED_RULES, state, mesh))
        wl, findings = tp_rule_gaps(state, rules=REPLICATED_RULES,
                                    axis_size=tp_axis_size,
                                    min_ch=tp_min_ch)
        for entry in wl:
            entry["preset"] = preset
        worklist.extend(wl)
        report.extend(findings)
    return worklist


def run_jaxpr_lint(report):
    """Trace the eval forward and the full GAN train step of the tiny
    config (abstract args — zero device compute) and walk them for host
    callbacks and f32 leaks under the declared bf16 policy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.analysis.findings import apply_pragma_waivers
    from p2p_tpu.analysis.jaxpr_lint import (
        f32_leak_findings,
        host_callback_findings,
    )
    from p2p_tpu.train.state import create_infer_state, create_train_state
    from p2p_tpu.train.step import build_train_step, make_infer_forward

    cfg = _tiny_cfg()
    bs, (h, w) = cfg.data.batch_size, cfg.image_hw
    sample = {"input": np.zeros((bs, h, w, cfg.model.input_nc), np.uint8),
              "target": np.zeros((bs, h, w, cfg.model.output_nc), np.uint8)}
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in sample.items()}

    findings = []
    # eval/serving forward (metrics tail included — its f32 quality convs
    # are the known, pragma-waived island in losses/metrics.py)
    ist = jax.eval_shape(lambda: create_infer_state(
        cfg, jax.random.key(0), sample, jnp.bfloat16))
    jx = jax.make_jaxpr(make_infer_forward(cfg, jnp.bfloat16))(
        _sds_tree(ist), batch)
    findings += host_callback_findings(jx, tag="eval_forward")
    findings += f32_leak_findings(jx, tag="eval_forward")

    # the full alternating-GAN train step (debug taps at their defaults:
    # a host callback here would fence every training dispatch)
    ts = jax.eval_shape(lambda: create_train_state(
        cfg, jax.random.key(0), sample, train_dtype=jnp.bfloat16))
    jx = jax.make_jaxpr(build_train_step(cfg, train_dtype=jnp.bfloat16,
                                         jit=False))(_sds_tree(ts), batch)
    findings += host_callback_findings(jx, tag="train_step")
    findings += f32_leak_findings(jx, tag="train_step")

    report.extend(apply_pragma_waivers(findings))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from p2p_tpu.analysis.ast_rules import lint_package
    from p2p_tpu.analysis.findings import Report

    try:
        report = lint_package()
        worklist = run_sharding_audit(report, args.tp_axis_size,
                                      args.tp_min_ch)
        if not args.skip_jaxpr:
            run_jaxpr_lint(report)
    except Exception:
        traceback.print_exc()
        print("lint: analyzer crashed (exit 2)", file=sys.stderr)
        return 2

    if args.format == "json":
        import json

        payload = json.loads(report.to_json())
        if args.tp_diff:
            # the machine-readable form of the item-3 worklist — the text
            # branch's per-leaf lines, with shapes/specs as fields
            payload["tp_worklist"] = worklist
        print(json.dumps(payload, indent=2))
    else:
        print(report.render())
        if args.tp_diff:
            print(f"\ntp-diff migration worklist ({len(worklist)} leaves "
                  "still need predicate rules — ROADMAP item 3):")
            for entry in worklist:
                print(f"  [{entry['preset']}] {entry['leaf']} "
                      f"shape={entry['shape']} tp={entry['tp_spec']} "
                      f"table={entry['rule_spec']} ({entry['direction']})")
    failing = report.failing(strict=args.strict)
    waived = len(report.waived)
    mode = "strict" if args.strict else "default"
    # json mode keeps stdout machine-parseable: the status line goes to
    # stderr there, stdout in text mode (the CI log greps it)
    status_stream = sys.stderr if args.format == "json" else sys.stdout
    if failing:
        print(f"lint: FAIL ({mode}) — {len(failing)} unwaived finding(s), "
              f"{waived} waiver(s)", file=sys.stderr)
        return 1
    print(f"lint: OK ({mode}) — 0 unwaived findings, {waived} waiver(s) "
          f"carried with reasons, tp worklist {len(worklist)} leaves",
          file=status_stream)
    return 0


if __name__ == "__main__":
    sys.exit(main())
