"""Static-analysis frontend — ``python -m p2p_tpu.cli.lint --strict``.

The standing CI correctness+performance gate (docs/STATIC_ANALYSIS.md).
Eight analyzers share one findings format and fail the gate on any
unwaived finding:

1. **AST rules** over every module of ``p2p_tpu/`` (traced randomness,
   ``jax.debug`` outside obs, hot-loop host syncs, CLI↔config flag drift).
2. **Collective-consistency checker** (analysis/collective_consistency):
   host-side collectives (the preempt-agreement allgather, eval stat
   combines, registry aggregation) reachable under per-host-divergent
   predicates or after divergent early exits — the multi-host-hang lint.
3. **Concurrency race lint** (analysis/concurrency_lint): signal-handler
   reentrancy, unlocked shared-state mutation in lock-owning classes,
   atexit-vs-thread shutdown ordering.
4. **Sharding audit**: the declarative rule tables (parallel/rules.py —
   THE partitioner for the whole TrainState since ISSUE 15) statically
   verified against full-size preset TrainStates built shape-only via
   ``jax.eval_shape``. Every family audits against its predicate-rule
   TP table (zero tp-diff gaps — drained) AND against the composed
   TP+FSDP table on an fsdp-bearing mesh; dead/shadowed fsdp rules fail
   like any other.
5. **Memory audit** (analysis/memory_audit): donation markers on the
   lowered train steps (a declared-donated leaf with no alias/donor
   marker is copied, not donated), the serving dead-restore check, and —
   with ``--memory-budget PATH`` — the per-config×mesh HBM budget table
   written as a JSON artifact (CI uploads it).
6. **jaxpr lint**: the traced-program set — tiny-config eval forward,
   GAN train step (plus a sentinel-enabled variant exercising the
   resolved-callback allow list), the video trainer step, and (given ≥2
   devices) the pipelined ``build_pp_train_step`` program — walked for
   host callbacks, f32 dot/conv leaks under the declared bf16 policy,
   and collectives under ``lax.cond``.
7. **Roofline cost model** (analysis/hlo_cost): per-program FLOPs /
   bytes-moved / arithmetic-intensity over the traced set, published as
   the ``perf_budget.json`` artifact via ``--perf-budget PATH``
   (``memory_budget.json``'s twin) with canonical-row bounds asserted
   (``perf-roofline-out-of-bounds``).
8. **Performance audit** (analysis/perf_audit): the fusion-gap lint
   (``perf-unfused-norm-chain`` over a ``P2P_TPU_FORCE_PALLAS``-traced
   fused program), the collective-overlap audit
   (``perf-serialized-collective`` over the overlap-scheduled PP
   program), and the delayed-int8 coverage worklist (``--int8-diff``,
   mirroring ``--tp-diff``). ISSUE 14 DRAINED the worklist: it audits
   the full-coverage program (``train_step[facades_int8_full]`` =
   ``core.config.int8_full_coverage``, the same override set the
   ``facades_int8_full`` sweep row measures) where every conv/dot is
   either quantized or carries a dated in-source waiver (measured-
   rejected stems/head, per-form dispatch-table backward islands) — CI
   asserts "0 sites" so a lost quantized route or an unknobbed new
   layer reappears as a live worklist line and fails the gate.

Waivers: ``# p2p-lint: disable=<rule> -- reason`` in source (findings
carry eqn source locations, so even jaxpr findings waive in-source); the
waiver COUNT is printed via the ONE shared formatter
(``findings.waiver_summary_line`` — exactly once per run, on the OK and
FAIL paths alike; CI greps the phrase) and tests pin a ceiling so it can
only go down.

Exit codes: 0 clean (waived-only), 1 unwaived findings, 2 analyzer crash.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import traceback


def _ensure_fake_devices() -> None:
    """Give the CPU platform 8 fake devices BEFORE jax initializes, so
    the mesh-bearing traced programs (PP) lint everywhere the CLI runs.
    A no-op when jax is already imported (tests set this in conftest)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu static-analysis gate")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings too (the CI mode); default "
                        "fails on errors only")
    p.add_argument("--format", type=str, default="text",
                   choices=["text", "json"],
                   help="findings output format")
    p.add_argument("--tp-diff", action="store_true", dest="tp_diff",
                   help="also print the sharding auditor's tp-vs-rule-"
                        "table migration worklist (ROADMAP item 3), one "
                        "line per leaf")
    p.add_argument("--int8-diff", action="store_true", dest="int8_diff",
                   help="also print the delayed-int8 coverage worklist "
                        "(ROADMAP item 2, DRAINED by ISSUE 14): every "
                        "conv/dot still contracting in bf16/f32 inside "
                        "the full-coverage int8 program without a dated "
                        "waiver, one line per source site — 0 is the "
                        "gated state")
    p.add_argument("--perf-budget", type=str, default=None,
                   dest="perf_budget", metavar="PATH",
                   help="ALSO write the static roofline table "
                        "(per-program FLOPs / bytes / arithmetic "
                        "intensity over the traced set) to PATH as JSON "
                        "— the CI artifact; canonical rows outside their "
                        "declared bands join the report as warnings")
    p.add_argument("--skip-jaxpr", action="store_true",
                   help="skip the (slower) traced-program analyses — "
                        "jaxpr walks AND the donation audit; AST + "
                        "sharding + dead-restore (+ budget table) only")
    p.add_argument("--memory-budget", type=str, default=None,
                   dest="memory_budget", metavar="PATH",
                   help="ALSO compute the per-config×mesh HBM budget "
                        "table (trace-heavy, ~30 s) and write it to PATH "
                        "as JSON — the CI artifact; its over-budget "
                        "findings join the report")
    p.add_argument("--tp-axis-size", type=int, default=2,
                   help="hypothetical model-axis width for the tp diff")
    p.add_argument("--tp-min-ch", type=int, default=512,
                   help="TP pair-rule channel floor for the tp diff")
    return p


def _tiny_cfg(preset: str = "facades", **model_kw):
    """A preset shrunk to trace-size: same code paths, seconds to trace."""
    from p2p_tpu.core.config import get_preset

    cfg = get_preset(preset)
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, ngf=8, ndf=8, **model_kw),
        data=dataclasses.replace(cfg.data, image_size=16, batch_size=2),
    )


def _sds_tree(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _tiny_batch(cfg, frames: int = 0):
    import jax
    import numpy as np

    bs, (h, w) = cfg.data.batch_size, cfg.image_hw
    lead = (bs, frames) if frames else (bs,)
    return {
        "input": jax.ShapeDtypeStruct(
            lead + (h, w, cfg.model.input_nc), np.uint8),
        "target": jax.ShapeDtypeStruct(
            lead + (h, w, cfg.model.output_nc), np.uint8),
    }


#: the sharding-audit preset set: every family audits (and diffs)
#: against its predicate-rule TP table — zero gaps everywhere is the
#: drained state (ISSUE 13 closed the ResNet/pix2pixHD families; the
#: empty worklist is CI-asserted so a drained family cannot regress).
AUDIT_PRESETS = ("facades", "facades_int8", "edges2shoes_dp",
                 "cityscapes_spatial", "pix2pixhd", "reference")


def run_sharding_audit(report, tp_axis_size: int, tp_min_ch: int):
    """Audit each preset against ITS rule table (family TP tables where
    drained, replicated elsewhere) AND against the composed TP+FSDP
    table on an fsdp mesh (ISSUE 15 — dead/shadowed fsdp rules are lint
    errors like any other); returns the remaining tp-diff worklist."""
    from jax.sharding import PartitionSpec as P

    from p2p_tpu.analysis.sharding_audit import (
        abstract_train_state,
        audit_rules,
        tp_rule_gaps,
    )
    from p2p_tpu.core.config import get_preset
    from p2p_tpu.parallel.rules import (
        REPLICATED_RULES,
        make_fsdp_rules,
        tp_equivalence_rules,
    )

    # the hypothetical target topology: every axis the mesh vocabulary
    # names, sized so divisibility is actually exercised (no devices)
    mesh = {"data": 8, "fsdp": 2, "spatial": 2, "time": 1,
            "model": tp_axis_size, "pipe": 2}
    worklist = []
    for preset in AUDIT_PRESETS:
        cfg = get_preset(preset)
        rules = tp_equivalence_rules(cfg, tp_axis_size, tp_min_ch) \
            or REPLICATED_RULES
        state = abstract_train_state(cfg)
        report.extend(audit_rules(rules, state, mesh))
        # the composed layout the fsdp trainers actually run: the
        # family's TP pairs first, then the ZeRO state rules (params
        # included — the stricter table), then the catch-all
        fsdp_rules = (rules[:-1]
                      + make_fsdp_rules(2, fsdp_params=True)
                      + ((r".*", P()),))
        report.extend(audit_rules(fsdp_rules, state, mesh))
        wl, findings = tp_rule_gaps(state, rules=rules,
                                    axis_size=tp_axis_size,
                                    min_ch=tp_min_ch)
        for entry in wl:
            entry["preset"] = preset
        worklist.extend(wl)
        report.extend(findings)
    return worklist


def _image_setup():
    """(cfg, abstract state, abstract batch) for the tiny image trainer —
    the ONE construction site shared by the traced analyses."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.train.state import create_train_state

    cfg = _tiny_cfg()
    batch = _tiny_batch(cfg)
    ts = jax.eval_shape(lambda: create_train_state(
        cfg, jax.random.key(0),
        {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()},
        train_dtype=jnp.bfloat16))
    return cfg, _sds_tree(ts), batch


def _video_setup():
    """The video-trainer twin of :func:`_image_setup`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.train.video_step import create_video_train_state

    vcfg = _tiny_cfg("vid2vid_temporal")
    vcfg = dataclasses.replace(
        vcfg, data=dataclasses.replace(vcfg.data, batch_size=1, n_frames=2))
    vbatch = _tiny_batch(vcfg, frames=2)
    vs = jax.eval_shape(lambda: create_video_train_state(
        vcfg, jax.random.key(0),
        {k: np.zeros(v.shape, v.dtype) for k, v in vbatch.items()},
        train_dtype=jnp.bfloat16))
    return vcfg, _sds_tree(vs), vbatch


def run_memory_audit(report, budget_path=None):
    """The trace-free memory checks: the serving dead-restore audit and —
    with ``budget_path`` — the HBM budget table (written as the JSON
    artifact). The donation audit lives with the traced analyses
    (:func:`run_traced_analyses`), where it shares each program's single
    trace."""
    from p2p_tpu.analysis.memory_audit import (
        dead_restore_findings,
        memory_budget_table,
    )

    report.extend(dead_restore_findings())

    if budget_path:
        import json

        rows, findings = memory_budget_table()
        report.extend(findings)
        with open(budget_path, "w") as fh:
            json.dump({"rows": rows}, fh, indent=2)
        print(f"memory budget table: {len(rows)} config×mesh rows -> "
              f"{budget_path}", file=sys.stderr)


def _pp_program(overlap: bool = False):
    """The pipelined train step's jaxpr on a tiny 2-stage mesh, or None
    when fewer than 2 devices are visible (the CLI forces 8 fake CPU
    devices when it owns jax initialization). ``overlap=True`` traces the
    latency-hiding schedule — the variant the collective-overlap audit
    and the roofline table pin."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    if len(jax.devices()) < 2:
        return None
    from p2p_tpu.parallel.pp import pp_split_state
    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_pp_train_step

    cfg = _tiny_cfg("reference", n_blocks=4)
    cfg = dataclasses.replace(
        cfg, parallel=dataclasses.replace(cfg.parallel,
                                          pp_overlap=overlap))
    bs, (h, w) = cfg.data.batch_size, cfg.image_hw
    sample = {
        "input": np.zeros((bs, h, w, cfg.model.input_nc), np.uint8),
        "target": np.zeros((bs, h, w, cfg.model.output_nc), np.uint8),
    }
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2),
                ("data", "pipe"))
    # pp_split_state stacks + places the trunk: a (tiny) concrete state
    state = create_train_state(cfg, jax.random.key(0), sample,
                               train_dtype=jnp.bfloat16)
    pp_state = pp_split_state(state, cfg, mesh)
    step = build_pp_train_step(cfg, mesh, n_micro=2,
                               train_dtype=jnp.bfloat16, jit=False)
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in sample.items()}
    return jax.make_jaxpr(step)(_sds_tree(pp_state), batch)


def run_traced_analyses(report, programs=None):
    """The traced-program analyses: jaxpr walks (host callbacks, f32
    leaks under the declared bf16 policy, collectives under ``lax.cond``)
    AND the donation-marker audit — each train-step program is traced
    ONCE (``jit(...).trace``) and both the jaxpr and the lowering come
    from that single trace. ``programs`` (a dict) collects the traced
    jaxprs by row name so the perf analyses / roofline table reuse them
    instead of re-tracing."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.analysis.collective_consistency import (
        collectives_under_cond,
    )
    from p2p_tpu.analysis.findings import apply_pragma_waivers
    from p2p_tpu.analysis.jaxpr_lint import (
        f32_leak_findings,
        host_callback_findings,
    )
    from p2p_tpu.analysis.memory_audit import donation_findings
    from p2p_tpu.train.state import create_infer_state
    from p2p_tpu.train.step import build_train_step, make_infer_forward

    findings = []
    programs = {} if programs is None else programs

    def walk(jx, tag, allow=()):
        findings.extend(host_callback_findings(jx, tag=tag, allow=allow))
        findings.extend(f32_leak_findings(jx, tag=tag))
        findings.extend(collectives_under_cond(jx, tag=tag))

    cfg, sds, batch = _image_setup()
    sample = {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()}

    # eval/serving forward (metrics tail included — its f32 quality convs
    # are the known, pragma-waived island in losses/metrics.py)
    ist = jax.eval_shape(lambda: create_infer_state(
        cfg, jax.random.key(0), sample, jnp.bfloat16))
    jx_eval = jax.make_jaxpr(make_infer_forward(cfg, jnp.bfloat16))(
        _sds_tree(ist), batch)
    programs["eval_forward[facades]"] = jx_eval
    walk(jx_eval, tag="eval_forward")

    # the full alternating-GAN train step (debug taps at their defaults:
    # a host callback here would fence every training dispatch) — ONE
    # trace of the jitted, donating step serves walks AND donation audit
    tr = build_train_step(cfg, train_dtype=jnp.bfloat16).trace(sds, batch)
    programs["train_step[facades]"] = tr.jaxpr
    walk(tr.jaxpr, tag="train_step")
    report.extend(donation_findings(tr.lower().as_text(), sds,
                                    tag="train_step", jaxpr=tr.jaxpr))

    # the sentinel-enabled variant: the obs tap's debug_callback is the
    # ONE sanctioned callback — allowed by its RESOLVED target function
    # (obs/taps._on_counts through jax's flat-callback closure and one
    # functools.partial level), so any OTHER callback still flags
    scfg = dataclasses.replace(
        cfg, debug=dataclasses.replace(cfg.debug, nan_sentinel=True))
    walk(jax.make_jaxpr(build_train_step(scfg, train_dtype=jnp.bfloat16,
                                         jit=False))(sds, batch),
         tag="train_step+sentinel", allow=("_on_counts",))

    # the video trainer step (satellite: trace-coverage gap — the video
    # loop's hot path was previously unlinted); same shared-trace shape
    from p2p_tpu.train.video_step import build_video_train_step

    vcfg, vsds, vbatch = _video_setup()
    vtr = build_video_train_step(
        vcfg, train_dtype=jnp.bfloat16).trace(vsds, vbatch)
    programs["video_train_step[vid2vid_temporal]"] = vtr.jaxpr
    walk(vtr.jaxpr, tag="video_train_step")
    report.extend(donation_findings(vtr.lower().as_text(), vsds,
                                    tag="video_train_step",
                                    jaxpr=vtr.jaxpr))

    # the pipelined program (needs >= 2 devices for a real pipe axis)
    pp = _pp_program()
    if pp is not None:
        walk(pp, tag="pp_train_step")
    else:
        print("lint: skipping pp_train_step trace (<2 devices — run with "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              file=sys.stderr)

    report.extend(apply_pragma_waivers(findings))


def _int8_train_program(full: bool = False):
    """The delayed-int8 GAN train step's jaxpr (tiny facades_int8).

    ``full=True`` traces the FULL-COVERAGE variant
    (``core.config.int8_full_coverage`` — every ISSUE-14 knob on, the
    same override set ``bench.py``'s ``facades_int8_full`` row measures):
    the program the drained int8-coverage worklist audits. The plain
    variant stays the roofline row for the shipping preset (the headline
    bench row's program)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_train_step

    cfg = _tiny_cfg("facades_int8")
    if full:
        from p2p_tpu.core.config import int8_full_coverage

        cfg = int8_full_coverage(cfg)
    batch = _tiny_batch(cfg)
    sds = _sds_tree(jax.eval_shape(lambda: create_train_state(
        cfg, jax.random.key(0),
        {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()},
        train_dtype=jnp.bfloat16)))
    return jax.make_jaxpr(build_train_step(
        cfg, train_dtype=jnp.bfloat16, jit=False))(sds, batch)


def _fused_train_program():
    """The pallas-fused train step's jaxpr: a tiny cityscapes config with
    ``norm=norm_d="pallas_instance"``, traced under
    ``P2P_TPU_FORCE_PALLAS=1`` so the dispatch seam routes to the REAL
    kernel even on a CPU runner — the fusion-gap lint then proves no
    chain silently fell back to the lax reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.train.state import create_train_state
    from p2p_tpu.train.step import build_train_step

    cfg = _tiny_cfg("cityscapes_spatial", norm="pallas_instance",
                    norm_d="pallas_instance")
    batch = _tiny_batch(cfg)
    sds = _sds_tree(jax.eval_shape(lambda: create_train_state(
        cfg, jax.random.key(0),
        {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()},
        train_dtype=jnp.bfloat16)))
    old = os.environ.get("P2P_TPU_FORCE_PALLAS")
    os.environ["P2P_TPU_FORCE_PALLAS"] = "1"
    try:
        return jax.make_jaxpr(build_train_step(
            cfg, train_dtype=jnp.bfloat16, jit=False))(sds, batch)
    finally:
        if old is None:
            os.environ.pop("P2P_TPU_FORCE_PALLAS", None)
        else:
            os.environ["P2P_TPU_FORCE_PALLAS"] = old


def _ensure_perf_programs(programs):
    """Add the perf traced programs (int8 train step, forced-pallas
    fused step, overlap-scheduled PP step — plus the base eval/train/
    video programs when the jaxpr stage didn't already stash them, so
    ``--skip-jaxpr --perf-budget`` still writes the COMPLETE table) to
    ``programs``, tracing each at most once per run."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if "eval_forward[facades]" not in programs \
            or "train_step[facades]" not in programs:
        from p2p_tpu.train.state import create_infer_state
        from p2p_tpu.train.step import build_train_step, make_infer_forward

        cfg, sds, batch = _image_setup()
        sample = {k: np.zeros(v.shape, v.dtype) for k, v in batch.items()}
        ist = jax.eval_shape(lambda: create_infer_state(
            cfg, jax.random.key(0), sample, jnp.bfloat16))
        programs["eval_forward[facades]"] = jax.make_jaxpr(
            make_infer_forward(cfg, jnp.bfloat16))(_sds_tree(ist), batch)
        programs["train_step[facades]"] = jax.make_jaxpr(build_train_step(
            cfg, train_dtype=jnp.bfloat16, jit=False))(sds, batch)
    if "video_train_step[vid2vid_temporal]" not in programs:
        from p2p_tpu.train.video_step import build_video_train_step

        vcfg, vsds, vbatch = _video_setup()
        programs["video_train_step[vid2vid_temporal]"] = jax.make_jaxpr(
            build_video_train_step(vcfg, train_dtype=jnp.bfloat16,
                                   jit=False))(vsds, vbatch)
    if "train_step[facades_int8]" not in programs:
        programs["train_step[facades_int8]"] = _int8_train_program()
    if "train_step[facades_int8_full]" not in programs:
        programs["train_step[facades_int8_full]"] = _int8_train_program(
            full=True)
    if "train_step[cityscapes_pallas]" not in programs:
        programs["train_step[cityscapes_pallas]"] = _fused_train_program()
    if "pp_train_step[reference]" not in programs:
        pp = _pp_program(overlap=True)
        if pp is not None:
            programs["pp_train_step[reference]"] = pp
        else:
            print("lint: skipping pp_train_step perf trace (<2 devices)",
                  file=sys.stderr)
    return programs


def run_perf_analyses(report, programs):
    """Analyzer 8 (analysis/perf_audit): the fusion-gap lint over the
    forced-pallas fused program, the collective-overlap audit over the
    overlap-scheduled PP program, and the delayed-int8 coverage worklist.
    Returns the worklist for ``--int8-diff``."""
    from p2p_tpu.analysis.findings import apply_pragma_waivers
    from p2p_tpu.analysis.perf_audit import (
        int8_coverage,
        serialized_collective_findings,
        unfused_norm_chain_findings,
    )

    _ensure_perf_programs(programs)
    findings = []
    findings.extend(unfused_norm_chain_findings(
        programs["train_step[cityscapes_pallas]"],
        tag="train_step[cityscapes_pallas]"))
    pp = programs.get("pp_train_step[reference]")
    if pp is not None:
        findings.extend(serialized_collective_findings(
            pp, tag="pp_train_step[reference]"))
    # The coverage worklist audits the FULL-COVERAGE program (ISSUE 14
    # drained it): every conv/dot there is either quantized or carries a
    # dated in-source waiver naming its measured-rejected / dispatch-
    # table verdict — waived sites leave the worklist, so "0 sites" is
    # the gate and ANY new bf16/f32 contraction (a lost QuantConv route,
    # a new layer without a knob) reappears as a live worklist line.
    worklist, info = int8_coverage(
        programs["train_step[facades_int8_full]"],
        tag="train_step[facades_int8_full]")
    info = apply_pragma_waivers(info)
    waived_sites = {(f.file, f.line) for f in info if f.waived}
    worklist = [w for w in worklist
                if (w["file"], w["line"]) not in waived_sites]
    report.extend(apply_pragma_waivers(findings))
    report.extend(info)
    return worklist


def run_perf_budget(report, programs, budget_path):
    """Analyzer 7 (analysis/hlo_cost): the static roofline table over
    every traced program, written as the ``perf_budget.json`` artifact
    (``memory_budget.json``'s twin); canonical rows outside their
    declared bands join the report as warnings."""
    import json

    from p2p_tpu.analysis.hlo_cost import CHIP_MODEL, perf_budget_rows

    _ensure_perf_programs(programs)
    rows, findings = perf_budget_rows(sorted(programs.items()))
    report.extend(findings)
    with open(budget_path, "w") as fh:
        json.dump({"chip": CHIP_MODEL, "rows": rows}, fh, indent=2)
    print(f"perf budget table: {len(rows)} roofline rows -> "
          f"{budget_path}", file=sys.stderr)


def run_ast_passes(report):
    """The three AST-family analyzers over ONE package walk and ONE
    parse per module (each lint_package_* entry point re-walks on its
    own — fine for tests, 3× the IO/parse cost for the gate)."""
    import ast

    from p2p_tpu.analysis.ast_rules import lint_source
    from p2p_tpu.analysis.collective_consistency import (
        lint_collective_source,
    )
    from p2p_tpu.analysis.concurrency_lint import lint_concurrency_source
    from p2p_tpu.analysis.findings import (
        ERROR,
        Finding,
        iter_package_sources,
    )

    for rel, text, err in iter_package_sources():
        if text is None:
            report.add(Finding(rule="ast-unreadable", severity=ERROR,
                               file=rel, message=str(err)))
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError:
            report.extend(lint_source(rel, text))  # emits ast-syntax-error
            continue
        report.extend(lint_source(rel, text, tree=tree))
        report.extend(lint_collective_source(rel, text, tree=tree))
        report.extend(lint_concurrency_source(rel, text, tree=tree))


def main(argv=None) -> int:
    _ensure_fake_devices()
    args = build_parser().parse_args(argv)

    from p2p_tpu.analysis.findings import Report

    try:
        report = Report()
        programs = {}   # traced jaxprs by row name, shared across stages
        run_ast_passes(report)
        worklist = run_sharding_audit(report, args.tp_axis_size,
                                      args.tp_min_ch)
        run_memory_audit(report, budget_path=args.memory_budget)
        int8_worklist = []
        if not args.skip_jaxpr:
            run_traced_analyses(report, programs=programs)
            int8_worklist = run_perf_analyses(report, programs)
        if args.perf_budget:
            run_perf_budget(report, programs, args.perf_budget)
    except Exception:
        traceback.print_exc()
        print("lint: analyzer crashed (exit 2)", file=sys.stderr)
        return 2

    if args.format == "json":
        import json

        payload = json.loads(report.to_json())
        if args.tp_diff:
            # the machine-readable form of the item-3 worklist — the text
            # branch's per-leaf lines, with shapes/specs as fields
            payload["tp_worklist"] = worklist
        if args.int8_diff:
            payload["int8_worklist"] = int8_worklist
        print(json.dumps(payload, indent=2))
    else:
        print(report.render())
        if args.tp_diff:
            print(f"\ntp-diff migration worklist ({len(worklist)} leaves "
                  "still need predicate rules — ROADMAP item 3):")
            for entry in worklist:
                print(f"  [{entry['preset']}] {entry['leaf']} "
                      f"shape={entry['shape']} tp={entry['tp_spec']} "
                      f"table={entry['rule_spec']} ({entry['direction']})")
        if args.int8_diff:
            print(f"\nint8-coverage worklist ({len(int8_worklist)} "
                  "conv/dot sites still contract in bf16/f32 under "
                  "delayed-int8 — ROADMAP item 2):")
            for w in int8_worklist:
                loc = f"{w['file']}:{w['line']}" if w["file"] else "<?>"
                print(f"  [{w['program']}] {w['op']} "
                      f"{tuple(w['dtypes'])} out={tuple(w['out_shape'])} "
                      f"{loc} x{w['eqns']}")
    failing = report.failing(strict=args.strict)
    from p2p_tpu.analysis.findings import waiver_summary_line

    # the ONE waiver-count line (findings.waiver_summary_line — the
    # prometheus_exposition pattern: one formatter, every surface), so
    # the CI grep sees it EXACTLY once per run, pass or fail
    waivers = waiver_summary_line(len(report.waived))
    mode = "strict" if args.strict else "default"
    # json mode keeps stdout machine-parseable: the status line goes to
    # stderr there, stdout in text mode (the CI log greps it)
    status_stream = sys.stderr if args.format == "json" else sys.stdout
    if failing:
        print(f"lint: FAIL ({mode}) — {len(failing)} unwaived "
              f"finding(s), {waivers}", file=status_stream)
        return 1
    print(f"lint: OK ({mode}) — 0 unwaived findings, {waivers}, "
          f"tp worklist {len(worklist)} leaves, int8 worklist "
          f"{len(int8_worklist)} sites",
          file=status_stream)
    return 0


if __name__ == "__main__":
    sys.exit(main())
