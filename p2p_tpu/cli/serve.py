"""Serving CLI — a micro-batching frontend over the inference engine.

``python -m p2p_tpu.cli.serve`` watches a directory of request images
(raw files are the "RPC": drop an image in, get its translation out),
groups arrivals into micro-batches (up to ``--max_batch``, lingering at
most ``--linger_ms`` for stragglers), pads each group to an AOT-compiled
bucket, and writes predictions named after their inputs. ``--once``
processes the directory's current contents and exits — the CI smoke mode.

Request semantics per preset family (same as eval — SURVEY Q10): with a
compression net the request image is the TARGET (G runs from its
quantized compressed form); plain pix2pix presets treat it as the INPUT.

Engine policies (params-only restore, buckets, bf16/frozen-int8 dtype,
TP mesh, persistent compilation cache) are shared with cli/infer.py —
see docs/SERVING.md.

Hardening (p2p_tpu.resilience, docs/RESILIENCE.md): the request queue is
BOUNDED (``--max_queue``; overflow arrivals are shed and counted), each
request carries a deadline (``--deadline_ms``; expired requests are
dropped at dispatch, not served late), decode failures retry with backoff
up to ``--max_attempts`` and then the file is MOVED to a quarantine dir
(``--quarantine_dir``, default ``<input_dir>/failed``) so one poison
input can never wedge the server, and predictions are written atomically
(temp + rename — serve/io.py). ``--chaos``/``P2P_CHAOS`` inject faults at
the decode/write seams to rehearse all of the above.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu serving frontend")
    p.add_argument("--preset", type=str, default="reference")
    p.add_argument("--name", type=str, default=None,
                   help="training name (checkpoint subdir; default preset)")
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to serve (default: latest)")
    p.add_argument("--workdir", type=str, default=".")
    p.add_argument("--input_dir", type=str, required=True,
                   help="request directory: image files dropped here are "
                        "served in arrival order")
    p.add_argument("--out", type=str, default=None,
                   help="prediction dir (default <input_dir>_out)")
    p.add_argument("--image_size", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--n_blocks", type=int, default=None)
    p.add_argument("--once", action="store_true",
                   help="serve the directory's current contents, drain, "
                        "exit (CI smoke mode)")
    p.add_argument("--max_requests", type=int, default=None,
                   help="exit after this many served requests (watch mode)")
    p.add_argument("--max_batch", type=int, default=16,
                   help="micro-batch cap (also the largest default bucket)")
    p.add_argument("--linger_ms", type=float, default=50.0,
                   help="max wait for stragglers before dispatching a "
                        "partial micro-batch")
    p.add_argument("--poll_ms", type=float, default=200.0,
                   help="directory scan cadence in watch mode")
    p.add_argument("--buckets", type=str, default=None,
                   help="comma-separated batch buckets (default: powers of "
                        "two up to --max_batch)")
    p.add_argument("--dtype", type=str, default="bf16",
                   choices=["bf16", "f32"])
    p.add_argument("--ema_decay", type=float, default=None,
                   help="the checkpoint was trained with --ema_decay: "
                        "restore the EMA generator weights and serve the "
                        "SMOOTHED G (bitwise == raw at decay 0)")
    p.add_argument("--mesh", type=str, default=None,
                   help="serving mesh 'data,spatial,time[,model]'")
    p.add_argument("--tp_min_ch", type=int, default=None)
    p.add_argument("--io_threads", type=int, default=4)
    p.add_argument("--compilation_cache", type=str, default=None,
                   metavar="DIR")
    # --- resilience knobs (docs/RESILIENCE.md) ---------------------------
    p.add_argument("--max_queue", type=int, default=512,
                   help="request queue depth cap; overflow arrivals are "
                        "SHED (counted, never served) — bounded memory "
                        "and bounded worst-case latency under overload")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="per-request deadline from arrival; requests "
                        "older than this at dispatch time are dropped "
                        "(0 = no deadline)")
    p.add_argument("--max_attempts", type=int, default=3,
                   help="decode attempts per request before the file is "
                        "moved to the quarantine dir")
    p.add_argument("--retry_delay_ms", type=float, default=1000.0,
                   help="base delay between decode attempts (a file still "
                        "being copied in gets this grace window, with "
                        "exponential backoff)")
    p.add_argument("--quarantine_dir", type=str, default=None,
                   help="poison inputs land here after --max_attempts "
                        "failed decodes (default <input_dir>/failed)")
    p.add_argument("--chaos", type=str, default=None, metavar="SPEC",
                   help="arm fault injection, e.g. 'decode:0.3' or "
                        "'serve_write:0.2x5' (p2p_tpu.resilience.chaos; "
                        "P2P_CHAOS env works too)")
    return p


def default_buckets(max_batch: int):
    """1, 2, 4, ... up to (and including) max_batch — a request group of
    any size <= max_batch pads to at most 2× its images."""
    b, out = 1, []
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(sorted(set(out)))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import dataclasses

    from p2p_tpu.cli import apply_overrides as over
    from p2p_tpu.cli.infer import _parse_mesh
    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.generate import is_image_file
    from p2p_tpu.data.pipeline import load_image
    from p2p_tpu.serve import engine_from_checkpoint

    cfg = get_preset(args.preset)
    if cfg.data.n_frames > 1:
        print("serve covers image presets; use cli/infer.py for video",
              file=sys.stderr)
        return 2
    data = over(cfg.data, dataset=args.dataset, image_size=args.image_size)
    model = over(cfg.model, ngf=args.ngf, n_blocks=args.n_blocks)
    health = over(cfg.health, ema_decay=args.ema_decay)
    cfg = dataclasses.replace(cfg, data=data, model=model, health=health,
                              name=args.name or cfg.name)

    h, w = cfg.image_hw
    as_uint8 = cfg.data.uint8_pipeline

    def decode(path):
        # eval semantics: the request image drives whichever slot the
        # preset reads (target for compression-net presets, input
        # otherwise); the engine's batch spec names the keys it compiled.
        # The `decode` chaos seam lives HERE, not in load_image — serving
        # has retry/quarantine around this call; training decode fails
        # fast and must never see injected faults.
        from p2p_tpu.resilience.chaos import chaos_point

        chaos_point("decode")
        return load_image(path, h, w, as_uint8=as_uint8)

    buckets = ([int(b) for b in args.buckets.split(",")] if args.buckets
               else default_buckets(args.max_batch))
    ckpt_dir = os.path.join(
        args.workdir, cfg.train.checkpoint_dir, cfg.data.dataset, cfg.name
    )
    sample = np.zeros((1, h, w, cfg.model.input_nc),
                      np.uint8 if as_uint8 else np.float32)
    sample_batch = {"input": sample, "target": sample}
    try:
        engine, step = engine_from_checkpoint(
            cfg, ckpt_dir, sample_batch, step=args.step,
            buckets=buckets, dtype=args.dtype,
            mesh=_parse_mesh(args.mesh), tp_min_ch=args.tp_min_ch,
            with_metrics=False,  # requests carry no ground truth
            compilation_cache_dir=args.compilation_cache,
            io_workers=args.io_threads,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    t0 = time.perf_counter()
    engine.warmup()
    print(f"serving checkpoint step {step}: {len(engine.buckets)} bucket "
          f"programs compiled in {time.perf_counter() - t0:.2f}s "
          f"(buckets {list(engine.buckets)})", flush=True)

    out_dir = args.out or args.input_dir.rstrip("/") + "_out"
    os.makedirs(out_dir, exist_ok=True)
    from p2p_tpu.obs import get_registry
    from p2p_tpu.resilience import (
        BoundedRequestQueue,
        ChaosMonkey,
        Quarantine,
        install_chaos,
    )

    reg = get_registry()
    prev_chaos = None
    if args.chaos:
        prev_chaos = install_chaos(
            ChaosMonkey.from_spec(args.chaos, registry=reg))
    queue = BoundedRequestQueue(
        max_depth=args.max_queue,
        deadline_s=(args.deadline_ms / 1e3) if args.deadline_ms > 0 else None,
        registry=reg,
    )
    quarantine = Quarantine(
        args.quarantine_dir or os.path.join(args.input_dir, "failed"),
        registry=reg,
    )
    from p2p_tpu.serve import AsyncImageWriter

    # fail_fast=False: a poison OUTPUT path (directory squatting on the
    # target name, dead volume) is recorded + counted, never fatal — the
    # write-side analog of decode quarantine
    writer = AsyncImageWriter(args.io_threads, fail_fast=False)
    served = 0
    keys = list(engine.batch_keys)
    retry_delay = args.retry_delay_ms / 1e3

    # requests queue as NAMES (BoundedRequestQueue of file names); decode
    # happens per micro-batch at dispatch time (a 10k-file backlog must
    # not be decoded into host RAM — or delay the first response — before
    # the first batch ships)
    def dispatch(group_reqs):
        """One micro-batch of requests: decode → engine → writer.

        A failed decode (file still being copied in, injected chaos, real
        corruption) re-enters the queue with exponential backoff up to
        --max_attempts; after that the file is MOVED to the quarantine
        dir — capped attempts, and a permanently-corrupt input can never
        be re-enqueued again. One bad request must never kill the server.
        """
        nonlocal served
        group = []
        for req in group_reqs:
            path = os.path.join(args.input_dir, req.name)
            try:
                group.append((req, decode(path)))
            except Exception as e:
                req.attempts += 1
                if req.attempts >= args.max_attempts:
                    dest = quarantine.quarantine(
                        path, f"{req.attempts} failed decodes; last: {e!r}")
                    print(f"WARNING: quarantined request {req.name!r} "
                          f"after {req.attempts} failed decodes → "
                          f"{dest or 'GONE'}: {e}",
                          file=sys.stderr, flush=True)
                else:
                    # exponential backoff on the re-enqueue — this IS the
                    # decode retry path (the dispatch loop must not sleep,
                    # so backoff lives in the queue, not a blocking
                    # retry_call). A full queue sheds the retry; dropping
                    # the name from `seen` lets a later, quieter scan
                    # re-offer the file instead of stranding it unserved.
                    if queue.requeue(
                            req, retry_delay * (2.0 ** (req.attempts - 1))):
                        reg.counter("retry_attempts_total",
                                    seam="decode").inc()
                    else:
                        seen.discard(req.name)
                        print(f"WARNING: queue full — decode retry for "
                              f"{req.name!r} shed; the file stays in the "
                              "input dir for a later scan",
                              file=sys.stderr, flush=True)
        if not group:
            return
        stack = np.stack([img for _, img in group])
        batch = {k: stack for k in keys}
        pred, _, n_real = engine.infer_batch(batch)
        paths = [os.path.join(out_dir,
                              os.path.splitext(req.name)[0] + ".png")
                 for req, _ in group]
        writer.submit_batch(pred, paths)
        served += len(group)

    # a custom --buckets list may top out below --max_batch: micro-batches
    # are capped at whichever is smaller, so dispatch never overflows the
    # largest compiled bucket (engine.stream would chunk; infer_batch won't)
    group_cap = min(args.max_batch, engine.buckets[-1])

    def drain_queue():
        """Dispatch everything currently DISPATCHABLE (not in a backoff
        window); expired requests are dropped — an answer after the
        deadline serves nobody — with their files left in place."""
        while True:
            ready, expired = queue.take(group_cap)
            for req in expired:
                print(f"note: request {req.name!r} exceeded its "
                      f"{args.deadline_ms:.0f} ms deadline — dropped",
                      file=sys.stderr, flush=True)
            if not ready:
                break
            dispatch(ready)

    seen = set()

    def scan():
        """Enqueue new arrivals; a full queue sheds them (counted). A
        shed arrival is dropped from `seen` so a later, quieter scan can
        re-offer the file — under transient overload shedding defers
        service rather than permanently denying it (watch mode; --once
        scans exactly once, so its sheds are final)."""
        try:
            entries = sorted(os.listdir(args.input_dir))
        except FileNotFoundError:
            return 0
        fresh = 0
        shed_now = 0
        for f in entries:
            if f in seen or not is_image_file(f):
                continue
            seen.add(f)
            if queue.offer(f):
                fresh += 1
            else:
                seen.discard(f)
                shed_now += 1
        if shed_now:
            print(f"WARNING: queue full ({args.max_queue}) — shed "
                  f"{shed_now} arrivals (files stay in the input dir for "
                  "a later scan)", file=sys.stderr, flush=True)
        return fresh

    try:
        scan()
        if args.once:
            drain_queue()
            while len(queue):    # wait out retry-backoff windows, then finish
                time.sleep(min(retry_delay / 2, 0.25))
                drain_queue()
        else:
            try:
                linger_start = time.perf_counter() if len(queue) else None
                while args.max_requests is None or served < args.max_requests:
                    if len(queue) >= args.max_batch or (
                        len(queue)
                        and linger_start is not None
                        and (time.perf_counter() - linger_start) * 1e3
                        >= args.linger_ms
                    ):
                        drain_queue()
                        linger_start = None
                    time.sleep(args.poll_ms / 1e3 if not len(queue) else
                               args.linger_ms / 1e3)
                    scan()
                    if len(queue) and linger_start is None:
                        linger_start = time.perf_counter()
            except KeyboardInterrupt:
                drain_queue()
        n_written = writer.drain()
        writer.close()
        for path, err in writer.write_errors:
            print(f"WARNING: prediction write failed permanently for "
                  f"{path!r}: {err}", file=sys.stderr, flush=True)
    finally:
        if args.chaos:
            # disarm even on a crashed serve: chaos is process-global and
            # in-process callers (tests) must not inherit the fault spec
            install_chaos(prev_chaos)
    wall = time.perf_counter() - t0

    print(json.dumps({
        "kind": "serve_summary", "served": served, "written": n_written,
        "out_dir": out_dir, "buckets": list(engine.buckets),
        "n_compiles": engine.n_compiles,
        "encode_sec": round(writer.encode_sec, 4),
        "wall_sec": round(wall, 4),
        "shed": queue.shed_count,
        "deadline_expired": queue.expired_count,
        "quarantined": quarantine.count,
        "write_failures": len(writer.write_errors),
        "decode_retries": int(reg.counter(
            "retry_attempts_total", seam="decode").value),
        "write_retries": int(reg.counter(
            "retry_attempts_total", seam="serve_write").value),
        "chaos_injected": int(reg.total("chaos_injected_total")),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
