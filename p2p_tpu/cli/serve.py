"""Serving CLI — a micro-batching frontend over the inference engine.

``python -m p2p_tpu.cli.serve`` watches a directory of request images
(raw files are the "RPC": drop an image in, get its translation out),
groups arrivals into micro-batches (up to ``--max_batch``, lingering at
most ``--linger_ms`` for stragglers), pads each group to an AOT-compiled
bucket, and writes predictions named after their inputs. ``--once``
processes the directory's current contents and exits — the CI smoke mode.

Request semantics per preset family (same as eval — SURVEY Q10): with a
compression net the request image is the TARGET (G runs from its
quantized compressed form); plain pix2pix presets treat it as the INPUT.

Engine policies (params-only restore, buckets, bf16/frozen-int8 dtype,
TP mesh, persistent compilation cache) are shared with cli/infer.py —
see docs/SERVING.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu serving frontend")
    p.add_argument("--preset", type=str, default="reference")
    p.add_argument("--name", type=str, default=None,
                   help="training name (checkpoint subdir; default preset)")
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to serve (default: latest)")
    p.add_argument("--workdir", type=str, default=".")
    p.add_argument("--input_dir", type=str, required=True,
                   help="request directory: image files dropped here are "
                        "served in arrival order")
    p.add_argument("--out", type=str, default=None,
                   help="prediction dir (default <input_dir>_out)")
    p.add_argument("--image_size", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--n_blocks", type=int, default=None)
    p.add_argument("--once", action="store_true",
                   help="serve the directory's current contents, drain, "
                        "exit (CI smoke mode)")
    p.add_argument("--max_requests", type=int, default=None,
                   help="exit after this many served requests (watch mode)")
    p.add_argument("--max_batch", type=int, default=16,
                   help="micro-batch cap (also the largest default bucket)")
    p.add_argument("--linger_ms", type=float, default=50.0,
                   help="max wait for stragglers before dispatching a "
                        "partial micro-batch")
    p.add_argument("--poll_ms", type=float, default=200.0,
                   help="directory scan cadence in watch mode")
    p.add_argument("--buckets", type=str, default=None,
                   help="comma-separated batch buckets (default: powers of "
                        "two up to --max_batch)")
    p.add_argument("--dtype", type=str, default="bf16",
                   choices=["bf16", "f32"])
    p.add_argument("--mesh", type=str, default=None,
                   help="serving mesh 'data,spatial,time[,model]'")
    p.add_argument("--tp_min_ch", type=int, default=None)
    p.add_argument("--io_threads", type=int, default=4)
    p.add_argument("--compilation_cache", type=str, default=None,
                   metavar="DIR")
    return p


def default_buckets(max_batch: int):
    """1, 2, 4, ... up to (and including) max_batch — a request group of
    any size <= max_batch pads to at most 2× its images."""
    b, out = 1, []
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(sorted(set(out)))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import dataclasses

    from p2p_tpu.cli import apply_overrides as over
    from p2p_tpu.cli.infer import _parse_mesh
    from p2p_tpu.core.config import get_preset
    from p2p_tpu.data.generate import is_image_file
    from p2p_tpu.data.pipeline import load_image
    from p2p_tpu.serve import engine_from_checkpoint

    cfg = get_preset(args.preset)
    if cfg.data.n_frames > 1:
        print("serve covers image presets; use cli/infer.py for video",
              file=sys.stderr)
        return 2
    data = over(cfg.data, dataset=args.dataset, image_size=args.image_size)
    model = over(cfg.model, ngf=args.ngf, n_blocks=args.n_blocks)
    cfg = dataclasses.replace(cfg, data=data, model=model,
                              name=args.name or cfg.name)

    h, w = cfg.image_hw
    as_uint8 = cfg.data.uint8_pipeline

    def decode(path):
        # eval semantics: the request image drives whichever slot the
        # preset reads (target for compression-net presets, input
        # otherwise); the engine's batch spec names the keys it compiled
        return load_image(path, h, w, as_uint8=as_uint8)

    buckets = ([int(b) for b in args.buckets.split(",")] if args.buckets
               else default_buckets(args.max_batch))
    ckpt_dir = os.path.join(
        args.workdir, cfg.train.checkpoint_dir, cfg.data.dataset, cfg.name
    )
    sample = np.zeros((1, h, w, cfg.model.input_nc),
                      np.uint8 if as_uint8 else np.float32)
    sample_batch = {"input": sample, "target": sample}
    try:
        engine, step = engine_from_checkpoint(
            cfg, ckpt_dir, sample_batch, step=args.step,
            buckets=buckets, dtype=args.dtype,
            mesh=_parse_mesh(args.mesh), tp_min_ch=args.tp_min_ch,
            with_metrics=False,  # requests carry no ground truth
            compilation_cache_dir=args.compilation_cache,
            io_workers=args.io_threads,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    t0 = time.perf_counter()
    engine.warmup()
    print(f"serving checkpoint step {step}: {len(engine.buckets)} bucket "
          f"programs compiled in {time.perf_counter() - t0:.2f}s "
          f"(buckets {list(engine.buckets)})", flush=True)

    out_dir = args.out or args.input_dir.rstrip("/") + "_out"
    os.makedirs(out_dir, exist_ok=True)
    from p2p_tpu.serve import AsyncImageWriter

    writer = AsyncImageWriter(args.io_threads)
    served = 0
    keys = list(engine.batch_keys)
    # requests queue as NAMES; decode happens per micro-batch at dispatch
    # time (a 10k-file backlog must not be decoded into host RAM — or
    # delay the first response — before the first batch ships)
    attempts: dict = {}
    retry_at: dict = {}          # name → monotonic time it may retry
    MAX_ATTEMPTS = 3
    RETRY_DELAY = 1.0            # seconds between attempts: a file still
    #                              being copied in gets a ~3 s grace window

    def dispatch(group_names):
        """One micro-batch of request names: decode → engine → writer.
        A file that fails to decode (e.g. still being copied in) is
        scheduled for retry RETRY_DELAY later, up to MAX_ATTEMPTS, then
        dropped with a warning — one bad request must never kill the
        server."""
        nonlocal served
        group = []
        for name in group_names:
            try:
                group.append((name, decode(os.path.join(args.input_dir,
                                                        name))))
            except Exception as e:
                attempts[name] = attempts.get(name, 0) + 1
                if attempts[name] < MAX_ATTEMPTS:
                    retry_at[name] = time.monotonic() + RETRY_DELAY
                else:
                    print(f"WARNING: dropping request {name!r} after "
                          f"{attempts[name]} failed decodes: {e}",
                          file=sys.stderr, flush=True)
        if not group:
            return
        stack = np.stack([img for _, img in group])
        batch = {k: stack for k in keys}
        pred, _, n_real = engine.infer_batch(batch)
        paths = [os.path.join(out_dir,
                              os.path.splitext(name)[0] + ".png")
                 for name, _ in group]
        writer.submit_batch(pred, paths)
        served += len(group)

    def collect_retries():
        """Requests whose retry time has come — re-enter the queue."""
        now = time.monotonic()
        ready = [n for n, t in retry_at.items() if t <= now]
        for n in ready:
            del retry_at[n]
        return ready

    # a custom --buckets list may top out below --max_batch: micro-batches
    # are capped at whichever is smaller, so dispatch never overflows the
    # largest compiled bucket (engine.stream would chunk; infer_batch won't)
    group_cap = min(args.max_batch, engine.buckets[-1])

    def drain_queue(queue):
        while queue:
            work = queue[:]
            del queue[:]
            for i in range(0, len(work), group_cap):
                dispatch(work[i : i + group_cap])

    seen = set()

    def scan():
        fresh = []
        try:
            entries = sorted(os.listdir(args.input_dir))
        except FileNotFoundError:
            return fresh
        for f in entries:
            if f in seen or not is_image_file(f):
                continue
            seen.add(f)
            fresh.append(f)
        return fresh

    queue = scan()
    if args.once:
        drain_queue(queue)
        while retry_at:          # wait out the retry windows, then finish
            time.sleep(RETRY_DELAY / 2)
            queue.extend(collect_retries())
            drain_queue(queue)
    else:
        try:
            linger_start = time.perf_counter() if queue else None
            while args.max_requests is None or served < args.max_requests:
                if len(queue) >= args.max_batch or (
                    queue
                    and linger_start is not None
                    and (time.perf_counter() - linger_start) * 1e3
                    >= args.linger_ms
                ):
                    drain_queue(queue)
                    linger_start = None
                time.sleep(args.poll_ms / 1e3 if not queue else
                           args.linger_ms / 1e3)
                fresh = scan() + collect_retries()
                if fresh and not queue:
                    linger_start = time.perf_counter()
                queue.extend(fresh)
        except KeyboardInterrupt:
            drain_queue(queue)
    n_written = writer.drain()
    writer.close()
    wall = time.perf_counter() - t0
    print(json.dumps({
        "kind": "serve_summary", "served": served, "written": n_written,
        "out_dir": out_dir, "buckets": list(engine.buckets),
        "n_compiles": engine.n_compiles,
        "encode_sec": round(writer.encode_sec, 4),
        "wall_sec": round(wall, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
