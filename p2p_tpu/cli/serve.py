"""Serving CLI — directory-watching and HTTP frontends over the engine.

Two transports, ONE hardened request lifecycle (p2p_tpu/serve/frontend.py
— bounded queue, load shedding, deadlines, decode-retry with backoff,
poison quarantine, bucket-occupancy accounting):

**Directory mode** (default): ``python -m p2p_tpu.cli.serve`` watches a
directory of request images (raw files are the "RPC": drop an image in,
get its translation out), groups arrivals into micro-batches (up to
``--max_batch``, lingering at most ``--linger_ms`` for stragglers), pads
each group to an AOT-compiled bucket, and writes predictions named after
their inputs. ``--once`` processes the directory's current contents and
exits — the CI smoke mode.

**HTTP mode** (``--http HOST:PORT``): the network-native frontend
(p2p_tpu/serve/server.py) — ``POST /v1/{model}/translate`` with an image
body returns the translated PNG; ``/healthz``; Prometheus ``/metrics``;
``POST /admin/reload`` hot-swaps a tenant's weights with zero downtime.
``--tenant`` (repeatable) makes N models resident in this one process,
each with its own engine and bucket programs, sharing the persistent
compilation cache; requests are batched CONTINUOUSLY across concurrent
in-flight connections (serve/batcher.py). SIGTERM drains gracefully
(stop accepting → run queues down → exit 0). Full API + runbook:
docs/SERVING.md.

Request semantics per preset family (same as eval — SURVEY Q10): with a
compression net the request image is the TARGET (G runs from its
quantized compressed form); plain pix2pix presets treat it as the INPUT.

Engine policies (params-only restore, buckets, bf16/frozen-int8 dtype,
TP mesh, persistent compilation cache) are shared with cli/infer.py —
see docs/SERVING.md.

Hardening (p2p_tpu.resilience, docs/RESILIENCE.md): the request queue is
BOUNDED (``--max_queue``; overflow arrivals are shed and counted), each
request carries a deadline (``--deadline_ms``; expired requests are
dropped at dispatch, not served late), decode failures retry with backoff
up to ``--max_attempts`` and then the file is MOVED to a quarantine dir
(``--quarantine_dir``, default ``<input_dir>/failed``) so one poison
input can never wedge the server, and predictions are written atomically
(temp + rename — serve/io.py). Over HTTP the same ladder answers in
status codes: shed → 429, deadline → 504, poison → 422, draining → 503.
``--chaos``/``P2P_CHAOS`` inject faults at the decode/write seams to
rehearse all of the above.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from p2p_tpu.serve.frontend import default_buckets  # noqa: F401 — re-export


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu serving frontend")
    p.add_argument("--preset", type=str, default="reference")
    p.add_argument("--name", type=str, default=None,
                   help="training name (checkpoint subdir; default preset)")
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to serve (default: latest)")
    p.add_argument("--workdir", type=str, default=".")
    p.add_argument("--input_dir", type=str, default=None,
                   help="directory mode's request directory: image files "
                        "dropped here are served in arrival order "
                        "(required unless --http)")
    p.add_argument("--out", type=str, default=None,
                   help="prediction dir (default <input_dir>_out)")
    p.add_argument("--image_size", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--n_blocks", type=int, default=None)
    p.add_argument("--once", action="store_true",
                   help="serve the directory's current contents, drain, "
                        "exit (CI smoke mode)")
    p.add_argument("--max_requests", type=int, default=None,
                   help="exit after this many served requests (watch mode)")
    p.add_argument("--max_batch", type=int, default=16,
                   help="micro-batch cap (also the largest default bucket)")
    p.add_argument("--linger_ms", type=float, default=50.0,
                   help="max wait for stragglers before dispatching a "
                        "partial micro-batch")
    p.add_argument("--poll_ms", type=float, default=200.0,
                   help="directory scan cadence in watch mode")
    p.add_argument("--buckets", type=str, default=None,
                   help="comma-separated batch buckets (default: powers of "
                        "two up to --max_batch)")
    p.add_argument("--dtype", type=str, default="bf16",
                   choices=["bf16", "f32"])
    p.add_argument("--ema_decay", type=float, default=None,
                   help="the checkpoint was trained with --ema_decay: "
                        "restore the EMA generator weights and serve the "
                        "SMOOTHED G (bitwise == raw at decay 0)")
    p.add_argument("--mesh", type=str, default=None,
                   help="serving mesh: positional 'data,spatial,time"
                        "[,model]' or named 'axis=size,...'")
    p.add_argument("--tp_min_ch", type=int, default=None)
    p.add_argument("--io_threads", type=int, default=4)
    p.add_argument("--compilation_cache", type=str, default=None,
                   metavar="DIR")
    # --- network frontend (docs/SERVING.md "HTTP API") -------------------
    p.add_argument("--http", type=str, default=None, metavar="HOST:PORT",
                   help="serve over HTTP instead of a watched directory "
                        "(e.g. '0.0.0.0:8000'; ':0' binds an ephemeral "
                        "port). POST /v1/<tenant>/translate, /healthz, "
                        "/metrics, POST /admin/reload")
    p.add_argument("--tenant", action="append", default=None,
                   metavar="SPEC",
                   help="HTTP mode: make a model resident, repeatable. "
                        "SPEC is comma-separated key=value overriding the "
                        "base flags, e.g. 'alias=hd,preset=pix2pixhd,"
                        "name=run3,step=2000' (keys: alias preset name "
                        "dataset step image_size ngf n_blocks ema_decay). "
                        "Default: one tenant from the base flags")
    p.add_argument("--drain_timeout", type=float, default=30.0,
                   help="HTTP mode: max seconds after SIGTERM to run the "
                        "queues down before stragglers are answered 503")
    p.add_argument("--tenant_quota", type=int, default=None,
                   help="HTTP mode: max in-flight requests PER TENANT "
                        "(admitted, not yet answered); arrivals beyond "
                        "it get 429 + serve_quota_rejected_total — the "
                        "fairness cap so one tenant's burst cannot "
                        "starve the other tenants' queue slots "
                        "(default: unlimited)")
    # --- resilience knobs (docs/RESILIENCE.md) ---------------------------
    p.add_argument("--max_queue", type=int, default=512,
                   help="request queue depth cap; overflow arrivals are "
                        "SHED (counted, never served) — bounded memory "
                        "and bounded worst-case latency under overload")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="per-request deadline from arrival; requests "
                        "older than this at dispatch time are dropped "
                        "(0 = no deadline)")
    p.add_argument("--max_attempts", type=int, default=3,
                   help="decode attempts per request before the file is "
                        "moved to the quarantine dir (HTTP: before the "
                        "request is answered 422)")
    p.add_argument("--retry_delay_ms", type=float, default=1000.0,
                   help="base delay between decode attempts (a file still "
                        "being copied in gets this grace window, with "
                        "exponential backoff)")
    p.add_argument("--quarantine_dir", type=str, default=None,
                   help="poison inputs land here after --max_attempts "
                        "failed decodes (default <input_dir>/failed)")
    p.add_argument("--chaos", type=str, default=None, metavar="SPEC",
                   help="arm fault injection, e.g. 'decode:0.3' or "
                        "'serve_write:0.2x5' (p2p_tpu.resilience.chaos; "
                        "P2P_CHAOS env works too)")
    return p


def _build_config(args, overrides=None):
    """One tenant's Config from the base flags plus optional per-tenant
    SPEC overrides ({key: str})."""
    import dataclasses

    from p2p_tpu.cli import apply_overrides as over
    from p2p_tpu.core.config import get_preset

    ov = dict(overrides or {})
    preset = ov.get("preset", args.preset)
    cfg = get_preset(preset)

    def _get(key, cast, default):
        if key in ov:
            return cast(ov[key])
        return default

    data = over(cfg.data,
                dataset=_get("dataset", str, args.dataset),
                image_size=_get("image_size", int, args.image_size))
    model = over(cfg.model, ngf=_get("ngf", int, args.ngf),
                 n_blocks=_get("n_blocks", int, args.n_blocks))
    health = over(cfg.health,
                  ema_decay=_get("ema_decay", float, args.ema_decay))
    name = _get("name", str, args.name) or cfg.name
    return dataclasses.replace(cfg, data=data, model=model, health=health,
                               name=name)


def _parse_tenant_spec(spec: str):
    """'alias=hd,preset=pix2pixhd,step=2000' → (alias, {key: value})."""
    allowed = {"alias", "preset", "name", "dataset", "step", "image_size",
               "ngf", "n_blocks", "ema_decay"}
    kv = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        if not eq or k not in allowed:
            raise ValueError(
                f"bad --tenant entry {part!r} (allowed keys: "
                f"{sorted(allowed)})")
        kv[k] = v
    alias = kv.pop("alias", None) or kv.get("name") or kv.get("preset")
    if not alias:
        raise ValueError(f"--tenant {spec!r} needs an alias= (or name=/"
                         "preset= to derive one)")
    return alias, kv


def _engine_kw(args, buckets):
    from p2p_tpu.cli.infer import _parse_mesh

    return dict(
        buckets=buckets, dtype=args.dtype, mesh=_parse_mesh(args.mesh),
        tp_min_ch=args.tp_min_ch, with_metrics=False,
        compilation_cache_dir=args.compilation_cache,
        io_workers=args.io_threads,
    )


def _serve_http(args, buckets) -> int:
    """The network frontend: N resident tenants, continuous batching,
    hot-swap, graceful drain (p2p_tpu/serve/server.py)."""
    from p2p_tpu.obs import get_registry
    from p2p_tpu.resilience import ChaosMonkey, install_chaos
    from p2p_tpu.serve.server import ServeApp, run_server
    from p2p_tpu.serve.tenancy import Tenant, checkpoint_dir

    host, _, port = args.http.rpartition(":")
    host = host or "0.0.0.0"
    try:
        port = int(port)
    except ValueError:
        print(f"--http wants HOST:PORT, got {args.http!r}",
              file=sys.stderr)
        return 2
    reg = get_registry()
    try:
        specs = ([_parse_tenant_spec(s) for s in args.tenant]
                 if args.tenant else [(None, {})])
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    prev_chaos = None
    if args.chaos:
        prev_chaos = install_chaos(
            ChaosMonkey.from_spec(args.chaos, registry=reg))
    app = ServeApp(
        registry=reg, io_threads=args.io_threads,
        max_queue=args.max_queue, deadline_ms=args.deadline_ms,
        linger_ms=args.linger_ms, group_cap=args.max_batch,
        max_attempts=args.max_attempts,
        retry_delay_ms=args.retry_delay_ms,
        tenant_quota=args.tenant_quota)
    try:
        for alias, ov in specs:
            cfg = _build_config(args, ov)
            alias = alias or cfg.name
            if alias in app.tenants:
                # caught BEFORE the (expensive) restore + AOT warmup —
                # two specs deriving the same alias is a flag error
                print(f"duplicate tenant alias {alias!r} — give each "
                      "--tenant a distinct alias=", file=sys.stderr)
                return 2
            step = int(ov["step"]) if "step" in ov else args.step
            t0 = time.perf_counter()
            try:
                tenant = Tenant(
                    alias, cfg, checkpoint_dir(cfg, args.workdir),
                    step=step, registry=reg, **_engine_kw(args, buckets))
            except (FileNotFoundError, ValueError) as e:
                print(f"tenant {alias!r}: {e}", file=sys.stderr)
                return 1
            tenant.warmup()
            app.add_tenant(tenant)
            print(f"tenant {alias!r}: checkpoint step {tenant.step}, "
                  f"{len(tenant.engine.buckets)} bucket programs in "
                  f"{time.perf_counter() - t0:.2f}s "
                  f"(buckets {list(tenant.engine.buckets)})", flush=True)
        return run_server(app, host, port,
                          drain_timeout_s=args.drain_timeout)
    finally:
        if args.chaos:
            install_chaos(prev_chaos)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    buckets = ([int(b) for b in args.buckets.split(",")] if args.buckets
               else default_buckets(args.max_batch))
    if args.http:
        return _serve_http(args, buckets)
    if not args.input_dir:
        print("--input_dir is required in directory mode (or pass --http)",
              file=sys.stderr)
        return 2

    from p2p_tpu.data.generate import is_image_file
    from p2p_tpu.data.pipeline import load_image
    from p2p_tpu.serve import engine_from_checkpoint
    from p2p_tpu.serve.frontend import DispatchLoop
    from p2p_tpu.serve.tenancy import checkpoint_dir, serving_sample_batch

    cfg = _build_config(args)
    if cfg.data.n_frames > 1:
        print("serve covers image presets; use cli/infer.py for video",
              file=sys.stderr)
        return 2

    h, w = cfg.image_hw
    as_uint8 = cfg.data.uint8_pipeline

    def decode_path(path):
        # eval semantics: the request image drives whichever slot the
        # preset reads (target for compression-net presets, input
        # otherwise); the engine's batch spec names the keys it compiled.
        # The `decode` chaos seam lives HERE, not in load_image — serving
        # has retry/quarantine around this call; training decode fails
        # fast and must never see injected faults.
        from p2p_tpu.resilience.chaos import chaos_point

        chaos_point("decode")
        return load_image(path, h, w, as_uint8=as_uint8)

    try:
        engine, step = engine_from_checkpoint(
            cfg, checkpoint_dir(cfg, args.workdir),
            serving_sample_batch(cfg),
            step=args.step, **_engine_kw(args, buckets))
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    t0 = time.perf_counter()
    engine.warmup()
    print(f"serving checkpoint step {step}: {len(engine.buckets)} bucket "
          f"programs compiled in {time.perf_counter() - t0:.2f}s "
          f"(buckets {list(engine.buckets)})", flush=True)

    out_dir = args.out or args.input_dir.rstrip("/") + "_out"
    os.makedirs(out_dir, exist_ok=True)
    from p2p_tpu.obs import get_registry
    from p2p_tpu.resilience import (
        BoundedRequestQueue,
        ChaosMonkey,
        Quarantine,
        install_chaos,
    )

    reg = get_registry()
    prev_chaos = None
    if args.chaos:
        prev_chaos = install_chaos(
            ChaosMonkey.from_spec(args.chaos, registry=reg))
    # serve-side counters are tenant-tagged even in single-model directory
    # mode (tenant = the model's name), so dashboards aggregate the two
    # frontends identically and the summary attributes failures per model
    tenant = cfg.name
    queue = BoundedRequestQueue(
        max_depth=args.max_queue,
        deadline_s=(args.deadline_ms / 1e3) if args.deadline_ms > 0 else None,
        registry=reg, tenant=tenant,
    )
    quarantine = Quarantine(
        args.quarantine_dir or os.path.join(args.input_dir, "failed"),
        registry=reg, tenant=tenant,
    )
    from p2p_tpu.serve import AsyncImageWriter

    # fail_fast=False: a poison OUTPUT path (directory squatting on the
    # target name, dead volume) is recorded + counted, never fatal — the
    # write-side analog of decode quarantine
    writer = AsyncImageWriter(args.io_threads, fail_fast=False)
    retry_delay = args.retry_delay_ms / 1e3
    seen = set()

    # requests queue as NAMES (BoundedRequestQueue of file names); decode
    # happens per micro-batch at dispatch time (a 10k-file backlog must
    # not be decoded into host RAM — or delay the first response — before
    # the first batch ships). The dispatch/decode-retry/quarantine
    # mechanics live in the shared DispatchLoop (serve/frontend.py);
    # the callbacks below are the directory frontend's POLICY.
    def decode_req(req):
        return decode_path(os.path.join(args.input_dir, req.name))

    def deliver(reqs, pred, n_real):
        paths = [os.path.join(out_dir,
                              os.path.splitext(req.name)[0] + ".png")
                 for req in reqs]
        writer.submit_batch(pred, paths)

    def on_poison(req, e):
        path = os.path.join(args.input_dir, req.name)
        dest = quarantine.quarantine(
            path, f"{req.attempts} failed decodes; last: {e!r}")
        print(f"WARNING: quarantined request {req.name!r} "
              f"after {req.attempts} failed decodes → "
              f"{dest or 'GONE'}: {e}",
              file=sys.stderr, flush=True)

    def on_expired(req):
        print(f"note: request {req.name!r} exceeded its "
              f"{args.deadline_ms:.0f} ms deadline — dropped",
              file=sys.stderr, flush=True)

    def on_retry_shed(req):
        # dropping the name from `seen` lets a later, quieter scan
        # re-offer the file instead of stranding it unserved
        seen.discard(req.name)
        print(f"WARNING: queue full — decode retry for "
              f"{req.name!r} shed; the file stays in the "
              "input dir for a later scan",
              file=sys.stderr, flush=True)

    loop = DispatchLoop(
        engine, queue, decode=decode_req, deliver=deliver,
        on_poison=on_poison, on_expired=on_expired,
        on_retry_shed=on_retry_shed, max_attempts=args.max_attempts,
        retry_delay_s=retry_delay, registry=reg, tenant=tenant,
        group_cap=args.max_batch,
    )

    def scan():
        """Enqueue new arrivals; a full queue sheds them (counted). A
        shed arrival is dropped from `seen` so a later, quieter scan can
        re-offer the file — under transient overload shedding defers
        service rather than permanently denying it (watch mode; --once
        scans exactly once, so its sheds are final)."""
        try:
            entries = sorted(os.listdir(args.input_dir))
        except FileNotFoundError:
            return 0
        fresh = 0
        shed_now = 0
        for f in entries:
            if f in seen or not is_image_file(f):
                continue
            seen.add(f)
            if queue.offer(f):
                fresh += 1
            else:
                seen.discard(f)
                shed_now += 1
        if shed_now:
            print(f"WARNING: queue full ({args.max_queue}) — shed "
                  f"{shed_now} arrivals (files stay in the input dir for "
                  "a later scan)", file=sys.stderr, flush=True)
        return fresh

    try:
        scan()
        if args.once:
            loop.drain()
            while len(queue):    # wait out retry-backoff windows, then finish
                time.sleep(min(retry_delay / 2, 0.25))
                loop.drain()
        else:
            try:
                linger_start = time.perf_counter() if len(queue) else None
                while (args.max_requests is None
                       or loop.served < args.max_requests):
                    if len(queue) >= args.max_batch or (
                        len(queue)
                        and linger_start is not None
                        and (time.perf_counter() - linger_start) * 1e3
                        >= args.linger_ms
                    ):
                        loop.drain()
                        linger_start = None
                    time.sleep(args.poll_ms / 1e3 if not len(queue) else
                               args.linger_ms / 1e3)
                    scan()
                    if len(queue) and linger_start is None:
                        linger_start = time.perf_counter()
            except KeyboardInterrupt:
                loop.drain()
        n_written = writer.drain()
        writer.close()
        for path, err in writer.write_errors:
            print(f"WARNING: prediction write failed permanently for "
                  f"{path!r}: {err}", file=sys.stderr, flush=True)
    finally:
        if args.chaos:
            # disarm even on a crashed serve: chaos is process-global and
            # in-process callers (tests) must not inherit the fault spec
            install_chaos(prev_chaos)
    wall = time.perf_counter() - t0

    occ = loop.occupancy_mean
    print(json.dumps({
        "kind": "serve_summary", "tenant": tenant, "served": loop.served,
        "written": n_written,
        "out_dir": out_dir, "buckets": list(engine.buckets),
        "n_compiles": engine.n_compiles,
        "encode_sec": round(writer.encode_sec, 4),
        "wall_sec": round(wall, 4),
        "shed": queue.shed_count,
        "deadline_expired": queue.expired_count,
        "quarantined": quarantine.count,
        "write_failures": len(writer.write_errors),
        "decode_retries": loop.decode_retries,
        "write_retries": int(reg.counter(
            "retry_attempts_total", seam="serve_write").value),
        "chaos_injected": int(reg.total("chaos_injected_total")),
        "batch_occupancy_mean": round(occ, 4) if occ is not None else None,
        "padded_images": loop.padded_images,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
