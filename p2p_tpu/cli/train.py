"""Training CLI — flag parity with the reference (train.py:133-157) plus
TPU-native knobs (--preset, --mesh).

Every reference flag is accepted with the same name and default. Flags the
reference parsed but never used are live here where the intent is clear
(--lamb wires the pix2pix L1 weight — SURVEY Q3) or accepted-and-ignored
with a warning where they are meaningless on TPU (--cuda).

Unset flags inherit from the chosen --preset, so
``--preset pix2pixhd --batch_size 2`` tweaks one knob of a BASELINE config.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

from p2p_tpu.core.config import Config, get_preset, list_presets


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu training")
    # --- TPU-native knobs -------------------------------------------------
    p.add_argument("--preset", type=str, default="reference",
                   help=f"named config preset: {', '.join(list_presets())}")
    p.add_argument("--data_root", type=str, default=None,
                   help="dataset root directory (default <root>/<dataset>)")
    p.add_argument("--workdir", type=str, default=".",
                   help="checkpoints/results/metrics land here")
    p.add_argument("--mesh", type=str, default=None,
                   help="mesh axes: positional "
                        "'data,spatial,time[,model[,pipe]]' (e.g. '4,2,1') "
                        "or named 'axis=size,...' over data/fsdp/spatial/"
                        "time/model/pipe (e.g. 'data=4,fsdp=2,model=2'; "
                        "data may be -1 = all remaining devices); model>1 "
                        "trains tensor-parallel, fsdp>1 shards optimizer+"
                        "EMA state ZeRO-style (docs/PARALLELISM.md)")
    p.add_argument("--tp_min_ch", type=int, default=None,
                   help="smallest channel count the TP pair rule shards "
                        "over the model axis (ParallelConfig.tp_min_ch; "
                        "default 512 — lower it only for toy models)")
    p.add_argument("--fsdp_params", action="store_true", default=None,
                   help="with mesh fsdp>1: shard the params themselves "
                        "over the fsdp axis too (ZeRO-3-ish gather-on-"
                        "use), not just optimizer moments + EMA "
                        "(ParallelConfig.fsdp_params)")
    p.add_argument("--image_width", type=int, default=None,
                   help="image width when not square (e.g. pix2pixhd "
                        "1024x512 trains height=512 width=1024)")
    p.add_argument("--image_size", type=int, default=None,
                   help="override preset image size (height; square unless "
                        "the preset sets a width)")
    p.add_argument("--n_blocks", type=int, default=None,
                   help="override generator residual block count")
    p.add_argument("--upsample_mode", type=str, default=None,
                   choices=["deconv", "subpixel", "resize"],
                   help="U-Net decoder upsampling (deconv = torch-parity "
                        "ConvTranspose; resize = nearest+conv)")
    p.add_argument("--augment", action="store_true", default=None,
                   help="paired resize-286/random-crop/flip augmentation")
    p.add_argument("--int8", action="store_true", default=None,
                   help="int8 QAT MXU path for the discriminator's inner "
                        "convs (ops/int8.py; ~1.1x step on v5e); "
                        "--int8_generator extends it to the U-Net G")
    p.add_argument("--int8_generator", action="store_true", default=None,
                   help="extend --int8 to the generator convs (measured "
                        "slower on v5e at 256^2; see ModelConfig)")
    p.add_argument("--int8_stem", action="store_true", default=None,
                   help="extend the int8 path to the 3/6-channel input "
                        "stems (U-Net down0, PatchGAN stage 0, net_c's "
                        "k5 conv). Off by default: the stems are "
                        "HBM-bound — measured-rejected on v5e, kept "
                        "measurable per chip/shape")
    p.add_argument("--int8_head", action="store_true", default=None,
                   help="discriminator logits head on the int8 kn2row "
                        "tap-decomposition path (ops/int8.py "
                        "int8_kn2row_conv); the U-Net IMAGE head always "
                        "stays bf16")
    p.add_argument("--int8_compression", action="store_true", default=None,
                   help="CompressionNetwork (net_c) convs on the int8 "
                        "path; its amax state rides the 'quant' "
                        "collection as quant_c end-to-end")
    p.add_argument("--int8_fused_epilogue", action="store_true",
                   default=None,
                   help="fuse the D inner-conv epilogue [instance norm + "
                        "LeakyReLU + quantize + amax] into one streaming "
                        "Pallas pass (needs --norm_d pallas_instance and "
                        "--int8_delayed; ops/pallas/norm_act.py)")
    p.add_argument("--int8_delayed", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="delayed (stored-scale) activation quantization: "
                        "per-layer amax carried in TrainState; removes "
                        "the absmax reductions from the critical path "
                        "(ops/int8.py int8_conv_ds). --no-int8_delayed "
                        "restores the dynamic-scale path (required to "
                        "RESUME pre-round-3 facades_int8 checkpoints — "
                        "the quant collection changes the TrainState "
                        "tree)")
    p.add_argument("--norm_d", type=str, default=None,
                   choices=["none", "instance", "pallas_instance"],
                   help="discriminator-side norm on the inner PatchGAN "
                        "convs (pix2pixHD-paper D layout; affine-free, so "
                        "checkpoints interchange with 'none'). "
                        "'pallas_instance' fuses norm+LeakyReLU into one "
                        "Pallas pass (ops/pallas/norm_act.py)")
    p.add_argument("--pp_overlap", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="latency-hiding GPipe schedule: the stage hand-off "
                        "ppermute is double-buffered so the transfer "
                        "overlaps stage compute (parallel/pp.py; costs S-1 "
                        "extra fill/drain ticks — see docs/PARALLELISM.md)")
    p.add_argument("--thin_head", action="store_true", default=None,
                   help="U-Net image head as the subpixel form (k2s1 "
                        "conv + interleave; measured a wash on v5e, "
                        "1708 vs 1715 img/s; see ModelConfig.thin_head)")
    p.add_argument("--legacy_layout", action="store_true", default=None,
                   help="keep the dead conv biases in front of norm "
                        "layers (round-2 checkpoint layout; see "
                        "ModelConfig.legacy_layout)")
    p.add_argument("--compilation_cache", type=str, default=None,
                   metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(core/cache.py): restarted runs reload compiled "
                        "programs from disk instead of recompiling; "
                        "hits/misses are counted through the obs retrace "
                        "watchdog")
    p.add_argument("--elastic", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="elastic relaunch (docs/RESILIENCE.md): on resume, "
                        "reconcile the checkpoint's recorded topology "
                        "(process count, mesh axes, global batch, dtype "
                        "policy) against this launch's and RESHARD "
                        "compatible deltas — a preemptible fleet rarely "
                        "hands back the slice size it reclaimed. On by "
                        "default; --no-elastic restores the strict "
                        "contract (any topology delta aborts)")
    p.add_argument("--cast_on_restore", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="opt-in dtype-policy migration on resume: a "
                        "mixed-precision/--moment_dtype change performs "
                        "an explicit, logged cast (moments follow the "
                        "migration policy table; the integrity manifest "
                        "is regenerated post-cast) instead of exiting 2 "
                        "(resilience/reshape.py)")
    p.add_argument("--recalibrate_steps", type=int, default=None,
                   help="after a TP-width int8-amax migration, hold the "
                        "remapped scales frozen for this many dispatches "
                        "before the decaying-max update resumes "
                        "(default 0 = trust the closed-form remap)")
    # --- self-healing knobs (p2p_tpu.resilience.health) -------------------
    p.add_argument("--health", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="divergence sentinel + recovery ladder (skip -> "
                        "LR cooldown -> rollback to the last-good "
                        "checkpoint; docs/RESILIENCE.md). On by default; "
                        "--no-health disables both the sentinel and the "
                        "in-step skip guard")
    p.add_argument("--ema_decay", type=float, default=None,
                   help="EMA generator decay (e.g. 0.999): TrainState "
                        "carries smoothed G weights, eval/serve use them "
                        "(0 = EMA tracks raw params exactly — the parity "
                        "mode; unset = off)")
    p.add_argument("--max_rollbacks", type=int, default=None,
                   help="rollbacks to the last-good checkpoint before the "
                        "run gives up with exit code 76 (default 3)")
    p.add_argument("--spike_zscore", type=float, default=None,
                   help="robust z-score over the loss window above which "
                        "a step classifies as a spike (default 6.0)")
    p.add_argument("--cooldown_steps", type=int, default=None,
                   help="steps the ladder's LR cooldown (rung 2) holds "
                        "the reduced LR before restoring (default 20)")
    p.add_argument("--health_window", type=int, default=None,
                   help="healthy steps in the sentinel's robust z-score "
                        "window (default 32)")
    # --- telemetry / debug knobs (p2p_tpu.obs) ----------------------------
    p.add_argument("--check_finite", action="store_true", default=None,
                   help="host-side non-finite guard on the step metrics "
                        "after every dispatch: emits a kind=nonfinite "
                        "record, then raises (fences each dispatch — "
                        "debug tool)")
    p.add_argument("--nan_sentinel", action="store_true", default=None,
                   help="in-jit NaN/Inf sentinel on the step losses via "
                        "jax.debug.callback (async, no fence on the "
                        "happy path) — events land in the metrics JSONL")
    p.add_argument("--grad_norms", action="store_true", default=None,
                   help="add grad_norm_g/d global-norm scalars to the "
                        "per-step metrics stream")
    p.add_argument("--tensorboard", action="store_true",
                   help="also write scalar records to TensorBoard event "
                        "files under <workdir>/tb/<name>")
    p.add_argument("--prom_textfile", type=str, default=None,
                   help="export registry metrics in Prometheus textfile "
                        "format to this path (atomic rewrite; point "
                        "node_exporter's textfile collector at its dir)")
    # --- reference flags (train.py:133-157), same names/defaults ---------
    p.add_argument("--dataset", type=str, default=None, help="facades")
    p.add_argument("--name", type=str, default=None, help="training name")
    p.add_argument("--epoch_count", type=int, default=None)
    p.add_argument("--nepoch", type=int, default=None)
    p.add_argument("--niter", type=int, default=None)
    p.add_argument("--niter_decay", type=int, default=None)
    p.add_argument("--cuda", action="store_true",
                   help="accepted for parity; ignored (always TPU/XLA)")
    p.add_argument("--epochsave", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--test_batch_size", type=int, default=None)
    p.add_argument("--direction", type=str, default=None, help="a2b or b2a")
    p.add_argument("--input_nc", type=int, default=None)
    p.add_argument("--output_nc", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--ndf", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr_policy", type=str, default=None,
                   help="lambda|step|plateau|cosine")
    p.add_argument("--lr_decay_iters", type=int, default=None)
    p.add_argument("--beta1", type=float, default=None)
    p.add_argument("--moment_dtype", type=str, default=None,
                   help="Adam moment STORAGE dtype (e.g. bfloat16): halves "
                        "optimizer-state HBM traffic, update math stays f32 "
                        "(train/state.py scale_by_adam_lp)")
    p.add_argument("--threads", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--lamb", type=float, default=None,
                   help="L1 weight (dead in the reference — Q3; live here)")
    p.add_argument("--lambda_vgg", type=float, default=None,
                   help="VGG perceptual weight (reference 10.0; set 0 when "
                        "no pretrained VGG asset exists — the random-feature "
                        "fallback at x10 can destabilize training)")
    p.add_argument("--lambda_feat", type=float, default=None,
                   help="feature-matching weight (reference 10.0)")
    p.add_argument("--lambda_tv", type=float, default=None,
                   help="total-variation weight (reference 1.0)")
    p.add_argument("--lambda_sobel", type=float, default=None,
                   help="Sobel edge-L1 weight (the reference's commented "
                        "edge experiment, train.py:362-363; 0 = off)")
    p.add_argument("--sobel_warmup_epochs", type=int, default=None,
                   help="ramp the sobel weight linearly over this many "
                        "epochs (reference train.py:445-448; 0 = constant)")
    p.add_argument("--lambda_angular", type=float, default=None,
                   help="mean-angular-error weight (the reference's "
                        "commented experiment, train.py:355-360; 0 = off)")
    p.add_argument("--grad_clip", type=float, default=None,
                   help="global-norm gradient clipping (0 = off; guards "
                        "per-sample-norm backward blowups on degenerate "
                        "images — see train/state.py)")
    p.add_argument("--pool_size", type=int, default=None,
                   help="historical-fake pool fed to D (reference "
                        "ImagePool(0) = passthrough); >0 enables a "
                        "device-side ring buffer. Image presets only — "
                        "the video step has no pool")
    p.add_argument("--save_masks", action="store_true", default=None,
                   help="dump mask.png = bitwise_and(uint8(fake_b), "
                        "uint8(real_a)) with the eval samples (the "
                        "reference's commented masking experiment, "
                        "train.py:324-334; visualization only)")
    p.add_argument("--eval_fid", action="store_true", default=None,
                   help="compute FID (VFID for video presets) per eval epoch "
                        "from VGG19 features; the feature source "
                        "(pretrained npz vs random init) is reported")
    p.add_argument("--scan_steps", type=int, default=None,
                   help="train steps fused into one lax.scan dispatch "
                        "(amortizes host/tunnel latency; metrics are still "
                        "logged per step)")
    p.add_argument("--log_every", type=int, default=None,
                   help="per-step metrics record + stdout heartbeat cadence "
                        "(TrainConfig.log_every; epoch/eval records are "
                        "always written)")
    p.add_argument("--phase", choices=["global", "full"], default=None,
                   help="pix2pixHD coarse-to-fine schedule: 'global' trains "
                        "G1 alone at half resolution (checkpoints under "
                        "<name>_g1); 'full' trains the enhancer-wrapped "
                        "generator with the phase-1 G1 weights grafted in")
    p.add_argument("--init_g1_from", type=str, default=None,
                   help="explicit phase-1 checkpoint dir for --phase full "
                        "(default: checkpoint/<dataset>/<name>_g1)")
    return p


def config_from_flags(args: argparse.Namespace) -> Config:
    """Build a Config: preset defaults overridden by explicitly-set flags."""
    cfg = get_preset(args.preset)
    model, loss, optim, data, train, par = (
        cfg.model, cfg.loss, cfg.optim, cfg.data, cfg.train, cfg.parallel
    )
    from p2p_tpu.cli import apply_overrides as over

    model = over(model, input_nc=args.input_nc, output_nc=args.output_nc,
                 ngf=args.ngf, ndf=args.ndf, n_blocks=args.n_blocks,
                 upsample_mode=args.upsample_mode, int8=args.int8,
                 int8_generator=args.int8_generator,
                 int8_delayed=args.int8_delayed,
                 int8_stem=args.int8_stem, int8_head=args.int8_head,
                 int8_compression=args.int8_compression,
                 int8_fused_epilogue=args.int8_fused_epilogue,
                 legacy_layout=args.legacy_layout,
                 thin_head=args.thin_head, norm_d=args.norm_d)
    loss = over(loss, lambda_l1=args.lamb, lambda_vgg=args.lambda_vgg,
                lambda_feat=args.lambda_feat, lambda_tv=args.lambda_tv,
                lambda_sobel=args.lambda_sobel,
                sobel_warmup_epochs=args.sobel_warmup_epochs,
                lambda_angular=args.lambda_angular)
    optim = over(optim, lr=args.lr, lr_policy=args.lr_policy,
                 lr_decay_iters=args.lr_decay_iters, beta1=args.beta1,
                 niter=args.niter, niter_decay=args.niter_decay,
                 grad_clip=args.grad_clip, moment_dtype=args.moment_dtype)
    data = over(data, dataset=args.dataset, direction=args.direction,
                batch_size=args.batch_size, image_size=args.image_size,
                image_width=args.image_width,
                test_batch_size=args.test_batch_size, threads=args.threads,
                augment=args.augment)
    if args.image_size is not None and args.image_width is None and \
            data.image_width is not None:
        # an explicit square --image_size overrides a rectangular preset
        # wholesale (halving only one dim silently breaks aspect handling)
        data = dataclasses.replace(data, image_width=None)
    train = over(train, nepoch=args.nepoch, epoch_count=args.epoch_count,
                 epoch_save=args.epochsave, seed=args.seed,
                 eval_fid=args.eval_fid, scan_steps=args.scan_steps,
                 pool_size=args.pool_size, save_masks=args.save_masks,
                 log_every=args.log_every,
                 compilation_cache_dir=args.compilation_cache,
                 elastic=args.elastic,
                 cast_on_restore=args.cast_on_restore,
                 recalibrate_steps=args.recalibrate_steps)
    debug = over(cfg.debug, check_finite=args.check_finite,
                 nan_sentinel=args.nan_sentinel, grad_norms=args.grad_norms)
    health = over(cfg.health, enabled=args.health,
                  ema_decay=args.ema_decay,
                  max_rollbacks=args.max_rollbacks,
                  spike_zscore=args.spike_zscore,
                  cooldown_steps=args.cooldown_steps,
                  window=args.health_window)
    par = over(par, tp_min_ch=args.tp_min_ch, pp_overlap=args.pp_overlap,
               fsdp_params=args.fsdp_params)
    if args.mesh is not None:
        from p2p_tpu.core.mesh import parse_mesh_arg

        try:
            spec = parse_mesh_arg(args.mesh)
        except ValueError as e:
            raise SystemExit(
                f"--mesh must be 'data,spatial,time[,model[,pipe]]' "
                f"comma-separated ints or named 'axis=size,...' (got "
                f"{args.mesh!r}: {e})"
            )
        par = dataclasses.replace(par, mesh=spec)
    name = args.name or cfg.name
    cfg = dataclasses.replace(
        cfg, name=name, model=model, loss=loss, optim=optim, data=data,
        train=train, parallel=par, debug=debug, health=health,
    )
    if getattr(args, "phase", None) == "global":
        # coarse-to-fine phase 1 — applied AFTER flag overrides so an
        # explicit --image_size/--name is halved/suffixed consistently,
        # and with the same helper phase 2 uses to locate the checkpoint.
        from p2p_tpu.train.graft import g1_phase_config

        cfg = g1_phase_config(cfg)
    return cfg


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cuda:
        print("note: --cuda accepted for parity but ignored (TPU/XLA build)",
              file=sys.stderr)
    cfg = config_from_flags(args)

    if cfg.data.n_frames > 1:
        from p2p_tpu.train.video_loop import VideoTrainer as Trainer
    else:
        from p2p_tpu.train.loop import Trainer

    trainer = Trainer(cfg, data_root=args.data_root, workdir=args.workdir)
    if args.tensorboard:
        import os

        from p2p_tpu.obs import TensorBoardSink

        try:
            trainer.logger.registry.add_sink(
                TensorBoardSink(os.path.join(args.workdir, "tb", cfg.name)))
        except ImportError as e:
            print(f"note: --tensorboard unavailable ({e}); continuing "
                  "with JSONL/stdout only", file=sys.stderr)
    if args.prom_textfile:
        from p2p_tpu.obs import PrometheusTextfileSink

        trainer.logger.registry.add_sink(PrometheusTextfileSink(
            args.prom_textfile, trainer.logger.registry))
    from p2p_tpu.core.mesh import TopologyMismatch

    try:
        resumed = trainer.maybe_resume()
    except TopologyMismatch as tm:
        # an elastic relaunch hit a delta the resharded-resume path cannot
        # reconcile (or --no-elastic forbade reconciling it). This is a
        # flags problem, not a transient: exit 2, NOT 75 — "re-run these
        # flags" would hit the same wall.
        print(f"topology mismatch: {tm}", file=sys.stderr, flush=True)
        return 2
    if resumed:
        print(f"resumed at epoch {trainer.epoch}")
    elif getattr(args, "phase", None) == "full":
        # coarse-to-fine phase 2: graft the phase-1 G1 checkpoint
        # (<name>_g1) into the full generator before training starts.
        from p2p_tpu.train.graft import load_and_graft_g1

        trainer.state = load_and_graft_g1(
            trainer.state, cfg, workdir=args.workdir,
            g1_dir=args.init_g1_from, mesh=getattr(trainer, "mesh", None),
        )
    from p2p_tpu.resilience import (
        DIVERGED_EXIT_CODE,
        PREEMPTED_EXIT_CODE,
        DivergenceError,
        Preempted,
    )

    try:
        trainer.fit()
    except Preempted as p:
        # graceful preemption (SIGTERM/SIGINT): the exact step is on disk —
        # exit 75 (EX_TEMPFAIL) tells the supervisor "re-run these flags";
        # the relaunch lands in maybe_resume's exact-step path above.
        print(f"preempted: checkpoint saved at step {p.step} — "
              f"relaunch with identical flags to resume "
              f"(exit {PREEMPTED_EXIT_CODE})", flush=True)
        return PREEMPTED_EXIT_CODE
    except DivergenceError as d:
        # the recovery ladder is exhausted: rolled back max_rollbacks
        # times and diverged again. Exit 76 — DISTINCT from preemption's
        # 75, because "relaunch with identical flags" would just diverge
        # again; this needs a human (or a config change).
        print(f"diverged: {d} (exit {DIVERGED_EXIT_CODE})", flush=True)
        trainer.logger.registry.flush()
        return DIVERGED_EXIT_CODE
    finally:
        trainer.close()  # unhook compile listener + sentinel handler
    return 0


if __name__ == "__main__":
    sys.exit(main())
