"""Training CLI — flag parity with the reference (train.py:133-157) plus
TPU-native knobs (--preset, --mesh).

Every reference flag is accepted with the same name and default. Flags the
reference parsed but never used are live here where the intent is clear
(--lamb wires the pix2pix L1 weight — SURVEY Q3) or accepted-and-ignored
with a warning where they are meaningless on TPU (--cuda).

Unset flags inherit from the chosen --preset, so
``--preset pix2pixhd --batch_size 2`` tweaks one knob of a BASELINE config.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

from p2p_tpu.core.config import Config, get_preset, list_presets


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="p2p_tpu training")
    # --- TPU-native knobs -------------------------------------------------
    p.add_argument("--preset", type=str, default="reference",
                   help=f"named config preset: {', '.join(list_presets())}")
    p.add_argument("--data_root", type=str, default=None,
                   help="dataset root directory (default <root>/<dataset>)")
    p.add_argument("--workdir", type=str, default=".",
                   help="checkpoints/results/metrics land here")
    p.add_argument("--mesh", type=str, default=None,
                   help="mesh axes 'data,spatial,time' e.g. '4,2,1' "
                        "(data may be -1 = all remaining devices)")
    p.add_argument("--image_size", type=int, default=None,
                   help="override preset image size (height; square unless "
                        "the preset sets a width)")
    p.add_argument("--n_blocks", type=int, default=None,
                   help="override generator residual block count")
    p.add_argument("--upsample_mode", type=str, default=None,
                   choices=["deconv", "resize"],
                   help="U-Net decoder upsampling (deconv = torch-parity "
                        "ConvTranspose; resize = nearest+conv)")
    p.add_argument("--augment", action="store_true", default=None,
                   help="paired resize-286/random-crop/flip augmentation")
    # --- reference flags (train.py:133-157), same names/defaults ---------
    p.add_argument("--dataset", type=str, default=None, help="facades")
    p.add_argument("--name", type=str, default=None, help="training name")
    p.add_argument("--epoch_count", type=int, default=None)
    p.add_argument("--nepoch", type=int, default=None)
    p.add_argument("--niter", type=int, default=None)
    p.add_argument("--niter_decay", type=int, default=None)
    p.add_argument("--cuda", action="store_true",
                   help="accepted for parity; ignored (always TPU/XLA)")
    p.add_argument("--epochsave", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--test_batch_size", type=int, default=None)
    p.add_argument("--direction", type=str, default=None, help="a2b or b2a")
    p.add_argument("--input_nc", type=int, default=None)
    p.add_argument("--output_nc", type=int, default=None)
    p.add_argument("--ngf", type=int, default=None)
    p.add_argument("--ndf", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr_policy", type=str, default=None,
                   help="lambda|step|plateau|cosine")
    p.add_argument("--lr_decay_iters", type=int, default=None)
    p.add_argument("--beta1", type=float, default=None)
    p.add_argument("--threads", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--lamb", type=float, default=None,
                   help="L1 weight (dead in the reference — Q3; live here)")
    p.add_argument("--pool_size", type=int, default=None,
                   help="historical-fake pool fed to D (reference "
                        "ImagePool(0) = passthrough); >0 enables a "
                        "device-side ring buffer. Image presets only — "
                        "the video step has no pool")
    p.add_argument("--eval_fid", action="store_true", default=None,
                   help="compute FID (VFID for video presets) per eval epoch "
                        "from VGG19 features; the feature source "
                        "(pretrained npz vs random init) is reported")
    p.add_argument("--scan_steps", type=int, default=None,
                   help="train steps fused into one lax.scan dispatch "
                        "(amortizes host/tunnel latency; metrics are still "
                        "logged per step)")
    return p


def config_from_flags(args: argparse.Namespace) -> Config:
    """Build a Config: preset defaults overridden by explicitly-set flags."""
    cfg = get_preset(args.preset)
    model, loss, optim, data, train, par = (
        cfg.model, cfg.loss, cfg.optim, cfg.data, cfg.train, cfg.parallel
    )
    from p2p_tpu.cli import apply_overrides as over

    model = over(model, input_nc=args.input_nc, output_nc=args.output_nc,
                 ngf=args.ngf, ndf=args.ndf, n_blocks=args.n_blocks,
                 upsample_mode=args.upsample_mode)
    loss = over(loss, lambda_l1=args.lamb)
    optim = over(optim, lr=args.lr, lr_policy=args.lr_policy,
                 lr_decay_iters=args.lr_decay_iters, beta1=args.beta1,
                 niter=args.niter, niter_decay=args.niter_decay)
    data = over(data, dataset=args.dataset, direction=args.direction,
                batch_size=args.batch_size, image_size=args.image_size,
                test_batch_size=args.test_batch_size, threads=args.threads,
                augment=args.augment)
    train = over(train, nepoch=args.nepoch, epoch_count=args.epoch_count,
                 epoch_save=args.epochsave, seed=args.seed,
                 eval_fid=args.eval_fid, scan_steps=args.scan_steps,
                 pool_size=args.pool_size)
    if args.mesh is not None:
        from p2p_tpu.core.mesh import MeshSpec

        try:
            d, s, t = (int(v) for v in args.mesh.split(","))
        except ValueError:
            raise SystemExit(
                f"--mesh must be three comma-separated ints "
                f"'data,spatial,time' (got {args.mesh!r})"
            )
        if s < 1 or t < 1 or (d < 1 and d != -1):
            raise SystemExit(
                "--mesh axes must be >=1 (data may be -1 = all remaining "
                f"devices); got {args.mesh!r}"
            )
        par = dataclasses.replace(par, mesh=MeshSpec(data=d, spatial=s, time=t))
    name = args.name or cfg.name
    return dataclasses.replace(
        cfg, name=name, model=model, loss=loss, optim=optim, data=data,
        train=train, parallel=par,
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cuda:
        print("note: --cuda accepted for parity but ignored (TPU/XLA build)",
              file=sys.stderr)
    cfg = config_from_flags(args)

    if cfg.data.n_frames > 1:
        from p2p_tpu.train.video_loop import VideoTrainer as Trainer
    else:
        from p2p_tpu.train.loop import Trainer

    trainer = Trainer(cfg, data_root=args.data_root, workdir=args.workdir)
    resumed = trainer.maybe_resume()
    if resumed:
        print(f"resumed at epoch {trainer.epoch}")
    trainer.fit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
