from p2p_tpu.core.config import (
    Config,
    DataConfig,
    LossConfig,
    ModelConfig,
    OptimConfig,
    ParallelConfig,
    get_preset,
    list_presets,
)
from p2p_tpu.core.dtypes import DTypePolicy, default_policy
from p2p_tpu.core.mesh import MeshSpec, make_mesh, local_batch_size
from p2p_tpu.core.rng import RngStream

__all__ = [
    "Config",
    "DataConfig",
    "LossConfig",
    "ModelConfig",
    "OptimConfig",
    "ParallelConfig",
    "get_preset",
    "list_presets",
    "DTypePolicy",
    "default_policy",
    "MeshSpec",
    "make_mesh",
    "local_batch_size",
    "RngStream",
]
