"""Persistent XLA compilation cache — cold-start pays compile ONCE ever.

Both the serving engine (p2p_tpu.serve: AOT bucket warmup) and the trainer
(cfg.train.compilation_cache_dir / --compilation_cache) route through
:func:`enable_compilation_cache`: jitted programs whose HLO+flags match a
prior run's are loaded from the on-disk cache instead of recompiled — a
pix2pixHD-scale XLA compile is minute-scale, so warm cold-starts matter for
rolling serving restarts and preemption-heavy training fleets alike.

Hit/miss visibility: jax.monitoring emits ``/jax/compilation_cache/
cache_hits`` / ``cache_misses`` events; the obs RetraceWatchdog counts them
(``persistent_cache_hits``/``persistent_cache_misses`` registry counters),
so a fleet that silently stopped hitting its cache shows up in metrics.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_enabled_dir: Optional[str] = None


def enable_compilation_cache(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at ``cache_dir``
    (created if missing) and drop the min-compile-time/min-entry-size
    gates so every program is eligible — the serving buckets include
    sub-second toy compiles in tests, and on TPU the big programs clear
    any threshold anyway. Idempotent; returns the active dir. Call BEFORE
    the first jit compile you want cached."""
    global _enabled_dir
    cache_dir = os.path.abspath(cache_dir)
    if _enabled_dir == cache_dir:
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        # jax latches cache-disabled at the FIRST backend compile of the
        # process (compilation_cache._cache_checked); any import-time jit
        # (dataset probes, shims) would otherwise leave the cache silently
        # inert for the whole run — reset the latch so the next compile
        # re-evaluates with the directory set.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass  # private API moved: cache still works when set early enough
    _enabled_dir = cache_dir
    return cache_dir


def compilation_cache_dir() -> Optional[str]:
    """The directory enabled via :func:`enable_compilation_cache` (None if
    the cache was never enabled by this process)."""
    return _enabled_dir
