"""Configuration system.

The reference configures everything through 21 argparse flags plus a pile of
hardcoded constants (SURVEY.md §5.6: dataset root, quantizer bits, loss
weights 10/10/1, Num_D=3 ...). Here every knob is an explicit dataclass
field, and the five BASELINE.json target configs are checked in as named
presets retrievable via :func:`get_preset`.

Reference flag parity (train.py:133-157) is kept by ``Config.from_flags`` in
``p2p_tpu.cli.train``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

from p2p_tpu.core.mesh import MeshSpec


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    # Generator family: "expand" (reference ExpandNetwork transform-net,
    # networks.py:447), "unet" (classic pix2pix U-Net), "pix2pixhd"
    # (coarse-to-fine global+local), "resnet" (9-block ResnetGenerator,
    # the commented alternative at networks.py:168).
    generator: str = "expand"
    input_nc: int = 3
    output_nc: int = 3
    ngf: int = 32            # reference ExpandNetwork base width (networks.py:460)
    ndf: int = 64            # discriminator base width (networks.py:708)
    n_blocks: int = 9        # residual blocks in expand/resnet G (networks.py:472)
    # Discriminator: multiscale PatchGAN (networks.py:716). num_D=3,
    # n_layers=3, spectral norm on inner convs, intermediate features kept
    # for the feature-matching loss.
    num_D: int = 3
    n_layers_D: int = 3
    use_spectral_norm: bool = True
    get_interm_feat: bool = True
    # Compression pre-filter (networks.py:201) + quantizer bits
    # (hardcoded 3 at train.py:297).
    use_compression_net: bool = True
    quant_bits: int = 3
    # Straight-through estimator through the quantizer. The reference has
    # none (SURVEY Q2) so its net_c never learns; True implements the
    # *intended* behavior, False is bug-compatible.
    quant_ste: bool = True
    # "batch" | "instance" | "pallas_instance"
    norm: str = "batch"
    # Discriminator-side normalization on the inner PatchGAN convs:
    # "none" (reference parity — networks.py:716 has no D norms) |
    # "instance" | "pallas_instance" (the pix2pixHD paper's D layout;
    # stateless/affine-free, so the param tree — and therefore
    # checkpoints — are identical either way). With "pallas_instance"
    # the conv epilogue (norm + LeakyReLU) is ONE fused Pallas pass
    # (ops/pallas/norm_act.py).
    norm_d: str = "none"
    # U-Net decoder dropout (the pix2pix noise source). The train step
    # threads a per-step dropout rng when this is on.
    use_dropout: bool = False
    # U-Net decoder upsampling: "deconv" (ConvTranspose k4 s2 — torch
    # parameter layout; the default), "subpixel" (conv k2s1 +
    # depth-to-space — same operator family/FLOPs, but the shifted
    # interleave costs an extra memory-bound pass per level: measured
    # SLOWER than deconv on v5e, kept as an option), or "resize"
    # (nearest + conv k3).
    upsample_mode: str = "deconv"
    init_type: str = "normal"   # normal | xavier | kaiming | orthogonal
    init_gain: float = 0.02
    # int8 QAT path (ops/int8.py): run the MXU-dominant inner convs of G
    # and D as s8×s8→s32 MXU convolutions (forward + both backward
    # contractions) with dynamic symmetric scales. The 3/6-channel stems
    # and the image-producing heads stay bf16 (HBM-bound + quality
    # critical). v5e: 2× MXU peak vs bf16. Applies to all discriminator
    # families (spectral norm composes: the power iteration tracks the
    # true f32 weight, only w/σ is quantized) and — via int8_generator —
    # to the "unet" encoder (deconv mode) and the ResNet-trunk families
    # (resnet / pix2pixhd / pix2pixhd_global k3-s1 blocks).
    int8: bool = False
    # Extend int8 to the generator too. Off by default: measured on v5e,
    # the U-Net's bf16 convs already run near MXU peak fused with their
    # norms/activations, and the int8 wgrad's slice materialization at
    # 128²+ spatial costs more than the MXU gain — int8 pays on the
    # discriminator (wide stride-1/2 convs at ≤65² spatial), where all
    # three contractions hit the doubled int8 MXU rate.
    int8_generator: bool = False
    # With int8_generator: also switch the U-Net decoder deconvs to the
    # quantized subpixel form (QuantSubpixelDeconv). Measured a net loss
    # on v5e (interleave + large-spatial wgrad slices); kept reachable
    # for other chips/shapes.
    int8_decoder: bool = False
    # Delayed (stored-scale) activation quantization: per-layer amax
    # carried in a 'quant' collection threaded through TrainState (like
    # batch_stats), so the forward quantize no longer serializes on an
    # absmax reduction — one HBM pass instead of two per quantized
    # activation (ops/int8.py int8_conv_ds). Measured +3% on the bs=128
    # headline (1632→1681 img/s); a no-op at bs=1 (185.8 vs 186.2 —
    # that shape is kernel-launch-latency-bound, not absmax-bound,
    # correcting round 2's hypothesis). Transient clipping after an
    # activation spike decays in one step (decaying-max update).
    int8_delayed: bool = False
    # ISSUE 14 coverage knobs (one per remaining --int8-diff site family;
    # every site is REACHABLE on the int8 path, and the default carries
    # the measured-rejected verdict where there is one):
    # 3/6-channel input stems (U-Net down0, the PatchGAN stage-0 conv,
    # net_c's k5 RGB conv) on the int8 path. Off by default: the stems
    # are HBM-bound (the MXU gains nothing on a 3-wide contraction) —
    # the round-2..5 doctrine — but the knob keeps the form measurable
    # per chip/shape (even the facades_int8_full row keeps it off).
    int8_stem: bool = False
    # Discriminator logits head on the int8 path: the kn2row-eligible
    # 512→1 head runs the s8×s8→s32 tap-decomposition dot
    # (ops/int8.py int8_kn2row_conv — fwd and wgrad on the int8 MXU,
    # the tiny-contraction dgrad stays bf16 per the per-form dispatch
    # table); a non-kn2row head falls back to QuantConv. The U-Net
    # IMAGE head stays bf16 always (quality + HBM critical — the dated
    # in-source waiver at models/unet.py documents the verdict).
    int8_head: bool = False
    # CompressionNetwork (net_c) convs on the int8 path. Its output is
    # already crushed to `quant_bits` (3) by the pipeline quantizer, so
    # int8 QAT noise inside the pre-filter is far below the signal the
    # net is trained to survive; amax state joins the 'quant' collection
    # as quant_c (train step, PP, frozen-scale eval/serving, elastic
    # reshard_amax all thread it).
    int8_compression: bool = False
    # Quantize-fused conv epilogues (ops/pallas/norm_act.py
    # norm_act_quant): with norm_d="pallas_instance" + int8_delayed the
    # discriminator's inner-conv epilogue [instance norm + LeakyReLU +
    # clip/round quantize + amax measurement] runs as ONE streaming
    # Pallas pass, so the newly quantized conv does not pay a separate
    # full-size read+write for the clip/round; the consumer conv takes
    # the prequantized activation (int8_conv_pq). Requires int8 +
    # int8_delayed + a stateless instance-family norm_d.
    int8_fused_epilogue: bool = False
    # Keep the mathematically-dead conv biases in front of mean-
    # subtracting norms (round-2 checkpoint param layout). Default False:
    # those biases are exactly cancelled by the norm in forward AND
    # receive identically-zero gradients (the norm backward emits
    # zero-channel-mean cotangents), yet computing those zero gradients
    # re-read full-size cotangents (~3 ms/step at bs=128/256²).
    legacy_layout: bool = False
    # U-Net image head as the subpixel form (plain k2s1 conv to 4·F
    # channels + shifted interleave) instead of ConvTranspose. Measured
    # a wash on v5e at 256²/bs=128 (1708 vs 1715 img/s; the kn2row
    # variant of the inner conv was distinctly slower, 1538 — see
    # ops/conv.py SubpixelDeconv.thin). Kept reachable for other
    # chips/shapes; the exact weight mapping between the layouts is
    # pinned in tests/test_models.py.
    thin_head: bool = False
    # With thin_head: run the head's k2 conv through the Pallas fused
    # kernel (ops/pallas/subpixel_head.py — x read once per sample
    # block, tap matmuls accumulated in VMEM) instead of the XLA conv.
    head_pallas: bool = False
    # U-Net k4-s2 RGB stem (down0) as strided im2col patches + one dense
    # matmul (ops/conv.py PatchesConv with stride) — targets the bs=1
    # profile's 0.7 TF/s / 17 GB/s down0 wgrad. Off by default pending
    # an on-chip win; A/B via BENCH_STEM=1.
    thin_stem: bool = False
    # Feed D the UNCONCATENATED (a, b) conditional pair (the split-stem
    # form, models/patchgan._SplitStemConv): no materialized 6-channel
    # full-res pair tensors, conv(a, W_a) CSE-shared across the fake/real
    # branches. MEASURED shape-dependent: loses at 256²/bs128 (1661 vs
    # 1701 — the concat was already fused into the stem's window gather)
    # but the pair tensors at 1024×512 run at 26 GB/s in the round-4
    # profile, so the HD preset flips it on (round-5 ledger).
    split_d_pairs: bool = False


@dataclasses.dataclass(frozen=True)
class LossConfig:
    gan_mode: str = "lsgan"          # lsgan | vanilla | hinge
    lambda_feat: float = 10.0        # train.py:351
    lambda_vgg: float = 10.0         # train.py:377
    lambda_tv: float = 1.0           # train.py:378
    lambda_l1: float = 0.0           # reference --lamb=10 but L1 is dead (Q3)
    # Gram-matrix style loss — the reference's commented-out experiment
    # (train.py:370-382), live behind this weight.
    lambda_style: float = 0.0
    # Feed [-1,1] images to VGG un-normalized, as the reference does
    # (networks.py:26 — no ImageNet mean/std). Changes loss scale; keep
    # faithful by default.
    vgg_imagenet_norm: bool = False
    # Sobel edge L1 between fake and real — the reference's commented-out
    # edge experiment (train.py:307,313,362-363; sobelLayer at
    # networks.py:852). Dead there (0 here) but live behind this weight.
    lambda_sobel: float = 0.0
    # The reference's commented warmup schedule (train.py:445-448):
    # effective sobel weight ramps linearly to lambda_sobel over this
    # many epochs (``100/20*epoch`` shape); 0 = constant weight.
    sobel_warmup_epochs: int = 0
    # Mean angular error (degrees) between fake and real per-pixel color
    # vectors — the reference's commented-out experiment
    # (train.py:355-360; angular_loss at networks.py:870). 0 = off.
    lambda_angular: float = 0.0


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    lr: float = 2e-4                 # train.py:241-243
    beta1: float = 0.5
    beta2: float = 0.999
    lr_policy: str = "lambda"        # lambda | step | plateau | cosine (networks.py:104)
    niter: int = 100                 # epochs at constant lr
    niter_decay: int = 100           # epochs of linear decay to 0
    lr_decay_iters: int = 50         # step policy period
    # Fix Q1: the reference's optimizer_c holds net_d's params so net_c
    # never trains. True wires C's optimizer to C (intended behavior).
    train_compression_net: bool = True
    # Global-norm gradient clipping (0 = off, reference parity). The guard
    # for per-sample-norm backward blowups on degenerate (near-constant)
    # images — see train/state.py:make_optimizers.
    grad_clip: float = 0.0
    # Storage dtype for BOTH Adam moments (None = f32, reference parity;
    # "bfloat16" halves the optimizer state's HBM footprint AND per-step
    # traffic — the bs=1 facades budget is parameter/moment-traffic-bound,
    # BASELINE.md round-4). Params stay f32 masters; the moment math runs
    # in f32 and only the STORED moments round (train/state.py
    # scale_by_adam_lp).
    moment_dtype: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class DataConfig:
    root: str = "dataset"
    dataset: str = "facades"
    direction: str = "b2a"           # train.py:139
    image_size: int = 256
    image_width: Optional[int] = None  # None → square
    batch_size: int = 1              # train.py:143
    test_batch_size: int = 1
    threads: int = 4
    # Paired augmentation (the reference's commented-out resize-286 +
    # random-crop-256 + flip, dataset.py:28-46) on the train split.
    augment: bool = False
    # Video clips for vid2vid-style configs
    n_frames: int = 1
    # uint8 input pipeline: the decode memo stores raw bytes (4× less host
    # RAM than f32), H2D ships uint8 (4× less PCIe), and the train/eval
    # steps normalize ON DEVICE — (f32(u8) − 127.5)·(1/127.5), the one
    # canonical FMA-proof expression (utils/images.ingest), bit-exact with
    # the host normalize — so this is a pure transport optimization
    # (round-5 ledger row in BASELINE.md).
    uint8_pipeline: bool = True


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    mesh: MeshSpec = MeshSpec(data=-1, spatial=1, time=1)
    # Tensor parallelism (mesh.model > 1): smallest channel count the
    # Megatron pair rule shards (parallel/rules.py make_tp_rules). 512
    # keeps the narrow layers replicated where a psum would cost more
    # than the shard saves; tests/dryruns lower it so tiny models shard.
    tp_min_ch: int = 512
    # With mesh.fsdp > 1: extend the ZeRO state sharding from the
    # optimizer moments + EMA (always sharded over the fsdp axis —
    # parallel/rules.py make_fsdp_rules) to the params themselves
    # (ZeRO-3-ish). Off by default: the param all-gather then sits on
    # every forward's critical path, which only pays once params
    # themselves blow the HBM budget; moments+EMA are ~2/3 of the state
    # bytes (memory_budget.json) and shard free of that trade.
    fsdp_params: bool = False
    # Sync batch-norm statistics across the data axis (pmean). At bs=1 per
    # device this is the only way BatchNorm matches reference semantics.
    sync_batchnorm: bool = True
    # Remat the generator blocks to trade FLOPs/recompute for HBM:
    # False = off; True/"full" = classic full remat (min memory, recomputes
    # block convs); "conv" = save conv outputs + norm stats, recompute only
    # elementwise chains (policy remat — no extra MXU work).
    remat: Union[bool, str] = False
    # Latency-hiding GPipe schedule (parallel/pp.py gpipe_trunk overlap=):
    # the stage→stage ppermute is issued on the PREVIOUS tick's output, so
    # the transfer runs concurrently with this tick's block compute
    # (double-buffered hand-off). Costs S-1 extra fill/drain ticks —
    # pays when the ICI hop is a meaningful fraction of stage compute
    # (transfer_time/stage_time > (S-1)/(M+S-1)); off by default pending
    # an on-chip win at the driver's mesh shapes.
    pp_overlap: bool = False


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    nepoch: int = 200
    epoch_count: int = 1             # resume start epoch (train.py:137)
    epoch_save: int = 20             # --epochsave
    seed: int = 123                  # train.py:166
    log_every: int = 50
    checkpoint_dir: str = "checkpoint"
    result_dir: str = "result"
    eval_every_epoch: bool = True
    mixed_precision: bool = True
    # >1: run this many train steps per dispatch via lax.scan
    # (build_multi_train_step) — amortizes host/tunnel dispatch overhead
    # (~1.6x on the tunneled bench); leftover steps use the single-step path.
    scan_steps: int = 1
    # VFID (Fréchet distance over pooled VGG19 taps) during eval — the
    # north-star quality metric; needs lambda_vgg>0 or a VGG asset loaded.
    eval_fid: bool = False
    # Historical-fake pool fed to D's fake branch (reference ImagePool,
    # instantiated size 0 = passthrough at train.py:248). pool_size > 0
    # enables a DEVICE-side ring buffer in TrainState (utils.pool.
    # device_pool_query) holding (real_a ‖ fake_b) pairs.
    pool_size: int = 0
    # Persistent XLA compilation cache directory (core/cache.py): compiled
    # programs are reused across PROCESSES, so restarts/preemptions pay
    # XLA compile only on the first run ever. None = off. The serving
    # engine (p2p_tpu.serve) has its own knob with the same plumbing.
    compilation_cache_dir: Optional[str] = None
    # Elastic relaunch (docs/RESILIENCE.md "Elastic relaunch"): on resume,
    # reconcile the checkpoint's recorded topology (process count, mesh
    # axis sizes, global batch, dtype policy) against the current one and
    # RESHARD compatible deltas — a preemptible-fleet relaunch may land on
    # a different slice size. False = the strict pre-elastic contract:
    # any topology delta aborts with a diagnostic instead of resharding.
    elastic: bool = True
    # Opt-in dtype-policy migration on resume (resilience/reshape.py):
    # a mixed_precision/moment_dtype delta performs an explicit, LOGGED
    # cast (moments per the MOMENT_MIGRATION policy table, integrity
    # manifest regenerated post-cast) instead of aborting. False = the
    # safe default: dtype deltas abort with the flag named.
    cast_on_restore: bool = False
    # After a TP-width amax migration (tp_amax_recalibrate), hold the
    # remapped int8 scales FROZEN for this many dispatches — the paranoid
    # path's warmup before the decaying-max update resumes. 0 = off.
    recalibrate_steps: int = 0
    # jax_debug_nans: first NaN-producing primitive raises with location.
    debug_nans: bool = False
    # The reference's commented "masking" experiment (train.py:324-334):
    # dump mask.png = bitwise_and(uint8(fake_b), uint8(real_a)) next to
    # the eval sample images. Pure visualization — it feeds no loss in
    # the reference either.
    save_masks: bool = False


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Self-healing training (p2p_tpu.resilience.health): divergence
    sentinel -> recovery ladder -> last-good rollback, plus the EMA
    generator. ``enabled`` default True: the sentinel consumes metrics the
    loop already computes (one delayed small D2H per dispatch) and the
    in-jit skip guard folds into the existing update-scale multiply —
    measured-in-band on the healthy path (bench.py --chaos)."""

    enabled: bool = True
    # Sentinel: robust z-score over the last `window` HEALTHY steps per
    # watched loss (G/D/C + grad norms when tapped); a step is a SPIKE
    # when |z| > spike_zscore, DIVERGED when any watched value is
    # non-finite. The EWMA (alpha) smooths the reference level the
    # z-score recenters on.
    window: int = 32
    spike_zscore: float = 6.0
    ewma_alpha: float = 0.1
    # Ladder rung 2: scale the (G/D/C) LR by cooldown_factor for
    # cooldown_steps observed steps, then restore.
    cooldown_steps: int = 20
    cooldown_factor: float = 0.1
    # Ladder rung 3: rollbacks to the last-good checkpoint before the run
    # gives up with DIVERGED_EXIT_CODE (76).
    max_rollbacks: int = 3
    # A healthy streak this long resets the ladder to rung 0.
    reset_after: int = 16
    # EMA generator params (ProGAN-lineage stabilization): None = off
    # (TrainState.ema_g stays None — old checkpoints restore bit-for-bit);
    # 0.0 = EMA tracks params exactly (the parity-pin mode); 0.999 = the
    # classic smoothing. Eval and serving use the EMA weights when present.
    ema_decay: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class DebugConfig:
    """Numerical/telemetry debug taps (p2p_tpu.obs; all off by default —
    the happy path pays nothing)."""

    # Host-side post-dispatch guard over the step metrics (core/debug.
    # check_finite): emits a kind="nonfinite" record into the metrics
    # stream, then raises. Fetches the metrics every dispatch — a fence;
    # debugging flag, not a production default.
    check_finite: bool = False
    # In-jit NaN/Inf sentinel over the step metrics via jax.debug.callback
    # (obs/taps.py): async device→host counts, NO fence on the happy path.
    # Cheap enough to leave on in production when chasing instabilities.
    nan_sentinel: bool = False
    # Add grad_norm_g / grad_norm_d global-norm scalars to the step metrics
    # (they ride the metrics fetch the loop already pays for).
    grad_norms: bool = False


@dataclasses.dataclass(frozen=True)
class Config:
    name: str = "default"
    model: ModelConfig = ModelConfig()
    loss: LossConfig = LossConfig()
    optim: OptimConfig = OptimConfig()
    data: DataConfig = DataConfig()
    parallel: ParallelConfig = ParallelConfig()
    train: TrainConfig = TrainConfig()
    debug: DebugConfig = DebugConfig()
    health: HealthConfig = HealthConfig()

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    @property
    def image_hw(self) -> Tuple[int, int]:
        h = self.data.image_size
        w = self.data.image_width or h
        return h, w


# ----------------------------------------------------------------------------
# The five BASELINE.json target configs, checked in as presets.
# ----------------------------------------------------------------------------

_PRESETS = {}


def _register(cfg: Config) -> Config:
    _PRESETS[cfg.name] = cfg
    return cfg


# 1. facades 256×256 pix2pix (U-Net G + 70×70 PatchGAN D, bs=1)
_register(
    Config(
        name="facades",
        model=ModelConfig(generator="unet", ngf=64, num_D=1, n_layers_D=3,
                          use_spectral_norm=False, use_compression_net=False,
                          use_dropout=True),
        loss=LossConfig(lambda_feat=0.0, lambda_vgg=0.0, lambda_tv=0.0,
                        lambda_l1=100.0),
        data=DataConfig(dataset="facades", image_size=256, batch_size=1),
        parallel=ParallelConfig(mesh=MeshSpec(data=1)),
    )
)

# facades on the int8 QAT MXU path (ops/int8.py): identical architecture
# and losses; the DISCRIMINATOR's inner convs run s8×s8→s32 on the MXU
# (2× peak on v5e) with DELAYED (stored-scale) activation quantization —
# the round-3 headline path, trained to quality over 40 epochs on real
# photos (metrics_facades_int8_decay.jsonl). The generator stays bf16
# (int8_generator measured slower at this shape), stems/heads bf16.
_register(
    Config(
        name="facades_int8",
        model=ModelConfig(generator="unet", ngf=64, num_D=1, n_layers_D=3,
                          use_spectral_norm=False, use_compression_net=False,
                          use_dropout=True, int8=True, int8_delayed=True),
        loss=LossConfig(lambda_feat=0.0, lambda_vgg=0.0, lambda_tv=0.0,
                        lambda_l1=100.0),
        data=DataConfig(dataset="facades", image_size=256, batch_size=1),
        # bf16-stored Adam moments (round-5 ledger): bs=1 204→228 img/s
        # (the parameter/moment-traffic-bound path), ≥neutral at bs=128
        # (1716.0); quality pinned by metrics_mom16_q.jsonl (e9 peak
        # 22.6 PSNR on the 10-epoch decayed real256 protocol) and the
        # optax-trajectory unit test.
        optim=OptimConfig(moment_dtype="bfloat16"),
        parallel=ParallelConfig(mesh=MeshSpec(data=1)),
    )
)

# Reference-faithful config: ExpandNetwork + CompressionNetwork + multiscale D
# with the exact loss surface of /root/reference/train.py.
_register(
    Config(
        name="reference",
        model=ModelConfig(generator="expand"),
        loss=LossConfig(),
        data=DataConfig(dataset="facades", image_size=256, batch_size=1),
        parallel=ParallelConfig(mesh=MeshSpec(data=1)),
    )
)

# 2. edges2shoes 256×256, bs=64 data-parallel
_register(
    Config(
        name="edges2shoes_dp",
        model=ModelConfig(generator="unet", ngf=64, num_D=1, n_layers_D=3,
                          use_spectral_norm=False, use_compression_net=False,
                          use_dropout=True),
        loss=LossConfig(lambda_feat=0.0, lambda_vgg=0.0, lambda_tv=0.0,
                        lambda_l1=100.0),
        data=DataConfig(dataset="edges2shoes", image_size=256, batch_size=64),
        parallel=ParallelConfig(mesh=MeshSpec(data=-1)),
    )
)

# 3. Cityscapes labels→photo 512×256 (GSPMD spatial shard)
_register(
    Config(
        name="cityscapes_spatial",
        model=ModelConfig(generator="resnet", ngf=64, norm="instance",
                          use_compression_net=False),
        loss=LossConfig(lambda_l1=0.0),
        data=DataConfig(dataset="cityscapes", image_size=256, image_width=512,
                        batch_size=4),
        parallel=ParallelConfig(mesh=MeshSpec(data=-1, spatial=2)),
    )
)

# 4. pix2pixHD multi-scale G/D at 1024×512 (Pallas InstanceNorm + conv)
_register(
    Config(
        name="pix2pixhd",
        # split_d_pairs: at 1024×512 the materialized 6-ch pair tensors
        # run at 26 GB/s (round-4 profile); the split-stem form measures
        # 8.76 vs 8.65 img/s (round-5 ledger). With the _NearestUp2Conv
        # subpixel dispatch (+7.5%) the preset is 8.05 → 8.76 overall.
        model=ModelConfig(generator="pix2pixhd", ngf=64, norm="pallas_instance",
                          num_D=3, n_layers_D=3, use_compression_net=False,
                          split_d_pairs=True),
        loss=LossConfig(lambda_feat=10.0, lambda_vgg=10.0, lambda_tv=0.0),
        data=DataConfig(dataset="cityscapes_hd", image_size=512,
                        image_width=1024, batch_size=1),
        # remat off: 1024×512 bs=1 fits single-chip HBM and full remat
        # costs 20% (README perf table); switch to remat="conv" (keep conv
        # outputs, recompute elementwise) on tighter-memory meshes.
        parallel=ParallelConfig(mesh=MeshSpec(data=-1, spatial=2)),
    )
)

# 5. vid2vid 8-frame temporal discriminator (sequence-parallel over ICI)
_register(
    Config(
        name="vid2vid_temporal",
        model=ModelConfig(generator="unet", ngf=64, norm="instance",
                          use_compression_net=False),
        loss=LossConfig(lambda_feat=10.0, lambda_vgg=0.0, lambda_tv=0.0),
        data=DataConfig(dataset="vid2vid", image_size=256, batch_size=1,
                        n_frames=8),
        parallel=ParallelConfig(mesh=MeshSpec(data=-1, time=4)),
    )
)


def get_preset(name: str) -> Config:
    try:
        return _PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown preset {name!r}; have {sorted(_PRESETS)}") from None


def int8_full_coverage(cfg: Config) -> Config:
    """The ONE definition of "full-model delayed int8" (ISSUE 14): every
    coverage knob the --int8-diff worklist drained, on top of ``cfg``.

    Shared by the lint CLI (the ``train_step[facades_int8_full]`` traced
    program the coverage worklist audits) and ``bench.py``'s
    ``facades_int8_full`` band-pending sweep row, so the statically audited
    program and the measured one can never drift apart. Deliberately NOT
    flipped: ``int8_stem`` (HBM-bound 3/6-ch stems — the measured-rejected
    verdict carried by dated in-source waivers) and the U-Net image head
    (quality + HBM critical, no knob)."""
    return dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model,
            int8=True,
            int8_delayed=True,
            int8_generator=True,
            int8_decoder=True,
            int8_head=True,
            use_compression_net=True,
            int8_compression=True,
        ),
    )


# The full-coverage int8 config as a FIRST-CLASS preset (ISSUE 15): the
# on-TPU measurement of record for the ROADMAP item-2 band decision rides
# the default sweep as a plain --preset/BENCH_PRESET row — no opt-out env
# gate between the measurement and the round. Same override set the lint
# CLI traces as train_step[facades_int8_full], so the static and measured
# programs still cannot drift.
_register(int8_full_coverage(_PRESETS["facades_int8"]).replace(
    name="facades_int8_full"))


def list_presets():
    return sorted(_PRESETS)
