"""Numerical-debug guards (SURVEY §5.2: the reference's only failure mode
is numerical — Inf-PSNR clamping at train.py:480-482, isnan import at
train.py:6 — and JAX's functional purity removes the race-condition class
entirely, so this is the sanitizer surface).

- :func:`enable_nan_debugging` — turn on ``jax_debug_nans`` so the first
  NaN-producing primitive raises with its location (re-runs the op
  un-jitted; debugging tool, not a production guard).
- :func:`check_finite` — host-side pytree guard for post-step use.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def enable_nan_debugging(enable: bool = True) -> None:
    jax.config.update("jax_debug_nans", enable)


def check_finite(tree: Any, name: str = "tree") -> None:
    """Raise FloatingPointError naming the first non-finite leaf."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.all(np.isfinite(arr)):
            keys = "/".join(str(getattr(p, "key", p)) for p in path)
            raise FloatingPointError(
                f"non-finite values in {name}:{keys} "
                f"(nan={int(np.isnan(arr).sum())}, inf={int(np.isinf(arr).sum())})"
            )
