"""Numerical-debug guards (SURVEY §5.2: the reference's only failure mode
is numerical — Inf-PSNR clamping at train.py:480-482, isnan import at
train.py:6 — and JAX's functional purity removes the race-condition class
entirely, so this is the sanitizer surface).

- :func:`enable_nan_debugging` — turn on ``jax_debug_nans`` so the first
  NaN-producing primitive raises with its location (re-runs the op
  un-jitted; debugging tool, not a production guard).
- :func:`check_finite` — host-side pytree guard, wired into the train loop
  behind ``cfg.debug.check_finite``: emits a ``kind="nonfinite"`` record
  into the telemetry stream (so the evidence survives the crash) and then
  raises. The fence-free in-jit variant is
  :func:`p2p_tpu.obs.taps.nan_sentinel`.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import numpy as np


def enable_nan_debugging(enable: bool = True) -> None:
    jax.config.update("jax_debug_nans", enable)


def find_nonfinite(tree: Any) -> List[Dict[str, int]]:
    """Host-side scan of a pytree for non-finite floats; returns one
    ``{"leaf": path, "nan": n, "inf": n}`` entry per offending leaf.
    Fetches every leaf — a fence; use only behind a debug flag or on
    already-fetched host values."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.all(np.isfinite(arr)):
            keys = "/".join(str(getattr(p, "key", p)) for p in path)
            out.append({"leaf": keys, "nan": int(np.isnan(arr).sum()),
                        "inf": int(np.isinf(arr).sum())})
    return out


def check_finite(tree: Any, name: str = "tree", registry=None,
                 raise_: bool = True) -> List[Dict[str, int]]:
    """Guard a pytree: emit a telemetry event for non-finite leaves, then
    raise ``FloatingPointError`` naming the first one (unless ``raise_`` is
    False, for callers that degrade instead of dying). ``registry`` is a
    :class:`p2p_tpu.obs.MetricsRegistry` (or anything with ``.record``)."""
    findings = find_nonfinite(tree)
    if not findings:
        return findings
    if registry is not None:
        registry.record(
            {"kind": "nonfinite", "name": name, "leaves": findings},
            force=True,
        )
    if raise_:
        f = findings[0]
        raise FloatingPointError(
            f"non-finite values in {name}:{f['leaf']} "
            f"(nan={f['nan']}, inf={f['inf']})"
        )
    return findings
