"""Dtype policy: bf16 compute on the MXU, fp32 where precision matters.

The reference trains everything in fp32 on CUDA (it sets no dtype anywhere;
torch defaults). On TPU the MXU natively multiplies bf16 at full rate, so the
policy here is the standard mixed-precision recipe: parameters and optimizer
state in fp32, matmul/conv compute in bf16, normalization statistics and loss
reductions in fp32.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DTypePolicy:
    """Which dtype each class of value uses.

    param_dtype: master copy of weights (fp32 keeps Adam stable).
    compute_dtype: activations + matmul/conv inputs (bf16 feeds the MXU at
        full rate and halves HBM traffic).
    norm_dtype: normalization statistics (mean/var) — fp32; bf16's 8-bit
        mantissa visibly degrades variance estimates at GAN scales.
    loss_dtype: loss reductions — fp32.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    norm_dtype: jnp.dtype = jnp.float32
    loss_dtype: jnp.dtype = jnp.float32

    def cast_compute(self, x):
        return jnp.asarray(x, self.compute_dtype)

    def cast_norm(self, x):
        return jnp.asarray(x, self.norm_dtype)

    def cast_loss(self, x):
        return jnp.asarray(x, self.loss_dtype)


def default_policy(mixed: bool = True) -> DTypePolicy:
    if mixed:
        return DTypePolicy()
    return DTypePolicy(compute_dtype=jnp.float32)
