"""Device mesh construction — the substrate for every parallelism strategy.

The framework uses one global ``jax.sharding.Mesh`` with up to three named
axes:

- ``data``    data parallelism (per-device batch shards, gradient psum)
- ``spatial`` GSPMD spatial sharding of the image H dimension (large images;
              conv halo exchange handled in ``p2p_tpu.parallel.spatial``)
- ``time``    temporal sequence parallelism for video discriminators

The reference has no distributed layer at all (SURVEY.md §2.3): its only
parallelism is DataLoader worker processes. Here the mesh is first-class and
every train step is jitted over it; XLA inserts the ICI collectives.

On a real multi-host slice call :func:`distributed_init` first (wraps
``jax.distributed.initialize``); on a single host (or the CPU test fixture
with ``--xla_force_host_platform_device_count=8``) meshes are built from the
locally visible devices.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"
TIME_AXIS = "time"
MODEL_AXIS = "model"   # tensor parallelism: conv channel dims (parallel/tp.py)
PIPE_AXIS = "pipe"     # pipeline parallelism: trunk stages (parallel/pp.py)
ALL_AXES = (DATA_AXIS, SPATIAL_AXIS, TIME_AXIS, MODEL_AXIS, PIPE_AXIS)


# --------------------------------------------------------------- jax compat
# The TPU image ships a vma-era jax (public ``jax.shard_map`` with
# varying-manual-axes typing); CPU-only CI containers may carry a 0.4.x
# jax where shard_map is experimental and typed by the older rep-checker.
# Every manual-sharding region in the repo goes through these two shims so
# both environments run the same programs.

def shard_map_compat(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    On vma-era jax this is the public API verbatim; on 0.4.x it falls back
    to ``jax.experimental.shard_map`` with ``check_rep=False`` (the old
    rep-checker cannot type the axis_index-dependent carries the pipeline
    and halo programs build — the vma system can)."""
    try:
        from jax import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def pcast_varying(x, axes):
    """``jax.lax.pcast(..., to='varying')`` where it exists — the vma
    system needs replicated constants cast to the varying type before they
    enter stage-varying control flow; identity on pre-vma jax, where the
    check_rep=False fallback above disables that tracking entirely."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return x


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 on the data axis means "all remaining devices"."""

    data: int = -1
    spatial: int = 1
    time: int = 1
    model: int = 1   # tensor-parallel axis (channel dims; parallel/tp.py)
    pipe: int = 1    # pipeline-parallel axis (trunk stages; parallel/pp.py)

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        d, s, t, m, p = (self.data, self.spatial, self.time, self.model,
                         self.pipe)
        fixed = s * t * m * p
        if d == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"spatial*time*model*pipe={fixed}"
                )
            d = n_devices // fixed
        if d * s * t * m * p > n_devices:
            raise ValueError(
                f"mesh {d}x{s}x{t}x{m}x{p} needs more than the {n_devices} "
                "devices available"
            )
        return d, s, t, m, p


def make_mesh(
    spec: MeshSpec = MeshSpec(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global mesh.

    Axis order is (data, spatial, time, model, pipe) with data outermost: JAX
    lays devices out so the *innermost* axes are nearest-neighbor on the ICI
    torus, which is where the bandwidth-hungry halo exchanges (spatial), ring
    shifts (time), and pipeline stage hand-offs (pipe: neighbor ppermute every
    tick) live; data-parallel all-reduces tolerate the longer hops.
    """
    devices = list(devices if devices is not None else jax.devices())
    d, s, t, m, p = spec.resolve(len(devices))
    dev_array = np.asarray(devices[: d * s * t * m * p]).reshape(d, s, t, m, p)
    return Mesh(
        dev_array,
        axis_names=(DATA_AXIS, SPATIAL_AXIS, TIME_AXIS, MODEL_AXIS, PIPE_AXIS),
    )


def single_device_mesh() -> Mesh:
    return make_mesh(MeshSpec(data=1), devices=jax.devices()[:1])


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host barrier/init. No-op when running single-process."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical sharding for NHWC image batches: N over data, H over spatial."""
    return NamedSharding(mesh, P(DATA_AXIS, SPATIAL_AXIS, None, None))


def video_sharding(mesh: Mesh) -> NamedSharding:
    """NTHWC video batches: N over data, T over time, H over spatial."""
    return NamedSharding(mesh, P(DATA_AXIS, TIME_AXIS, SPATIAL_AXIS, None, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


_ACTIVE_MESH: contextvars.ContextVar[Optional[Mesh]] = contextvars.ContextVar(
    "p2p_tpu_active_mesh", default=None
)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]):
    """Expose ``mesh`` to layers traced within this context.

    The parallel step builders (p2p_tpu.parallel.dp) enter this around the
    step body so ops that need manual sharding regions — the Pallas
    InstanceNorm, which GSPMD would otherwise wrap in a full all-gather of
    the activations (custom calls have no partitioning rule) — can wrap
    themselves in ``shard_map`` over the active mesh at trace time.
    """
    token = _ACTIVE_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _ACTIVE_MESH.reset(token)


def current_mesh() -> Optional[Mesh]:
    """The mesh made visible by :func:`mesh_context`, or None."""
    return _ACTIVE_MESH.get()


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-host batch for the input pipeline (global / number of processes)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    del mesh
    return global_batch // n_proc
