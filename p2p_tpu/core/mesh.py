"""Device mesh construction — the substrate for every parallelism strategy.

The framework uses one global ``jax.sharding.Mesh`` with up to three named
axes:

- ``data``    data parallelism (per-device batch shards, gradient psum)
- ``spatial`` GSPMD spatial sharding of the image H dimension (large images;
              conv halo exchange handled in ``p2p_tpu.parallel.spatial``)
- ``time``    temporal sequence parallelism for video discriminators

The reference has no distributed layer at all (SURVEY.md §2.3): its only
parallelism is DataLoader worker processes. Here the mesh is first-class and
every train step is jitted over it; XLA inserts the ICI collectives.

On a real multi-host slice call :func:`distributed_init` first (wraps
``jax.distributed.initialize``); on a single host (or the CPU test fixture
with ``--xla_force_host_platform_device_count=8``) meshes are built from the
locally visible devices.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"
TIME_AXIS = "time"
MODEL_AXIS = "model"   # tensor parallelism: conv channel dims (parallel/tp.py)
PIPE_AXIS = "pipe"     # pipeline parallelism: trunk stages (parallel/pp.py)
ALL_AXES = (DATA_AXIS, SPATIAL_AXIS, TIME_AXIS, MODEL_AXIS, PIPE_AXIS)


# --------------------------------------------------------------- jax compat
# The TPU image ships a vma-era jax (public ``jax.shard_map`` with
# varying-manual-axes typing); CPU-only CI containers may carry a 0.4.x
# jax where shard_map is experimental and typed by the older rep-checker.
# Every manual-sharding region in the repo goes through these two shims so
# both environments run the same programs.

def shard_map_compat(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    On vma-era jax this is the public API verbatim; on 0.4.x it falls back
    to ``jax.experimental.shard_map`` with ``check_rep=False`` (the old
    rep-checker cannot type the axis_index-dependent carries the pipeline
    and halo programs build — the vma system can)."""
    try:
        from jax import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def pcast_varying(x, axes):
    """``jax.lax.pcast(..., to='varying')`` where it exists — the vma
    system needs replicated constants cast to the varying type before they
    enter stage-varying control flow; identity on pre-vma jax, where the
    check_rep=False fallback above disables that tracking entirely."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return x


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 on the data axis means "all remaining devices"."""

    data: int = -1
    spatial: int = 1
    time: int = 1
    model: int = 1   # tensor-parallel axis (channel dims; parallel/tp.py)
    pipe: int = 1    # pipeline-parallel axis (trunk stages; parallel/pp.py)

    def resolve(self, n_devices: int,
                context: str = "") -> tuple[int, int, int, int, int]:
        """Concrete per-axis sizes for ``n_devices``.

        ``context`` (optional) is appended to the failure diagnostics —
        the elastic-relaunch path passes the topology the checkpoint was
        saved on, so "my relaunch flags don't fit this slice" reads as
        exactly that instead of a bare divisibility error.
        """
        d, s, t, m, p = (self.data, self.spatial, self.time, self.model,
                         self.pipe)
        fixed = s * t * m * p
        suffix = f"; {context}" if context else ""
        if d == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"mesh data=-1,spatial={s},time={t},model={m},pipe={p} "
                    f"cannot resolve: {n_devices} device(s) not divisible "
                    f"by spatial*time*model*pipe={fixed} — pick axes whose "
                    f"product divides the device count{suffix}"
                )
            d = n_devices // fixed
        if d * s * t * m * p > n_devices:
            raise ValueError(
                f"mesh data={d},spatial={s},time={t},model={m},pipe={p} "
                f"needs {d * s * t * m * p} devices but only {n_devices} "
                f"are available — shrink an axis or use data=-1 (all "
                f"remaining devices){suffix}"
            )
        return d, s, t, m, p


class TopologyMismatch(ValueError):
    """An elastic relaunch hit a topology delta the resharded-resume path
    cannot reconcile (classified ``abort`` by
    :func:`classify_topology_delta`), or elastic resume was disabled.
    The message names the saved vs. current topology and what to change."""


def mesh_topology(mesh: Optional[Mesh]) -> dict:
    """The recorded topology block for the checkpoint aux sidecar: the
    facts a relaunch must reconcile against before it can restore.

    JSON-able on purpose — this rides the iterator-state sidecar
    (train/checkpoint.py save_aux), not the Orbax tree."""
    sizes = {str(a): int(s) for a, s in dict(mesh.shape).items()} \
        if mesh is not None else {}
    return {
        "process_count": int(jax.process_count()),
        "device_count": int(mesh.size) if mesh is not None
        else len(jax.devices()),
        "mesh": sizes,
    }


def describe_topology(topo: dict) -> str:
    """One-line human form of a topology block (for diagnostics/logs)."""
    mesh = topo.get("mesh") or {}
    axes = ",".join(f"{a}={mesh[a]}" for a in mesh) or "none"
    return (f"{topo.get('process_count', '?')} process(es) x "
            f"{topo.get('device_count', '?')} device(s), mesh [{axes}], "
            f"global_batch={topo.get('global_batch', '?')}")


@dataclasses.dataclass(frozen=True)
class TopologyDelta:
    """Classification of a saved-vs-current topology difference.

    ``kind``:
    - ``"same"``    identical topology — the plain exact-step resume path
    - ``"reshard"`` a compatible delta (process count, data/spatial/time
      axis widths, device count): restore proceeds with target shardings
      derived for the NEW mesh, and the per-host data skip re-derives
      from the global step
    - ``"abort"``   an incompatible delta (global batch, dtype policy,
      pipe width, TP width under int8 amax state): resuming would corrupt
      sample accounting or state semantics — fail with instructions
    """

    kind: str
    reason: str


def classify_topology_delta(saved: dict, current: dict,
                            has_quant_state: bool = False) -> TopologyDelta:
    """Reconcile a checkpoint's recorded topology block against the
    relaunch's. Rules (the narrow, auditable core of elastic resume):

    - ``global_batch`` change → abort: ``steps_per_epoch`` and the
      optimizer trajectory both shift, so gapless sample accounting is
      impossible — the step counter no longer names a sample position.
    - dtype-policy change (``mixed_precision``/``moment_dtype``/
      ``int8_delayed``) → abort: Orbax would silently cast, changing
      numerics without a trace.
    - ``pipe`` width change → abort: pp_split_state restructures the
      TrainState tree itself, not just shardings.
    - ``model`` (TP) width change under delayed-int8 quant state →
      abort: the stored per-layer amax scales were calibrated against
      the saved shard width.
    - any other mesh-axis / process-count / device-count change →
      reshard (params are replicated or rule-resharded over these axes;
      the input pipeline re-derives per-host shards from the global
      step).

    Keys absent from ``saved`` (older sidecars) are treated as matching —
    forward-compatible by construction.
    """
    def differs(key):
        return key in saved and saved[key] != current.get(key)

    for key, why in (
        ("global_batch",
         "the global batch size changed — steps_per_epoch and sample "
         "accounting cannot line up; relaunch with the original "
         "--batch_size"),
        ("mixed_precision",
         "the mixed-precision policy changed — restore would silently "
         "cast the state; relaunch with the original precision flags"),
        ("moment_dtype",
         "the Adam moment storage dtype changed — restore would silently "
         "cast the optimizer state; relaunch with the original "
         "--moment_dtype"),
        ("int8_delayed",
         "the delayed-int8 policy changed — the TrainState tree differs "
         "(quant collections); relaunch with the original --int8_delayed"),
    ):
        if differs(key):
            return TopologyDelta("abort", why)
    # A sidecar with no "mesh" key at all (pre-elastic) recorded nothing
    # to reconcile mesh-wise — skip the axis comparisons. An EMPTY
    # recorded mesh (a single-device save) is different: relaunching onto
    # a real mesh is a legitimate reshard.
    has_saved_mesh = "mesh" in saved
    saved_mesh = saved.get("mesh") or {}
    cur_mesh = current.get("mesh") or {}

    def axis(block, name):
        return int(block.get(name, 1))

    if has_saved_mesh:
        if axis(saved_mesh, PIPE_AXIS) != axis(cur_mesh, PIPE_AXIS):
            return TopologyDelta(
                "abort",
                "the pipeline-parallel width changed — pp_split_state "
                "restructures the TrainState tree; relaunch with the "
                "original pipe axis")
        if axis(saved_mesh, MODEL_AXIS) != axis(cur_mesh, MODEL_AXIS) \
                and has_quant_state:
            return TopologyDelta(
                "abort",
                "the tensor-parallel width changed under delayed-int8 amax "
                "state — stored activation scales are calibrated per shard "
                "width; relaunch with the original model axis (or resume "
                "without --int8_delayed from a fresh run)")
    changed = [k for k in ("process_count", "device_count")
               if differs(k)]
    if has_saved_mesh:
        changed += [f"mesh.{a}" for a in set(saved_mesh) | set(cur_mesh)
                    if axis(saved_mesh, a) != axis(cur_mesh, a)]
    if changed:
        return TopologyDelta(
            "reshard", "topology delta: " + ", ".join(sorted(changed)))
    return TopologyDelta("same", "identical topology")


def make_mesh(
    spec: MeshSpec = MeshSpec(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global mesh.

    Axis order is (data, spatial, time, model, pipe) with data outermost: JAX
    lays devices out so the *innermost* axes are nearest-neighbor on the ICI
    torus, which is where the bandwidth-hungry halo exchanges (spatial), ring
    shifts (time), and pipeline stage hand-offs (pipe: neighbor ppermute every
    tick) live; data-parallel all-reduces tolerate the longer hops.
    """
    devices = list(devices if devices is not None else jax.devices())
    d, s, t, m, p = spec.resolve(len(devices))
    dev_array = np.asarray(devices[: d * s * t * m * p]).reshape(d, s, t, m, p)
    return Mesh(
        dev_array,
        axis_names=(DATA_AXIS, SPATIAL_AXIS, TIME_AXIS, MODEL_AXIS, PIPE_AXIS),
    )


def single_device_mesh() -> Mesh:
    return make_mesh(MeshSpec(data=1), devices=jax.devices()[:1])


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host barrier/init. No-op when running single-process."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical sharding for NHWC image batches: N over data, H over spatial."""
    return NamedSharding(mesh, P(DATA_AXIS, SPATIAL_AXIS, None, None))


def video_sharding(mesh: Mesh) -> NamedSharding:
    """NTHWC video batches: N over data, T over time, H over spatial."""
    return NamedSharding(mesh, P(DATA_AXIS, TIME_AXIS, SPATIAL_AXIS, None, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


_ACTIVE_MESH: contextvars.ContextVar[Optional[Mesh]] = contextvars.ContextVar(
    "p2p_tpu_active_mesh", default=None
)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]):
    """Expose ``mesh`` to layers traced within this context.

    The parallel step builders (p2p_tpu.parallel.dp) enter this around the
    step body so ops that need manual sharding regions — the Pallas
    InstanceNorm, which GSPMD would otherwise wrap in a full all-gather of
    the activations (custom calls have no partitioning rule) — can wrap
    themselves in ``shard_map`` over the active mesh at trace time.
    """
    token = _ACTIVE_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _ACTIVE_MESH.reset(token)


def current_mesh() -> Optional[Mesh]:
    """The mesh made visible by :func:`mesh_context`, or None."""
    return _ACTIVE_MESH.get()


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-host batch for the input pipeline (global / number of processes)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    del mesh
    return global_batch // n_proc
