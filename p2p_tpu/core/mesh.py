"""Device mesh construction — the substrate for every parallelism strategy.

The framework uses one global ``jax.sharding.Mesh`` with named axes:

- ``data``    data parallelism (per-device batch shards, gradient psum)
- ``fsdp``    ZeRO-style state sharding (parallel/rules.py): batches shard
              over it exactly like ``data``, but optimizer moments / EMA
              (and, behind ``ParallelConfig.fsdp_params``, params) are
              PARTITIONED over it instead of replicated — gather-on-use
              is GSPMD's job via the pjit in/out shardings
- ``spatial`` GSPMD spatial sharding of the image H dimension (large images;
              conv halo exchange handled in ``p2p_tpu.parallel.spatial``)
- ``time``    temporal sequence parallelism for video discriminators

The reference has no distributed layer at all (SURVEY.md §2.3): its only
parallelism is DataLoader worker processes. Here the mesh is first-class and
every train step is jitted over it; XLA inserts the ICI collectives.

On a real multi-host slice call :func:`distributed_init` first (wraps
``jax.distributed.initialize``); on a single host (or the CPU test fixture
with ``--xla_force_host_platform_device_count=8``) meshes are built from the
locally visible devices.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"     # ZeRO state sharding: moments/EMA/params (parallel/rules.py)
SPATIAL_AXIS = "spatial"
TIME_AXIS = "time"
MODEL_AXIS = "model"   # tensor parallelism: conv channel dims (parallel/tp.py)
PIPE_AXIS = "pipe"     # pipeline parallelism: trunk stages (parallel/pp.py)
ALL_AXES = (DATA_AXIS, FSDP_AXIS, SPATIAL_AXIS, TIME_AXIS, MODEL_AXIS,
            PIPE_AXIS)
#: the axes a batch's leading (N) dimension shards over — fsdp devices
#: see distinct samples exactly like data devices; only the STATE layout
#: differs between the two axes
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


# --------------------------------------------------------------- jax compat
# The TPU image ships a vma-era jax (public ``jax.shard_map`` with
# varying-manual-axes typing); CPU-only CI containers may carry a 0.4.x
# jax where shard_map is experimental and typed by the older rep-checker.
# Every manual-sharding region in the repo goes through these two shims so
# both environments run the same programs.

def shard_map_compat(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    On vma-era jax this is the public API verbatim; on 0.4.x it falls back
    to ``jax.experimental.shard_map`` with ``check_rep=False`` (the old
    rep-checker cannot type the axis_index-dependent carries the pipeline
    and halo programs build — the vma system can)."""
    try:
        from jax import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def pcast_varying(x, axes):
    """``jax.lax.pcast(..., to='varying')`` where it exists — the vma
    system needs replicated constants cast to the varying type before they
    enter stage-varying control flow; identity on pre-vma jax, where the
    check_rep=False fallback above disables that tracking entirely."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return x


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 on the data axis means "all remaining devices"."""

    data: int = -1
    spatial: int = 1
    time: int = 1
    model: int = 1   # tensor-parallel axis (channel dims; parallel/tp.py)
    pipe: int = 1    # pipeline-parallel axis (trunk stages; parallel/pp.py)
    fsdp: int = 1    # ZeRO state-sharding axis (parallel/rules.py)

    def resolve(self, n_devices: int,
                context: str = "") -> tuple[int, int, int, int, int, int]:
        """Concrete per-axis sizes ``(data, fsdp, spatial, time, model,
        pipe)`` for ``n_devices``.

        ``context`` (optional) is appended to the failure diagnostics —
        the elastic-relaunch path passes the topology the checkpoint was
        saved on, so "my relaunch flags don't fit this slice" reads as
        exactly that instead of a bare divisibility error.
        """
        d, f, s, t, m, p = (self.data, self.fsdp, self.spatial, self.time,
                            self.model, self.pipe)
        fixed = f * s * t * m * p
        suffix = f"; {context}" if context else ""
        if d == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"mesh data=-1,fsdp={f},spatial={s},time={t},model={m},"
                    f"pipe={p} cannot resolve: {n_devices} device(s) not "
                    f"divisible by fsdp*spatial*time*model*pipe={fixed} — "
                    f"pick axes whose product divides the device "
                    f"count{suffix}"
                )
            d = n_devices // fixed
        if d * fixed > n_devices:
            raise ValueError(
                f"mesh data={d},fsdp={f},spatial={s},time={t},model={m},"
                f"pipe={p} needs {d * fixed} devices but only {n_devices} "
                f"are available — shrink an axis or use data=-1 (all "
                f"remaining devices){suffix}"
            )
        return d, f, s, t, m, p


def parse_mesh_arg(text: str) -> MeshSpec:
    """The ``--mesh`` flag grammar, shared by every CLI.

    Two forms:

    - positional (legacy): ``data,spatial,time[,model[,pipe]]``
      comma-separated ints — ``2,1,1,2`` is data=2 × model=2;
    - named: ``axis=size[,axis=size...]`` over the full vocabulary
      (``data``/``fsdp``/``spatial``/``time``/``model``/``pipe``), any
      order, unnamed axes default to 1 (data to -1 when omitted) —
      ``data=4,fsdp=2,model=2``. The named form is the only way to
      address the ``fsdp`` axis.

    Raises ``ValueError`` with the offending text; CLIs turn that into
    their usage error.
    """
    text = text.strip()
    if "=" in text:
        sizes = {}
        for part in text.split(","):
            if not part.strip():
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            if key not in ALL_AXES:
                raise ValueError(
                    f"unknown mesh axis {key!r} (have {ALL_AXES})")
            if key in sizes:
                raise ValueError(f"mesh axis {key!r} named twice")
            sizes[key] = int(val)
        spec = MeshSpec(data=sizes.pop(DATA_AXIS, -1), **sizes)
    else:
        vals = [int(v) for v in text.split(",")]
        if len(vals) < 3:   # only model/pipe are optional
            raise ValueError("too few axes")
        while len(vals) < 5:
            vals.append(1)
        if len(vals) > 5:
            raise ValueError("too many axes (use the named form for fsdp)")
        d, s, t, m, p = vals
        spec = MeshSpec(data=d, spatial=s, time=t, model=m, pipe=p)
    for axis in ALL_AXES:
        size = getattr(spec, axis)
        if size < 1 and not (axis == DATA_AXIS and size == -1):
            raise ValueError(
                f"mesh axis {axis}={size}: axes must be >=1 (data may be "
                "-1 = all remaining devices)")
    return spec


class TopologyMismatch(ValueError):
    """An elastic relaunch hit a topology delta the resharded-resume path
    cannot reconcile (classified ``abort`` by
    :func:`classify_topology_delta`), or elastic resume was disabled.
    The message names the saved vs. current topology and what to change."""


def mesh_topology(mesh: Optional[Mesh]) -> dict:
    """The recorded topology block for the checkpoint aux sidecar: the
    facts a relaunch must reconcile against before it can restore.

    JSON-able on purpose — this rides the iterator-state sidecar
    (train/checkpoint.py save_aux), not the Orbax tree."""
    sizes = {str(a): int(s) for a, s in dict(mesh.shape).items()} \
        if mesh is not None else {}
    return {
        "process_count": int(jax.process_count()),
        "device_count": int(mesh.size) if mesh is not None
        else len(jax.devices()),
        "mesh": sizes,
    }


def describe_topology(topo: dict) -> str:
    """One-line human form of a topology block (for diagnostics/logs)."""
    mesh = topo.get("mesh") or {}
    axes = ",".join(f"{a}={mesh[a]}" for a in mesh) or "none"
    return (f"{topo.get('process_count', '?')} process(es) x "
            f"{topo.get('device_count', '?')} device(s), mesh [{axes}], "
            f"global_batch={topo.get('global_batch', '?')}")


@dataclasses.dataclass(frozen=True)
class TopologyDelta:
    """Classification of a saved-vs-current topology difference.

    ``kind``:
    - ``"same"``    identical topology — the plain exact-step resume path
    - ``"reshard"`` a compatible delta (process count, data/fsdp/
      spatial/time axis widths, device count): restore proceeds with
      target shardings derived for the NEW mesh, and the per-host data
      skip re-derives from the global step
    - ``"migrate"`` a delta that is lawful only THROUGH a restore-time
      state transform (p2p_tpu.resilience.reshape): ``chain`` names the
      transforms, in application order — ``batch_rebase`` (global-batch
      change: step/epoch/LR basis re-derived from cumulative samples),
      ``pp_restructure`` (pipe-width change: trunk merge + re-split),
      ``tp_amax_recalibrate`` (TP-width change under delayed-int8 amax
      state: closed-form max/broadcast scale remap), ``dtype_cast``
      (explicit, logged dtype-policy cast — opt-in via
      ``--cast_on_restore``)
    - ``"abort"``   a genuinely unreconcilable delta (dtype policy
      without the cast opt-in, ``int8_delayed`` on/off — the TrainState
      TREE differs, no cast fixes that): fail with instructions
    """

    kind: str
    reason: str
    #: migrate-only: transform names, in the order reshape.py applies them
    chain: tuple = ()


def classify_topology_delta(saved: dict, current: dict,
                            has_quant_state: bool = False,
                            cast_on_restore: bool = False) -> TopologyDelta:
    """Reconcile a checkpoint's recorded topology block against the
    relaunch's. Rules (the narrow, auditable core of elastic resume):

    - ``global_batch`` change → migrate (``batch_rebase``): the step
      counter stops naming a sample position, so step/epoch position,
      ``steps_per_epoch``, the LR-schedule basis, and the loader's skip
      arithmetic are re-derived from the sidecar's cumulative
      ``samples_seen`` — accounting stays gapless in SAMPLES.
    - ``mixed_precision``/``moment_dtype`` change → migrate
      (``dtype_cast``) when ``cast_on_restore`` (the ``--cast_on_restore``
      opt-in): the cast is explicit and logged, optimizer moments follow
      the migration policy table, and the integrity manifest is
      regenerated post-cast; WITHOUT the opt-in → abort (Orbax would
      silently cast, changing numerics without a trace).
    - ``int8_delayed`` change → abort always: the TrainState TREE
      differs (quant collections appear/disappear) — not a cast.
    - ``pipe`` width change → migrate (``pp_restructure``): the
      stage-stacked trunk merges back to the flat trunk and re-splits at
      the new width (pipe→no-pipe and no-pipe→pipe are the degenerate
      cases), optimizer moments preserved.
    - ``model`` (TP) width change under delayed-int8 quant state →
      migrate (``tp_amax_recalibrate``): amax is a max statistic, so the
      resharding law is closed-form (ops/int8.reshard_amax).
    - any other mesh-axis / process-count / device-count change →
      reshard (params are replicated or rule-resharded over these axes;
      the input pipeline re-derives per-host shards from the global
      step). The ``fsdp`` axis deliberately rides this row: an
      fsdp↔replicated delta is a pure LAYOUT change — the Orbax load
      lands the moments/EMA on the new mesh's rule-derived target
      shardings (parallel/rules.py), no state transform needed.

    Keys absent from ``saved`` (older sidecars) are treated as matching —
    forward-compatible by construction.
    """
    def differs(key):
        if key not in saved:
            return False
        a, b = saved[key], current.get(key)
        if key == "moment_dtype":
            # None IS float32 (the optimizer default, train/state.py):
            # an explicit --moment_dtype float32 against an unset save
            # (or vice versa) is a spelling difference, not a cast
            a, b = a or "float32", b or "float32"
        return a != b

    chain = []
    reasons = []
    if differs("global_batch"):
        chain.append("batch_rebase")
        reasons.append(
            f"the global batch size changed "
            f"({saved.get('global_batch')} -> "
            f"{current.get('global_batch')}) — step/epoch position and "
            "the LR-schedule basis re-derive from cumulative samples")
    for key, what in (("mixed_precision", "the mixed-precision policy"),
                      ("moment_dtype", "the Adam moment storage dtype")):
        if differs(key):
            if not cast_on_restore:
                return TopologyDelta(
                    "abort",
                    f"{what} changed ({saved.get(key)} -> "
                    f"{current.get(key)}) — restore would silently cast "
                    "the state; relaunch with the original dtype flags, "
                    "or opt in to an explicit, logged cast with "
                    "--cast_on_restore")
            if "dtype_cast" not in chain:
                chain.append("dtype_cast")
            reasons.append(
                f"{what} changed ({saved.get(key)} -> "
                f"{current.get(key)}) — cast on restore "
                "(--cast_on_restore)")
    if differs("int8_delayed"):
        return TopologyDelta(
            "abort",
            "the delayed-int8 policy changed — the TrainState tree "
            "differs (quant collections), which no cast reconciles; "
            "relaunch with the original --int8_delayed")
    # A sidecar with no "mesh" key at all (pre-elastic) recorded nothing
    # to reconcile mesh-wise — skip the axis comparisons. An EMPTY
    # recorded mesh (a single-device save) is different: relaunching onto
    # a real mesh is a legitimate reshard.
    has_saved_mesh = "mesh" in saved
    saved_mesh = saved.get("mesh") or {}
    cur_mesh = current.get("mesh") or {}

    def axis(block, name):
        return int(block.get(name, 1))

    if has_saved_mesh:
        if axis(saved_mesh, PIPE_AXIS) != axis(cur_mesh, PIPE_AXIS):
            chain.append("pp_restructure")
            reasons.append(
                f"the pipeline-parallel width changed "
                f"({axis(saved_mesh, PIPE_AXIS)} -> "
                f"{axis(cur_mesh, PIPE_AXIS)}) — the stacked trunk "
                "merges and re-splits at the new width")
        if axis(saved_mesh, MODEL_AXIS) != axis(cur_mesh, MODEL_AXIS) \
                and has_quant_state:
            chain.append("tp_amax_recalibrate")
            reasons.append(
                f"the tensor-parallel width changed "
                f"({axis(saved_mesh, MODEL_AXIS)} -> "
                f"{axis(cur_mesh, MODEL_AXIS)}) under delayed-int8 amax "
                "state — stored scales remap by the closed-form max law")
    changed = [k for k in ("process_count", "device_count")
               if differs(k)]
    if has_saved_mesh:
        changed += [f"mesh.{a}" for a in set(saved_mesh) | set(cur_mesh)
                    if axis(saved_mesh, a) != axis(cur_mesh, a)]
    if chain:
        if changed:
            reasons.append("topology delta: " + ", ".join(sorted(changed)))
        return TopologyDelta("migrate", "; ".join(reasons),
                             chain=tuple(chain))
    if changed:
        return TopologyDelta(
            "reshard", "topology delta: " + ", ".join(sorted(changed)))
    return TopologyDelta("same", "identical topology")


def make_mesh(
    spec: MeshSpec = MeshSpec(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global mesh.

    Axis order is (data, fsdp, spatial, time, model, pipe) with data
    outermost: JAX lays devices out so the *innermost* axes are
    nearest-neighbor on the ICI torus, which is where the bandwidth-hungry
    halo exchanges (spatial), ring shifts (time), and pipeline stage
    hand-offs (pipe: neighbor ppermute every tick) live; data-parallel
    all-reduces tolerate the longer hops. ``fsdp`` sits right under
    ``data``: its param/moment all-gathers and reduce-scatters are the
    next-chattiest collectives after the inner-axis exchanges.
    """
    devices = list(devices if devices is not None else jax.devices())
    d, f, s, t, m, p = spec.resolve(len(devices))
    n = d * f * s * t * m * p
    dev_array = np.asarray(devices[:n]).reshape(d, f, s, t, m, p)
    return Mesh(dev_array, axis_names=ALL_AXES)


def single_device_mesh() -> Mesh:
    return make_mesh(MeshSpec(data=1), devices=jax.devices()[:1])


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host barrier/init. No-op when running single-process."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical sharding for NHWC image batches: N over (data, fsdp) —
    fsdp devices consume distinct samples like data devices — H over
    spatial."""
    return NamedSharding(mesh, P(BATCH_AXES, SPATIAL_AXIS, None, None))


def video_sharding(mesh: Mesh) -> NamedSharding:
    """NTHWC video batches: N over (data, fsdp), T over time, H over
    spatial."""
    return NamedSharding(
        mesh, P(BATCH_AXES, TIME_AXIS, SPATIAL_AXIS, None, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


_ACTIVE_MESH: contextvars.ContextVar[Optional[Mesh]] = contextvars.ContextVar(
    "p2p_tpu_active_mesh", default=None
)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]):
    """Expose ``mesh`` to layers traced within this context.

    The parallel step builders (p2p_tpu.parallel.dp) enter this around the
    step body so ops that need manual sharding regions — the Pallas
    InstanceNorm, which GSPMD would otherwise wrap in a full all-gather of
    the activations (custom calls have no partitioning rule) — can wrap
    themselves in ``shard_map`` over the active mesh at trace time.
    """
    token = _ACTIVE_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _ACTIVE_MESH.reset(token)


def current_mesh() -> Optional[Mesh]:
    """The mesh made visible by :func:`mesh_context`, or None."""
    return _ACTIVE_MESH.get()


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-host batch for the input pipeline (global / number of processes)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    del mesh
    return global_batch // n_proc
