"""Functional RNG threading.

The reference seeds ``torch.manual_seed`` once and relies on global stateful
RNG (train.py:166-168). Under jit everything must be explicit, so training
code carries a single key and derives per-step, per-purpose subkeys by
folding in the step counter — reproducible regardless of how many steps are
fused, resumed, or re-ordered.
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class RngStream:
    """A named, step-indexed PRNG stream derived from one base key."""

    base: jax.Array

    @classmethod
    def from_seed(cls, seed: int) -> "RngStream":
        return cls(jax.random.key(seed))

    def at_step(self, step) -> "RngStream":
        return RngStream(jax.random.fold_in(self.base, step))

    def key(self, name: str) -> jax.Array:
        # Stable hash: fold in a deterministic int derived from the name.
        h = int.from_bytes(name.encode()[:4].ljust(4, b"\0"), "little")
        return jax.random.fold_in(self.base, h)

    def split(self, n: int = 2):
        return jax.random.split(self.base, n)
