from p2p_tpu.data.generate import compress_uint8, generate_dataset, generate_patches
from p2p_tpu.data.pipeline import (
    PairedImageDataset,
    device_prefetch,
    place_global,
    make_loader,
)
from p2p_tpu.data.synthetic import make_synthetic_dataset, synthetic_batch

__all__ = [
    "compress_uint8",
    "generate_dataset",
    "generate_patches",
    "PairedImageDataset",
    "make_loader",
    "device_prefetch",
    "place_global",
    "make_synthetic_dataset",
    "synthetic_batch",
]
