"""Offline paired-dataset generation.

Capability parity with /root/reference/generate_dataset.py: walk a source
image directory, optionally nearest-upsample small images, trim each image
to a multiple of the crop size, tile it, and save each patch twice —
original → ``a/``, bit-depth-quantized → ``b/`` — under
``<out>/<split>/{a,b}/``. The reference caps patches per source image
(max_patches, generate_dataset.py:87) and hardcodes 3 bits (line 90).

This port runs the whole thing vectorized on numpy (one quantize per
image, tiles via reshape — the reference loops PIL crops per patch) and
parallelizes across source images with a process pool (the reference's
multiprocessing scaffolding is commented out — generate_dataset.py:139-147).
"""

from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor
from typing import List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

IMG_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".webp")


def is_image_file(name: str) -> bool:
    """Extension whitelist (utils.py:5-6, case-insensitive superset)."""
    return name.lower().endswith(IMG_EXTENSIONS)


def compress_uint8(img: np.ndarray, bits: int = 3) -> np.ndarray:
    """Bit-depth quantization on uint8 HWC images.

    Matches compress() (generate_dataset.py:29-34) composed with the
    ToTensor/save roundtrip: x/255 → round(x*(2^b-1))/(2^b-1) → *255.
    """
    n = float(2**bits - 1)
    x = img.astype(np.float32) / 255.0
    q = np.round(np.clip(x, 0.0, 1.0) * n) / n
    return np.round(q * 255.0).astype(np.uint8)


def _tile(img: np.ndarray, crop: int, crop_w: Optional[int] = None) -> np.ndarray:
    """Trim to a multiple of the crop and tile: (H,W,C) -> (T, ch, cw, C).

    ``crop_w`` admits rectangular patches (e.g. 512×1024 pix2pixHD
    frames); the reference's datagen is square-only (its crop_size is a
    single int) — this is the TPU framework's HD-dataset extension.
    """
    cw = crop_w or crop
    h, w, c = img.shape
    th, tw = (h // crop) * crop, (w // cw) * cw
    img = img[:th, :tw]
    t = img.reshape(th // crop, crop, tw // cw, cw, c)
    return t.transpose(0, 2, 1, 3, 4).reshape(-1, crop, cw, c)


def generate_patches(
    src_path: str,
    a_dir: str,
    b_dir: str,
    crop_size: Optional[int] = 256,
    max_patches: int = 100,
    bits: int = 3,
    upsample: int = 0,
    min_std: float = 0.0,
    crop_width: Optional[int] = None,
) -> int:
    """Tile one source image into paired patches. Returns patches written.

    ``min_std`` (uint8 units) drops near-constant patches. Degenerate tiles
    (flat sky, solid fills) are not just useless training signal — under
    per-sample InstanceNorm a constant image has var≈0 in EVERY layer, and
    each norm's backward amplifies cotangents by rsqrt(eps)≈316; ~20
    stacked norms overflow f32 to inf in one step (identical math in torch
    InstanceNorm2d). Filtering at the source is the principled guard;
    OptimConfig.grad_clip is the belt-and-braces one.
    """
    img = Image.open(src_path).convert("RGB")
    if upsample > 0:
        # nearest x|upsample| of EVERY source (generate_dataset.py:60-64)
        scale = abs(upsample)
        img = img.resize((img.width * scale, img.height * scale), Image.NEAREST)
    arr = np.asarray(img)
    if crop_size is None:
        # whole-image mode (reference --crop_size -1)
        tiles = [arr]
    else:
        cw = crop_width or crop_size
        if arr.shape[0] < crop_size or arr.shape[1] < cw:
            return 0
        tiles = _tile(arr, crop_size, crop_width)
        if min_std > 0:
            tiles = [t for t in tiles
                     if float(t.astype(np.float32).std()) >= min_std]
        tiles = tiles[:max_patches]
    stem = os.path.splitext(os.path.basename(src_path))[0]
    for i, patch in enumerate(tiles):
        name = f"{stem}_{i:04d}.png"
        Image.fromarray(patch).save(os.path.join(a_dir, name))
        Image.fromarray(compress_uint8(patch, bits)).save(os.path.join(b_dir, name))
    return len(tiles)


def generate_dataset(
    src_dir: str,
    out_dir: str,
    split: str = "train",
    crop_size: Optional[int] = 256,
    max_patches: int = 100,
    bits: int = 3,
    upsample: int = 0,
    workers: int = 0,
    min_std: float = 0.0,
    crop_width: Optional[int] = None,
) -> int:
    """Generate <out>/<split>/{a,b}/ from every image under src_dir."""
    a_dir = os.path.join(out_dir, split, "a")
    b_dir = os.path.join(out_dir, split, "b")
    os.makedirs(a_dir, exist_ok=True)
    os.makedirs(b_dir, exist_ok=True)
    if not os.path.isdir(src_dir):
        raise RuntimeError(f"source folder {src_dir!r} does not exist")
    sources = sorted(
        os.path.join(src_dir, f) for f in os.listdir(src_dir) if is_image_file(f)
    )
    args = [(s, a_dir, b_dir, crop_size, max_patches, bits, upsample,
             min_std, crop_width) for s in sources]
    if workers and len(sources) > 1:
        with ProcessPoolExecutor(max_workers=workers) as pool:
            counts = list(pool.map(_gen_star, args))
    else:
        counts = [_gen_star(a) for a in args]
    return int(sum(counts))


def _gen_star(args) -> int:
    return generate_patches(*args)
