"""Input pipeline: paired-image loading → host batches → device prefetch.

Replaces the reference's ``DatasetFromFolder`` + ``torch DataLoader``
(dataset.py:12-54, train.py:174-175) with a Grain pipeline:

- :class:`PairedImageDataset` — random-access source pairing
  ``<root>/<split>/a/<name>`` with ``b/<name>`` (same filename, dataset.py:26-27),
  bicubic-resized to the target size (utils.py:11) and normalized to [-1,1]
  (dataset.py:31-40), with the direction swap (``a2b``/``b2a``, dataset.py:48-51).
  The reference's commented-out random-crop/flip augmentation
  (dataset.py:28-46) is implemented behind ``augment=True``.
- :func:`make_loader` — Grain DataLoader with per-host sharding
  (``ShardByJaxProcess``) and worker processes for decode parallelism; falls
  back to a plain in-process iterator when Grain is unavailable.
- :func:`device_prefetch` — double-buffered host→HBM transfer: keeps N
  batches in flight via ``jax.device_put`` with the target sharding so the
  TPU never waits on PCIe/DCN. This is the north-star "host→HBM
  double-buffer prefetch" component.
"""

from __future__ import annotations

import collections
import os
from typing import Iterator, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from PIL import Image

from p2p_tpu.data.generate import is_image_file


def load_image(path: str, h: int, w: int,
               as_uint8: bool = False) -> np.ndarray:
    """Decode + resize-to-(h,w); float32 [-1,1] or raw uint8 [0,255].

    Native C++ fast path (p2p_tpu.native) for PNGs already at target size
    (header probe before any inflate work); PIL + bicubic resize otherwise.
    Normalize(.5,.5,.5) semantics: x/127.5 - 1. ``as_uint8`` returns the
    decoded bytes instead — the uint8 input pipeline normalizes on device
    (utils/images.ingest), bit-exact with the host normalize because both
    round through the same f32 values.

    Resilience note (docs/RESILIENCE.md): this function itself fails
    FAST — a decode error on a training input set is a data bug, not a
    blip. The serve frontend wraps its calls with the ``decode`` chaos
    seam + re-enqueue-with-backoff + quarantine (cli/serve.py), so chaos
    drills against ``decode`` never kill a training run.
    """
    from p2p_tpu import native

    fast = native.load_image_fast(path, expect_hw=(h, w))
    if fast is not None:
        return fast[0] if as_uint8 else fast[1]
    img = Image.open(path).convert("RGB")
    if img.size != (w, h):
        img = img.resize((w, h), Image.BICUBIC)
    arr = np.asarray(img, np.uint8)
    if as_uint8:
        return arr
    # the canonical normalize: (x − 127.5)·(1/127.5) — exact subtraction
    # then ONE rounding multiply, and no mul+add pattern any backend can
    # FMA-contract. Same expression as fastimage.cpp normalize_f32 and
    # the device-side utils/images.ingest → all three bit-identical.
    return ((arr.astype(np.float32) - np.float32(127.5))
            * np.float32(1.0 / 127.5))


def load_image_bytes(data: bytes, h: int, w: int,
                     as_uint8: bool = False) -> np.ndarray:
    """:func:`load_image` over an in-memory encoded image — the HTTP
    request body of the network serving frontend (serve/server.py).
    Identical decode/resize/normalize semantics; no native fast path
    (it is keyed on file paths) — PIL decodes from the bytes directly,
    so a request never touches disk."""
    import io

    img = Image.open(io.BytesIO(data)).convert("RGB")
    if img.size != (w, h):
        img = img.resize((w, h), Image.BICUBIC)
    arr = np.asarray(img, np.uint8)
    if as_uint8:
        return arr
    return ((arr.astype(np.float32) - np.float32(127.5))
            * np.float32(1.0 / 127.5))


class PairedImageDataset:
    """Random-access paired dataset; items are dicts of HWC images —
    float32 [-1,1] by default, raw uint8 [0,255] with ``dtype='uint8'``
    (the uint8 input pipeline: smaller memo/PCIe, device-side normalize
    via utils/images.ingest — numerically identical)."""

    def __init__(
        self,
        root: str,
        split: str = "train",
        direction: str = "b2a",
        image_size: int = 256,
        image_width: Optional[int] = None,
        augment: bool = False,
        aug_seed: int = 0,
        cache: Union[bool, str] = "auto",
        dtype: str = "float32",
    ):
        self.a_dir = os.path.join(root, split, "a")
        self.b_dir = os.path.join(root, split, "b")
        self.direction = direction
        self.h = image_size
        self.w = image_width or image_size
        self.augment = augment
        # Augmentation entropy root. Crops/flips are a pure function of
        # (aug_seed, item index) — the trainer bumps aug_seed once per
        # epoch, so same-seed runs see identical augmented streams
        # (functional-RNG stance of core/rng.py) while epochs still get
        # fresh crops. Set BEFORE building a loader: Grain pickles the
        # dataset into its worker processes at creation time.
        self.aug_seed = aug_seed
        self.names = sorted(f for f in os.listdir(self.a_dir) if is_image_file(f))
        if not self.names:
            raise RuntimeError(f"no images in {self.a_dir}")
        # Decoded-image memo. This image class of host (often 1 vCPU next
        # to a >1400 img/s chip) cannot re-decode every epoch — tf.data
        # ``.cache()`` semantics: decode once, serve from RAM. "auto" =
        # cache when the decoded split fits comfortably (<4 GB). The memo
        # sits UPSTREAM of augmentation (scaled source images are cached,
        # crops/flips stay per-(seed, epoch, idx)).
        if dtype not in ("float32", "uint8"):
            raise ValueError(f"dtype must be float32|uint8, got {dtype!r}")
        self.as_uint8 = dtype == "uint8"
        if cache == "auto":
            lh = (self.h * 286 // 256) if augment else self.h
            lw = (self.w * 286 // 256) if augment else self.w
            bpp = 1 if self.as_uint8 else 4  # the uint8 memo is 4× smaller
            cache = len(self.names) * lh * lw * 3 * bpp * 2 <= 4 << 30
        self.cache_enabled = bool(cache)
        self._memo: dict = {}

    def __len__(self) -> int:
        return len(self.names)

    def _load(self, path: str, h: Optional[int] = None,
              w: Optional[int] = None) -> np.ndarray:
        h = h or self.h
        w = w or self.w
        if not self.cache_enabled:
            return load_image(path, h, w, self.as_uint8)
        key = (path, h, w)
        hit = self._memo.get(key)
        if hit is None:
            hit = load_image(path, h, w, self.as_uint8)
            hit.setflags(write=False)
            self._memo[key] = hit
        return hit

    def __getitem__(self, idx: int):
        if hasattr(idx, "__index__"):
            idx = idx.__index__()
        name = self.names[idx]
        if self.augment:
            # the reference's commented-out aug (dataset.py:28-46): load at
            # 286/256-scaled size, take the SAME random crop from a and b,
            # flip both. Deterministic per (aug_seed, idx) — see __init__.
            lh = self.h * 286 // 256
            lw = self.w * 286 // 256
            a = self._load(os.path.join(self.a_dir, name), lh, lw)
            b = self._load(os.path.join(self.b_dir, name), lh, lw)
            rng = np.random.default_rng((0x9E3779B9, self.aug_seed, idx))
            oy = int(rng.integers(0, lh - self.h + 1))
            ox = int(rng.integers(0, lw - self.w + 1))
            a = a[oy : oy + self.h, ox : ox + self.w]
            b = b[oy : oy + self.h, ox : ox + self.w]
            if rng.random() < 0.5:
                a, b = a[:, ::-1], b[:, ::-1]
            a, b = np.ascontiguousarray(a), np.ascontiguousarray(b)
        else:
            a = self._load(os.path.join(self.a_dir, name))
            b = self._load(os.path.join(self.b_dir, name))
        if self.direction == "a2b":
            return {"input": a, "target": b}
        return {"input": b, "target": a}


class _Stacked:
    """Batch a random-access dataset by stacking consecutive items."""

    def __init__(self, ds, batch_size, indices, drop_remainder=True):
        self.ds = ds
        self.bs = batch_size
        self.indices = indices
        self.drop_remainder = drop_remainder

    def __iter__(self):
        end = len(self.indices) if not self.drop_remainder else (
            len(self.indices) - self.bs + 1
        )
        for i in range(0, end, self.bs):
            items = [self.ds[j] for j in self.indices[i : i + self.bs]]
            yield {
                k: np.stack([it[k] for it in items]) for k in items[0]
            }


_WORKERS_WARNED = False


def _warn_fallback_workers(num_workers: int, registry=None) -> None:
    """One-time (per process) warning that the no-Grain fallback decodes
    single-threaded — the requested ``num_workers`` silently doing nothing
    is a perf cliff worth a visible record (obs counter + stderr). The
    trainers pass their run registry so the record reaches the run's
    metrics JSONL, not just the sink-less process default."""
    global _WORKERS_WARNED
    if _WORKERS_WARNED:
        return
    _WORKERS_WARNED = True
    if registry is None:
        from p2p_tpu.obs import get_registry

        registry = get_registry()
    registry.counter("fallback_loader_workers_ignored").inc()
    registry.record(
        {"kind": "warn", "what": "fallback_loader_workers_ignored",
         "num_workers": num_workers},
        force=True,
    )
    import sys

    print(
        f"WARNING: Grain unavailable — the fallback loader decodes "
        f"in-process and single-threaded; num_workers={num_workers} is "
        f"ignored (expect slower epochs on uncached splits)",
        file=sys.stderr, flush=True,
    )


def loader_kind() -> str:
    """Which loader :func:`make_loader` will build on this process:
    ``"grain"`` or ``"fallback"``. Recorded in the checkpoint topology
    sidecar (train/loop.py ``trainer_topology``) because the elastic
    MID-EPOCH reshard guarantee only holds for the fallback's stride
    arithmetic: Grain's ShardByJaxProcess hands each process a
    CONTIGUOUS block of record keys before shuffling, so no global epoch
    permutation survives a process-count change — the reconciliation
    (``plan_elastic_restore``) must abort rather than silently replay or
    drop samples."""
    if os.environ.get("P2P_TPU_NO_GRAIN") == "1":
        return "fallback"
    try:
        import grain.python  # noqa: F401
    except Exception:
        return "fallback"
    return "grain"


def shard_epoch_indices(
    idx: np.ndarray,
    batch_size: int,
    skip_batches: int = 0,
    n_proc: Optional[int] = None,
    pid: Optional[int] = None,
    drop_remainder: bool = True,
    skip_samples: int = 0,
) -> list:
    """THE per-host index arithmetic of the fallback loader: one epoch's
    (already shuffled) global index vector → this host's batch-aligned,
    post-skip slice. Factored out of :func:`make_loader` so the elastic
    shard-accounting tests can drive it at ARBITRARY (n_proc, pid) pairs
    — the exact production arithmetic, not a reimplementation.

    Sharding is by stride (``idx[pid::n_proc]``, mirroring Grain's
    ShardByJaxProcess): host ``p``'s shard position ``s`` is flat shuffled
    position ``s*n_proc + p``. That makes the arithmetic ELASTIC: host
    ``p``'s local batch ``i`` covers shard positions
    ``[i*local_bs, (i+1)*local_bs)`` = flat positions
    ``[i*local_bs*n_proc + p, ...]``, so the union over hosts of local
    batch ``i`` is exactly flat positions ``[i*B, (i+1)*B)`` of the epoch
    permutation (``B`` = global batch = ``local_bs * n_proc``) —
    INDEPENDENT of ``n_proc``. A relaunch at a different process count
    that skips ``skip_batches`` = (global mid-epoch step) local batches
    per host therefore consumes exactly the samples the dead run did not,
    zero duplicated, zero dropped — the gapless-accounting pin of
    tests/test_data.py + test_multiprocess.py. The one precondition is a
    FIXED global batch, which the topology reconciliation enforces
    (core/mesh.classify_topology_delta classifies a global-batch delta
    as must-abort).

    With ``drop_remainder`` the pre-shard trim (``len % n_proc``) and the
    per-host batch floor depend on ``n_proc`` only in the epoch TAIL —
    samples no topology ever consumed: writing ``n = q*B + r`` (r < B),
    every host gets exactly ``q`` full local batches regardless of
    ``n_proc`` (shard length is ``q*local_bs + floor-of-(r/n_proc)`` and
    ``r/n_proc < local_bs``), so steps-per-epoch is the topology-invariant
    ``floor(n/B)``.

    ``skip_samples`` is the SAMPLE-granular form of the skip: drop the
    flat permutation prefix ``[0, S)`` — host ``p`` drops its shard rows
    with flat position ``s·n_proc + p < S``, i.e. ``ceil((S − p)/n_proc)``
    rows. This is the elastic BATCH-CHANGE resume law
    (resilience/reshape.py ``batch_rebase``): the dead run consumed a
    prefix that is a multiple of the OLD global batch, which the NEW
    batch need not divide — sample granularity keeps the union of the
    relaunch's batch ``i`` at exactly flat ``[S + i·B_new, S + (i+1)·B_new)``
    (any length-``B`` flat window holds exactly ``local_bs`` members of
    every congruence class — even when ``S`` is unaligned), so old-batch
    prefix ∪ new-batch suffix tiles the permutation gaplessly. Under
    ``drop_remainder`` every host is additionally truncated to
    ``usable//B − ceil(S/B)`` batches: hosts whose post-skip row counts
    differ by one (unaligned ``S``) agree on the epoch's step count, and
    the count matches the ceil-charged step re-base
    (reshape.apply_batch_rebase charges ``ceil(S/B)`` steps for the
    prefix, so prefix-steps + suffix-batches == the topology-invariant
    ``steps_per_epoch`` exactly — a plain ``(usable−S)//B`` floor would
    overshoot by one whenever the unconsumed part of the prefix's last
    window fits in the epoch tail, desynchronizing ``step %
    steps_per_epoch`` forever after). ``skip_batches`` (``= S/B`` when
    aligned) is the legacy form; the two are mutually exclusive.
    """
    idx = np.asarray(idx)
    if n_proc is None:
        n_proc = jax.process_count()
    if pid is None:
        pid = jax.process_index()
    if skip_batches and skip_samples:
        raise ValueError("pass skip_batches OR skip_samples, not both")
    n_usable = len(idx)
    if n_proc > 1:
        if drop_remainder:
            # equal-sized shards (Grain's drop_remainder semantics): an
            # uneven split would hand one process an extra batch whose
            # collectives the others never join — deadlock
            idx = idx[: len(idx) - len(idx) % n_proc]
            n_usable = len(idx)
        idx = idx[pid::n_proc]
    if skip_samples > 0:
        s = int(skip_samples)
        drop = (s - pid + n_proc - 1) // n_proc if s > pid else 0
        idx = idx[drop:]
        if drop_remainder:
            b = batch_size * n_proc
            n_b = max(0, n_usable // b - -(-s // b))
            idx = idx[: n_b * batch_size]
    elif skip_batches > 0:
        # resume mid-epoch: local batch i is shard rows [i·bs, (i+1)·bs),
        # so dropping skip·bs leading indices leaves every later batch's
        # membership and order IDENTICAL to an uninterrupted epoch — zero
        # decodes spent on the skip
        idx = idx[skip_batches * batch_size:]
    return list(idx)


def make_loader(
    dataset: PairedImageDataset,
    batch_size: int,
    shuffle: bool = True,
    seed: int = 0,
    num_workers: int = 0,
    num_epochs: Optional[int] = 1,
    drop_remainder: bool = True,
    skip_batches: int = 0,
    registry=None,
    skip_samples: int = 0,
):
    """Host-batch iterator with per-JAX-process sharding.

    Uses Grain's DataLoader (worker processes decode in parallel, exactly the
    role of the reference's DataLoader(num_workers=opt.threads)); plain
    Python fallback keeps tests hermetic if Grain is missing (or when
    ``P2P_TPU_NO_GRAIN=1`` forces the fallback — resilience tests pin the
    fallback's exact-resume accounting).

    ``skip_batches`` drops the FIRST N batches of the FIRST epoch — the
    exact-step resume path (train/loop.py): a run killed mid-epoch resumes
    its epoch from batch N without replaying batches 0..N-1. The fallback
    skips by index arithmetic (no decode cost); Grain consumes and
    discards N batches once (decode cost paid, order preserved).

    ``skip_samples`` is the sample-granular form (global flat-permutation
    prefix — see :func:`shard_epoch_indices`): the elastic batch-change
    resume uses it because the consumed prefix is a multiple of the OLD
    global batch only. On the Grain path it must be batch-aligned (mid-
    epoch topology changes under Grain are refused upstream by
    ``plan_elastic_restore``; a same-run resume is always aligned).
    """
    try:
        if os.environ.get("P2P_TPU_NO_GRAIN") == "1":
            raise ImportError("fallback forced by P2P_TPU_NO_GRAIN")
        import grain.python as pg
    except Exception:
        if num_workers > 0:
            _warn_fallback_workers(num_workers, registry)

        def fallback():
            rng = np.random.default_rng(seed)
            epoch = 0
            skip = max(0, int(skip_batches))
            skip_s = max(0, int(skip_samples))
            while num_epochs is None or epoch < num_epochs:
                idx = np.arange(len(dataset))
                if shuffle:
                    rng.shuffle(idx)
                # per-process record sharding + mid-epoch skip — ONE
                # arithmetic (shard_epoch_indices), shared with the
                # elastic shard-accounting tests
                local = shard_epoch_indices(
                    idx, batch_size, skip_batches=skip,
                    drop_remainder=drop_remainder, skip_samples=skip_s)
                skip = 0
                skip_s = 0
                yield from _Stacked(dataset, batch_size, local,
                                    drop_remainder)
                epoch += 1

        return fallback()

    sampler = pg.IndexSampler(
        num_records=len(dataset),
        shard_options=pg.ShardByJaxProcess(drop_remainder=drop_remainder),
        shuffle=shuffle,
        num_epochs=num_epochs,
        seed=seed,
    )
    loader = pg.DataLoader(
        data_source=dataset,
        sampler=sampler,
        operations=[pg.Batch(batch_size=batch_size, drop_remainder=drop_remainder)],
        worker_count=num_workers,
    )
    it = iter(loader)
    skip = max(0, int(skip_batches))
    if skip_samples > 0:
        # Grain consumes whole local batches; a sample-granular prefix
        # only arises on a batch-change migration, which the elastic
        # reconciliation already refuses under Grain mid-epoch.
        global_b = batch_size * jax.process_count()
        if skip_samples % global_b:
            raise ValueError(
                f"skip_samples={skip_samples} is not a whole number of "
                f"global batches ({global_b}) — the Grain loader cannot "
                "skip a partial batch; run with P2P_TPU_NO_GRAIN=1 for "
                "sample-granular elastic accounting")
        skip += skip_samples // global_b
    if skip > 0:
        def skipping():
            for i, b in enumerate(it):
                if i >= skip:
                    yield b

        return skipping()
    return it


def place_global(batch, sharding):
    """Place a host batch (or any pytree of host arrays) under ``sharding``.

    Single process: ``jax.device_put``. Multi-process: each process holds
    its LOCAL shard and the global array is assembled with
    ``jax.make_array_from_process_local_data`` — a plain device_put cannot
    build a global array from per-process shards. Shared by
    :func:`device_prefetch` and ``parallel.dp.shard_batch``.
    """
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding(x) if callable(sharding) else sharding,
                np.asarray(x),
            ),
            batch,
        )
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, sharding(x) if callable(sharding) else sharding),
        batch,
    )


def device_prefetch(
    iterator: Iterator,
    sharding=None,
    buffer_size: int = 2,
    with_aux: bool = False,
):
    """Double-buffered host→device transfer.

    Eagerly enqueues ``buffer_size`` batches (async on TPU) so step N+1's
    H2D copy overlaps step N's compute.

    Single process: ``jax.device_put(batch, sharding)``. Multi-process
    (``jax.process_count() > 1``): each process feeds its LOCAL shard (the
    loader shards records per process via ShardByJaxProcess and batches
    ``local_batch_size``) and the GLOBAL array is assembled with
    ``jax.make_array_from_process_local_data`` — ``device_put`` against a
    cross-process sharding cannot build a global array from per-process
    shards (VERDICT r1 missing#5; SURVEY §7 hard part 6).

    ``with_aux``: the iterator yields ``(batch, aux)`` pairs; the batch is
    device-put, the aux rides along untouched.
    """
    queue = collections.deque()

    def _put(batch):
        if sharding is None:
            return jax.tree_util.tree_map(jax.numpy.asarray, batch)
        return place_global(batch, sharding)

    for item in iterator:
        if with_aux:
            batch, aux = item
            queue.append((_put(batch), aux))
        else:
            queue.append(_put(item))
        if len(queue) >= buffer_size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
