"""Synthetic paired data for tests and benchmarks.

Procedurally generated RGB images (smooth gradients + random rectangles and
disks — enough structure that quantization visibly banding-degrades them),
run through the same quantizer as real data. Used by the integration tests
(SURVEY §4.4: tiny synthetic set driven N steps) and by bench.py when no
real dataset is mounted.
"""

from __future__ import annotations

import os
from typing import Tuple, Optional

import numpy as np
from PIL import Image

from p2p_tpu.data.generate import compress_uint8


def _synthetic_image(rng: np.random.Generator, size: Tuple[int, int]) -> np.ndarray:
    h, w = size
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    img = np.zeros((h, w, 3), np.float32)
    # smooth background gradient with random orientation/phase per channel
    for c in range(3):
        fx, fy = rng.uniform(0.5, 3.0, 2)
        phase = rng.uniform(0, 2 * np.pi)
        img[:, :, c] = 0.5 + 0.5 * np.sin(
            2 * np.pi * (fx * xx / w + fy * yy / h) + phase
        )
    # random rectangles
    for _ in range(rng.integers(3, 8)):
        y0, x0 = rng.integers(0, h // 2), rng.integers(0, w // 2)
        y1, x1 = y0 + rng.integers(4, h // 2), x0 + rng.integers(4, w // 2)
        img[y0:y1, x0:x1] = rng.uniform(0, 1, 3)
    # random disks
    for _ in range(rng.integers(2, 6)):
        cy, cx = rng.integers(0, h), rng.integers(0, w)
        r = rng.integers(3, max(4, h // 6))
        mask = (yy - cy) ** 2 + (xx - cx) ** 2 < r**2
        img[mask] = rng.uniform(0, 1, 3)
    return (img * 255).astype(np.uint8)


def make_synthetic_dataset(
    out_dir: str,
    n_train: int = 8,
    n_test: int = 4,
    size: int = 64,
    bits: int = 3,
    seed: int = 0,
) -> str:
    """Write a/ + b/ splits of procedural images; returns out_dir."""
    rng = np.random.default_rng(seed)
    for split, n in (("train", n_train), ("test", n_test)):
        a_dir = os.path.join(out_dir, split, "a")
        b_dir = os.path.join(out_dir, split, "b")
        os.makedirs(a_dir, exist_ok=True)
        os.makedirs(b_dir, exist_ok=True)
        for i in range(n):
            img = _synthetic_image(rng, (size, size))
            name = f"synth_{i:04d}.png"
            Image.fromarray(img).save(os.path.join(a_dir, name))
            Image.fromarray(compress_uint8(img, bits)).save(
                os.path.join(b_dir, name)
            )
    return out_dir


def synthetic_batch(
    batch_size: int = 1, size: int = 64, bits: int = 3, seed: int = 0,
    width: Optional[int] = None, dtype: str = "float32",
):
    """In-memory batch dict {'input','target'}, b2a direction — float32
    [-1,1] by default, raw uint8 with ``dtype='uint8'`` (the uint8 input
    pipeline contract; the steps normalize on device via ingest).

    ``size`` is the height; ``width`` defaults to square (the wide presets —
    Cityscapes 512×256, pix2pixHD 1024×512 — pass it explicitly)."""
    rng = np.random.default_rng(seed)
    targets = np.stack(
        [_synthetic_image(rng, (size, width or size))
         for _ in range(batch_size)]
    )
    inputs = np.stack([compress_uint8(t, bits) for t in targets])
    if dtype == "uint8":
        return {"input": inputs, "target": targets}
    # the canonical normalize (see utils/images.ingest) so the f32 and
    # uint8 synthetic batches are bit-identical after device ingest
    to_f = lambda x: ((x.astype(np.float32) - np.float32(127.5))
                      * np.float32(1.0 / 127.5))
    return {"input": to_f(inputs), "target": to_f(targets)}
