"""Video clip dataset for the vid2vid-style configs.

Layout: ``root/<split>/{a,b}/<video_id>/<frame>.png`` — per-video frame
directories, paired by identical video-id + frame name (the video analogue
of the reference's paired a/b folders, dataset.py:18-27). Items are
consecutive ``n_frames`` windows as (T, H, W, C) dicts — float32 [-1,1]
by default, uint8 with ``dtype='uint8'`` (device-side normalize, see
data/pipeline.py) — and the batcher stacks them to NTHWC for the video
train step.

Synthetic clips (moving discs over a gradient background, quantized b/
stream) mirror data.synthetic for tests and benches.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np
from PIL import Image

from p2p_tpu.data.generate import compress_uint8, is_image_file


class VideoClipDataset:
    """Random-access dataset of fixed-length clip windows."""

    def __init__(
        self,
        root: str,
        split: str = "train",
        direction: str = "b2a",
        image_size: int = 256,
        image_width: Optional[int] = None,
        n_frames: int = 8,
        stride: Optional[int] = None,
        dtype: str = "float32",
    ):
        if dtype not in ("float32", "uint8"):
            raise ValueError(f"dtype must be float32|uint8, got {dtype!r}")
        self.as_uint8 = dtype == "uint8"
        self.a_dir = os.path.join(root, split, "a")
        self.b_dir = os.path.join(root, split, "b")
        self.direction = direction
        self.h = image_size
        self.w = image_width or image_size
        self.n_frames = n_frames
        stride = stride or n_frames
        self.windows: List[Tuple[str, List[str]]] = []
        if not os.path.isdir(self.a_dir):
            raise RuntimeError(f"no video dir {self.a_dir}")
        for vid in sorted(os.listdir(self.a_dir)):
            vdir = os.path.join(self.a_dir, vid)
            if not os.path.isdir(vdir):
                continue
            frames = sorted(f for f in os.listdir(vdir) if is_image_file(f))
            for s in range(0, len(frames) - n_frames + 1, stride):
                self.windows.append((vid, frames[s : s + n_frames]))
        if not self.windows:
            raise RuntimeError(
                f"no {n_frames}-frame windows under {self.a_dir}"
            )

    def __len__(self) -> int:
        return len(self.windows)

    def _load(self, path: str) -> np.ndarray:
        from p2p_tpu.data.pipeline import load_image

        return load_image(path, self.h, self.w, self.as_uint8)

    def _clip(self, base: str, vid: str, frames: List[str]) -> np.ndarray:
        return np.stack(
            [self._load(os.path.join(base, vid, f)) for f in frames]
        )

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        if hasattr(idx, "__index__"):
            idx = idx.__index__()
        vid, frames = self.windows[idx]
        a = self._clip(self.a_dir, vid, frames)
        b = self._clip(self.b_dir, vid, frames)
        if self.direction == "a2b":
            return {"input": a, "target": b}
        return {"input": b, "target": a}


def make_synthetic_video_dataset(
    out_dir: str,
    n_videos: int = 2,
    n_frames: int = 10,
    size: int = 32,
    bits: int = 3,
    seed: int = 0,
    splits: Tuple[str, ...] = ("train", "test"),
) -> str:
    """Moving-disc clips: a/ originals, b/ quantized (paired by name)."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    for split in splits:
        for v in range(n_videos):
            base = np.zeros((size, size, 3), np.float32)
            for c in range(3):
                fx, fy = rng.uniform(0.5, 2.0, 2)
                base[:, :, c] = 0.5 + 0.5 * np.sin(
                    2 * np.pi * (fx * xx / size + fy * yy / size)
                )
            cx, cy = rng.uniform(size * 0.2, size * 0.8, 2)
            dx, dy = rng.uniform(-2, 2, 2)
            r = rng.uniform(size * 0.1, size * 0.25)
            color = rng.uniform(0, 1, 3)
            for t in range(n_frames):
                img = base.copy()
                px, py = cx + dx * t, cy + dy * t
                mask = (yy - py) ** 2 + (xx - px) ** 2 < r**2
                img[mask] = color
                u8 = (np.clip(img, 0, 1) * 255).astype(np.uint8)
                for stream, arr in (("a", u8), ("b", compress_uint8(u8, bits))):
                    d = os.path.join(out_dir, split, stream, f"v{v:03d}")
                    os.makedirs(d, exist_ok=True)
                    Image.fromarray(arr).save(
                        os.path.join(d, f"f{t:04d}.png")
                    )
    return out_dir
