from p2p_tpu.losses.gan import gan_loss
from p2p_tpu.losses.feature_matching import feature_matching_loss
from p2p_tpu.losses.perceptual import VGG_SLICE_WEIGHTS, vgg_loss
from p2p_tpu.losses.metrics import psnr, ssim
from p2p_tpu.losses.fid import (
    FIDEvaluator,
    frechet_distance,
    gaussian_stats,
    make_vgg_feature_fn,
)
from p2p_tpu.losses.style import gram_matrix, style_loss

__all__ = [
    "gan_loss",
    "feature_matching_loss",
    "vgg_loss",
    "VGG_SLICE_WEIGHTS",
    "psnr",
    "ssim",
    "frechet_distance",
    "gaussian_stats",
    "FIDEvaluator",
    "make_vgg_feature_fn",
    "gram_matrix",
    "style_loss",
]
