"""Multiscale feature-matching loss.

Behavior parity with train.py:344-351: L1 between every intermediate D
activation of fake vs real (all but the final prediction map), weighted
``(4/(n_layers+1)) * (1/num_D) * lambda_feat``, with real features
stop-gradiented. The reference hardcodes Num_D=3 / N_Layers_D=3; here both
come from the prediction structure itself.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def feature_matching_loss(
    pred_fake: Sequence[Sequence[jax.Array]],
    pred_real: Sequence[Sequence[jax.Array]],
    n_layers: int = 3,
    lambda_feat: float = 10.0,
) -> jax.Array:
    num_D = len(pred_fake)
    feat_w = 4.0 / (n_layers + 1)
    d_w = 1.0 / num_D
    total = jnp.zeros((), jnp.float32)
    for scale_f, scale_r in zip(pred_fake, pred_real):
        for f, r in zip(scale_f[:-1], scale_r[:-1]):
            diff = jnp.abs(
                f.astype(jnp.float32) - jax.lax.stop_gradient(r).astype(jnp.float32)
            )
            total = total + d_w * feat_w * jnp.mean(diff) * lambda_feat
    return total
