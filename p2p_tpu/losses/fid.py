"""Fréchet distance machinery for FID-style metrics.

The BASELINE.json north star requires FID parity; the reference computes no
FID at all (PSNR/SSIM only — train.py:54-65). The Fréchet computation here
is feature-extractor-agnostic: pair it with InceptionV3 activations when
that asset is available, or with VGG19 tap activations ("VFID") from
:mod:`p2p_tpu.models.vgg` — the asset situation is reported by
``p2p_tpu.models.vgg.vgg19_params_source()``.

Statistics accumulate incrementally on device (sum / outer-product sums) so
eval never materializes the full activation matrix; the final distance runs
on host in float64 where the matrix sqrt wants the precision.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def gaussian_stats(feats: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Mean and covariance of (N, D) features, fp32 on device."""
    f = feats.astype(jnp.float32)
    mu = jnp.mean(f, axis=0)
    centered = f - mu
    cov = centered.T @ centered / (f.shape[0] - 1)
    return mu, cov


class RunningStats:
    """Host-side incremental accumulator for activation statistics."""

    def __init__(self, dim: int):
        self.n = 0
        self.sum = np.zeros(dim, np.float64)
        self.outer = np.zeros((dim, dim), np.float64)

    def update(self, feats) -> None:
        f = np.asarray(feats, np.float64)
        self.n += f.shape[0]
        self.sum += f.sum(axis=0)
        self.outer += f.T @ f

    def finalize(self) -> Tuple[np.ndarray, np.ndarray]:
        mu = self.sum / self.n
        cov = (self.outer - self.n * np.outer(mu, mu)) / (self.n - 1)
        return mu, cov


def frechet_distance(mu1, cov1, mu2, cov2, eps: float = 1e-6) -> float:
    """d² = |μ1−μ2|² + tr(C1 + C2 − 2·(C1·C2)^½), via scipy-free eigendecomp."""
    mu1 = np.asarray(mu1, np.float64)
    mu2 = np.asarray(mu2, np.float64)
    cov1 = np.asarray(cov1, np.float64)
    cov2 = np.asarray(cov2, np.float64)
    diff = mu1 - mu2

    # sqrtm(C1 C2) trace via the symmetric-product trick:
    # tr sqrt(C1 C2) = tr sqrt(S1 C2 S1) where S1 = sqrt(C1) (symmetric PSD).
    def _sym_sqrt(m):
        vals, vecs = np.linalg.eigh(m)
        vals = np.clip(vals, 0, None)
        return (vecs * np.sqrt(vals)) @ vecs.T

    s1 = _sym_sqrt(cov1 + eps * np.eye(len(cov1)))
    inner = s1 @ cov2 @ s1
    vals = np.linalg.eigvalsh((inner + inner.T) / 2)
    tr_sqrt = np.sqrt(np.clip(vals, 0, None)).sum()
    d2 = diff @ diff + np.trace(cov1) + np.trace(cov2) - 2.0 * tr_sqrt
    return float(max(d2, 0.0))  # eps regularization can leave tiny negatives


def make_vgg_feature_fn(vgg_params, imagenet_norm: bool = False):
    """Jitted ``images → (N, D)`` feature embedding for VFID: the five VGG19
    tap activations spatially mean-pooled and concatenated (D = 1472)."""
    from p2p_tpu.models.vgg import VGG19Features

    model = VGG19Features(imagenet_norm=imagenet_norm)

    @jax.jit
    def fn(images):
        feats = model.apply({"params": vgg_params}, images)
        pooled = [jnp.mean(f.astype(jnp.float32), axis=(1, 2)) for f in feats]
        return jnp.concatenate(pooled, axis=-1)

    return fn


class FIDEvaluator:
    """Accumulate real/fake feature stats batch-by-batch, then distance.

    >>> ev = FIDEvaluator(make_vgg_feature_fn(vgg_params))
    >>> for batch: ev.update(real_images, fake_images)
    >>> ev.compute()
    """

    def __init__(self, feature_fn, dim: int = 1472):
        self.feature_fn = feature_fn
        self.real = RunningStats(dim)
        self.fake = RunningStats(dim)

    def update(self, real_images, fake_images) -> None:
        self.real.update(self.feature_fn(real_images))
        self.fake.update(self.feature_fn(fake_images))

    def compute(self) -> float:
        mu_r, cov_r = self.real.finalize()
        mu_f, cov_f = self.fake.finalize()
        return frechet_distance(mu_r, cov_r, mu_f, cov_f)
