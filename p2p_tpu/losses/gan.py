"""Adversarial losses.

Behavior parity with the reference ``GANLoss`` (networks.py:808-850):
LSGAN (MSE) default, BCE option; multiscale nested-list predictions use only
the LAST feature per scale and the per-scale losses are SUMMED (not
averaged). The reference's lazily-cached CUDA target tensors (SURVEY Q6)
are replaced by ``jnp.full_like`` — free under XLA fusion and device-neutral.

Also provides hinge loss (standard in modern GAN training; not in the
reference) behind ``mode='hinge'``.
"""

from __future__ import annotations

from typing import List, Sequence, Union

import jax
import jax.numpy as jnp

Preds = Union[Sequence[jax.Array], Sequence[Sequence[jax.Array]]]


def _final_preds(preds: Preds) -> List[jax.Array]:
    if isinstance(preds[0], (list, tuple)):
        return [scale[-1] for scale in preds]
    return [preds[-1]]


def _elementwise(pred: jax.Array, target_is_real: bool, mode: str,
                 for_discriminator: bool) -> jax.Array:
    p = pred.astype(jnp.float32)
    if mode == "lsgan":
        target = jnp.full_like(p, 1.0 if target_is_real else 0.0)
        return jnp.mean((p - target) ** 2)
    if mode == "vanilla":
        # BCE-with-logits (the reference applies BCE after an explicit
        # sigmoid stage; fused here for numerical stability).
        target = jnp.full_like(p, 1.0 if target_is_real else 0.0)
        return jnp.mean(
            jnp.maximum(p, 0) - p * target + jnp.log1p(jnp.exp(-jnp.abs(p)))
        )
    if mode == "hinge":
        if for_discriminator:
            if target_is_real:
                return jnp.mean(jax.nn.relu(1.0 - p))
            return jnp.mean(jax.nn.relu(1.0 + p))
        return -jnp.mean(p)
    raise ValueError(f"unknown gan mode {mode!r}")


def gan_loss(preds: Preds, target_is_real: bool, mode: str = "lsgan",
             for_discriminator: bool = True) -> jax.Array:
    """Sum of per-scale losses on the final prediction map of each scale."""
    losses = [
        _elementwise(p, target_is_real, mode, for_discriminator)
        for p in _final_preds(preds)
    ]
    return jnp.sum(jnp.stack(losses))
