"""Image quality metrics — in-graph PSNR/SSIM.

The reference computes PSNR/SSIM per epoch on uint8-roundtripped images
(train.py:54-65) — and does so in a DISTORTED space: its ``tensor2img``
multiplies tanh [-1,1] outputs by 255 and clips, zeroing all negative pixels
(SURVEY Q8), which is where its Inf-PSNR anomalies come from.

This build computes metrics correctly by default — images mapped
(x+1)/2*255 with optional uint8 quantization to match the reference's
roundtrip — and keeps the bug-compatible scaling behind
``ref_buggy_scale=True`` so the deviation is reproducible on demand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def to_uint8_space(x: jax.Array, ref_buggy_scale: bool = False,
                   quantize_uint8: bool = True) -> jax.Array:
    """Map [-1,1] images to the [0,255] space metrics are computed in."""
    x = x.astype(jnp.float32)
    if ref_buggy_scale:
        y = jnp.clip(x * 255.0, 0, 255)  # train.py:38-39 semantics
    else:
        y = jnp.clip((x + 1.0) * 0.5 * 255.0, 0, 255)  # utils.py:17 semantics
    if quantize_uint8:
        y = jnp.round(y)
    return y


def psnr(target: jax.Array, pred: jax.Array, ref_buggy_scale: bool = False,
         max_db: float = 60.0, per_image: bool = False) -> jax.Array:
    """10·log10(255²/MSE), clamped to ``max_db`` (the reference clamps its
    Inf-PSNR readings to 60.0 — train.py:480-482).

    ``per_image=True`` reduces over HWC only, returning one value per batch
    element — needed for the reference's per-image max-PSNR report
    (train.py:498-502) at test_batch_size > 1.
    """
    t = to_uint8_space(target, ref_buggy_scale)
    p = to_uint8_space(pred, ref_buggy_scale)
    axes = tuple(range(1, t.ndim)) if per_image else None
    mse = jnp.mean((t - p) ** 2, axis=axes)
    val = 10.0 * jnp.log10(255.0**2 / jnp.maximum(mse, 1e-12))
    return jnp.minimum(val, max_db)


def _uniform_window(x: jax.Array, win: int) -> jax.Array:
    """Mean filter over win×win windows, per channel (NHWC), VALID.

    precision=HIGHEST: the default conv precision runs bf16 passes on the
    TPU MXU (and a reduced-precision path on CPU) — measured window-mean
    errors of ~0.3 at the 0..255 scale, which explodes the E[x²]−μ²
    moment terms in SSIM (values > 1 / < 0 at high PSNR)."""
    c = x.shape[-1]
    kernel = jnp.full((win, win, 1, 1), 1.0 / (win * win), jnp.float32)
    kernel = jnp.tile(kernel, (1, 1, 1, c))
    # p2p-lint: disable=jaxpr-f32-leak -- deliberate: SSIM/PSNR are QUALITY metrics; the window mean runs f32 at HIGHEST precision because bf16 window means measured ~0.3 error at the 0..255 scale (docstring above)
    return jax.lax.conv_general_dilated(
        x, kernel, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
        precision=jax.lax.Precision.HIGHEST,
    )


def ssim(target: jax.Array, pred: jax.Array, ref_buggy_scale: bool = False,
         win: int = 7, per_image: bool = False) -> jax.Array:
    """Mean SSIM with a uniform win×win window, matching
    skimage.metrics.structural_similarity defaults for uint8 inputs
    (win=7, uniform filter, L=255, K1=0.01, K2=0.03, multichannel mean) —
    the exact configuration the reference calls at train.py:54-58."""
    t = to_uint8_space(target, ref_buggy_scale)
    p = to_uint8_space(pred, ref_buggy_scale)
    L = 255.0
    c1, c2 = (0.01 * L) ** 2, (0.03 * L) ** 2
    # Shifted moments: remove the per-image/channel mean before the
    # second-moment windows. Variance and covariance are shift-invariant,
    # and centering drops the 0..255 offset out of the E[x²]−μ² subtraction
    # (catastrophic cancellation in fp32 once pred ≈ target); the luminance
    # terms add the shift back exactly.
    s_t = jnp.mean(t, axis=(1, 2), keepdims=True)
    s_p = jnp.mean(p, axis=(1, 2), keepdims=True)
    tc = t - s_t
    pc = p - s_p
    mu_tc = _uniform_window(tc, win)
    mu_pc = _uniform_window(pc, win)
    mu_t = mu_tc + s_t
    mu_p = mu_pc + s_p
    # skimage uses unbiased covariance (ddof=1) via cov_norm = N/(N-1)
    n = win * win
    cov_norm = n / (n - 1.0)
    var_t = cov_norm * (_uniform_window(tc * tc, win) - mu_tc**2)
    var_p = cov_norm * (_uniform_window(pc * pc, win) - mu_pc**2)
    cov = cov_norm * (_uniform_window(tc * pc, win) - mu_tc * mu_pc)
    num = (2 * mu_t * mu_p + c1) * (2 * cov + c2)
    den = (mu_t**2 + mu_p**2 + c1) * (var_t + var_p + c2)
    smap = num / den
    if per_image:
        return jnp.mean(smap, axis=tuple(range(1, smap.ndim)))
    return jnp.mean(smap)
