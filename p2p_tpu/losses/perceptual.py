"""VGG19 perceptual loss.

Behavior parity with the reference ``VGGLoss`` (networks.py:18-30): L1
between the five tap activations with weights [1/32, 1/16, 1/8, 1/4, 1],
target features detached. The reference feeds [-1,1] images straight into
VGG with no ImageNet normalization (networks.py:26) — kept as the default
(``imagenet_norm=False``) since it changes the loss scale.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from p2p_tpu.models.vgg import VGG19Features

VGG_SLICE_WEIGHTS = (1.0 / 32, 1.0 / 16, 1.0 / 8, 1.0 / 4, 1.0)


def vgg_loss(
    vgg_params: Dict[str, Any],
    x: jax.Array,
    y: jax.Array,
    imagenet_norm: bool = False,
    dtype=None,
) -> jax.Array:
    """Perceptual distance between x and y (target y stop-gradiented)."""
    model = VGG19Features(dtype=dtype, imagenet_norm=imagenet_norm)
    feats_x = model.apply({"params": vgg_params}, x)
    feats_y = model.apply({"params": vgg_params}, jax.lax.stop_gradient(y))
    total = jnp.zeros((), jnp.float32)
    for w, fx, fy in zip(VGG_SLICE_WEIGHTS, feats_x, feats_y):
        fy = jax.lax.stop_gradient(fy)
        total = total + w * jnp.mean(
            jnp.abs(fx.astype(jnp.float32) - fy.astype(jnp.float32))
        )
    return total
