"""Gram-matrix style loss.

The reference carries this as a dead experiment (``gram`` /
``calc_Gram_Loss`` at train.py:67-101, call sites commented at
train.py:370-382); here it is live behind ``LossConfig.lambda_style``
(consumed by ``build_train_step``).

Gram of NHWC features: per-image G = FᵀF / (H·W·C) over the flattened
spatial dims (the reference normalizes by h*w*ch — train.py:84-90).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from p2p_tpu.losses.perceptual import VGG_SLICE_WEIGHTS
from p2p_tpu.models.vgg import VGG19Features


def gram_matrix(feats: jax.Array) -> jax.Array:
    """(N, H, W, C) → (N, C, C) normalized Gram matrices."""
    n, h, w, c = feats.shape
    f = feats.astype(jnp.float32).reshape(n, h * w, c)
    return jnp.einsum("nsc,nsd->ncd", f, f) / float(h * w * c)


def style_loss(
    vgg_params: Any,
    fake: jax.Array,
    real: jax.Array,
    imagenet_norm: bool = False,
    weights: Optional[List[float]] = None,
) -> jax.Array:
    """Σ_i w_i · L1(Gram(VGG_i(fake)), Gram(VGG_i(real)))."""
    model = VGG19Features(imagenet_norm=imagenet_norm)
    f_feats = model.apply({"params": vgg_params}, fake)
    r_feats = model.apply({"params": vgg_params}, real)
    w = weights or VGG_SLICE_WEIGHTS
    total = jnp.zeros((), jnp.float32)
    for wi, ff, rf in zip(w, f_feats, r_feats):
        gf = gram_matrix(ff)
        gr = jax.lax.stop_gradient(gram_matrix(rf))
        total = total + wi * jnp.mean(jnp.abs(gf - gr))
    return total
