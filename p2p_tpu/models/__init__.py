from p2p_tpu.models.compression import CompressionNetwork
from p2p_tpu.models.compression_ae import (
    CompressionAutoencoder,
    CompressionDecoder,
    CompressionEncoder,
)
from p2p_tpu.models.expand import ExpandNetwork, ResidualBlock
from p2p_tpu.models.patchgan import MultiscaleDiscriminator, NLayerDiscriminator
from p2p_tpu.models.pix2pixhd import GlobalGenerator, Pix2PixHDGenerator
from p2p_tpu.models.resnet_gen import ResnetBlock, ResnetGenerator
from p2p_tpu.models.temporal_d import (
    MultiscaleTemporalDiscriminator,
    TemporalDiscriminator,
)
from p2p_tpu.models.unet import UNetGenerator
from p2p_tpu.models.vgg import VGG19Features
from p2p_tpu.models.registry import define_C, define_D, define_G

__all__ = [
    "CompressionNetwork",
    "CompressionAutoencoder",
    "CompressionDecoder",
    "CompressionEncoder",
    "ExpandNetwork",
    "ResidualBlock",
    "MultiscaleDiscriminator",
    "NLayerDiscriminator",
    "GlobalGenerator",
    "Pix2PixHDGenerator",
    "ResnetBlock",
    "ResnetGenerator",
    "UNetGenerator",
    "TemporalDiscriminator",
    "MultiscaleTemporalDiscriminator",
    "VGG19Features",
    "define_C",
    "define_D",
    "define_G",
]
