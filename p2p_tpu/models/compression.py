"""CompressionNetwork — learned residual pre-filter before quantization.

Behavior parity with /root/reference/networks.py:201-236:
input x → conv(3→64,k5)+PReLU → conv(64→64,k3)+BN+PReLU →
conv(64→12,k3,s2)+PixelShuffle(2) → per-pixel L2-normalize over channels →
x + residual.

Differences by design (TPU-first): NHWC, bf16-capable, BatchNorm stats in
fp32 threaded through the 'batch_stats' collection, pixel shuffle as a
reshape/transpose instead of torch's builtin.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.ops.activations import PReLU
from p2p_tpu.ops.conv import ConvLayer
from p2p_tpu.ops.norm import BatchNorm
from p2p_tpu.ops.pixel_shuffle import pixel_shuffle


class CompressionNetwork(nn.Module):
    features: int = 64

    # int8 QAT path (ops/int8.py, ISSUE 14): all three convs through
    # QuantConv — including the k5 RGB stem, because net_c's OUTPUT is
    # crushed to `quant_bits` (3) by the pipeline quantizer immediately
    # after, so int8 QAT noise inside the pre-filter sits far below the
    # signal the net is trained to survive (the stem's HBM-bound caveat
    # from the G/D doctrine is noted in docs/PERFORMANCE.md; the
    # per-net knob lets on-chip measurement overrule). ``int8_delayed``
    # stores the activation amax in a 'quant' collection the train step
    # threads as ``quant_c`` (frozen at eval/serve, remapped by the
    # elastic ``reshard_amax`` law like quant_g/quant_d).
    int8: bool = False
    int8_delayed: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        identity = x
        i8, dly = self.int8, self.int8_delayed
        y = ConvLayer(self.features, kernel_size=5, int8=i8,
                      int8_delayed=dly, dtype=self.dtype)(x)
        y = PReLU()(y)
        y = ConvLayer(self.features, kernel_size=3, int8=i8,
                      int8_delayed=dly, dtype=self.dtype)(y)
        y = BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
        y = PReLU()(y)
        y = ConvLayer(12, kernel_size=3, stride=2, int8=i8,
                      int8_delayed=dly, dtype=self.dtype)(y)
        y = pixel_shuffle(y, 2)
        # Per-pixel L2 normalization over channels (torch F.normalize dim=1).
        norm = jnp.maximum(jnp.linalg.norm(y, axis=-1, keepdims=True), 1e-12)
        y = y / norm
        return identity + y
