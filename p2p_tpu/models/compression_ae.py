"""Learned-compression autoencoder — the reference's commented-out
candidate feature (networks.py:238-392: ``CompressionEncoder``,
``CompressionResidualBlock``, ``CompressionGenerator``, ``CompressNetwork``),
implemented live as an optional model family.

Architecture (HiFiC-flavored, widths from the reference):
- **Encoder** (networks.py:238-289): c7s1-ngf, then 4× [reflect-pad conv
  k3 s2 + InstanceNorm + ReLU] doubling channels (ngf→16·ngf), project
  k3 → ``latent_channels`` (reference: 60→960, latent 220).
- **Decoder** (networks.py:322-384): InstanceNorm → conv k3 → IN, 8
  residual blocks (IN, no activation after add — networks.py:292-319)
  with a long skip from the head, 4× ConvTranspose k3 s2 + IN + ReLU
  halving channels, c7s1-3 out.
- **CompressionAutoencoder**: decoder∘(optional STE quantizer)∘encoder.
  The reference's ``CompressNetwork`` stub carries an ``entropy_code``
  flag with no implementation (networks.py:386-392); entropy coding is
  likewise out of scope here — the latent quantizer models the rate
  bottleneck.

TPU notes: InstanceNorm reduces over H,W per (N,C) — see ops.norm (and
the Pallas fusion for HD shapes); transposed convs lower to MXU-friendly
conv-gradients under XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.models.resnet_gen import ResnetBlock
from p2p_tpu.ops.conv import ConvLayer, normal_init
from p2p_tpu.ops.norm import InstanceNorm
from p2p_tpu.ops.quantize import quantize, quantize_ste
from p2p_tpu.ops.activations import relu_y


class CompressionEncoder(nn.Module):
    ngf: int = 60
    latent_channels: int = 220
    n_down: int = 4
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        y = ConvLayer(self.ngf, kernel_size=7, dtype=self.dtype)(x)
        y = relu_y(InstanceNorm(dtype=self.dtype)(y))
        for i in range(self.n_down):
            f = self.ngf * (2 ** (i + 1))
            y = ConvLayer(f, kernel_size=3, stride=2, dtype=self.dtype)(y)
            y = relu_y(InstanceNorm(dtype=self.dtype)(y))
        return ConvLayer(self.latent_channels, kernel_size=3,
                         dtype=self.dtype)(y)


class CompressionDecoder(nn.Module):
    """Latent channel count is implicit in the input ``z``."""

    ngf: int = 60
    n_blocks: int = 8
    n_up: int = 4
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, z):
        f_top = self.ngf * (2 ** self.n_up)
        y = InstanceNorm(dtype=self.dtype)(z)
        y = ConvLayer(f_top, kernel_size=3, dtype=self.dtype)(y)
        head = InstanceNorm(dtype=self.dtype)(y)
        y = head
        # same block as the resnet G family (networks.py:292-319 matches
        # the classic no-post-add-activation shape)
        for _ in range(self.n_blocks):
            # legacy_layout pinned: this module mirrors the reference's
            # commented-out AE verbatim (biases and all) and is not on a
            # perf-critical path — keep its param tree stable
            y = ResnetBlock(f_top, norm="instance", legacy_layout=True,
                            dtype=self.dtype)(y)
        y = y + head  # long skip (networks.py:375)
        for i in reversed(range(self.n_up)):
            f = self.ngf * (2 ** i)
            y = nn.ConvTranspose(
                f, kernel_size=(3, 3), strides=(2, 2), padding="SAME",
                dtype=self.dtype, kernel_init=normal_init(),
            )(y)
            y = relu_y(InstanceNorm(dtype=self.dtype)(y))
        return ConvLayer(3, kernel_size=7, dtype=self.dtype)(y)


class CompressionAutoencoder(nn.Module):
    """decode(quantize(encode(x))); latent quantization models the rate
    bottleneck (``quant_bits=0`` disables it)."""

    ngf: int = 60
    latent_channels: int = 220
    n_blocks: int = 8
    quant_bits: int = 0
    quant_ste: bool = True
    dtype: Optional[jnp.dtype] = None

    def setup(self):
        self.encoder = CompressionEncoder(
            ngf=self.ngf, latent_channels=self.latent_channels,
            dtype=self.dtype,
        )
        self.decoder = CompressionDecoder(
            ngf=self.ngf, n_blocks=self.n_blocks, dtype=self.dtype,
        )

    def encode(self, x) -> jax.Array:
        z = self.encoder(x)
        if self.quant_bits > 0:
            q = quantize_ste if self.quant_ste else quantize
            # latent is unbounded; squash to [0,1] for the bit quantizer
            z = q(jax.nn.sigmoid(z), self.quant_bits)
        return z

    def decode(self, z) -> jax.Array:
        return self.decoder(z)

    def __call__(self, x) -> jax.Array:
        return self.decode(self.encode(x))
