"""ExpandNetwork — the flagship generator (transform-net style).

Behavior parity with /root/reference/networks.py:447-523:
PixelUnshuffle(2) → nearest ×2 upsample (3ch→12ch at original spatial size)
→ encoder [conv k9 12→32, conv k3 s2 32→64, conv k3 s2 64→128], each
BN+PReLU → 9 residual blocks (128) → long skip + LeakyReLU(0.2) →
decoder [up×2 conv 128→64, up×2 conv 64→32, conv k9 32→3], BN each,
tanh output.

The reference shares ONE nn.PReLU scalar across all encoder/decoder call
sites (networks.py:452); replicated here via a single shared PReLU module.
Residual blocks use BatchNorm (not InstanceNorm) exactly like the reference
(networks.py:433) — a ``norm`` knob swaps in InstanceNorm / Pallas
InstanceNorm for the HD configs.

TPU-first: the residual trunk is where the FLOPs live — it runs on the MXU
in bf16, or on the s8×s8→s32 int8 path when ``int8`` is set
(ops/int8.py; the k3-s1 trunk is the form where all three quantized
contractions win), and is optionally rematerialized (``remat``) to trade
FLOPs for HBM when spatial extents are large.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.ops.activations import PReLU, leaky_relu_y, tanh_y
from p2p_tpu.ops.conv import ConvLayer, UpsampleConvLayer, remat_wrap
from p2p_tpu.ops.norm import make_norm, make_norm_act
from p2p_tpu.ops.pixel_shuffle import pixel_unshuffle
from p2p_tpu.ops.conv import upsample_nearest


class ResidualBlock(nn.Module):
    """conv-norm-relu-conv-norm + identity, relu after add.
    Ref: networks.py:429-444. ``int8``: both k3-s1 convs on the int8
    MXU path (ops/int8.py)."""

    features: int
    norm: str = "batch"
    int8: bool = False
    int8_delayed: bool = False
    # see UNetGenerator.legacy_layout: conv biases before mean-subtracting
    # norms are exactly dead; default drops them (True = round-2 layout)
    legacy_layout: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        # norm_act: the conv epilogue (norm → [+residual] → relu) behind
        # ONE seam so the instance-norm HD configs fuse the whole chain
        # into the Pallas normalize pass (ops/pallas/norm_act.py)
        na = make_norm_act(self.norm, train=train, dtype=self.dtype)
        ub = self.legacy_layout or self.norm == "none"
        y = ConvLayer(self.features, kernel_size=3, int8=self.int8, int8_delayed=self.int8_delayed,
                      use_bias=ub, dtype=self.dtype)(x)
        y = na(y, act="relu")
        y = ConvLayer(self.features, kernel_size=3, int8=self.int8, int8_delayed=self.int8_delayed,
                      use_bias=ub, dtype=self.dtype)(y)
        return na(y, act="relu", residual=x)


class ExpandNetwork(nn.Module):
    ngf: int = 32
    n_blocks: int = 9
    out_channels: int = 3
    norm: str = "batch"
    remat: Union[bool, str] = False
    # int8 MXU path for the residual trunk's k3-s1 convs (stem/updown/
    # head stay bf16)
    int8: bool = False
    int8_delayed: bool = False
    legacy_layout: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True, trunk_fn=None):
        mk = make_norm(self.norm, train=train, dtype=self.dtype)
        # EVERY conv here (head included, networks.py:471-475 BN after the
        # k9 head) is norm-followed → all conv biases are dead
        ub = self.legacy_layout or self.norm == "none"
        act = PReLU()  # single shared learned scalar, as in the reference

        y = pixel_unshuffle(x, 2)
        y = upsample_nearest(y, 2)

        y = act(mk()(ConvLayer(self.ngf, kernel_size=9, use_bias=ub,
                               dtype=self.dtype)(y)))
        y = act(mk()(ConvLayer(self.ngf * 2, kernel_size=3, stride=2,
                               use_bias=ub, dtype=self.dtype)(y)))
        y = act(mk()(ConvLayer(self.ngf * 4, kernel_size=3, stride=2,
                               use_bias=ub, dtype=self.dtype)(y)))

        residual = y
        if trunk_fn is not None:
            # externally-scheduled trunk (the GPipe path, parallel/pp.py):
            # the block submodules are never created, so their variables
            # live outside this module — in the pipe-sharded stage stack
            y = trunk_fn(y)
        else:
            block_cls = remat_wrap(ResidualBlock, self.remat)
            for i in range(self.n_blocks):
                # explicit name: remat wrapping must not change param paths
                y = block_cls(self.ngf * 4, norm=self.norm, int8=self.int8, int8_delayed=self.int8_delayed,
                              legacy_layout=self.legacy_layout, dtype=self.dtype,
                              name=f"ResidualBlock_{i}")(y, train)
        y = leaky_relu_y(y + residual, 0.2)

        y = act(mk()(UpsampleConvLayer(self.ngf * 2, kernel_size=3,
                                       upsample=2, use_bias=ub,
                                       dtype=self.dtype)(y)))
        y = act(mk()(UpsampleConvLayer(self.ngf, kernel_size=3, upsample=2,
                                       use_bias=ub, dtype=self.dtype)(y)))
        y = UpsampleConvLayer(self.out_channels, kernel_size=9, use_bias=ub,
                              dtype=self.dtype)(y)
        y = mk()(y)
        return tanh_y(y)
