"""PatchGAN discriminators (pix2pixHD-style multiscale).

Behavior parity with /root/reference/networks.py:716-806, num_D=3,
n_layers=3, spectral norm on the inner convs, intermediate features
returned for the feature-matching loss.

A single NLayerDiscriminator with n_layers=3 has 5 stages (model0..model4):
  0: conv(in→ndf,   k4, s2, pad2) + LeakyReLU(0.2)
  1: SN conv(ndf→2ndf,  k4, s2, pad2) + LeakyReLU     [spectral norm]
  2: SN conv(2ndf→4ndf, k4, s2, pad2) + LeakyReLU     [spectral norm]
  3: SN conv(4ndf→8ndf, k4, s1, pad2) + LeakyReLU     [spectral norm]
  4: conv(8ndf→1, k4, s1, pad2)
(channel growth capped at 512; pad = ceil(3/2) = 2 exactly as the
reference's ``padw``.)

Multiscale: num_D independent discriminators; scale i sees the input
downsampled i times by AvgPool(3, s2, pad1, count_include_pad=False).
Output ordering matches the reference: result[0] is the FINEST scale
(applied to the un-downsampled input) — networks.py:749.

Each forward returns ``[[act_0..act_4] per scale]``. The 70×70-PatchGAN of
classic pix2pix is the num_D=1, no-SN, no-interm-feat corner of this module.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.ops.activations import leaky_relu_y
from p2p_tpu.ops.conv import KN2RowConv, normal_init, save_conv_out
from p2p_tpu.ops.norm import make_norm_act
from p2p_tpu.ops.spectral_norm import SpectralConv


def avg_pool_downsample(x: jax.Array) -> jax.Array:
    """AvgPool2d(3, stride=2, padding=1, count_include_pad=False) in NHWC."""
    ones = jnp.ones(x.shape[1:3] + (1,), x.dtype)[None]
    sum_ = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)]
    )
    cnt = jax.lax.reduce_window(
        ones, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)]
    )
    return sum_ / cnt


class _SplitStemConv(nn.Module):
    """The conditional-D stem conv applied to an UNCONCATENATED (a, b)
    pair: ``conv(concat(a,b), W) == conv(a, W[:,:,:ca]) + conv(b, W[:,:,ca:])``
    by linearity of convolution in the input channels.

    Why: the reference concatenates (input ‖ output) before D
    (train.py:308,315) and so did round 3 — materializing two 6-channel
    NHWC pairs per step (~100 MB each at 256²/bs128) that the stem
    immediately re-reads, and computing the conditioning half
    ``conv(real_a, W_a)`` twice (fake and real branches — XLA CSE dedupes
    the identical subexpression once the halves are separate ops). The
    fake branch's input cotangent also becomes per-half, so the dead
    ``real_a`` dgrad disappears structurally instead of being sliced off
    after computation (train/step.py round-3 ``[..., in_c:]``).

    Param tree matches the concat path exactly (``Conv_0/{kernel,bias}``
    with the full 6-channel HWIO kernel) — checkpoints interchange, and
    init still runs the concat path.
    """

    features: int
    stride: int
    padding: int = 2
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, a, b):
        c = a.shape[-1] + b.shape[-1]
        kernel = self.param("kernel", normal_init(),
                            (4, 4, c, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        dt = self.dtype or jnp.float32
        ca = a.shape[-1]
        pad = [(self.padding, self.padding)] * 2

        def cv(inp, kk):
            dn = jax.lax.conv_dimension_numbers(
                inp.shape, kk.shape, ("NHWC", "HWIO", "NHWC"))
            return jax.lax.conv_general_dilated(
                inp.astype(dt), kk.astype(dt),
                (self.stride, self.stride), pad, dimension_numbers=dn,
            )

        y = cv(a, kernel[:, :, :ca]) + cv(b, kernel[:, :, ca:])
        return save_conv_out(y + bias.astype(y.dtype))


class _PlainConv(nn.Module):
    features: int
    stride: int
    padding: int = 2
    # int8 QAT MXU path (ops/int8.py) — set by NLayerDiscriminator on
    # its wide inner convs (and, under int8_stem/int8_head, the stem
    # and logits head).
    int8: bool = False
    int8_delayed: bool = False
    # quantize-fused input epilogue threading (ops/int8.py QuantConv)
    epilogue: Optional[Callable] = None
    epilogue_tap: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        if isinstance(x, (tuple, list)):
            # unconcatenated conditional pair — the split-stem path
            # (param tree identical to the concat path: Conv_0 holds the
            # full 6-channel kernel). Stays bf16 even under int8_stem:
            # the split form exists precisely because the halves are
            # HBM-bound image reads.
            a, b = x
            return _SplitStemConv(
                self.features, stride=self.stride, padding=self.padding,
                dtype=self.dtype, name="Conv_0",
            )(a, b)
        if self.stride == 1 and self.features * 16 <= x.shape[-1]:
            # thin head (e.g. 512→1): kn2row matmul decomposition — the
            # MXU conv runs at 3-6 TF/s with one live output lane; this
            # form is one full-rate HBM pass over x (ops/conv.py). With
            # int8 the tap dot runs s8×s8→s32 (int8_kn2row_conv).
            return KN2RowConv(self.features, kernel_size=4,
                              padding=self.padding, int8=self.int8,
                              int8_delayed=self.int8_delayed,
                              dtype=self.dtype, name="Conv_0")(x)
        if self.int8:
            from p2p_tpu.ops.int8 import QuantConv

            return QuantConv(
                self.features, kernel_size=4, strides=self.stride,
                padding=self.padding, dtype=self.dtype,
                kernel_init=normal_init(), name="Conv_0",
                delayed=self.int8_delayed,
                epilogue=self.epilogue, epilogue_tap=self.epilogue_tap,
            )(x)
        # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 measured-rejected: only the 6-ch stage-0 stem reaches this line under delayed-int8 (inner convs take the int8 branch above, the head the kn2row branch); the 6-wide contraction leaves the MXU idle in any dtype — HBM-bound, the rounds 2-5 stems-stay-bf16 doctrine. ModelConfig.int8_stem keeps the form measurable per chip.
        return save_conv_out(nn.Conv(
            self.features,
            kernel_size=(4, 4),
            strides=(self.stride, self.stride),
            padding=self.padding,
            dtype=self.dtype,
            kernel_init=normal_init(),
        )(x))


class NLayerDiscriminator(nn.Module):
    ndf: int = 64
    n_layers: int = 3
    use_spectral_norm: bool = True
    use_sigmoid: bool = False
    get_interm_feat: bool = True
    # int8 QAT path for the wide inner convs (stages 1..n_layers); by
    # default the 6-ch stem and the 1-ch head stay bf16. Composes with
    # spectral norm: the power iteration tracks the true f32 weight and
    # only the normalized w/σ is quantized (SpectralConv.int8).
    int8: bool = False
    int8_delayed: bool = False
    # ISSUE 14 coverage knobs (core/config.py ModelConfig docs):
    # int8_stem quantizes the stage-0 conv (concat form only — the
    # split-pair stem stays bf16 by design); int8_head runs the logits
    # head on the int8 kn2row path; int8_fused_epilogue fuses each inner
    # conv's input epilogue [norm+LeakyReLU+quantize+amax] into one
    # streaming pass (needs int8_delayed + an instance-family norm).
    int8_stem: bool = False
    int8_head: bool = False
    int8_fused_epilogue: bool = False
    # Normalization on the inner (stage 1..n_layers) convs — the pix2pixHD
    # paper's D carries InstanceNorm there; this repo's reference lineage
    # (networks.py:716) has none, so "none" is the parity default.
    # "instance"/"pallas_instance" norms are affine-free → the param tree
    # is IDENTICAL either way (checkpoints interchange); with
    # "pallas_instance" the whole conv epilogue (norm + LeakyReLU) runs as
    # ONE fused Pallas pass (ops/pallas/norm_act.py) — the D-side leaky
    # variant of the generator's fused chains.
    norm: str = "none"
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x) -> List[jax.Array]:
        if self.norm not in ("none", "instance", "pallas_instance"):
            # the train step threads no batch_stats for D — stat-free
            # (per-forward) norms only
            raise ValueError(
                f"discriminator norm must be none/instance/pallas_instance "
                f"(stateless), got {self.norm!r}")
        fused_q = (self.int8 and self.int8_delayed
                   and self.int8_fused_epilogue)
        if fused_q and self.norm not in ("instance", "pallas_instance"):
            raise ValueError(
                "int8_fused_epilogue needs a stateless instance-family "
                f"discriminator norm (norm_d), got {self.norm!r}")
        feats = []
        nf = self.ndf
        na = (make_norm_act(self.norm, dtype=self.dtype)
              if self.norm != "none" else None)
        y = _PlainConv(nf, stride=2,
                       int8=self.int8 and self.int8_stem,
                       int8_delayed=self.int8_delayed,
                       dtype=self.dtype)(x)
        y = leaky_relu_y(y, 0.2)
        feats.append(y)

        def inner_conv(y, features, stride, ep=None, tap=False):
            if self.use_spectral_norm:
                return SpectralConv(
                    features, kernel_size=4, stride=stride, padding=2,
                    int8=self.int8, int8_delayed=self.int8_delayed,
                    epilogue=ep, epilogue_tap=tap, dtype=self.dtype
                )(y)
            return _PlainConv(features, stride=stride, int8=self.int8,
                              int8_delayed=self.int8_delayed,
                              epilogue=ep, epilogue_tap=tap,
                              dtype=self.dtype)(y)

        def inner(y, features, stride):
            y = inner_conv(y, features, stride)
            if na is not None:
                return na(y, act="leaky", slope=0.2)
            return leaky_relu_y(y, 0.2)

        widths = []
        for _ in range(1, self.n_layers):
            nf = min(nf * 2, 512)
            widths.append((nf, 2))
        nf = min(nf * 2, 512)
        widths.append((nf, 1))

        if not fused_q:
            for features, stride in widths:
                y = inner(y, features, stride)
                feats.append(y)
        else:
            # quantize-fused epilogues: each inner conv after the first
            # consumes the PREVIOUS conv's raw output through its fused
            # [norm + LeakyReLU + clip/round + amax] input epilogue
            # (ops/pallas/norm_act.py) — the float activation between
            # inner stages is never materialized. Feature-matching taps
            # become the dequantized surrogate sx·q: exactly the values
            # the downstream conv contracts (QAT-faithful taps). Module
            # construction order is identical to the unfused branch, so
            # flax auto-naming — and the whole param/quant tree — is
            # unchanged; only the LAST inner epilogue stays unfused (the
            # logits head quantizes its own input).
            ep = (lambda y_, sx: na(y_, act="leaky", slope=0.2,
                                    quant_scale=sx))
            raw = None
            for features, stride in widths:
                if raw is None:
                    raw = inner_conv(y, features, stride)
                else:
                    raw, tap = inner_conv(raw, features, stride, ep=ep,
                                          tap=True)
                    feats.append(tap)
            y = na(raw, act="leaky", slope=0.2)
            feats.append(y)

        y = _PlainConv(1, stride=1,
                       int8=self.int8 and self.int8_head,
                       int8_delayed=self.int8_delayed,
                       dtype=self.dtype)(y)
        if self.use_sigmoid:
            y = nn.sigmoid(y)
        feats.append(y)

        if self.get_interm_feat:
            return feats
        return [feats[-1]]


class MultiscaleDiscriminator(nn.Module):
    ndf: int = 64
    n_layers: int = 3
    num_D: int = 3
    use_spectral_norm: bool = True
    use_sigmoid: bool = False
    get_interm_feat: bool = True
    int8: bool = False
    int8_delayed: bool = False
    int8_stem: bool = False
    int8_head: bool = False
    int8_fused_epilogue: bool = False
    norm: str = "none"
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x) -> List[List[jax.Array]]:
        results = []
        current = x
        for i in range(self.num_D):
            # Finest-first result ordering; submodule index num_D-1-i keeps
            # parameter naming aligned with the reference's scale{i} layout.
            d = NLayerDiscriminator(
                ndf=self.ndf,
                n_layers=self.n_layers,
                use_spectral_norm=self.use_spectral_norm,
                use_sigmoid=self.use_sigmoid,
                get_interm_feat=self.get_interm_feat,
                int8=self.int8,
                int8_delayed=self.int8_delayed,
                int8_stem=self.int8_stem,
                int8_head=self.int8_head,
                int8_fused_epilogue=self.int8_fused_epilogue,
                norm=self.norm,
                dtype=self.dtype,
                name=f"scale{self.num_D - 1 - i}",
            )
            results.append(d(current))
            if i != self.num_D - 1:
                # unconcatenated (a, b) pairs downsample elementwise —
                # AvgPool is channelwise, so pooling the halves equals
                # pooling the concat
                if isinstance(current, (tuple, list)):
                    current = tuple(avg_pool_downsample(t) for t in current)
                else:
                    current = avg_pool_downsample(current)
        return results
