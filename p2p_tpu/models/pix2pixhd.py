"""pix2pixHD coarse-to-fine generator (BASELINE configs[3]: 1024×512).

Global generator G1 (a deeper ResnetGenerator: 4 stride-2 downsamples, 9
blocks, channels capped at 1024) learns at half resolution; a local enhancer
G2 wraps it at full resolution: the input is avg-pool-downsampled for G1,
G1's pre-output features are added into G2's half-res features, 3 residual
blocks and one upsample produce the full-res image. The reference has no HD
path (the capability comes from BASELINE.json, not /root/reference) —
architecture follows the pix2pixHD paper's G, re-expressed with this
framework's reflection-padded resize-conv layers.

Width convention matches the torch lineage: ``ngf`` names the GLOBAL
generator width (paper: 64); the enhancer runs at ``ngf//2``.

TPU-first: InstanceNorm here is the Pallas-fused kernel when the preset
says so (norm='pallas_instance'). The trunk honors ``ParallelConfig.remat``
(off by default — 1024×512 bs=1 fits single-chip HBM and full remat costs
20%; 'conv' keeps conv outputs and recomputes only elementwise chains for
tighter-memory meshes).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.models.patchgan import avg_pool_downsample
from p2p_tpu.models.resnet_gen import ResnetBlock, ResnetGenerator
from p2p_tpu.ops.conv import ConvLayer, UpsampleConvLayer, remat_wrap
from p2p_tpu.ops.norm import make_norm_act
from p2p_tpu.ops.activations import tanh_y


def GlobalGenerator(
    ngf: int = 64,
    out_channels: int = 3,
    n_blocks: int = 9,
    norm: str = "instance",
    return_features: bool = False,
    remat: Union[bool, str] = False,
    int8: bool = False,
    int8_delayed: bool = False,
    legacy_layout: bool = False,
    dtype=None,
    name: Optional[str] = None,
) -> ResnetGenerator:
    """G1: the ResnetGenerator configured as pix2pixHD's global net
    (4 downsamples, channel cap 1024)."""
    return ResnetGenerator(
        ngf=ngf, n_blocks=n_blocks, out_channels=out_channels,
        n_downsampling=4, norm=norm, max_features=1024,
        return_features=return_features, remat=remat, int8=int8,
        int8_delayed=int8_delayed, legacy_layout=legacy_layout, dtype=dtype,
        name=name,
    )


class Pix2PixHDGenerator(nn.Module):
    """G2∘G1: one local enhancer around the global generator."""

    ngf: int = 64              # global width; the enhancer runs at ngf//2
    out_channels: int = 3
    n_blocks_global: int = 9
    n_blocks_local: int = 3
    norm: str = "instance"
    remat: Union[bool, str] = False
    # int8 MXU path for the G1 trunk + local enhancer ResnetBlocks
    int8: bool = False
    int8_delayed: bool = False
    # see UNetGenerator.legacy_layout: conv biases before mean-subtracting
    # norms are exactly dead; default drops them (True = round-2 layout)
    legacy_layout: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        # fused conv epilogues for norm='pallas_instance' (ops/norm.py
        # make_norm_act — the same seam the ResNet family uses)
        na = make_norm_act(self.norm, train=train, dtype=self.dtype)
        ub = self.legacy_layout or self.norm == "none"
        ngf_local = self.ngf // 2

        # G1 on the avg-pooled half-res input, pre-output features
        x_half = avg_pool_downsample(x)
        g1_feats = GlobalGenerator(
            ngf=self.ngf, n_blocks=self.n_blocks_global, norm=self.norm,
            return_features=True, remat=self.remat, int8=self.int8, int8_delayed=self.int8_delayed,
            legacy_layout=self.legacy_layout, dtype=self.dtype, name="global",
        )(x_half, train)

        # G2 front end on the full-res input, down to half res
        y = ConvLayer(ngf_local, kernel_size=7, use_bias=ub,
                      dtype=self.dtype)(x)
        y = na(y, act="relu")
        y = ConvLayer(self.ngf, kernel_size=3, stride=2, use_bias=ub,
                      dtype=self.dtype)(y)
        y = na(y, act="relu")

        # fuse + local trunk
        y = y + g1_feats
        block_cls = remat_wrap(ResnetBlock, self.remat)
        for i in range(self.n_blocks_local):
            # explicit name: remat wrapping must not change param paths
            y = block_cls(self.ngf, norm=self.norm, int8=self.int8, int8_delayed=self.int8_delayed,
                          legacy_layout=self.legacy_layout, dtype=self.dtype,
                          name=f"ResnetBlock_{i}")(y, train)

        y = UpsampleConvLayer(ngf_local, kernel_size=3, upsample=2,
                              use_bias=ub, dtype=self.dtype)(y)
        y = na(y, act="relu")
        y = ConvLayer(self.out_channels, kernel_size=7, dtype=self.dtype)(y)
        return tanh_y(y)
