"""Model factories — the TPU-native counterpart of the reference's
``define_C`` / ``define_G`` / ``define_D`` (networks.py:157,164,708).

Factories build flax modules from :class:`p2p_tpu.core.config.ModelConfig`
and expose :func:`init_variables`, which re-draws weights per the configured
init type (normal/xavier/kaiming/orthogonal — networks.py:128-150 semantics:
conv/linear kernels re-initialized, BatchNorm γ~N(1,0.02), biases zero).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.core import freeze, unfreeze

from p2p_tpu.core.config import ModelConfig
from p2p_tpu.models.compression import CompressionNetwork
from p2p_tpu.models.expand import ExpandNetwork
from p2p_tpu.models.patchgan import MultiscaleDiscriminator


def define_C(cfg: ModelConfig, dtype=None) -> nn.Module:
    return CompressionNetwork(
        int8=cfg.int8 and cfg.int8_compression,
        int8_delayed=cfg.int8_delayed,
        dtype=dtype,
    )


def define_G(cfg: ModelConfig, dtype=None, remat=False) -> nn.Module:
    int8_g = cfg.int8 and cfg.int8_generator
    delayed = cfg.int8_delayed
    if cfg.generator == "expand":
        return ExpandNetwork(
            ngf=cfg.ngf,
            n_blocks=cfg.n_blocks,
            out_channels=cfg.output_nc,
            norm=cfg.norm,
            remat=remat,
            int8=int8_g,
            int8_delayed=delayed,
            legacy_layout=cfg.legacy_layout,
            dtype=dtype,
        )
    if cfg.generator == "unet":
        from p2p_tpu.models.unet import UNetGenerator

        return UNetGenerator(
            ngf=cfg.ngf, out_channels=cfg.output_nc, norm=cfg.norm,
            use_dropout=cfg.use_dropout, upsample_mode=cfg.upsample_mode,
            int8=int8_g and cfg.upsample_mode == "deconv",
            int8_decoder=cfg.int8_decoder,
            int8_delayed=delayed,
            int8_stem=cfg.int8_stem,
            legacy_layout=cfg.legacy_layout,
            thin_head=cfg.thin_head,
            head_pallas=cfg.head_pallas,
            thin_stem=cfg.thin_stem,
            dtype=dtype,
        )
    if cfg.generator == "resnet":
        from p2p_tpu.models.resnet_gen import ResnetGenerator

        return ResnetGenerator(
            ngf=cfg.ngf,
            n_blocks=cfg.n_blocks,
            out_channels=cfg.output_nc,
            norm=cfg.norm,
            remat=remat,
            int8=int8_g,
            int8_delayed=delayed,
            legacy_layout=cfg.legacy_layout,
            dtype=dtype,
        )
    if cfg.generator == "pix2pixhd":
        from p2p_tpu.models.pix2pixhd import Pix2PixHDGenerator

        return Pix2PixHDGenerator(
            ngf=cfg.ngf, out_channels=cfg.output_nc,
            n_blocks_global=cfg.n_blocks, norm=cfg.norm,
            remat=remat, int8=int8_g, int8_delayed=delayed,
            legacy_layout=cfg.legacy_layout, dtype=dtype,
        )
    if cfg.generator == "pix2pixhd_global":
        # phase 1 of the coarse-to-fine schedule: G1 alone at half res
        from p2p_tpu.models.pix2pixhd import GlobalGenerator

        return GlobalGenerator(
            ngf=cfg.ngf, out_channels=cfg.output_nc, n_blocks=cfg.n_blocks,
            norm=cfg.norm, remat=remat, int8=int8_g, int8_delayed=delayed,
            legacy_layout=cfg.legacy_layout, dtype=dtype,
        )
    raise ValueError(f"unknown generator {cfg.generator!r}")


def define_D(cfg: ModelConfig, dtype=None) -> nn.Module:
    return MultiscaleDiscriminator(
        ndf=cfg.ndf,
        n_layers=cfg.n_layers_D,
        num_D=cfg.num_D,
        use_spectral_norm=cfg.use_spectral_norm,
        get_interm_feat=cfg.get_interm_feat,
        int8=cfg.int8,
        int8_delayed=cfg.int8_delayed,
        int8_stem=cfg.int8_stem,
        int8_head=cfg.int8_head,
        int8_fused_epilogue=cfg.int8_fused_epilogue,
        norm=cfg.norm_d,
        dtype=dtype,
    )


# ---------------------------------------------------------------- init types

def _kernel_initializer(init_type: str, gain: float):
    if init_type == "normal":
        return nn.initializers.normal(stddev=gain)
    if init_type == "xavier":
        return nn.initializers.xavier_normal()
    if init_type == "kaiming":
        return nn.initializers.kaiming_normal()
    if init_type == "orthogonal":
        return nn.initializers.orthogonal(scale=gain)
    raise ValueError(f"unknown init type {init_type!r}")


def apply_init_type(
    params: Dict[str, Any], rng: jax.Array, init_type: str = "normal",
    gain: float = 0.02
) -> Dict[str, Any]:
    """Re-draw conv/linear kernels per the configured initializer.

    Leaves biases, norm affines (already γ~N(1,0.02)/β=0 at init), PReLU
    alphas and spectral-norm state untouched.
    """
    init_fn = _kernel_initializer(init_type, gain)
    flat = jax.tree_util.tree_flatten_with_path(unfreeze(params))[0]
    treedef = jax.tree_util.tree_structure(unfreeze(params))
    leaves = []
    for i, (path, leaf) in enumerate(flat):
        last = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if last == "kernel" and getattr(leaf, "ndim", 0) >= 2:
            sub = jax.random.fold_in(rng, i)
            leaves.append(init_fn(sub, leaf.shape, leaf.dtype))
        else:
            leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def init_variables(module: nn.Module, rng: jax.Array, sample_input,
                   init_type: str = "normal", gain: float = 0.02, **kwargs):
    """init() + configured weight re-draw; returns the full variable dict."""
    variables = unfreeze(module.init(rng, sample_input, **kwargs))
    if init_type != "normal":  # modules already default to normal(0.02)
        variables["params"] = apply_init_type(
            variables["params"], jax.random.fold_in(rng, 7), init_type, gain
        )
    return variables
