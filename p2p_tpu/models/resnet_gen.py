"""ResNet generator — the commented-out alternative of the reference
(networks.py:168 ``ResnetGenerator``; Johnson-style transform net used by
pix2pix/CycleGAN) and the G of the Cityscapes spatial-shard preset.

c7s1-ngf → 2× stride-2 down conv (k3) → ``n_blocks`` residual blocks →
2× resize-conv up → c7s1-out, tanh. All convs reflection-padded; norm/ReLU
after every conv. Unlike ExpandNetwork's ResidualBlock (relu after add,
networks.py:429-444), the classic ResnetBlock has NO activation after the
residual add.

TPU-first: the residual trunk (the FLOPs bulk) runs in bf16 on the MXU and
is optionally rematerialized; upsampling is nearest-resize + conv.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.ops.conv import ConvLayer, UpsampleConvLayer, remat_wrap
from p2p_tpu.ops.norm import make_norm_act
from p2p_tpu.ops.activations import tanh_y


class ResnetBlock(nn.Module):
    """reflectpad-conv-norm-relu-reflectpad-conv-norm + identity (no final
    activation). ``int8``: both k3-s1 convs on the int8 MXU path — the
    stride-1 form where all three quantized contractions win on v5e
    (ops/int8.py)."""

    features: int
    norm: str = "instance"
    int8: bool = False
    int8_delayed: bool = False
    # see UNetGenerator.legacy_layout: conv biases before mean-subtracting
    # norms are exactly dead; default drops them (True = round-2 layout)
    legacy_layout: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        # norm_act: the conv epilogue (norm → [+residual] → act) behind ONE
        # seam so norm='pallas_instance' fuses the whole chain into the
        # Pallas normalize pass (ops/pallas/norm_act.py)
        na = make_norm_act(self.norm, train=train, dtype=self.dtype)
        ub = self.legacy_layout or self.norm == "none"
        y = ConvLayer(self.features, kernel_size=3, int8=self.int8, int8_delayed=self.int8_delayed,
                      use_bias=ub, dtype=self.dtype)(x)
        y = na(y, act="relu")
        y = ConvLayer(self.features, kernel_size=3, int8=self.int8, int8_delayed=self.int8_delayed,
                      use_bias=ub, dtype=self.dtype)(y)
        return na(y, residual=x)


class ResnetGenerator(nn.Module):
    """``max_features`` caps channel growth (pix2pixHD's G1 uses 1024);
    ``return_features`` skips the c7s1-out head and returns the ngf-channel
    feature map (the pix2pixHD enhancer taps it)."""

    ngf: int = 64
    n_blocks: int = 9
    out_channels: int = 3
    n_downsampling: int = 2
    norm: str = "instance"
    max_features: Optional[int] = None
    return_features: bool = False
    remat: Union[bool, str] = False
    # int8 MXU path for the residual trunk's k3-s1 convs (the stem,
    # stride-2 downs, upsample convs and head stay bf16 — HBM-bound or
    # quality-critical).
    int8: bool = False
    int8_delayed: bool = False
    legacy_layout: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True, trunk_fn=None):
        na = make_norm_act(self.norm, train=train, dtype=self.dtype)
        cap = self.max_features or (1 << 30)
        # every conv below except the head is norm-followed → dead bias
        ub = self.legacy_layout or self.norm == "none"

        y = ConvLayer(self.ngf, kernel_size=7, use_bias=ub,
                      dtype=self.dtype)(x)
        y = na(y, act="relu")
        for i in range(self.n_downsampling):
            f = min(self.ngf * (2 ** (i + 1)), cap)
            y = ConvLayer(f, kernel_size=3, stride=2, use_bias=ub,
                          dtype=self.dtype)(y)
            y = na(y, act="relu")

        if trunk_fn is not None:
            # externally-scheduled trunk (the GPipe path, parallel/pp.py):
            # block submodules never instantiate — their variables live in
            # the pipe-sharded stage stack, not this module's tree
            y = trunk_fn(y)
        else:
            block_cls = remat_wrap(ResnetBlock, self.remat)
            f_trunk = min(self.ngf * (2 ** self.n_downsampling), cap)
            for i in range(self.n_blocks):
                # explicit name: remat wrapping must not change param paths
                # (nn.remat's auto-name is 'CheckpointResnetBlock_i', which
                # would silently re-key checkpoints when remat is toggled)
                y = block_cls(f_trunk, norm=self.norm, int8=self.int8, int8_delayed=self.int8_delayed,
                              legacy_layout=self.legacy_layout, dtype=self.dtype,
                              name=f"ResnetBlock_{i}")(y, train)

        for i in reversed(range(self.n_downsampling)):
            f = min(self.ngf * (2 ** i), cap)
            y = UpsampleConvLayer(f, kernel_size=3, upsample=2,
                                  use_bias=ub, dtype=self.dtype)(y)
            y = na(y, act="relu")
        if self.return_features:
            return y
        y = ConvLayer(self.out_channels, kernel_size=7, dtype=self.dtype)(y)
        return tanh_y(y)
