"""Temporal (video) discriminator — the vid2vid capability target
(BASELINE configs[4]: 8-frame temporal D, sequence-parallel over ICI).

The reference is image-only (SURVEY §5.7: no sequence dimension anywhere);
this is a new capability, designed TPU-first rather than ported: a 3-D-conv
PatchGAN over NTHWC clips. Temporal kernels are k_t=3 stride-1 ('same'), so
under sequence parallelism each conv needs exactly one frame of halo from
each neighbor — supplied by ``p2p_tpu.parallel.temporal``'s ppermute
exchange (the conv-GAN equivalent of ring attention's block rotation), or
inserted automatically by GSPMD when the clip is sharded
``P('data','time',None,None,None)`` and the apply is jitted over the mesh.

Structure mirrors NLayerDiscriminator (networks.py:758-806) lifted to 3-D:
stage 0   conv3d(in→ndf, k=(3,4,4), s=(1,2,2)) + LeakyReLU(0.2)
stages i  conv3d(→min(2^i·ndf,512), k=(3,4,4), s=(1,2,2)) + LReLU
last      conv3d(→8ndf cap 512, k=(3,4,4), s=(1,1,1)) + LReLU
head      conv3d(→1, k=(3,4,4), s=1)
Intermediate activations are returned for temporal feature matching.
Multiscale: ``num_D`` copies at spatially avg-pooled scales (T untouched).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.models.patchgan import avg_pool_downsample
from p2p_tpu.ops.conv import normal_init, save_conv_out
from p2p_tpu.ops.spectral_norm import _l2norm, spectral_normalize
from p2p_tpu.ops.activations import leaky_relu_y


def avg_pool_spatial_3d(x: jax.Array) -> jax.Array:
    """AvgPool(3, s2, pad1, count_include_pad=False) over H,W of NTHWC —
    frames folded into batch so the 2-D helper is the single source of
    truth."""
    n, t = x.shape[0], x.shape[1]
    y = avg_pool_downsample(x.reshape((n * t,) + x.shape[2:]))
    return y.reshape((n, t) + y.shape[1:])


class _SplitTimeStem(nn.Module):
    """The 6-channel 3-D stem as THREE per-time-tap 2-D convs over the
    frame-folded batch, summed.

    XLA's 3-D conv collapses on thin-input stems the same way its 2-D one
    does (profiled 4.2-4.6 TF/s, ~3.3 ms of the 49 ms vid2vid step); its
    2-D kernels handle the identical shape markedly better. Only the
    k_t=3 time taps move out of the conv — time is padded explicitly and
    sliced per tap, so the autodiff transpose is 3 cheap slice-adds (no
    k²-pad chain), and under ``P('data','time',…)`` sharding GSPMD still
    inserts the one-frame halos the pad/slice needs.

    Param tree matches the plain ``nn.Conv`` path exactly
    (``Conv_0/{kernel,bias}`` with the (3,4,4,C,F) kernel).
    """

    features: int
    stride_hw: int = 2
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        n, t, h, w, c = x.shape
        kernel = self.param("kernel", normal_init(),
                            (3, 4, 4, c, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        dt_ = self.dtype or jnp.float32
        s = self.stride_hw
        xp = jnp.pad(x, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        # f32 partials + f32 accumulation, cast ONCE at the end: the plain
        # 3-D conv rounds once after f32 MXU accumulation — summing
        # bf16-rounded partials would diverge by ~2⁻⁸ per add. Fully-f32
        # convs (not preferred_element_type on bf16 operands, whose
        # autodiff transpose builds a mixed-dtype conv and fails to
        # trace): the stem's FLOPs/bytes are trivial, f32 costs nothing.
        y = None
        for dt in range(3):
            xs = xp[:, dt:dt + t].reshape(n * t, h, w, c).astype(jnp.float32)
            dn = jax.lax.conv_dimension_numbers(
                xs.shape, kernel.shape[1:], ("NHWC", "HWIO", "NHWC"))
            # p2p-lint: disable=jaxpr-f32-leak -- deliberate (docstring above): fully-f32 taps match the 3-D conv's round-once f32 accumulation; preferred_element_type on bf16 operands breaks the autodiff transpose, and the thin stem's FLOPs are trivial
            part = jax.lax.conv_general_dilated(
                xs, kernel[dt], (s, s), ((2, 2), (2, 2)),
                dimension_numbers=dn,
            )
            y = part if y is None else y + part
        y = (y + bias).astype(dt_)
        return save_conv_out(y.reshape((n, t) + y.shape[1:]))


class _Conv3D(nn.Module):
    features: int
    stride_hw: int = 2
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        if x.shape[-1] <= 8:
            # thin-input stem: per-dt 2-D decomposition (see
            # _SplitTimeStem). Deliberately NOT gated on spatial extent
            # like ops/conv.py's 2-D thin dispatches: there the BASELINE
            # is XLA's decent small-extent 2-D conv and the dispatch's
            # own overhead loses below ~300k pixels, while here the
            # baseline is XLA's 3-D thin conv (4.2-4.6 TF/s at the vid
            # preset's native 256², already far below the gate) and the
            # decomposition's overhead is three slice-adds on the k_t=3
            # taps only. Measured +31% at the native extent; equivalence
            # holds at every shape.
            return _SplitTimeStem(
                self.features, stride_hw=self.stride_hw, dtype=self.dtype,
                name="Conv_0",
            )(x)
        return nn.Conv(
            self.features,
            kernel_size=(3, 4, 4),
            strides=(1, self.stride_hw, self.stride_hw),
            padding=((1, 1), (2, 2), (2, 2)),
            dtype=self.dtype,
            kernel_init=normal_init(),
        )(x)


class SpectralConv3D(nn.Module):
    """3-D conv (NTHWC, k=(3,4,4)) with spectral weight norm — the temporal
    lift of ops.spectral_norm.SpectralConv, sharing its power iteration and
    'spectral' collection semantics."""

    features: int
    stride_hw: int = 2
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()
    n_power_iterations: int = 1

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", self.kernel_init, (3, 4, 4, cin, self.features),
            jnp.float32,
        )
        w_mat = kernel.transpose(4, 0, 1, 2, 3).reshape(self.features, -1)
        u_var = self.variable(
            "spectral", "u",
            lambda: _l2norm(
                jax.random.normal(self.make_rng("params"), (self.features,))
            ),
        )
        sigma, new_u, _ = spectral_normalize(
            w_mat, u_var.value, self.n_power_iterations
        )
        if self.is_mutable_collection("spectral"):
            u_var.value = new_u
        kernel_sn = (kernel / sigma).astype(self.dtype or x.dtype)
        y = jax.lax.conv_general_dilated(
            x.astype(kernel_sn.dtype),
            kernel_sn,
            window_strides=(1, self.stride_hw, self.stride_hw),
            padding=[(1, 1), (2, 2), (2, 2)],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias.astype(y.dtype)
        return save_conv_out(y)


class TemporalDiscriminator(nn.Module):
    """Single-scale 3-D PatchGAN on NTHWC clips of (cond ‖ frames).

    ``use_spectral_norm`` puts spectral norm on the inner convs, matching
    NLayerDiscriminator's placement (first and head convs plain)."""

    ndf: int = 64
    n_layers: int = 3
    use_spectral_norm: bool = True
    get_interm_feat: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x) -> List[jax.Array]:
        def inner(y, features, stride_hw):
            if self.use_spectral_norm:
                return SpectralConv3D(features, stride_hw=stride_hw,
                                      dtype=self.dtype)(y)
            return _Conv3D(features, stride_hw=stride_hw, dtype=self.dtype)(y)

        feats = []
        nf = self.ndf
        y = _Conv3D(nf, dtype=self.dtype)(x)
        y = leaky_relu_y(y, 0.2)
        feats.append(y)
        for _ in range(1, self.n_layers):
            nf = min(nf * 2, 512)
            y = inner(y, nf, 2)
            y = leaky_relu_y(y, 0.2)
            feats.append(y)
        nf = min(nf * 2, 512)
        y = inner(y, nf, 1)
        y = leaky_relu_y(y, 0.2)
        feats.append(y)
        y = _Conv3D(1, stride_hw=1, dtype=self.dtype)(y)
        feats.append(y)
        if self.get_interm_feat:
            return feats
        return [feats[-1]]


class MultiscaleTemporalDiscriminator(nn.Module):
    """num_D temporal PatchGANs at spatially downsampled scales (finest
    first, matching MultiscaleDiscriminator's ordering)."""

    ndf: int = 64
    n_layers: int = 3
    num_D: int = 2
    use_spectral_norm: bool = True
    get_interm_feat: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x) -> List[List[jax.Array]]:
        results = []
        current = x
        for i in range(self.num_D):
            d = TemporalDiscriminator(
                ndf=self.ndf,
                n_layers=self.n_layers,
                use_spectral_norm=self.use_spectral_norm,
                get_interm_feat=self.get_interm_feat,
                dtype=self.dtype,
                name=f"tscale{self.num_D - 1 - i}",
            )
            results.append(d(current))
            if i != self.num_D - 1:
                current = avg_pool_spatial_3d(current)
        return results
