"""U-Net generator — classic pix2pix (the BASELINE facades/edges2shoes
configs; the reference's BASELINE.json mislabels its ExpandNetwork a
"U-Net", see SURVEY §0 — this is the real one).

Architecture follows the pix2pix U-Net-256: ``num_downs`` stride-2 encoder
convs (k4) with LeakyReLU(0.2), channel growth ngf→8·ngf (capped), skip
connections at every resolution, decoder mirrors with norm+ReLU, tanh head.
Innermost and outermost levels carry no norm, as in the original.

TPU-first deviations from the torch lineage (semantics, not translation):
- Decoder upsampling is nearest-resize + conv k3 (MXU-friendly, no
  checkerboard) instead of ConvTranspose2d k4 s2 — the same choice the
  reference made for its own decoder (networks.py:408-423).
- Dropout (the pix2pix noise source, 0.5 on the three innermost decoder
  levels) is off by default; when ``use_dropout`` is set the caller passes
  an ``rngs={'dropout': ...}`` to apply().
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.ops.conv import (
    SubpixelDeconv,
    UpsampleConvLayer,
    normal_init,
    save_conv_out,
)
from p2p_tpu.ops.activations import leaky_relu_y, relu_y, tanh_y
from p2p_tpu.ops.norm import make_norm


class UNetGenerator(nn.Module):
    ngf: int = 64
    out_channels: int = 3
    num_downs: int = 8         # 256x256 → 1x1 bottleneck
    norm: str = "batch"
    use_dropout: bool = False
    # "deconv": ConvTranspose k4 s2 (torch pix2pix parameter layout); the
    #   default — fastest measured on v5e despite XLA's reverse-heavy
    #   transposed-conv backward.
    # "subpixel": conv k2s1 + depth-to-space — same operator family
    #   (identical FLOPs/receptive field), clean conv backward, but the
    #   shifted interleave costs an extra memory-bound pass per level.
    # "resize": nearest-resize + conv k3 (no checkerboard risk; 2.25×
    #   decoder FLOPs).
    upsample_mode: str = "deconv"
    # int8 QAT MXU path (ops/int8.py) for the encoder convs (all except
    # the 3-ch stem down0). int8_decoder additionally switches the
    # decoder deconvs (except the image head up0) to the quantized
    # subpixel form — measured a net loss on v5e, kept as an option.
    # Requires upsample_mode == "deconv".
    int8: bool = False
    int8_decoder: bool = False
    int8_delayed: bool = False
    # Extend int8 to the k4-s2 RGB stem (down0). Default off — the
    # measured-rejected verdict: the 3-wide contraction leaves the MXU
    # idle either way (the stem is HBM-bound; see the dated waiver at
    # the down_conv site) — but the knob keeps the form measurable per
    # chip/shape (the facades_int8_full preset does not flip it).
    int8_stem: bool = False
    # Keep the (mathematically dead) conv biases in front of norm layers.
    # A per-channel bias immediately followed by a mean-subtracting norm
    # (BatchNorm OR InstanceNorm) is exactly cancelled in the forward
    # (mean absorbs it), and the norm backward emits zero-channel-mean
    # cotangents so the bias gradient is identically ~0 — yet computing
    # it re-reads the full cotangent (profiled ~3 ms/step of reduce_sum
    # kernels at bs=128/256²). Default: drop those biases (exact same
    # function, same training dynamics — they initialize at 0 and never
    # move). True restores the round-2 checkpoint param layout.
    legacy_layout: bool = False
    # Image head as the subpixel form (plain k2s1 conv + interleave)
    # instead of ConvTranspose. Measured a wash on v5e at 256²/bs=128
    # (1708 vs 1715 img/s; the kn2row inner-conv variant was slower,
    # 1538). Kept as an option for other chips/shapes;
    # tests/test_models.py pins the exact weight mapping.
    thin_head: bool = False
    # with thin_head: Pallas fused kernel for the head's k2 conv
    head_pallas: bool = False
    # k4-s2 RGB stem as strided patches + dense matmul (PatchesConv):
    # the zero-padded 3-ch stem's wgrad collapses XLA to 0.7 TF/s at
    # bs=1 (profiles/prof_r5_facades_bs1.txt); the patch form makes
    # fwd AND dW full-rate dot_generals (dx is dead — input is the
    # image). Param tree identical to nn.Conv (kernel HWIO + bias).
    thin_stem: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        mk = make_norm(self.norm, train=train, dtype=self.dtype)
        # Shapes are static under jit: clamp the depth to the factor-of-2
        # content of H and W so every decoder upsample exactly mirrors its
        # encoder level (96 = 2^5·3 → 5 levels, 3px bottleneck).
        def pow2_levels(n: int) -> int:
            k = 0
            while n % 2 == 0 and n > 1:
                n //= 2
                k += 1
            return k

        num_downs = min(self.num_downs, pow2_levels(x.shape[1]),
                        pow2_levels(x.shape[2]))

        normed = self.norm != "none" and not self.legacy_layout
        if self.head_pallas and (not self.thin_head or self.legacy_layout):
            raise ValueError(
                "head_pallas requires thin_head (the subpixel head form) "
                "and the default (non-legacy) layout")

        def down_conv(y, features, name, int8=False, norm_after=False,
                      stem=False):
            bias = not norm_after
            if stem and self.int8 and self.int8_stem:
                int8 = True
            if int8:
                from p2p_tpu.ops.int8 import QuantConv

                return QuantConv(
                    features, kernel_size=4, strides=2, padding=1,
                    use_bias=bias, dtype=self.dtype,
                    kernel_init=normal_init(), name=name,
                    delayed=self.int8_delayed,
                )(y)
            # stem only: PatchesConv's input cotangent is the slow
            # k²-pad accumulation — dead for the image stem, live (and
            # pathological) anywhere deeper
            if self.thin_stem and stem and y.shape[-1] <= 8:
                from p2p_tpu.ops.conv import PatchesConv

                return PatchesConv(
                    features, kernel_size=4, stride=2, zero_pad=1,
                    use_bias=bias, dtype=self.dtype,
                    kernel_init=normal_init(), name=name,
                )(y)
            # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 measured-rejected: only the 3-ch stem (down0) reaches this line under delayed-int8 (encoder i>0 takes the QuantConv branch above); its k4·3-wide contraction leaves the MXU idle in ANY dtype — the conv is HBM-bound, int8 buys nothing and costs the quantize pass (rounds 2-5 doctrine). ModelConfig.int8_stem keeps the form measurable per chip.
            return save_conv_out(nn.Conv(
                features, kernel_size=(4, 4), strides=(2, 2), padding=1,
                use_bias=bias, dtype=self.dtype, kernel_init=normal_init(),
                name=name,
            )(y))

        # ---- encoder ----------------------------------------------------
        feats = [min(self.ngf * (2 ** i), self.ngf * 8)
                 for i in range(num_downs)]
        skips = []
        y = x
        for i, f in enumerate(feats):
            if i > 0:
                y = leaky_relu_y(y, 0.2)
            y = down_conv(y, f, name=f"down{i}",
                          int8=self.int8 and i > 0,
                          norm_after=normed and 0 < i < num_downs - 1,
                          stem=i == 0)
            # no norm on the outermost and innermost encoder convs
            if 0 < i < num_downs - 1:
                y = mk()(y)
            skips.append(y)

        # ---- decoder ----------------------------------------------------
        for i in reversed(range(num_downs)):
            f = self.out_channels if i == 0 else feats[i - 1]
            y = relu_y(y)
            if self.upsample_mode == "subpixel":
                # bias kept: after the shifted interleave it is a per-
                # PHASE (2×2-periodic) offset, which a norm's global mean
                # only partially absorbs — not dead, unlike plain convs
                y = SubpixelDeconv(
                    f, dtype=self.dtype, name=f"up{i}",
                )(y)
            elif self.upsample_mode == "deconv":
                if self.int8 and self.int8_decoder and i > 0:
                    # conv-k2s1 subpixel form: the ConvTranspose family
                    # member whose int8 lowering wins in all three
                    # contractions (see ops/int8.py). Off by default:
                    # measured on v5e the interleave + large-spatial
                    # wgrad slices cost more than the MXU gain.
                    from p2p_tpu.ops.int8 import QuantSubpixelDeconv

                    # bias kept — per-phase offset, see subpixel note
                    y = QuantSubpixelDeconv(
                        f, dtype=self.dtype, delayed=self.int8_delayed,
                        kernel_init=normal_init(), name=f"up{i}",
                    )(y)
                elif (i == 0 and self.thin_head
                      and not self.legacy_layout and 16 * f <= y.shape[-1]):
                    # image head as the subpixel form (see thin_head doc).
                    # Plain k2s1 conv, NOT the kn2row variant: the dense
                    # 128→4F conv reads x once at full HBM rate and its
                    # backward is a regular conv backward (no deconv
                    # `reverse` kernels); kn2row's z round-trip measured
                    # slower here (1538).
                    y = SubpixelDeconv(
                        f, pallas=self.head_pallas, dtype=self.dtype,
                        kernel_init=normal_init(), name=f"up{i}",
                    )(y)
                else:
                    # bias dropped when a norm follows (i>0): the norm's
                    # mean subtraction cancels it exactly (see legacy_layout)
                    # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 measured-rejected: under delayed-int8 with int8_decoder only the IMAGE head (up0) reaches this line (i>0 takes QuantSubpixelDeconv above); the tanh-facing head is quality-critical AND HBM-bound (3 live output lanes) — it stays bf16 by doctrine, deliberately without a knob (ops/int8.py module docstring).
                    y = save_conv_out(nn.ConvTranspose(
                        f, kernel_size=(4, 4), strides=(2, 2),
                        padding="SAME", use_bias=not (normed and i > 0),
                        dtype=self.dtype,
                        kernel_init=normal_init(), name=f"up{i}",
                    )(y))
            elif self.upsample_mode == "resize":
                y = UpsampleConvLayer(
                    f, kernel_size=3, upsample=2,
                    use_bias=not (normed and i > 0), dtype=self.dtype,
                    name=f"up{i}",
                )(y)
            else:
                raise ValueError(
                    f"unknown upsample_mode {self.upsample_mode!r}; "
                    "expected 'deconv', 'subpixel', or 'resize'"
                )
            if i > 0:
                y = mk()(y)
                # dropout on the three decoder levels after the innermost
                if self.use_dropout and num_downs - 4 <= i < num_downs - 1:
                    y = nn.Dropout(0.5, deterministic=not train)(y)
                y = jnp.concatenate([y, skips[i - 1]], axis=-1)
        return tanh_y(y)
