"""VGG19 feature extractor for the perceptual loss.

Behavior parity with /root/reference/networks.py:32-62: the torchvision
VGG19 ``features`` trunk split at indices 2/7/12/21/30, returning the five
activations after relu1_1, relu2_1, relu3_1, relu4_1, relu5_1. The
reference feeds [-1,1] images with NO ImageNet normalization
(networks.py:26); that choice is preserved at the loss level
(LossConfig.vgg_imagenet_norm).

Weights: this environment has no torchvision / no egress, so pretrained
weights load from an ``.npz`` asset when available (path via
``P2P_TPU_VGG19_NPZ`` or ``p2p_tpu/assets/vgg19.npz``); otherwise the
extractor falls back to a FIXED-SEED random init — still a valid (random
projection) perceptual loss for smoke tests, and flagged via
``vgg19_params_source()`` so quality claims are made only with real weights.
``scripts/convert_vgg19.py`` converts torchvision's state-dict when run in an
environment that has it.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from p2p_tpu.ops.conv import save_conv_out
from p2p_tpu.ops.activations import relu_y

# (name, out_channels); 'M' = maxpool. Standard VGG19 trunk through conv5_1.
_CFG = [
    ("conv1_1", 64), ("conv1_2", 64), ("M", 0),
    ("conv2_1", 128), ("conv2_2", 128), ("M", 0),
    ("conv3_1", 256), ("conv3_2", 256), ("conv3_3", 256), ("conv3_4", 256), ("M", 0),
    ("conv4_1", 512), ("conv4_2", 512), ("conv4_3", 512), ("conv4_4", 512), ("M", 0),
    ("conv5_1", 512),
]
# Taps after these convs' relus == torchvision indices 2/7/12/21/30.
_TAPS = ("conv1_1", "conv2_1", "conv3_1", "conv4_1", "conv5_1")

_IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class VGG19Features(nn.Module):
    """Frozen VGG19 trunk; returns the 5 tap activations (NHWC)."""

    dtype: Optional[jnp.dtype] = None
    imagenet_norm: bool = False

    @nn.compact
    def __call__(self, x) -> List[jax.Array]:
        if self.imagenet_norm:
            # incoming images are [-1,1]; map to [0,1] then standardize
            x = (x + 1.0) * 0.5
            x = (x - _IMAGENET_MEAN) / _IMAGENET_STD
        outs = []
        y = x
        for name, ch in _CFG:
            if name == "M":
                y = nn.max_pool(y, (2, 2), strides=(2, 2))
                continue
            y = save_conv_out(nn.Conv(
                ch, kernel_size=(3, 3), padding=1, dtype=self.dtype, name=name
            )(y))
            y = relu_y(y)
            if name in _TAPS:
                outs.append(y)
        return outs


_DEFAULT_ASSET = os.path.join(os.path.dirname(__file__), "..", "assets", "vgg19.npz")


def vgg19_npz_path() -> Optional[str]:
    p = os.environ.get("P2P_TPU_VGG19_NPZ", _DEFAULT_ASSET)
    return p if os.path.exists(p) else None


def vgg19_params_source() -> str:
    """'pretrained' if an npz asset is present, else 'random'."""
    return "pretrained" if vgg19_npz_path() else "random"


def load_vgg19_params(dtype=jnp.float32, seed: int = 190):
    """Build the frozen VGG19 param tree (pretrained npz or fixed-seed
    random).

    ``seed`` selects the random-feature draw when no pretrained asset
    exists — the multi-seed VFID robustness protocol
    (scripts/eval_fid_parity.py --seeds) scores the same predictions
    under several independent extractors; it is ignored when the npz
    asset is present.
    """
    path = vgg19_npz_path()
    model = VGG19Features()
    if path is None:
        dummy = jnp.zeros((1, 64, 64, 3), dtype)
        return model.init(jax.random.key(seed), dummy)["params"]
    data = np.load(path)
    params = {}
    for name, ch in _CFG:
        if name == "M":
            continue
        kernel = jnp.asarray(data[f"{name}_kernel"], dtype)  # HWIO
        bias = jnp.asarray(data[f"{name}_bias"], dtype)
        assert kernel.shape[-1] == ch, (name, kernel.shape)
        params[name] = {"kernel": kernel, "bias": bias}
    return params
