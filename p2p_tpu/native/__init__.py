"""Native (C++) host-side data-path kernels, bound via ctypes.

Built lazily with g++ on first use and cached next to the source (no
pybind11 in this image — plain C ABI + ctypes, per the environment
constraints). Everything has a pure-Python fallback: ``available()`` tells
you which path you're on, and the public helpers raise nothing at import
time on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fastimage.cpp")
_LIB_PATH = os.path.join(_DIR, "_fastimage.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # compile to a private temp path and rename into place: atomic on
    # POSIX, so concurrent dataloader worker processes never dlopen a
    # half-written .so
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        _SRC, "-o", tmp, "-lz",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.png_decode.restype = ctypes.c_int
        lib.png_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.normalize_f32.restype = None
        lib.normalize_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64
        ]
        lib.quantize_u8.restype = None
        lib.quantize_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def png_decode(data: bytes) -> Optional[np.ndarray]:
    """Decode an 8-bit RGB/RGBA non-interlaced PNG to (H, W, 3) uint8.

    Returns None for unsupported inputs (caller falls back to PIL)."""
    lib = _load()
    if lib is None:
        return None
    w = ctypes.c_int64()
    h = ctypes.c_int64()
    rc = lib.png_decode(data, len(data), None, ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return None
    out = np.empty((h.value, w.value, 3), np.uint8)
    rc = lib.png_decode(
        data, len(data), out.ctypes.data_as(ctypes.c_void_p),
        ctypes.byref(w), ctypes.byref(h),
    )
    if rc != 0:
        return None
    return out


def normalize_f32(img: np.ndarray) -> Optional[np.ndarray]:
    """uint8 HWC → float32 [-1,1] (ToTensor + Normalize(.5) semantics)."""
    lib = _load()
    if lib is None:
        return None
    img = np.ascontiguousarray(img, np.uint8)
    out = np.empty(img.shape, np.float32)
    lib.normalize_f32(
        img.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        img.size,
    )
    return out


def quantize_u8(img: np.ndarray, bits: int = 3) -> Optional[np.ndarray]:
    """Bit-depth quantizer on uint8 (compress_uint8 parity)."""
    lib = _load()
    if lib is None:
        return None
    img = np.ascontiguousarray(img, np.uint8)
    out = np.empty(img.shape, np.uint8)
    lib.quantize_u8(
        img.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        img.size, bits,
    )
    return out


def load_image_fast(
    path: str, expect_hw: Optional[Tuple[int, int]] = None
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Read + decode + normalize a PNG entirely natively.

    ``expect_hw``: bail out after the cheap header probe (no inflate) when
    the stored size differs — the caller's PIL+resize path takes over
    without having paid for a full decode.

    Returns (uint8_hwc, float32_hwc_in_[-1,1]) or None (fallback)."""
    if not path.lower().endswith(".png"):
        return None
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as f:
        data = f.read()
    if expect_hw is not None:
        w = ctypes.c_int64()
        h = ctypes.c_int64()
        rc = lib.png_decode(
            data, len(data), None, ctypes.byref(w), ctypes.byref(h)
        )
        if rc != 0 or (h.value, w.value) != tuple(expect_hw):
            return None
    u8 = png_decode(data)
    if u8 is None:
        return None
    f32 = normalize_f32(u8)
    return u8, f32
