// fastimage — native data-path kernels for the host input pipeline.
//
// The reference's data path is pure-Python PIL (utils.py:9-12,
// dataset.py:26-40); at the north-star throughput (thousands of 256x256
// images/sec/chip) Python decode becomes the bottleneck (SURVEY §7 hard
// part 6). This module implements the hot path in C++:
//
//   - png_decode:      8-bit RGB/RGBA non-interlaced PNG -> RGB bytes
//                      (zlib inflate + per-row defilter; the formats our
//                      own generate_dataset writes)
//   - normalize_f32:   uint8 HWC -> float32 [-1,1] (ToTensor+Normalize(.5))
//   - quantize_u8:     bit-depth quantizer on uint8 (compress() parity)
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// image). Thread-safe; no global state.

#include <cstdint>
#include <cstring>
#include <vector>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- PNG

static uint32_t be32(const uint8_t* p) {
    return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
           (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

static inline int paeth(int a, int b, int c) {
    int p = a + b - c;
    int pa = p > a ? p - a : a - p;
    int pb = p > b ? p - b : b - p;
    int pc = p > c ? p - c : c - p;
    if (pa <= pb && pa <= pc) return a;
    if (pb <= pc) return b;
    return c;
}

// Returns 0 on success. Negative error codes:
//  -1 bad signature  -2 no IHDR  -3 unsupported format  -4 inflate error
//  -5 size mismatch  -6 bad filter
// out must hold h*w*3 bytes; w/h are read from the header into *out_w/h
// after a probe call with out == nullptr.
int png_decode(const uint8_t* data, int64_t size, uint8_t* out,
               int64_t* out_w, int64_t* out_h) {
    static const uint8_t sig[8] = {137, 80, 78, 71, 13, 10, 26, 10};
    if (size < 8 || std::memcmp(data, sig, 8) != 0) return -1;

    int64_t pos = 8;
    int64_t w = 0, h = 0;
    int bit_depth = 0, color_type = 0, interlace = 0;
    std::vector<uint8_t> idat;
    bool saw_ihdr = false;

    while (pos + 8 <= size) {
        uint32_t len = be32(data + pos);
        const uint8_t* type = data + pos + 4;
        const uint8_t* body = data + pos + 8;
        if (pos + 8 + len + 4 > (uint64_t)size) break;
        if (std::memcmp(type, "IHDR", 4) == 0 && len >= 13) {
            w = be32(body);
            h = be32(body + 4);
            bit_depth = body[8];
            color_type = body[9];
            interlace = body[12];
            saw_ihdr = true;
        } else if (std::memcmp(type, "IDAT", 4) == 0) {
            idat.insert(idat.end(), body, body + len);
        } else if (std::memcmp(type, "IEND", 4) == 0) {
            break;
        }
        pos += 8 + len + 4;  // len + type + body + crc
    }
    if (!saw_ihdr) return -2;
    if (bit_depth != 8 || interlace != 0 ||
        (color_type != 2 && color_type != 6))
        return -3;  // only 8-bit RGB/RGBA non-interlaced
    *out_w = w;
    *out_h = h;
    if (out == nullptr) return 0;  // header probe

    const int ch = (color_type == 2) ? 3 : 4;
    const int64_t stride = w * ch;
    std::vector<uint8_t> raw((stride + 1) * h);
    uLongf raw_len = raw.size();
    if (uncompress(raw.data(), &raw_len, idat.data(), idat.size()) != Z_OK)
        return -4;
    if ((int64_t)raw_len != (int64_t)raw.size()) return -5;

    std::vector<uint8_t> prev(stride, 0);
    std::vector<uint8_t> cur(stride);
    for (int64_t y = 0; y < h; ++y) {
        const uint8_t* row = raw.data() + y * (stride + 1);
        const uint8_t filter = row[0];
        const uint8_t* src = row + 1;
        switch (filter) {
            case 0:
                std::memcpy(cur.data(), src, stride);
                break;
            case 1:  // Sub
                for (int64_t i = 0; i < stride; ++i)
                    cur[i] = src[i] + (i >= ch ? cur[i - ch] : 0);
                break;
            case 2:  // Up
                for (int64_t i = 0; i < stride; ++i)
                    cur[i] = src[i] + prev[i];
                break;
            case 3:  // Average
                for (int64_t i = 0; i < stride; ++i) {
                    int a = i >= ch ? cur[i - ch] : 0;
                    cur[i] = src[i] + ((a + prev[i]) >> 1);
                }
                break;
            case 4:  // Paeth
                for (int64_t i = 0; i < stride; ++i) {
                    int a = i >= ch ? cur[i - ch] : 0;
                    int c = i >= ch ? prev[i - ch] : 0;
                    cur[i] = src[i] + paeth(a, prev[i], c);
                }
                break;
            default:
                return -6;
        }
        // emit RGB
        uint8_t* dst = out + y * w * 3;
        if (ch == 3) {
            std::memcpy(dst, cur.data(), stride);
        } else {
            for (int64_t x = 0; x < w; ++x) {
                dst[x * 3 + 0] = cur[x * 4 + 0];
                dst[x * 3 + 1] = cur[x * 4 + 1];
                dst[x * 3 + 2] = cur[x * 4 + 2];
            }
        }
        std::swap(prev, cur);
    }
    return 0;
}

// ------------------------------------------------------------ normalize

// uint8 HWC -> float32 in [-1,1]: x/127.5 - 1  (ToTensor + Normalize(.5))
void normalize_f32(const uint8_t* src, float* dst, int64_t n) {
    // (x - 127.5) * (1/127.5), NOT x*(1/127.5) - 1: the subtraction is
    // exact in f32 (integer ± 127.5 needs 8 significand bits) so the
    // expression has a single rounding step AND no mul+add pattern a
    // compiler could contract into a differently-rounded FMA — the same
    // canonical expression as data/pipeline.load_image and the device-
    // side utils/images.ingest, keeping all three paths bit-identical.
    constexpr float k = 1.0f / 127.5f;
    for (int64_t i = 0; i < n; ++i) dst[i] = (src[i] - 127.5f) * k;
}

// ------------------------------------------------------------- quantize

// bit-depth quantizer on uint8, matching data.generate.compress_uint8:
// q = round(round(clip(x/255)* (2^b-1)) / (2^b-1) * 255)
void quantize_u8(const uint8_t* src, uint8_t* dst, int64_t n, int bits) {
    uint8_t lut[256];
    const float levels = float((1 << bits) - 1);
    for (int v = 0; v < 256; ++v) {
        float x = v / 255.0f;
        float q = (float)(int64_t)(x * levels + 0.5f) / levels;
        lut[v] = (uint8_t)(int64_t)(q * 255.0f + 0.5f);
    }
    for (int64_t i = 0; i < n; ++i) dst[i] = lut[src[i]];
}

}  // extern "C"
