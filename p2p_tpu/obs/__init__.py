"""Unified telemetry subsystem (SURVEY §5.1: the reference had a tqdm bar).

One import surface for everything a production trainer reports through:

- **metrics registry** (:mod:`.registry`): counters / gauges / histograms /
  EWMA rates with tags, pluggable record sinks, cross-host aggregation;
- **sinks** (:mod:`.sinks`): crash-safe JSONL (the ``metrics_<name>.jsonl``
  stream), stdout heartbeat, TensorBoard event files, Prometheus textfile;
- **span tracing** (:mod:`.spans`): host wall-clock spans paired with
  ``jax.profiler.TraceAnnotation``, exported as Perfetto-loadable JSON, plus
  the ``trace()`` XPlane capture;
- **in-jit taps** (:mod:`.taps`): NaN/Inf sentinels and grad-norm scalars
  via ``jax.debug.callback`` — no device fence on the happy path;
- **watchdogs** (:mod:`.watchdogs`): unexpected-recompile detection off the
  ``jax.monitoring`` compile events; per-device HBM sampling;
- **timing** (:mod:`.timing`): the fenced ``StepTimer`` with the chained
  tunnel-safe mode ``bench.py`` uses — one img/sec/chip definition;
- **manifest** (:mod:`.manifest`): the per-run provenance JSON (config hash,
  git SHA, mesh shape, dtype policy).
"""

from p2p_tpu.obs.manifest import build_manifest, config_hash, write_manifest
from p2p_tpu.obs.registry import (
    Counter,
    EWMARate,
    Gauge,
    Histogram,
    MetricsRegistry,
    combine_host_snapshots,
    get_registry,
    set_registry,
)
from p2p_tpu.obs.sinks import (
    JSONLSink,
    MetricsLogger,
    PrometheusTextfileSink,
    Sink,
    StdoutSink,
    TensorBoardSink,
    prometheus_exposition,
)
from p2p_tpu.obs.spans import (
    SpanRecorder,
    annotate,
    get_recorder,
    span,
    timed_annotation,
    trace,
)
from p2p_tpu.obs.taps import (
    add_sentinel_handler,
    grad_norm_taps,
    nan_sentinel,
    remove_sentinel_handler,
)
from p2p_tpu.obs.timing import StepTimer, measure_rtt
from p2p_tpu.obs.watchdogs import (
    MemoryWatchdog,
    RetraceWatchdog,
    budget_drift,
    crosscheck_hbm_budget,
)

__all__ = [
    "Counter",
    "EWMARate",
    "Gauge",
    "Histogram",
    "JSONLSink",
    "MemoryWatchdog",
    "MetricsLogger",
    "MetricsRegistry",
    "PrometheusTextfileSink",
    "RetraceWatchdog",
    "budget_drift",
    "crosscheck_hbm_budget",
    "Sink",
    "SpanRecorder",
    "StdoutSink",
    "StepTimer",
    "TensorBoardSink",
    "add_sentinel_handler",
    "annotate",
    "build_manifest",
    "combine_host_snapshots",
    "config_hash",
    "get_recorder",
    "get_registry",
    "grad_norm_taps",
    "measure_rtt",
    "nan_sentinel",
    "prometheus_exposition",
    "remove_sentinel_handler",
    "set_registry",
    "span",
    "timed_annotation",
    "trace",
    "write_manifest",
]
