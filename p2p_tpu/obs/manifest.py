"""Run manifest — the provenance record written once at startup.

One JSON file per run answering "what exactly produced these numbers":
the full config (plus its hash, so runs are comparable by one string), the
git SHA of the tree, the mesh shape, the dtype policy, and the JAX/device
inventory. Written atomically; multi-host runs write from process 0 only
(callers gate) with per-process info included for debugging.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional

import jax


def _git_sha() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def config_hash(cfg) -> str:
    """Stable short hash of a (nested, frozen) Config dataclass."""
    blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_manifest(cfg, mesh=None,
                   extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    devices = jax.devices()
    man: Dict[str, Any] = {
        "kind": "manifest",
        "name": getattr(cfg, "name", None),
        "config_hash": config_hash(cfg),
        "config": dataclasses.asdict(cfg),
        "git_sha": _git_sha(),
        "argv": list(sys.argv),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "jax_version": jax.__version__,
        "backend": devices[0].platform if devices else None,
        "device_kind": devices[0].device_kind if devices else None,
        "n_devices": len(devices),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "mesh_shape": dict(mesh.shape) if mesh is not None else None,
        # dtype policy: compute dtype of the jitted step + optimizer moments
        "dtype_policy": {
            "compute": ("bfloat16" if cfg.train.mixed_precision
                        else "float32"),
            "params": "float32",
            "adam_moments": cfg.optim.moment_dtype or "float32",
            "input_pipeline": ("uint8" if cfg.data.uint8_pipeline
                               else "float32"),
        },
    }
    if extra:
        man.update(extra)
    return man


def write_manifest(path: str, cfg, mesh=None,
                   extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    man = build_manifest(cfg, mesh=mesh, extra=extra)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1, default=str)
    os.replace(tmp, path)
    return man
