"""Process-wide metrics registry — counters, gauges, histograms, EWMA rates.

The reference's observability was a tqdm bar (SURVEY §5.1); the seed's was a
JSONL logger welded into the train loop. This registry is the one place every
subsystem reports through: metric objects are cheap host-side accumulators
keyed by (name, tags), structured records fan out to pluggable sinks
(:mod:`p2p_tpu.obs.sinks`), and :meth:`MetricsRegistry.aggregate` reduces a
snapshot across JAX processes so multi-host runs report ONE set of numbers.

Nothing here touches devices: in-jit values reach the registry either as
already-fetched host floats (the train loop's ``log`` path) or through the
async ``jax.debug.callback`` taps in :mod:`p2p_tpu.obs.taps`.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

Tags = Tuple[Tuple[str, str], ...]


def _tags_key(tags: Dict[str, Any]) -> Tags:
    return tuple(sorted((k, str(v)) for k, v in tags.items()))


class Counter:
    """Monotonic count (events, images, retraces). Cross-host reduce: sum."""

    kind = "counter"

    def __init__(self, name: str, tags: Tags = ()):
        self.name, self.tags = name, tags
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict[str, float]:
        return {"value": self._value}


class Gauge:
    """Last-written level (lr, HBM bytes, pool fill). Cross-host: mean+max."""

    kind = "gauge"

    def __init__(self, name: str, tags: Tags = ()):
        self.name, self.tags = name, tags
        self._value = float("nan")

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict[str, float]:
        return {"value": self._value}


class Histogram:
    """Streaming distribution over fixed log-spaced buckets.

    Default buckets span 1 µs .. ~1000 s in half-decade steps — sized for
    wall-clock durations, the dominant histogram use. ``observe`` is O(log B);
    count/sum/min/max are exact, quantiles are bucket-resolution estimates.
    Cross-host reduce: bucket-wise sum (count/sum add; min/max min/max).
    """

    kind = "histogram"
    DEFAULT_BOUNDS = tuple(
        10.0 ** (e / 2.0) for e in range(-12, 7)
    )  # 1e-6 .. ~1e3

    def __init__(self, name: str, tags: Tags = (),
                 bounds: Optional[Iterable[float]] = None):
        self.name, self.tags = name, tags
        self.bounds = tuple(bounds) if bounds is not None else self.DEFAULT_BOUNDS
        self.buckets = [0] * (len(self.bounds) + 1)  # last = +inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        import bisect

        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.buckets[i] += 1
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper bound of the bucket
        holding the q-th observation)."""
        if not self.count:
            return float("nan")
        target = q * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def snapshot(self) -> Dict[str, float]:
        return {"count": float(self.count), "sum": self.sum,
                "min": self.min, "max": self.max,
                "p50": self.quantile(0.5), "p99": self.quantile(0.99)}


class EWMARate:
    """Exponentially-weighted event rate (img/sec, records/sec).

    ``mark(n)`` credits n events; the rate is an EWMA of per-interval rates
    with the given half-life in seconds, so a stall decays visibly instead of
    being averaged away by a long warm history. Cross-host reduce: sum (rates
    add across hosts — each host pushes its own shard of the global batch).
    """

    kind = "ewma"

    def __init__(self, name: str, tags: Tags = (), halflife_s: float = 30.0,
                 clock=time.monotonic):
        self.name, self.tags = name, tags
        self.halflife_s = halflife_s
        self._clock = clock
        self._rate = float("nan")
        self._t_last: Optional[float] = None
        self._lock = threading.Lock()

    def mark(self, n: float = 1.0) -> None:
        # locked like Counter.inc/Histogram.observe: the HTTP serving
        # frontend marks admission rates from N handler threads — an
        # unguarded read-modify-write of (_t_last, _rate) would compute
        # instantaneous rates over wrong intervals under exactly the
        # concurrent load the series exists to measure
        now = self._clock()
        with self._lock:
            if self._t_last is None:
                self._t_last = now
                return
            dt = max(now - self._t_last, 1e-9)
            self._t_last = now
            inst = n / dt
            if math.isnan(self._rate):
                self._rate = inst
            else:
                alpha = 1.0 - 0.5 ** (dt / self.halflife_s)
                self._rate += alpha * (inst - self._rate)

    @property
    def rate(self) -> float:
        return self._rate

    def snapshot(self) -> Dict[str, float]:
        return {"rate": self._rate}


# Reduction rule per metric kind for the cross-host combine: each entry maps
# snapshot-field -> reducer over the per-host column.
_REDUCERS = {
    "counter": {"value": sum},
    "gauge": {"value_mean": None, "value_max": None},  # special-cased below
    "ewma": {"rate": sum},
    "histogram": {"count": sum, "sum": sum, "min": min, "max": max},
}


def combine_host_snapshots(rows: List[Dict[str, Dict[str, float]]],
                           kinds: Dict[str, str]) -> Dict[str, Dict[str, float]]:
    """Pure combine of per-host ``snapshot()`` dicts — unit-testable without
    a multi-host runtime. ``rows[i]`` is host i's ``{metric_key: fields}``;
    ``kinds`` maps metric_key -> metric kind. Metrics missing on some host
    (e.g. a sentinel that only fired on one) combine over the hosts that
    have them."""
    out: Dict[str, Dict[str, float]] = {}
    for key, kind in kinds.items():
        cols = [r[key] for r in rows if key in r]
        if not cols:
            continue
        if kind == "gauge":
            vals = [c["value"] for c in cols if not math.isnan(c["value"])]
            out[key] = {
                "value_mean": sum(vals) / len(vals) if vals else float("nan"),
                "value_max": max(vals) if vals else float("nan"),
            }
            continue
        fields = {}
        for f, red in _REDUCERS[kind].items():
            vals = [c[f] for c in cols if f in c]
            if vals:
                fields[f] = red(vals)
        if kind == "histogram" and fields.get("count"):
            fields["mean"] = fields["sum"] / fields["count"]
        out[key] = fields
    return out


class MetricsRegistry:
    """Metric factory + record bus.

    - ``counter/gauge/histogram/ewma(name, **tags)`` get-or-create a metric
      (idempotent per (name, tags) — safe to call in hot loops).
    - ``record(payload, force=)`` stamps and fans a structured record out to
      every attached sink (the JSONL/stdout/TensorBoard/Prometheus writers).
    - ``snapshot()/aggregate()`` expose the metric state for exporters and
      cross-process reduction.
    """

    def __init__(self):
        self._metrics: Dict[Tuple[str, Tags], Any] = {}
        self._sinks: List[Any] = []
        self._lock = threading.Lock()

    # -- metric factories --------------------------------------------------
    def _get(self, cls, name: str, tags: Dict[str, Any], **kw):
        key = (name, _tags_key(tags))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, key[1], **kw)
                self._metrics[key] = m
            return m

    def counter(self, name: str, **tags) -> Counter:
        return self._get(Counter, name, tags)

    def gauge(self, name: str, **tags) -> Gauge:
        return self._get(Gauge, name, tags)

    def histogram(self, name: str, bounds=None, **tags) -> Histogram:
        return self._get(Histogram, name, tags, bounds=bounds)

    def ewma(self, name: str, halflife_s: float = 30.0, **tags) -> EWMARate:
        return self._get(EWMARate, name, tags, halflife_s=halflife_s)

    # -- record bus --------------------------------------------------------
    # The sink list is mutated from setup/teardown code while records fan
    # out from OTHER threads (the in-jit sentinel callbacks, the signal
    # guard's flush helper): mutations hold the registry lock and every
    # fan-out iterates a snapshot, so a sink attached mid-record can never
    # corrupt the iteration (conc-unlocked-shared-mutation).
    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    @property
    def sinks(self):
        with self._lock:
            return tuple(self._sinks)

    def record(self, payload: Dict[str, Any], force: bool = False) -> None:
        """Fan a structured record (a flat dict with a ``kind`` field, e.g.
        the per-step/eval/epoch records of the train loop) out to every sink.
        Device scalars are coerced to host floats here so sinks never hold
        device references alive."""
        rec = {
            k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float))
                else v)
            for k, v in payload.items()
        }
        rec.setdefault("ts", round(time.time(), 3))
        for s in self.sinks:   # snapshot: add/remove race-free
            s.write(rec, force=force)

    def flush(self) -> None:
        for s in self.sinks:
            s.flush()

    def close(self) -> None:
        for s in self.sinks:
            s.close()

    # -- snapshots & cross-host aggregation --------------------------------
    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            items = list(self._metrics.items())
        out = {}
        for (name, tags), m in items:
            key = name + ("{" + ",".join(f"{k}={v}" for k, v in tags) + "}"
                          if tags else "")
            out[key] = m.snapshot()
        return out

    def kinds(self) -> Dict[str, str]:
        with self._lock:
            items = list(self._metrics.items())
        return {
            name + ("{" + ",".join(f"{k}={v}" for k, v in tags) + "}"
                    if tags else ""): m.kind
            for (name, tags), m in items
        }

    def total(self, name: str) -> float:
        """Sum a counter's value across ALL tag variants — e.g.
        ``total("retry_attempts_total")`` over every ``seam=`` tag. Gauges/
        histograms/EWMAs are excluded (summing those is meaningless)."""
        with self._lock:
            items = list(self._metrics.items())
        return sum(m.value for (n, _), m in items
                   if n == name and m.kind == "counter")

    def aggregate(self) -> Dict[str, Dict[str, float]]:
        """Cross-process reduction of the snapshot. On one process this is
        the snapshot itself (combined through the same pure path, so the
        field names match multi-host output). Every process must call this
        together — it enters collectives on >1 process.

        Key sets may DIFFER across processes (a sentinel counter exists
        only where it fired), so snapshots are exchanged as length-padded
        JSON blobs — two fixed-shape allgathers — rather than a dense
        sorted-key array that would misalign or go ragged.
        """
        import jax

        snap = self.snapshot()
        kinds = self.kinds()
        if jax.process_count() == 1:
            return combine_host_snapshots([snap], kinds)
        import json

        import numpy as np
        from jax.experimental import multihost_utils

        blob = json.dumps([snap, kinds]).encode()
        lens = np.asarray(multihost_utils.process_allgather(
            np.array([len(blob)], np.int64))).reshape(-1)
        buf = np.zeros(int(lens.max()), np.uint8)
        buf[: len(blob)] = np.frombuffer(blob, np.uint8)
        rows = np.asarray(multihost_utils.process_allgather(buf))
        rows = rows.reshape(len(lens), -1)
        host_rows, all_kinds = [], {}
        for r, n in zip(rows, lens):
            s, k = json.loads(bytes(r[: int(n)]).decode())
            host_rows.append(s)
            all_kinds.update(k)
        return combine_host_snapshots(host_rows, all_kinds)


_default_registry: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use). Components
    that cannot be handed one explicitly — the in-jit sentinel callbacks,
    the compile watchdog — report here."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


def set_registry(reg: Optional[MetricsRegistry]) -> Optional[MetricsRegistry]:
    """Swap the process default (tests); returns the previous one."""
    global _default_registry
    with _default_lock:
        prev = _default_registry
        _default_registry = reg
        return prev
