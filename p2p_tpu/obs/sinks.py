"""Pluggable record sinks: JSONL, stdout heartbeat, TensorBoard, Prometheus.

Every sink implements ``write(rec, force=False)`` / ``flush()`` / ``close()``
and receives the already-host-coerced record dicts the registry fans out.

Crash-safety contract (tests/test_kill_resume.py): a SIGKILLed run must keep
every record written with ``force=True`` — the JSONL sink flushes those to the
OS immediately, registers an ``atexit`` close for orderly exits, and works as
a context manager for scoped use.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Dict, Optional


class Sink:
    """Interface; also a no-op null sink."""

    def write(self, rec: Dict[str, Any], force: bool = False) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JSONLSink(Sink):
    """Append-only JSON-lines file — the metrics_<name>.jsonl stream.

    ``flush_every`` buffers that many records between flushes; ``force=True``
    records (epoch summaries, eval, sentinel events) always flush so a killed
    run keeps its partial epoch. flush_every=1 (default) preserves the seed
    ``MetricsLogger``'s flush-per-record behavior.
    """

    def __init__(self, path: str, flush_every: int = 1):
        self.path = path
        self.flush_every = max(1, flush_every)
        self._pending = 0
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        atexit.register(self.close)

    def write(self, rec: Dict[str, Any], force: bool = False) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(json.dumps(rec) + "\n")
            self._pending += 1
            if force or self._pending >= self.flush_every:
                self._f.flush()
                self._pending = 0

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._pending = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None


class StdoutSink(Sink):
    """The seed logger's heartbeat rules, verbatim: print on force, on eval
    records, and every ``print_every`` steps."""

    def __init__(self, print_every: int = 50):
        self.print_every = max(1, print_every)

    def write(self, rec: Dict[str, Any], force: bool = False) -> None:
        step = rec.get("step", 0)
        if force or rec.get("kind") == "eval" or step % self.print_every == 0:
            msg = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in rec.items() if k != "ts"
            )
            print(msg, flush=True)


class TensorBoardSink(Sink):
    """Scalar records into TensorBoard event files.

    Uses the pure-python event writer bundled with the ``tensorboard``
    package (no TF dependency). Raises ImportError at construction when the
    package is absent — callers treat the sink as optional.

    Numeric fields of each record become ``<kind>/<field>`` scalars at the
    record's ``step`` (or an internal monotonic index when absent).
    """

    def __init__(self, logdir: str):
        from tensorboard.compat.proto.event_pb2 import Event
        from tensorboard.compat.proto.summary_pb2 import Summary
        from tensorboard.summary.writer.event_file_writer import (
            EventFileWriter,
        )

        os.makedirs(logdir, exist_ok=True)
        self._Event, self._Summary = Event, Summary
        self._writer = EventFileWriter(logdir)
        self._auto_step = 0
        atexit.register(self.close)

    def write(self, rec: Dict[str, Any], force: bool = False) -> None:
        if self._writer is None:
            return
        kind = rec.get("kind", "metric")
        step = rec.get("step")
        if step is None:
            self._auto_step += 1
            step = self._auto_step
        values = [
            self._Summary.Value(tag=f"{kind}/{k}", simple_value=float(v))
            for k, v in rec.items()
            if isinstance(v, (int, float)) and k not in ("step", "ts")
        ]
        if values:
            self._writer.add_event(
                self._Event(step=int(step), wall_time=rec.get("ts"),
                            summary=self._Summary(value=values))
            )

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


def _prom_name(s: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in s)
    return ("p2p_" + out) if not out or out[0].isdigit() else out


def prometheus_exposition(registry) -> str:
    """A registry's metric state in the Prometheus text exposition
    format — the ONE formatter behind both the textfile sink below and
    the HTTP server's live ``GET /metrics`` endpoint (serve/server.py),
    so a series scraped from either surface has identical names/labels.

    Snapshot FIRST: sentinel-callback / compile-listener threads register
    metrics concurrently, so a key can appear in a later ``kinds()`` that
    a snapshot taken first won't have — never the reverse — and unknown
    kinds are skipped rather than KeyError-ing the caller."""
    lines = []
    snap = sorted(registry.snapshot().items())
    kinds = registry.kinds()
    for key, fields in snap:
        if key not in kinds:
            continue
        name, _, tagpart = key.partition("{")
        labels = ""
        if tagpart:
            # registry keys carry tags as k=v,...} — the exposition
            # format requires label VALUES quoted (k="v"), and one
            # malformed line makes the collector drop the whole file
            pairs = []
            for kv in tagpart.rstrip("}").split(","):
                k, _, v = kv.partition("=")
                v = v.replace("\\", r"\\").replace('"', r"\"")
                pairs.append(f'{_prom_name(k)}="{v}"')
            labels = "{" + ",".join(pairs) + "}"
        base = _prom_name(name)
        ptype = {"counter": "counter", "ewma": "gauge",
                 "gauge": "gauge", "histogram": "summary"}[kinds[key]]
        lines.append(f"# TYPE {base} {ptype}")
        for f, v in fields.items():
            suffix = "" if f in ("value", "rate") else "_" + _prom_name(f)
            if v != v:  # NaN gauges poison dashboards; skip them
                continue
            lines.append(f"{base}{suffix}{labels} {v}")
    return "\n".join(lines) + "\n"


class PrometheusTextfileSink(Sink):
    """Textfile-exporter format (node_exporter's ``--collector.textfile``).

    This sink exports the REGISTRY's metric state, not the record stream: on
    every ``export_every``-th record (and on flush/close) it rewrites the
    target file atomically with the current snapshot. Point node_exporter at
    the directory and the trainer's counters/gauges land in Prometheus with
    zero daemon code here.
    """

    def __init__(self, path: str, registry, export_every: int = 50):
        self.path = path
        self.registry = registry
        self.export_every = max(1, export_every)
        self._n = 0
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        atexit.register(self.close)
        self._closed = False

    def write(self, rec: Dict[str, Any], force: bool = False) -> None:
        # counted under the lock (records arrive from any thread; a bare
        # += would lose updates), exported OUTSIDE it — export() takes
        # the same non-reentrant lock for the atomic rename
        with self._lock:
            self._n += 1
            n = self._n
        if force or n % self.export_every == 0:
            self.export()

    def export(self) -> None:
        if self._closed:
            return
        # formatted OUTSIDE the lock (prometheus_exposition snapshots the
        # registry race-free); the lock serializes the tmp-file rename
        # against other threads' force-records.
        text = prometheus_exposition(self.registry)
        with self._lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, self.path)  # atomic: scrapers never see torn files

    def flush(self) -> None:
        self.export()

    def close(self) -> None:
        if not self._closed:
            try:
                self.export()
            finally:
                self._closed = True


class MetricsLogger:
    """The train loop's logging facade — a registry wired with the JSONL +
    stdout sinks, keeping the seed ``MetricsLogger(path, print_every)`` API
    (``.log(record, force)``) that loop.py/video_loop.py and downstream
    tooling grew around. Extra sinks (TensorBoard, Prometheus) attach via
    ``.registry.add_sink``."""

    def __init__(self, path: Optional[str] = None, print_every: int = 50,
                 registry=None):
        from p2p_tpu.obs.registry import MetricsRegistry

        self.registry = registry if registry is not None else MetricsRegistry()
        self.path = path
        self._jsonl: Optional[JSONLSink] = None
        if path:
            self._jsonl = JSONLSink(path)
            self.registry.add_sink(self._jsonl)
        self.registry.add_sink(StdoutSink(print_every))
        # Abnormal-exit flush (docs/RESILIENCE.md): atexit covers orderly
        # interpreter teardown (sys.exit, uncaught exception) for EVERY
        # attached sink — buffered records of the last partial step reach
        # disk. The signal path is covered separately: the train loop's
        # PreemptionGuard runs this same flush inside its SIGTERM/SIGINT
        # handler. (SIGKILL keeps whatever force=True already flushed.)
        self._atexit_flush = self.registry.flush
        atexit.register(self._atexit_flush)

    def log(self, record: Dict[str, Any], force: bool = False) -> None:
        self.registry.record(record, force=force)

    def close(self) -> None:
        # unhook the atexit flush: processes that build many loggers
        # (tests, sweeps) must not pin every registry until exit
        if self._atexit_flush is not None:
            atexit.unregister(self._atexit_flush)
            self._atexit_flush = None
        self.registry.close()
