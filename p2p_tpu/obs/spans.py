"""Span tracing: wall-clock phases paired with device-trace annotations.

A span is a named host-side interval (epoch, eval, dispatch, checkpoint).
Each ``span(...)`` does three things at once:

1. times the block on the host clock and keeps the (name, ts, dur, depth)
   tuple in a :class:`SpanRecorder` ring;
2. enters a ``jax.profiler.TraceAnnotation`` so the same name shows up on
   the device timeline when a ``trace()`` capture is running;
3. optionally emits a ``kind="span"`` record into a registry (→ JSONL).

:meth:`SpanRecorder.export_perfetto` writes the collected spans as a
Chrome-trace JSON that https://ui.perfetto.dev loads directly — the
host-side complement of the XPlane trace ``trace()`` captures.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax

# Bound at import: span timing must not be hijacked when a test (or tool)
# monkeypatches time.perf_counter to drive the TRAIN LOOP's accounting
# clock (tests/test_loop.py's FakeClock patches the module attribute,
# which is global) — spans would otherwise consume fake ticks and skew
# the loop's hand-computed throughput traces.
_perf_counter = time.perf_counter
_wall_clock = time.time


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device+host ``jax.profiler`` trace for the enclosed block
    (XPlane; view in TensorBoard/XProf or convert for Perfetto)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Bare named region on the device trace timeline (no host timing)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed_annotation(name: str, histogram=None):
    """Lightweight hot-path variant of a span: TraceAnnotation + an
    optional histogram observation, but NO entry in a recorder ring —
    for per-dispatch use, where recording every interval would flood the
    exported trace (the trainers sample only each epoch's first dispatches
    into the ring and route the rest here)."""
    t0 = _perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        if histogram is not None:
            histogram.observe(_perf_counter() - t0)


class SpanRecorder:
    """Collects finished spans, bounded; the ring drops OLDEST first, so
    after a long run the exported trace shows the most recent window —
    the part you want when debugging a late-run slowdown."""

    def __init__(self, max_spans: int = 200_000):
        import collections

        self.max_spans = max_spans
        self.spans: Any = collections.deque(maxlen=max_spans)
        self._total = 0
        self._lock = threading.Lock()
        self._tls = threading.local()

    @property
    def dropped(self) -> int:
        return max(0, self._total - len(self.spans))

    def _depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    @contextlib.contextmanager
    def span(self, name: str, registry=None, force: bool = False,
             histogram=None, **attrs):
        """Time the block; pair with a TraceAnnotation; record on exit.

        ``attrs`` (e.g. epoch=3) ride along into the span record and the
        optional registry record; ``histogram`` additionally receives the
        duration."""
        depth = self._depth()
        self._tls.depth = depth + 1
        ts = _wall_clock()
        t0 = _perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield self
        finally:
            dur = _perf_counter() - t0
            self._tls.depth = depth
            rec = {"name": name, "ts": ts, "dur_s": dur, "depth": depth,
                   **attrs}
            with self._lock:
                self.spans.append(rec)  # deque(maxlen): oldest falls out
                self._total += 1
            if histogram is not None:
                histogram.observe(dur)
            if registry is not None:
                registry.record(
                    {"kind": "span", "span": name, "sec": round(dur, 6),
                     **attrs},
                    force=force,
                )

    def export_perfetto(self, path: str) -> str:
        """Write the spans as Chrome-trace JSON (Perfetto-loadable).

        Complete events ("ph": "X") with microsecond wall-clock timestamps;
        nesting falls out of the ts/dur containment, matching the recorded
        depths."""
        pid = os.getpid()
        with self._lock:
            spans = list(self.spans)
            dropped = self.dropped
        events = [
            {
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": "p2p_tpu host spans"},
            }
        ]
        for s in spans:
            events.append({
                "name": s["name"], "ph": "X", "cat": "obs",
                "ts": int(s["ts"] * 1e6), "dur": max(int(s["dur_s"] * 1e6), 1),
                "pid": pid, "tid": 0,
                "args": {k: v for k, v in s.items()
                         if k not in ("name", "ts", "dur_s")},
            })
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            doc["p2p_tpu_dropped_spans"] = dropped
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


_default_recorder: Optional[SpanRecorder] = None
_default_lock = threading.Lock()


def get_recorder() -> SpanRecorder:
    global _default_recorder
    with _default_lock:
        if _default_recorder is None:
            _default_recorder = SpanRecorder()
        return _default_recorder


def span(name: str, recorder: Optional[SpanRecorder] = None, registry=None,
         **attrs):
    """Module-level convenience: span on the process-default recorder."""
    return (recorder or get_recorder()).span(name, registry=registry, **attrs)
