"""In-jit telemetry taps — NaN/Inf sentinels and grad-norm scalars.

The reference's only recurring failure mode is numerical (SURVEY §5.2:
Inf-PSNR clamping, ``isnan`` guards); the seed's answer was a host-side
``check_finite`` that nothing called. These taps put the guard INSIDE the
jitted step without fencing it:

- :func:`nan_sentinel` counts non-finite entries of a pytree in-graph (a
  per-leaf ``isnan``/``isinf`` reduction — tiny for the metrics dict it
  guards) and ships the counts to the host through ``jax.debug.callback``.
  Unordered callbacks don't serialize the program: the device-to-host copy
  rides the async stream, so the happy path gains no fence — only the small
  reduction. Works under ``lax.scan`` (the multi-step path) and donation.
- :func:`grad_norm_taps` adds global-norm scalars for the step's gradient
  trees to the metrics dict (they come home with the metrics fetch the loop
  already pays for).

When a sentinel fires it increments ``nonfinite_events`` on the process
registry and calls every registered handler (the Trainer registers one that
writes a ``kind="sentinel"`` record into the metrics JSONL).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

_handlers: List[Callable[[Dict[str, Any]], None]] = []
_handlers_lock = threading.Lock()


def add_sentinel_handler(fn: Callable[[Dict[str, Any]], None]) -> None:
    with _handlers_lock:
        if fn not in _handlers:
            _handlers.append(fn)


def remove_sentinel_handler(fn) -> None:
    with _handlers_lock:
        if fn in _handlers:
            _handlers.remove(fn)


def _leaf_name(path) -> str:
    return ("/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path) or "leaf")


def _on_counts(counts, *, tag: str, names: tuple) -> None:
    counts = np.asarray(counts)
    if counts.sum() == 0:  # happy path: nothing to report
        return
    from p2p_tpu.obs.registry import get_registry

    bad = {
        names[i]: {"nan": int(counts[i, 0]), "inf": int(counts[i, 1])}
        for i in range(len(names))
        if counts[i].sum()
    }
    event = {"kind": "sentinel", "tag": tag,
             "nan": int(counts[:, 0].sum()), "inf": int(counts[:, 1].sum()),
             "leaves": bad}
    get_registry().counter("nonfinite_events", tag=tag).inc()
    print(f"WARNING: non-finite values in {tag}: {bad}", flush=True)
    with _handlers_lock:
        handlers = list(_handlers)
    for h in handlers:
        try:
            h(event)
        except Exception as e:  # a dead handler must not kill the run
            print(f"WARNING: sentinel handler failed: {e!r}", flush=True)


def nan_sentinel(tree, tag: str = "tree") -> None:
    """Trace-time: attach a non-finite sentinel to a pytree of arrays.

    Call inside a jitted function. Costs one isnan+isinf reduction per
    floating leaf plus an async (L, 2) int32 device→host copy; no fence.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names, rows = [], []
    for path, leaf in flat:
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            continue
        names.append(_leaf_name(path))
        rows.append(jnp.stack([
            jnp.sum(jnp.isnan(leaf), dtype=jnp.int32),
            jnp.sum(jnp.isinf(leaf), dtype=jnp.int32),
        ]))
    if not rows:
        return
    counts = jnp.stack(rows)
    import functools

    jax.debug.callback(
        functools.partial(_on_counts, tag=tag, names=tuple(names)), counts
    )


def grad_norm_taps(metrics: Dict[str, jax.Array],
                   **grad_trees) -> Dict[str, jax.Array]:
    """Add ``grad_norm_<key>`` global-norm scalars to a metrics dict.

    In-graph and fence-free: the norms ride the metrics pytree the host was
    going to fetch anyway. ``grad_norm_taps(metrics, g=grads_g, d=grads_d)``.
    """
    import optax

    for key, tree in grad_trees.items():
        if tree is not None:
            metrics[f"grad_norm_{key}"] = optax.global_norm(tree).astype(
                jnp.float32)
    return metrics
