"""Fenced step timing — the one img/sec/chip definition.

:class:`StepTimer` (moved from ``utils/profiling.py``) measures wall-clock
over FENCED step boundaries two ways:

- ``tick()`` per step with ``block_until_ready`` on the metrics pytree —
  the loop-style API the seed had;
- ``chain()`` around K chained dispatches fenced ONCE by a host fetch at the
  end — the tunneled-TPU-safe methodology ``bench.py`` pioneered
  (``block_until_ready`` does not reliably fence the tunneled 'axon'
  platform, and per-step fetches bill one tunnel round-trip each), with the
  measured RTT of a trivial fetch subtracted.

Both paths feed the same accumulator, so ``images_per_sec`` means the same
thing in BENCH_*.json and in the metrics stream.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


_TRIVIAL = None


def measure_rtt() -> float:
    """Round-trip cost of one trivial jitted-fetch — the per-dispatch tunnel
    tax ``chain()`` subtracts from its fenced interval. The probe program is
    cached process-wide: repeated calls (the serving engine measures per
    run) must not recompile — a fresh lambda per call would both skew the
    first measurement and trip the retrace watchdog."""
    global _TRIVIAL
    import jax.numpy as jnp

    if _TRIVIAL is None:
        _TRIVIAL = jax.jit(lambda v: v + 1)
        float(_TRIVIAL(jnp.ones(())))  # compile outside the measured fetch
    else:
        float(_TRIVIAL(jnp.ones(())))  # warm transfer path
    t0 = time.perf_counter()
    float(_TRIVIAL(jnp.ones(())))
    return time.perf_counter() - t0


class _Chain:
    """Handle yielded by :meth:`StepTimer.chain`; call :meth:`fence` on a
    device value produced by the LAST dispatch to force the whole chained
    sequence before the timer stops."""

    def __init__(self):
        self.fenced = False

    def fence(self, value) -> None:
        import numpy as np

        np.asarray(jax.device_get(value))  # host fetch == reliable fence
        self.fenced = True


class StepTimer:
    """Wall-clock over fenced steps.

    Loop style (per-step fences):

    >>> t = StepTimer(batch_size=64)
    >>> for batch in data:
    ...     state, m = step(state, batch)
    ...     t.tick(m)           # fences on the metrics pytree
    >>> t.images_per_sec

    Chained style (one fence for K steps, tunnel-safe):

    >>> t = StepTimer(batch_size=64)
    >>> with t.chain(steps=K * n_calls, rtt=measure_rtt()) as ch:
    ...     for _ in range(n_calls):
    ...         state, m = step(state, batches)   # each consumes the last
    ...     ch.fence(m["loss_g"][-1])
    """

    def __init__(self, batch_size: int, skip_first: int = 1):
        self.batch_size = batch_size
        self.skip_first = skip_first       # warmup tick intervals to discard
        self.intervals = 0                 # timed step intervals
        self.elapsed = 0.0
        self._seen = 0
        self._t0: Optional[float] = None

    def tick(self, fence_on=None) -> None:
        if fence_on is not None:
            jax.block_until_ready(fence_on)
        now = time.perf_counter()
        if self._t0 is not None:
            self._seen += 1
            if self._seen > self.skip_first:
                self.elapsed += now - self._t0
                self.intervals += 1
        self._t0 = now

    @contextlib.contextmanager
    def chain(self, steps: int, rtt: float = 0.0):
        """Time a block of ``steps`` chained steps, fenced by the caller's
        ``ch.fence(...)`` host fetch (or, failing that, at exit — unfenced
        exits still measure dispatch time, but warn via the missing fence).
        The interval, minus ``rtt``, credits ``steps`` intervals."""
        ch = _Chain()
        t0 = time.perf_counter()
        try:
            yield ch
        finally:
            dt = time.perf_counter() - t0
            if not ch.fenced:
                print("WARNING: StepTimer.chain exited without a fence — "
                      "the measured interval may exclude device time",
                      flush=True)
            self.elapsed += max(dt - rtt, 1e-9)
            self.intervals += steps

    def credit(self, steps: int, seconds: float) -> None:
        """Account an externally-fenced interval (e.g. the serving
        engine's dispatch→drain window, already RTT-corrected) into the
        shared accumulator, so its img/sec is THIS definition too."""
        self.elapsed += max(seconds, 1e-9)
        self.intervals += steps

    @property
    def images_per_sec(self) -> float:
        if self.elapsed <= 0 or self.intervals <= 0:
            return 0.0
        return self.batch_size * self.intervals / self.elapsed
