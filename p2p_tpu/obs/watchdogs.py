"""Runtime watchdogs: unexpected-recompile detection and HBM sampling.

RetraceWatchdog
    A silent recompile mid-training is the classic JAX perf bug: a shape or
    dtype wobble (an odd tail batch reaching the scanned path, a python
    float flipping a weak dtype) recompiles a minute-scale XLA program and
    the step time graph grows a mystery cliff. The watchdog listens to
    ``jax.monitoring``'s backend-compile duration events (process-wide —
    every jit, pjit, and pallas call funnels through them); after ``arm()``
    (call it once warmup compiles are done, e.g. after the first epoch)
    any further compile is counted, logged as a ``kind="retrace"`` record,
    and printed.

MemoryWatchdog
    Samples ``Device.memory_stats()`` per local device into gauges — the
    HBM fill/peak numbers that tell you how close a preset is to the OOM
    cliff. CPU backends report nothing; ``sample()`` returns {} there.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

# Fires once per XLA backend compile (empirically present on the CPU and TPU
# runtimes of the pinned jax; registration is version-guarded regardless).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# Persistent-compilation-cache outcome events (jax/_src/compiler.py): one
# per backend-compile request once a cache dir is set (core/cache.py).
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


class RetraceWatchdog:
    """Count backend compiles; warn on any that happen after ``arm()``.

    Also counts persistent-compilation-cache hits/misses (``cache_hits`` /
    ``cache_misses`` attributes + ``persistent_cache_hits``/``_misses``
    registry counters) when the cache is enabled — a fleet that silently
    stopped hitting its cache is a cold-start regression the metrics
    stream should show."""

    def __init__(self, registry=None, logger=None):
        self.registry = registry
        self.logger = logger            # optional MetricsLogger for records
        self.compiles = 0               # total since construction
        self.unexpected = 0             # compiles seen while armed
        self.cache_hits = 0             # persistent-cache loads (no compile)
        self.cache_misses = 0           # persistent-cache misses (compiled)
        self.armed = False
        self._registered = False
        self._event_registered = False
        try:
            from jax._src import monitoring as _mon

            self._mon = _mon
            _mon.register_event_duration_secs_listener(self._on_event)
            self._registered = True
            try:
                _mon.register_event_listener(self._on_plain_event)
                self._event_registered = True
            except Exception:
                pass
        except Exception:               # jax moved the private API: degrade
            self._mon = None

    # NOTE: listener signature is (event, duration, **kwargs) in the pinned
    # jax; absorb extras so minor-version drift doesn't raise in a callback.
    def _on_event(self, event: str, duration: float, **kw) -> None:
        if event != _COMPILE_EVENT:
            return
        self.compiles += 1
        reg = self.registry
        if reg is not None:
            reg.counter("xla_compiles").inc()
            reg.histogram("xla_compile_secs").observe(duration)
        if self.armed:
            self.unexpected += 1
            if reg is not None:
                reg.counter("unexpected_recompiles").inc()
            rec = {"kind": "retrace", "compile_secs": round(duration, 3),
                   "n_unexpected": self.unexpected}
            if self.logger is not None:
                try:
                    self.logger.log(rec, force=True)
                except Exception:
                    pass
            print(f"WARNING: unexpected XLA recompile "
                  f"#{self.unexpected} ({duration:.2f}s) — check for "
                  "shape/dtype wobble in the input pipeline", flush=True)

    def _on_plain_event(self, event: str, **kw) -> None:
        """Counter-style monitoring events (no duration): the persistent
        compilation cache's hit/miss stream."""
        if event == _CACHE_HIT_EVENT:
            self.cache_hits += 1
            if self.registry is not None:
                self.registry.counter("persistent_cache_hits").inc()
        elif event == _CACHE_MISS_EVENT:
            self.cache_misses += 1
            if self.registry is not None:
                self.registry.counter("persistent_cache_misses").inc()

    def arm(self) -> None:
        """Call once expected warmup compiles are done; later compiles are
        flagged as unexpected."""
        self.armed = True

    def disarm(self) -> None:
        self.armed = False

    def close(self) -> None:
        if self._registered and self._mon is not None:
            try:
                self._mon._unregister_event_duration_listener_by_callback(
                    self._on_event)
            except Exception:
                pass
            self._registered = False
        if self._event_registered and self._mon is not None:
            try:
                self._mon._unregister_event_listener_by_callback(
                    self._on_plain_event)
            except Exception:
                pass
            self._event_registered = False


class MemoryWatchdog:
    """Per-device HBM statistics into gauges + a ``kind="memory"`` record."""

    def __init__(self, registry=None):
        self.registry = registry

    def sample(self, logger=None) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            keep = {
                k: int(v) for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit", "largest_alloc_size")
            }
            if not keep:
                continue
            out[str(d.id)] = keep
            if self.registry is not None:
                for k, v in keep.items():
                    self.registry.gauge(f"hbm_{k}", device=d.id).set(v)
        if out and logger is not None:
            worst = max(out.values(),
                        key=lambda s: s.get("bytes_in_use", 0))
            logger.log({"kind": "memory", "n_devices": len(out), **worst},
                       force=True)
        return out


#: tolerated |live − static| / static before the startup cross-check
#: warns — past this the static memory model (memory_budget.json) has
#: rotted relative to what the runtime actually allocates
HBM_BUDGET_DRIFT = 0.10


def budget_drift(live_bytes: int, static_bytes: int,
                 tolerance: float = HBM_BUDGET_DRIFT):
    """``(drift_fraction, out_of_band)`` for a live-vs-static byte pair —
    the pure comparison behind :func:`crosscheck_hbm_budget`, unit-tested
    without a TPU."""
    if static_bytes <= 0:
        return 0.0, False
    drift = abs(int(live_bytes) - int(static_bytes)) / float(static_bytes)
    return drift, drift > tolerance


def crosscheck_hbm_budget(cfg, mesh, registry=None, logger=None,
                          samples=None, extra_bytes: int = 0):
    """Startup cross-check (ISSUE 15): the live per-host HBM fill
    (``Device.memory_stats``) against the static ``memory_budget.json``
    state law (``analysis/memory_audit.state_budget`` over the SAME rule
    tables the trainer placed the state with). Call right after state
    placement, before the first step compiles — at that point the device
    holds essentially the TrainState, so live-vs-static is a direct test
    of the static model.

    ``extra_bytes`` covers device residents the state law does not model
    (the trainer passes its VGG feature tree — loaded before this check
    runs, so it is part of the honest baseline, not drift).

    Publishes ``hbm_budget_state_bytes`` / ``hbm_budget_live_bytes``
    gauges and a ``kind="hbm_budget"`` record; WARNS (and counts
    ``hbm_budget_drift_total``) past :data:`HBM_BUDGET_DRIFT`. Returns
    the record, or None on backends that report no memory stats (CPU
    CI)."""
    if samples is None:
        samples = MemoryWatchdog(registry).sample()
    if not samples:
        return None          # CPU/test backend: nothing to cross-check
    from p2p_tpu.analysis.memory_audit import state_budget

    sizes = {str(a): int(s) for a, s in dict(mesh.shape).items()} \
        if mesh is not None else {}
    static = state_budget(cfg, sizes, tp_min_ch=cfg.parallel.tp_min_ch,
                          fsdp_params=cfg.parallel.fsdp_params)
    expected = int(static["state_total"]) + int(extra_bytes)
    live = max(int(s.get("bytes_in_use", 0)) for s in samples.values())
    drift, out_of_band = budget_drift(live, expected)
    rec = {"kind": "hbm_budget", "static_state_bytes": expected,
           "extra_bytes": int(extra_bytes),
           "live_bytes_in_use": live, "drift": round(drift, 4),
           "out_of_band": out_of_band, "mesh": sizes}
    if registry is not None:
        registry.gauge("hbm_budget_state_bytes").set(expected)
        registry.gauge("hbm_budget_live_bytes").set(live)
        if out_of_band:
            registry.counter("hbm_budget_drift_total").inc()
    if logger is not None:
        logger.log(rec, force=True)
    if out_of_band:
        print(f"WARNING: live HBM {live / (1 << 20):.1f} MiB vs static "
              f"state budget {expected / (1 << 20):.1f} MiB — "
              f"{drift * 100:.1f}% drift (> {HBM_BUDGET_DRIFT * 100:.0f}%)"
              " — the static memory model (memory_budget.json law) no "
              "longer matches the runtime; re-derive it before trusting "
              "budget rows", flush=True)
    return rec
