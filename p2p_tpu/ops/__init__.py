from p2p_tpu.ops.quantize import quantize, quantize_ste
from p2p_tpu.ops.pixel_shuffle import pixel_shuffle, pixel_unshuffle
from p2p_tpu.ops.conv import ConvLayer, UpsampleConvLayer, reflect_pad_2d
from p2p_tpu.ops.norm import BatchNorm, InstanceNorm, make_norm
from p2p_tpu.ops.spectral_norm import SpectralConv, spectral_normalize
from p2p_tpu.ops.tv import total_variation_loss
from p2p_tpu.ops.sobel import sobel_edges, angular_loss

__all__ = [
    "quantize",
    "quantize_ste",
    "pixel_shuffle",
    "pixel_unshuffle",
    "ConvLayer",
    "UpsampleConvLayer",
    "reflect_pad_2d",
    "BatchNorm",
    "InstanceNorm",
    "make_norm",
    "SpectralConv",
    "spectral_normalize",
    "total_variation_loss",
    "sobel_edges",
    "angular_loss",
]
