"""Activations the reference uses that flax lacks.

``nn.PReLU()`` in torch carries ONE learned scalar (init 0.25) shared over
all channels; the reference's ExpandNetwork even shares a single instance
across every call site (networks.py:452,500-520), so the module here is
instantiated once and reused to keep parameter-count parity.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


class PReLU(nn.Module):
    init: float = 0.25

    @nn.compact
    def __call__(self, x):
        a = self.param("alpha", nn.initializers.constant(self.init), (), jnp.float32)
        return jnp.maximum(x, 0) + a.astype(x.dtype) * jnp.minimum(x, 0)


def leaky_relu(x, slope: float = 0.2):
    return nn.leaky_relu(x, negative_slope=slope)
