"""Activations the reference uses that flax lacks, plus residual-lean
variants.

``nn.PReLU()`` in torch carries ONE learned scalar (init 0.25) shared over
all channels; the reference's ExpandNetwork even shares a single instance
across every call site (networks.py:452,500-520), so the module here is
instantiated once and reused to keep parameter-count parity.

``leaky_relu_y`` / ``relu_y`` / ``tanh_y`` are custom-VJP activations whose
backward is computed FROM THE OUTPUT instead of the input: for
sign-preserving activations ``y>0 ⟺ x>0`` (and ``tanh' = 1-y²``), so the
pre-activation tensor need not be kept as a residual — the output already
lives in HBM as the next conv's saved input. On the 256² pix2pix step the
default (input-saved) rule makes XLA keep BOTH the norm output and the
activation output per block; these variants drop the former and cut
backward residual traffic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class PReLU(nn.Module):
    init: float = 0.25

    @nn.compact
    def __call__(self, x):
        a = self.param("alpha", nn.initializers.constant(self.init), (), jnp.float32)
        return jnp.maximum(x, 0) + a.astype(x.dtype) * jnp.minimum(x, 0)


def leaky_relu(x, slope: float = 0.2):
    return nn.leaky_relu(x, negative_slope=slope)


@jax.custom_vjp
def _leaky_relu_y(x, slope):
    return jnp.where(x >= 0, x, slope * x)


def _leaky_fwd(x, slope):
    y = _leaky_relu_y(x, slope)
    return y, (y, slope)


def _leaky_bwd(res, ct):
    y, slope = res
    return (jnp.where(y >= 0, ct, slope * ct), None)


_leaky_relu_y.defvjp(_leaky_fwd, _leaky_bwd)


def leaky_relu_y(x, slope: float = 0.2):
    """LeakyReLU whose VJP mask comes from the output (slope>0 preserves
    sign, so ``y>=0 ⟺ x>=0``; at exactly 0 both rules agree).

    The output-mask rule requires a sign-preserving slope — for slope<=0
    use :func:`relu_y` / plain ``nn.leaky_relu`` instead.
    """
    if slope <= 0:
        raise ValueError(
            f"leaky_relu_y needs slope > 0 (got {slope}); the output-based "
            "gradient mask is only valid for sign-preserving activations"
        )
    return _leaky_relu_y(x, slope)


@jax.custom_vjp
def relu_y(x):
    """ReLU whose VJP mask comes from the output (grad 0 at x==0,
    matching ``jnp.where(x > 0)`` a.e.)."""
    return jnp.maximum(x, 0)


def _relu_fwd(x):
    y = relu_y(x)
    return y, y


def _relu_bwd(y, ct):
    return (jnp.where(y > 0, ct, jnp.zeros_like(ct)),)


relu_y.defvjp(_relu_fwd, _relu_bwd)


@jax.custom_vjp
def tanh_y(x):
    """tanh whose VJP uses ``1 - y²`` from the output."""
    return jnp.tanh(x)


def _tanh_fwd(x):
    y = tanh_y(x)
    return y, y


def _tanh_bwd(y, ct):
    one = jnp.ones((), y.dtype)
    return (ct * (one - y * y),)


tanh_y.defvjp(_tanh_fwd, _tanh_bwd)
