"""Convolution layers (NHWC, MXU-friendly).

Reference layer library (networks.py:395-423):
- ``ConvLayer``: ReflectionPad2d(k//2) + Conv2d, no norm/activation.
- ``UpsampleConvLayer``: optional nearest Upsample(×s) + ReflectionPad + Conv.

TPU-first notes: NHWC keeps channels on the 128-wide lane dimension; the
reflect pad is a cheap gather XLA fuses into the conv's input; upsampling is
nearest-neighbor (a broadcast-reshape, fusable) rather than transposed conv —
same choice the reference made to avoid checkerboard artifacts.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name


def remat_wrap(block_cls, mode, static_argnums=(2,)):
    """Wrap a flax module class in nn.remat according to ``mode``.

    - falsy: no remat.
    - "conv": remat with policy save_only_these_names('conv_out',
      'norm_stats') — conv outputs stay resident, only the elementwise
      norm-apply/activation chains are recomputed in the backward. Costs
      the conv-output memory but no extra MXU work; the measured sweet
      spot for the 1024×512 presets.
    - True / "full": classic full remat — minimum memory, recomputes the
      block's convs (+~⅓ generator MXU work).
    """
    if not mode:
        return block_cls
    if mode == "conv":
        return nn.remat(
            block_cls, static_argnums=static_argnums,
            policy=jax.checkpoint_policies.save_only_these_names(
                "conv_out", "norm_stats"
            ),
        )
    if mode is True or mode == "full":
        return nn.remat(block_cls, static_argnums=static_argnums)
    raise ValueError(
        f"unknown remat mode {mode!r}; expected False, True/'full', or 'conv'"
    )


def save_conv_out(y: jax.Array) -> jax.Array:
    """Tag a conv output as a named saveable residual (name ``conv_out``).

    Autodiff of a conv→norm→activation stack saves BOTH the conv output and
    the post-norm/activation tensors as residuals — ~2× the activation HBM
    traffic on the backward pass, which profiling shows is the bound on the
    256² pix2pix step. Under ``jax.checkpoint(fn,
    policy=save_only_these_names('conv_out', 'norm_stats'))`` (see
    train/step.py) only these tagged tensors are kept; the elementwise
    norm-apply/LeakyReLU/pad/upsample ops are recomputed in the backward,
    where they fuse into the gradient kernels for free.
    """
    return checkpoint_name(y, "conv_out")


# Spatial gate for the thin-conv dispatches (PatchesConv / ThinHeadConv):
# XLA's thin-channel conv collapse is catastrophic at LARGE spatial extents
# (pix2pixHD 1024×512: 0.5-1 TF/s, +14% step win from the dispatches) but
# at small extents the dispatches' own overheads win instead — measured:
# ExpandNetwork's k9 head at 256²/bs=1 regressed 0.059 → 0.087 s/step
# (the k²-tap tensor + slice-adds), cityscapes 512×256 was a wash. Gate on
# the padded spatial area; 300k ≈ "bigger than 512×512".
_THIN_DISPATCH_MIN_PIXELS = 300_000


def _thin_head_eligible(x, features: int, kernel_size: int,
                        stride: int) -> bool:
    """Shared ConvLayer/UpsampleConvLayer predicate for the ThinHeadConv
    dispatch (x is the PADDED input).

    The tap-channel bound ``F·k² ≤ 8·C_in`` keeps the dispatch inside the
    measured-winning regime (HD k7 64→3: 147 ≤ 512; Expand k9 32→3:
    243 ≤ 256) and excludes shapes like 16→4 at k7/k9 where the kn2row
    tap tensor would carry 12-20× the input's channels at full res —
    far outside anything profiled, risking a memory/perf regression for
    small-ngf configs at big extents."""
    in_c = x.shape[-1]
    return (stride == 1
            and x.shape[1] * x.shape[2] >= _THIN_DISPATCH_MIN_PIXELS
            and (features * 16 <= in_c
                 or (features <= 4 and in_c >= 16))
            and features * kernel_size * kernel_size <= 8 * in_c)


def _thin_stem_eligible(x, features: int, stride: int) -> bool:
    """Shared predicate for the PatchesConv thin-INPUT stem dispatch."""
    return (stride == 1 and x.shape[-1] <= 8 and features >= 16
            and x.shape[1] * x.shape[2] >= _THIN_DISPATCH_MIN_PIXELS)


def reflect_pad_2d(x: jax.Array, pad: int) -> jax.Array:
    """Reflection-pad H and W of an NHWC tensor."""
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect")


def normal_init(stddev: float = 0.02):
    """Reference default weight init: N(0, 0.02) (networks.py:131)."""
    return nn.initializers.normal(stddev=stddev)


class ConvLayer(nn.Module):
    """ReflectionPad(k//2) + conv. Ref: networks.py:395-405.

    ``int8`` routes the conv through the int8 MXU path (ops/int8.py);
    the reflect pad stays outside (the quantized conv pads with zeros
    only), parameter tree unchanged.
    """

    features: int
    kernel_size: int
    stride: int = 1
    use_bias: bool = True
    int8: bool = False
    int8_delayed: bool = False
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        pad = self.kernel_size // 2
        x = reflect_pad_2d(x, pad)
        if self.int8:
            from p2p_tpu.ops.int8 import QuantConv

            return QuantConv(
                self.features, kernel_size=self.kernel_size,
                strides=self.stride, padding=0, use_bias=self.use_bias,
                dtype=self.dtype, kernel_init=self.kernel_init,
                name="Conv_0", delayed=self.int8_delayed,
            )(x)
        if _thin_stem_eligible(x, self.features, self.stride):
            # thin-INPUT stems (RGB → ngf at full res, e.g. the pix2pixHD
            # enhancer's k7 stem): XLA's conv/wgrad collapse to
            # 0.5-0.6 TF/s at these shapes — one materialized patch
            # tensor turns fwd and wgrad into dense matmuls (PatchesConv)
            return PatchesConv(
                self.features, kernel_size=self.kernel_size,
                use_bias=self.use_bias, dtype=self.dtype,
                kernel_init=self.kernel_init, name="Conv_0",
            )(x)
        if _thin_head_eligible(x, self.features, self.kernel_size,
                               self.stride):
            # thin image heads (e.g. the ResNet/Expand generators' k9→3
            # and the pix2pixHD enhancer's k7→3): XLA's conv runs the MXU
            # at ~4.5 TF/s with 3 of 128 output lanes live (profiled
            # 2.3 ms/step fwd on cityscapes 512×256). ThinHeadConv, NOT
            # KN2RowConv: the kn2row forward is right, but its naive
            # autodiff backward is k² sequential pad+adds (profiled
            # 296 ms/step at k7 — the hand-written VJP through patches
            # of dz is the fix). Param tree unchanged (Conv_0).
            return ThinHeadConv(
                self.features, kernel_size=self.kernel_size,
                use_bias=self.use_bias, dtype=self.dtype,
                kernel_init=self.kernel_init, name="Conv_0",
            )(x)
        return save_conv_out(nn.Conv(
            features=self.features,
            kernel_size=(self.kernel_size, self.kernel_size),
            strides=(self.stride, self.stride),
            padding="VALID",
            use_bias=self.use_bias,
            dtype=self.dtype,
            kernel_init=self.kernel_init,
        )(x))


def kn2row_thin_conv(x: jax.Array, w: jax.Array, pad: int) -> jax.Array:
    """Stride-1 conv for THIN outputs (C_out·k² ≪ C_in) as a 1×1 matmul
    plus shifted slice-adds — the kn2row decomposition.

    A k4 conv from 512 → 1 channel (the PatchGAN head) runs the MXU at
    3–6 TF/s: one output lane of 128 is live, and XLA's conv kernels
    re-read the input window-by-window (profiled ~4 ms/step of the
    256²/bs=128 train step). Rewriting it as

        z[p, t·o] = x[p, :] @ w[t, :, o]        (one 1×1 matmul, one
                                                 HBM pass over x)
        y[i, j, o] = Σ_t z_pad[i+dh_t, j+dw_t, t, o]

    moves the only large-tensor traffic into a plain matmul (bandwidth-
    bound at full HBM rate) and does the k² shift-adds on the tiny tap
    tensor z (k²·C_out channels). The backward that jax derives is just
    as lean: dx = dz @ wᵀ (one pass over dx), dw = xᵀ·dz (one re-read of
    x), slice-transposes on z only.

    x: (N,H,W,C) NHWC; w: (kh,kw,C,O) HWIO; zero padding ``pad`` both
    sides, stride 1. Returns (N, H+2·pad−kh+1, W+2·pad−kw+1, O).
    """
    kh, kw, c, o = w.shape
    n, h, wd, _ = x.shape
    ho, wo = h + 2 * pad - kh + 1, wd + 2 * pad - kw + 1
    wt = w.reshape(kh * kw, c, o).transpose(1, 0, 2).reshape(c, kh * kw * o)
    # 4-D contraction over the channel dim (NO flattening reshape: a
    # (-1, C) reshape of e.g. a concat output forces XLA to materialize
    # layout copies of the big input — profiled +6 ms/step)
    # p2p-lint: disable=jaxpr-f32-leak -- deliberate: z is f32 (MXU accumulation matching the XLA conv this replaces); its backward dots contract the f32 cotangent against the bf16 weight/input, which is the accumulation design, not a leak
    z = jax.lax.dot_general(
        x, wt.astype(x.dtype), (((3,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,  # f32 MXU accumulation
    ).reshape(n, h, wd, kh * kw, o)
    z = jnp.pad(z, ((0, 0), (pad, pad), (pad, pad), (0, 0), (0, 0)))
    # f32 accumulation of the k² partial sums: the XLA conv this replaces
    # accumulates all kh·kw·C terms in f32 and rounds once — matching
    # that costs nothing (y is the thin output tensor)
    y = jnp.zeros((n, ho, wo, o), jnp.float32)
    for t in range(kh * kw):
        dh, dw = divmod(t, kw)
        y = y + jax.lax.dynamic_slice(
            z, (0, dh, dw, t, 0), (n, ho, wo, 1, o)
        ).reshape(n, ho, wo, o).astype(jnp.float32)
    return y.astype(x.dtype)


def im2col_patches(x: jax.Array, k: int, stride: int = 1) -> jax.Array:
    """VALID im2col: (N, H, W, C) → (N, (H−k)//s+1, (W−k)//s+1, k²·C),
    feature order (kh, kw, c) — i.e. an HWIO kernel flattens to the
    matching matrix with a plain ``w.reshape(k·k·C, F)``.

    Built from k² static (strided) slices + one channel concat (pure HBM
    movement at full rate) — NOT ``lax.conv_general_dilated_patches``,
    whose lowering is itself a thin-input conv and inherits the 3 TF/s
    pathology this path exists to avoid (measured on the pix2pixHD
    enhancer stem).
    """
    n, h, w, c = x.shape
    ho = (h - k) // stride + 1
    wo = (w - k) // stride + 1
    cols = [
        jax.lax.slice(
            x, (0, kh, kw, 0),
            (n, kh + stride * (ho - 1) + 1, kw + stride * (wo - 1) + 1, c),
            (1, stride, stride, 1))
        for kh in range(k) for kw in range(k)
    ]
    return jnp.concatenate(cols, axis=-1)


class PatchesConv(nn.Module):
    """Conv for THIN-INPUT stems (C_in ≤ 8, e.g. the pix2pixHD enhancer's
    RGB stem at 1024×512; optionally strided/zero-padded for the U-Net's
    k4-s2 stem) as explicit im2col patches + one dense matmul. The
    ConvLayer auto-dispatch (`_thin_stem_eligible`) covers only the
    stride-1 pre-padded form; strided use is opt-in via
    ``ModelConfig.thin_stem``.

    XLA's conv kernels collapse on 3-input-channel convs at big spatial
    extents: the pix2pixHD enhancer stem profiled 0.6 TF/s forward and
    its weight gradient 0.5 TF/s / 4 GB/s (~11 ms/step of a 141 ms step).
    The patch tensor is materialized once (~150 MB bf16 at 1024×512 —
    C_in is tiny, so the k² blow-up is bounded), after which forward AND
    weight-gradient are plain full-rate ``dot_general``s.

    The INPUT cotangent transposes through the slice-concat as a k²-pad
    accumulation — slow at big k, but for the stems this dispatch targets
    it is dead code (the input is the image) and XLA removes it; a
    learned input would be correct but slow (use ThinHeadConv's dz-side
    patches instead if that ever matters).

    Param tree ("kernel" HWIO + "bias") matches ``nn.Conv``; callers name
    it ``Conv_0`` so checkpoints interchange. Input arrives pre-padded
    (VALID), as with the other ConvLayer branches — except when
    ``zero_pad`` is set (the U-Net's zero-padded k4-s2 stem, whose bs=1
    wgrad profiles at 0.7 TF/s / 17 GB/s — utilization-bound, exactly
    this dispatch's target).
    """

    features: int
    kernel_size: int
    stride: int = 1
    zero_pad: int = 0
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        k = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (k, k, cin, self.features), jnp.float32)
        dt = self.dtype or jnp.float32
        if self.zero_pad:
            p = self.zero_pad
            x = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
        patches = im2col_patches(x.astype(dt), k, self.stride)
        wmat = kernel.reshape(k * k * cin, self.features)
        y = jax.lax.dot_general(
            patches, wmat.astype(dt), (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dt)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(y.dtype)
        return save_conv_out(y)


@partial(jax.custom_vjp, nondiff_argnums=())
def thin_head_conv(x: jax.Array, w: jax.Array) -> jax.Array:
    """VALID stride-1 conv for THIN-OUTPUT heads (F ≤ 4 from a wide
    trunk, e.g. ResNet-G's k9→3 and the pix2pixHD enhancer's k7→3 image
    heads), with a hand-written VJP.

    Forward is the kn2row tap decomposition (one full-rate matmul + k²
    shifted slice-adds on the tiny tap tensor). The NAIVE autodiff of
    that forward transposes the slice-adds into k² sequential full-size
    pad+add kernels — profiled 296 ms/step (0 TF/s, 1 GB/s) on the
    pix2pixHD head, 2/3 of the whole step — so the backward here is
    derived by hand THROUGH PATCHES OF dz (which is the thin tensor, so
    its k²·F-channel patch tensor stays small):

      dx = patches(pad(dz, k−1)) @ flip(w)ᵀ          (one matmul)
      dw = xpadᵀ ⋅ patches(pad(dz, k−1))             (one matmul, then
                                                      unflip/reorder)

    using that patches(pad(dz, k−1)) at position q holds
    dz[q − (k−1) + (kh′,kw′)], i.e. every shifted dz view both
    cotangents need. x arrives pre-padded (VALID), matching ConvLayer.
    """
    return kn2row_thin_conv(x, w, 0)


def _thin_head_fwd(x, w):
    return kn2row_thin_conv(x, w, 0), (x, w)


def _thin_head_bwd(res, dz):
    x, w = res
    kh, kw_, cin, f = w.shape
    assert kh == kw_, "square kernels only"
    k = kh
    dzf = dz.astype(x.dtype)
    # patches of the (k−1)-padded dz: position q (over xpad coords) holds
    # dz[q − (k−1) + (kh′, kw′)] at feature (kh′, kw′, f)
    dzp = jnp.pad(dzf, ((0, 0), (k - 1, k - 1), (k - 1, k - 1), (0, 0)))
    pz = im2col_patches(dzp, k)            # (N, Hp, Wp, k²·f)
    # dx[q, c] = Σ_{kh,kw} dz[q − (kh,kw)] · w[kh,kw,c]
    #          = Σ_{kh′=k−1−kh} pz[q, (kh′,kw′,f)] · w[kh,kw,c,f]
    wd = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2).reshape(
        k * k * f, cin)                    # [(kh′,kw′,f), c]
    dx = jax.lax.dot_general(
        pz, wd.astype(pz.dtype), (((3,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    # dw[kh,kw,c,f] = Σ_p xpad[p + (kh,kw), c] · dz[p, f]
    #              = Σ_q xpad[q, c] · pz[q, (k−1−kh, k−1−kw, f)]
    dwm = jax.lax.dot_general(
        x, pz, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                      # (c, k²·f) in (kh′,kw′,f) order
    dw = jnp.flip(
        dwm.reshape(cin, k, k, f), (1, 2)
    ).transpose(1, 2, 0, 3)
    return dx, dw.astype(w.dtype)


thin_head_conv.defvjp(_thin_head_fwd, _thin_head_bwd)


class ThinHeadConv(nn.Module):
    """Stride-1 thin-OUTPUT conv module on the custom-VJP kn2row path
    (see :func:`thin_head_conv`). Param tree matches ``nn.Conv``."""

    features: int
    kernel_size: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        k = self.kernel_size
        kernel = self.param("kernel", self.kernel_init,
                            (k, k, x.shape[-1], self.features), jnp.float32)
        dt = self.dtype or jnp.float32
        y = thin_head_conv(x.astype(dt), kernel.astype(dt))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(y.dtype)
        return save_conv_out(y)


class KN2RowConv(nn.Module):
    """Stride-1 thin-output conv module on the kn2row path.

    Param tree ("kernel" HWIO + optional "bias") matches ``nn.Conv`` so
    checkpoints interchange with the plain path; callers name it
    ``Conv_0`` to mirror an anonymous inner ``nn.Conv``.

    ``int8`` routes the tap decomposition through the s8×s8→s32 form
    (ops/int8.py ``int8_kn2row_conv``: fwd + wgrad on the int8 MXU, the
    tiny-contraction dgrad bf16 per the per-form dispatch table);
    ``int8_delayed`` switches to the stored-scale variant (the caller
    threads the 'quant' collection). Param tree unchanged either way.
    """

    features: int
    kernel_size: int
    padding: int
    use_bias: bool = True
    int8: bool = False
    int8_delayed: bool = False
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        k = self.kernel_size
        kernel = self.param("kernel", self.kernel_init,
                            (k, k, x.shape[-1], self.features), jnp.float32)
        dt = self.dtype or jnp.float32
        if self.int8 and self.int8_delayed:
            from p2p_tpu.ops.int8 import _delayed_scale, int8_kn2row_conv_ds

            sx, update = _delayed_scale(self, x)
            # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch: the kn2row backward's dgrad contracts over k²·O (16 lanes for the k4→1 head) — below one MXU tile, the int8 rate is unrealizable there; it stays bf16 on the dequantized surrogate while fwd+wgrad run s8×s8→s32 (ops/int8.py kn2row dispatch table; backward eqns attribute to this call site)
            y, amax = int8_kn2row_conv_ds(
                x.astype(dt), kernel.astype(dt), sx, self.padding)
            update(amax)
        elif self.int8:
            from p2p_tpu.ops.int8 import int8_kn2row_conv

            # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch: see the delayed branch above — the kn2row dgrad stays bf16 by design
            y = int8_kn2row_conv(x.astype(dt), kernel.astype(dt),
                                 self.padding)
        else:
            y = kn2row_thin_conv(x.astype(dt), kernel.astype(dt),
                                 self.padding)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(y.dtype)
        return save_conv_out(y)


def upsample_nearest(x: jax.Array, factor: int) -> jax.Array:
    """Nearest-neighbor ×factor upsample in NHWC via broadcast-reshape."""
    if factor == 1:
        return x
    n, h, w, c = x.shape
    x = x[:, :, None, :, None, :]
    x = jnp.broadcast_to(x, (n, h, factor, w, factor, c))
    return x.reshape(n, h * factor, w * factor, c)


def subpixel_interleave(out: jax.Array, features: int) -> jax.Array:
    """The shifted depth-to-space of SubpixelDeconv: maps the k2-s1 conv
    output (N, H+1, W+1, 4F) to (N, 2H, 2W, F) via
    ``y[2i+u, 2j+v] = out[i+u, j+v, (u,v)]``. Shared by the bf16 and
    int8 (ops/int8.py QuantSubpixelDeconv) variants."""
    n, h1, w1, c4 = out.shape
    h, w, f = h1 - 1, w1 - 1, features
    out = out.reshape(n, h1, w1, 2, 2, f)
    rows = []
    for u in range(2):
        cols = [out[:, u:u + h, v:v + w, u, v] for v in range(2)]
        rows.append(jnp.stack(cols, axis=3))          # (N,H,W,2,F)
    y = jnp.stack(rows, axis=2)                       # (N,H,2,W,2,F)
    return y.reshape(n, 2 * h, 2 * w, f)


class _PallasHeadConv(nn.Module):
    """k2-s1 pad-1 conv via the Pallas subpixel-head kernel; param tree
    ("kernel" HWIO (2,2,C,F) + optional "bias") matches ``nn.Conv``."""

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        from p2p_tpu.ops.pallas.subpixel_head import subpixel_head_conv

        kernel = self.param("kernel", self.kernel_init,
                            (2, 2, x.shape[-1], self.features), jnp.float32)
        dt = self.dtype or jnp.float32
        import os

        interpret = jax.devices()[0].platform != "tpu"
        if not interpret and os.environ.get("P2P_HPAL_FORCE", "") != "1":
            # The v3 kernel COMPILES and RUNS on this runtime but measures
            # 1130 img/s vs 1708 for the XLA deconv head at 256²/bs=128
            # (sublane-shift chains per band + lost fusions around the
            # custom call — ops/pallas/subpixel_head.py STATUS). Gated
            # until a future Mosaic makes it competitive; P2P_HPAL_FORCE=1
            # (the bench's BENCH_HPAL path) re-measures.
            raise NotImplementedError(
                "SubpixelDeconv(pallas=True) measures SLOWER than the XLA "
                "deconv head on this TPU runtime (1130 vs 1708 img/s); "
                "use the default head, or set P2P_HPAL_FORCE=1 to force")
        y = subpixel_head_conv(x.astype(dt), kernel.astype(dt), interpret)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias
        return save_conv_out(y.astype(dt))


class SubpixelDeconv(nn.Module):
    """ConvTranspose(k4, s2, 'SAME') re-expressed as conv(k2, s1) + shifted
    depth-to-space — the TPU-friendly learned 2× upsample.

    Mathematically the SAME operator family: with k=4, s=2 every output
    pixel receives contributions from exactly a 2×2 input window, so
    ``y[2i+u, 2j+v] = Σ_{dh,dw∈{0,1}} W'[dh,dw,(u,v)] · x[i+u-1+dh, j+v-1+dw]``
    — one dense stride-1 k2 conv producing 4·F channels on the 1-padded
    input, then a (u,v)-shifted interleave. (Exact weight mapping from a
    flax ConvTranspose kernel: ``W'[dh, dw, (u,v)·F] = W[2·dh+u, 2·dw+v]``;
    tested against flax ConvTranspose in tests/test_ops.py.)

    Why: XLA TPU's backward for transposed convs materializes full spatial
    ``reverse`` of activations in the weight-gradient path (~2.4 ms/step on
    the 256² pix2pix profile) and its strided-deconv kernels run well below
    conv peak; the k2s1 formulation has byte-identical FLOPs and a clean
    conv backward.
    """

    features: int
    use_bias: bool = True
    # kn2row for the inner k2 conv (see kn2row_thin_conv). Measured
    # SLOWER than the plain conv on v5e as the U-Net image head (1538
    # vs 1708 img/s at 256²/bs=128 — the z-tensor round-trip loses);
    # kept as an op-level variant for thin-output experiments, pinned
    # equivalent to the plain path in tests/test_ops.py.
    thin: bool = False
    # Pallas fused path for the inner k2 conv: the 4 tap matmuls
    # accumulate in VMEM, x is read once per sample block
    # (ops/pallas/subpixel_head.py). Param tree unchanged (Conv_0).
    pallas: bool = False
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        n, h, w, c = x.shape
        f = self.features
        if self.pallas:
            out = _PallasHeadConv(
                4 * f, use_bias=self.use_bias, dtype=self.dtype,
                kernel_init=self.kernel_init, name="Conv_0",
            )(x)                                # (N, H+1, W+1, 4F)
        elif self.thin and 16 * f <= c:
            out = KN2RowConv(
                4 * f, kernel_size=2, padding=1, use_bias=self.use_bias,
                dtype=self.dtype, kernel_init=self.kernel_init,
                name="Conv_0",
            )(x)                                # (N, H+1, W+1, 4F)
        else:
            out = save_conv_out(nn.Conv(
                4 * f, kernel_size=(2, 2), strides=(1, 1),
                padding=((1, 1), (1, 1)), use_bias=self.use_bias,
                dtype=self.dtype, kernel_init=self.kernel_init,
            )(x))                               # (N, H+1, W+1, 4F)
        return subpixel_interleave(out, self.features)


def depth_to_space_2x(out: jax.Array, features: int) -> jax.Array:
    """Plain ×2 depth-to-space: (N,H,W,4F) → (N,2H,2W,F) with phase (u,v)
    at channel block u·2+v — ``y[2i+u, 2j+v] = out[i, j, (u·2+v)·F:]``."""
    n, h, w, _ = out.shape
    out = out.reshape(n, h, w, 2, 2, features)
    out = out.transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(n, 2 * h, 2 * w, features)


class _NearestUp2Conv(nn.Module):
    """EXACT subpixel decomposition of UpsampleConvLayer's
    (nearest ×2 upsample → ReflectionPad(1) → 3×3 conv) chain.

    With ``up(x)[p,q] = x[p//2, q//2]``, each output phase (u,v)∈{0,1}²
    reads low-res offsets ``o = floor((u+a)/2)`` per tap a∈{-1,0,1}, so

        out[2i+u, 2j+v] = Σ_{o_r,o_c} Wp[u,v][o_r,o_c] · x[i+o_r, j+o_c]

    where the phase kernels Wp are pairwise sums of the original taps
    (e.g. u=0 rows: [W₋₁, W₀+W₁]). All four phases fit a 3×3 support on
    the LOW-RES grid, so the whole layer is ONE 3×3 conv ci→4·co at half
    resolution + :func:`depth_to_space_2x`: the same FLOPs land on full
    128-lane MXU tiles (vs a 32-lane-wide conv over the 4×-materialized
    upsampled tensor) and the activation traffic drops ~4× — the
    round-4 profile has this layer at 4.2 TF/s / ~4.7 ms of the
    pix2pixHD step (BASELINE.md). Boundary: reflect-padding the UPSAMPLED
    image equals EDGE-padding the low-res input for the single ring a 3×3
    needs (up[-1]=up[0]=x[0], up[2H]=up[2H-2]=x[H-1]); k≥5 needs a second
    ring where that identity breaks — hence the k==3 gate in the
    dispatcher. Param tree identical to ``nn.Conv`` ("kernel" (3,3,ci,co)
    [+ "bias"]), so checkpoints and the TP sharding rules are unchanged.
    """

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        ci, co = x.shape[-1], self.features
        kernel = self.param("kernel", self.kernel_init, (3, 3, ci, co),
                            jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros, (co,), jnp.float32)
                if self.use_bias else None)
        # M[u, o+1, a+1] = 1 where floor((u+a)/2) == o — the tap→offset
        # folding matrix (constant, folded into the weights at trace time)
        m = np.zeros((2, 3, 3), np.float32)
        for u in (0, 1):
            for ia, a in enumerate((-1, 0, 1)):
                m[u, (u + a) // 2 + 1, ia] = 1.0
        m = jnp.asarray(m)
        # Wc[r,c,i,(u,v,o)] = Σ_{a,b} M[u,r,a]·M[v,c,b]·W[a,b,i,o]
        wc = jnp.einsum("ura,vcb,abio->rciuvo", m, m, kernel)
        wc = wc.reshape(3, 3, ci, 4 * co)
        # house convention for dispatch targets (cf. _SplitStemConv):
        # dtype=None computes in f32, keeping the P2P_UP2SP A/B
        # numerically comparable with the plain nn.Conv path
        dt = self.dtype or jnp.float32
        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="edge")
        y = jax.lax.conv_general_dilated(
            xp.astype(dt), wc.astype(dt), window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = save_conv_out(y)
        y = depth_to_space_2x(y, co)
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y


class UpsampleConvLayer(nn.Module):
    """Optional nearest ×upsample → ReflectionPad → conv.
    Ref: networks.py:408-423."""

    features: int
    kernel_size: int
    stride: int = 1
    upsample: int = 0
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()

    @nn.compact
    def __call__(self, x):
        if (self.upsample == 2 and self.kernel_size == 3 and self.stride == 1
                and 4 * x.shape[1] * x.shape[2] >= _THIN_DISPATCH_MIN_PIXELS
                and os.environ.get("P2P_UP2SP", "1") == "1"):
            # subpixel decomposition of upsample→conv at big extents (the
            # pix2pixHD enhancer's 64→32 at 1024×512 — see _NearestUp2Conv;
            # gated on the POST-upsample extent with the same constant as
            # the thin dispatches; P2P_UP2SP=0 opts out for A/B measurement)
            return _NearestUp2Conv(
                self.features, use_bias=self.use_bias, dtype=self.dtype,
                kernel_init=self.kernel_init, name="Conv_0",
            )(x)
        if self.upsample:
            x = upsample_nearest(x, self.upsample)
        pad = self.kernel_size // 2
        x = reflect_pad_2d(x, pad)
        if _thin_head_eligible(x, self.features, self.kernel_size,
                               self.stride):
            # thin image heads (ExpandNetwork's k9→3 lives HERE, not in
            # ConvLayer — networks.py:518-520): same ThinHeadConv
            # dispatch as ConvLayer, same param tree (Conv_0)
            return ThinHeadConv(
                self.features, kernel_size=self.kernel_size,
                use_bias=self.use_bias, dtype=self.dtype,
                kernel_init=self.kernel_init, name="Conv_0",
            )(x)
        return save_conv_out(nn.Conv(
            features=self.features,
            kernel_size=(self.kernel_size, self.kernel_size),
            strides=(self.stride, self.stride),
            padding="VALID",
            use_bias=self.use_bias,
            dtype=self.dtype,
            kernel_init=self.kernel_init,
        )(x))
