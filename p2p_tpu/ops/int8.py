"""int8 quantization-aware convolutions on the TPU MXU.

The v5e MXU executes s8×s8→s32 at 2× its bf16 rate (394 vs 197 peak
TOP/s; measured 229 TOP/s vs 139 TF/s on this repo's dominant
discriminator conv shape — 1.65× in practice). The reference trains
fp32 cuDNN convolutions (/root/reference/train.py:164
``cudnn.benchmark``); this module is the TPU-native opt-in
acceleration the hardware invites: symmetric dynamic quantization with
**int8 convs in the forward AND both backward contractions** (dgrad +
wgrad), so the MXU-bound ~80% of the step runs at the doubled rate.

Scheme (per conv, no state to thread):
- activations: per-tensor scale ``s_x = max|x| / 127``;
- weights: per-output-channel scale ``s_w[o] = max|w[..,o]| / 127``;
- forward: ``y = (Q(x) ⊛ Q(w))_int32 · s_x · s_w``;
- backward is the exact gradient of the dequantized surrogate
  (straight-through through both quantizers):
  - dgrad: the per-channel ``s_w`` is *folded into the cotangent*
    before its own quantization (``g̃ = g · s_w``), which turns the
    per-channel factor inside the contraction into a per-tensor one:
    ``dx = s_g̃ · (Q(g̃) ⊛ᵀ Q(w))``; the ``s_x`` factors cancel.
  - wgrad: ``dw = s_x · s_g · (Q(x) ⊛ Q(g))`` — per-tensor scales
    only; the ``s_w`` factors cancel.
- the int8 transpose convolutions replicate XLA's own conv-VJP
  padding/dilation algebra (jax._src.lax.convolution
  ``_conv_general_dilated_transpose_{lhs,rhs}``), with the dimension
  permutations done as explicit array transposes; exactness is pinned
  by tests that compare against ``jax.vjp`` of the float conv on
  integer-valued tensors (where quantization is lossless).

What stays bf16: quality- and bandwidth-critical layers — the 3/6-ch
stem convs and the image-producing head (they are HBM-bound, the MXU
gains nothing) — plus biases, norms, losses, and the optimizer. The
models opt in per-layer via ``QuantConv`` / ``QuantConvTranspose``,
which are parameter-compatible with ``nn.Conv`` / ``nn.ConvTranspose``
(same param names/shapes → checkpoints interchange with the bf16
path).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from p2p_tpu.ops.conv import normal_init, save_conv_out, subpixel_interleave

Pads = Tuple[Tuple[int, int], Tuple[int, int]]

_DN = ("NHWC", "HWIO", "NHWC")

# Dispatch bounds for the unrolled int8 wgrad (see _int8_bwd_core):
# output spatial sizes in [MIN, MAX] use the k²-unrolled int8
# dot_general form; the rest fall back to the bf16 CHWN conv.
# - MIN = 0 (round 4): the round-2/3 runtime kernel-faulted the int8
#   strided slices below ~16² output positions (MIN was 256 then); the
#   round-4 runtime upgrade FIXED it — verified by the on-TPU repro
#   (tests/test_int8.py::test_tiny_spatial_wgrad_guard_on_tpu, which ran
#   the unguarded 2×2-output wgrad successfully). The env knob stays for
#   older runtimes: set P2P_INT8_WGRAD_SLICE_MIN=256 to restore the
#   guard if the fault reappears.
# - MAX = 4096 (64²): above it the k² slices of the padded input
#   materialize more HBM traffic than the int8 MXU rate buys back (the
#   round-2 "decoder int8 loses" finding).
_INT8_WGRAD_SLICE_MIN = int(
    os.environ.get("P2P_INT8_WGRAD_SLICE_MIN", "0"))
_INT8_WGRAD_SLICE_MAX = int(
    os.environ.get("P2P_INT8_WGRAD_SLICE_MAX", "4096"))


def absmax_scale(x: jax.Array, axis=None) -> jax.Array:
    """Symmetric scale max|x|/127 in f32; keepdims when axis given."""
    m = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                keepdims=axis is not None)
    return jnp.maximum(m, 1e-12) / 127.0


def quantize_int8(x: jax.Array, scale: jax.Array) -> jax.Array:
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)


def _conv_i32(lhs8, rhs8, strides, padding, lhs_dil=(1, 1), rhs_dil=(1, 1)):
    dn = jax.lax.conv_dimension_numbers(lhs8.shape, rhs8.shape, _DN)
    return jax.lax.conv_general_dilated(
        lhs8, rhs8, window_strides=strides, padding=padding,
        lhs_dilation=lhs_dil, rhs_dilation=rhs_dil, dimension_numbers=dn,
        preferred_element_type=jnp.int32,
    )


def _dilate(shape, dil):
    return tuple(0 if d == 0 else (d - 1) * r + 1 for d, r in zip(shape, dil))


def _vjp_lhs_padding(in_hw, k_hw, strides, out_hw, padding, lhs_dil, rhs_dil):
    """XLA's dgrad padding (jax._src.lax.convolution
    _conv_general_vjp_lhs_padding), inlined for the 2-spatial-dim case."""
    lhs_d = _dilate(in_hw, lhs_dil)
    rhs_d = _dilate(k_hw, rhs_dil)
    out_d = _dilate(out_hw, strides)
    lo = tuple(r - p[0] - 1 for r, p in zip(rhs_d, padding))
    hi = tuple(l + r - 1 - o - b
               for l, r, o, b in zip(lhs_d, rhs_d, out_d, lo))
    return tuple(zip(lo, hi))


def _vjp_rhs_padding(in_hw, k_hw, strides, out_hw, padding, lhs_dil, rhs_dil):
    """XLA's wgrad padding (_conv_general_vjp_rhs_padding), inlined."""
    lhs_d = _dilate(in_hw, lhs_dil)
    rhs_d = _dilate(k_hw, rhs_dil)
    out_d = _dilate(out_hw, strides)
    lo = tuple(p[0] for p in padding)
    hi = tuple((o - l) + (r - p - 1)
               for o, l, r, p in zip(out_d, lhs_d, rhs_d, lo))
    return tuple(zip(lo, hi))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def int8_conv(x: jax.Array, w: jax.Array, strides: Tuple[int, int],
              padding: Pads, lhs_dilation: Tuple[int, int] = (1, 1)):
    """NHWC ⊛ HWIO conv computed on the int8 MXU path.

    ``lhs_dilation`` ≠ 1 expresses transposed convolution (the flax
    ``ConvTranspose`` lowering: strides=(1,1), lhs_dilation=s).
    """
    y, _ = _int8_conv_fwd(x, w, strides, padding, lhs_dilation)
    return y


def _int8_conv_fwd(x, w, strides, padding, lhs_dilation):
    sx = absmax_scale(x)                          # scalar
    sw = absmax_scale(w, axis=(0, 1, 2))          # (1,1,1,O)
    xq = quantize_int8(x, sx)
    wq = quantize_int8(w, sw)
    y32 = _conv_i32(xq, wq, strides, padding, lhs_dil=lhs_dilation)
    y = (y32.astype(jnp.float32) * (sx * sw.reshape(1, 1, 1, -1)))
    # zero-sized dtype carriers: residuals must be JAX types
    x_tok = jnp.zeros((0,), x.dtype)
    w_tok = jnp.zeros((0,), w.dtype)
    return y.astype(x.dtype), (xq, sx, wq, sw, x_tok, w_tok)


def _int8_bwd_core(strides, padding, lhs_dilation, res, g):
    """Mixed-form backward. Each contraction runs in whichever of int8 /
    bf16 measured faster on v5e for its structural form (chained
    microbenchmarks, see module docstring table):

    - dgrad is ``conv(g, rev(w)ᵀ, window_strides=lhs_dil, lhs_dil=strides)``
      — a *plain* conv when the forward had ``strides == 1`` (s1 conv) or
      when the forward was a transposed conv (then window_strides=2):
      int8 wins (2×/1.5×). When the forward had stride 2 the dgrad is
      lhs-dilated, where int8 measured SLOWER than bf16 → bf16 on the
      dequantized surrogate ŵ (keeps the exact-surrogate-VJP semantics).
    - wgrad as a conv puts the batch dim on channels (CHWN/IHWO), a
      layout whose int8 lowering is catastrophic (~5 T/s) and whose bf16
      lowering reaches only ~103 TF/s; an unrolled k² sum of strided-
      slice ``dot_general``s in int8 reaches ~157 TF/s → int8 dot_general
      for plain convs, bf16 conv for transposed (dilated-x) ones.
    """
    xq, sx, wq, sw, x_tok, w_tok = res
    x_dt, w_dt = x_tok.dtype, w_tok.dtype
    k_hw = wq.shape[:2]
    in_hw = xq.shape[1:3]
    out_hw = g.shape[1:3]
    gf = g.astype(jnp.float32)
    plain = lhs_dilation == (1, 1)

    # ---- dgrad --------------------------------------------------------
    pad_lhs = _vjp_lhs_padding(in_hw, k_hw, strides, out_hw, padding,
                               lhs_dilation, (1, 1))
    if strides == (1, 1):
        # plain (or transposed-fwd) dgrad → int8. Per-channel s_w folds
        # into the cotangent before quantization (module docstring).
        gt = gf * sw.reshape(1, 1, 1, -1)
        sgt = absmax_scale(gt)
        gtq = quantize_int8(gt, sgt)
        wq_r = wq[::-1, ::-1]
        dn = jax.lax.conv_dimension_numbers(
            gtq.shape, wq_r.shape, ("NHWC", "HWOI", "NHWC"))
        dx32 = jax.lax.conv_general_dilated(
            gtq, wq_r, window_strides=lhs_dilation, padding=pad_lhs,
            lhs_dilation=strides, dimension_numbers=dn,
            preferred_element_type=jnp.int32,
        )
        dx = (dx32.astype(jnp.float32) * sgt).astype(x_dt)
    else:
        # stride-2 dgrad is lhs-dilated → bf16 on the dequantized ŵ
        w_hat = (wq.astype(jnp.float32) * sw).astype(jnp.bfloat16)
        w_r = w_hat[::-1, ::-1]
        dn = jax.lax.conv_dimension_numbers(
            g.shape, w_r.shape, ("NHWC", "HWOI", "NHWC"))
        dx = jax.lax.conv_general_dilated(
            g.astype(jnp.bfloat16), w_r, window_strides=lhs_dilation,
            padding=pad_lhs, lhs_dilation=strides, dimension_numbers=dn,
            preferred_element_type=jnp.float32,
        ).astype(x_dt)

    # ---- wgrad --------------------------------------------------------
    ho, wo = out_hw
    # Static spatial dispatch window. The round-2/3 runtime kernel-faulted
    # the int8 strided slices below ~16² output positions (MIN was 256);
    # the round-4 runtime fixed it and the default window now starts at 0
    # (see _INT8_WGRAD_SLICE_MIN above). The UPPER bound stands: above
    # ~64² output positions the k² strided slices of the (already large)
    # padded input materialize more HBM traffic than the int8 MXU rate
    # buys back (the round-2 "decoder int8 loses" finding) — those
    # big-spatial wgrads take the bf16 CHWN conv below.
    if plain and _INT8_WGRAD_SLICE_MIN <= ho * wo <= _INT8_WGRAD_SLICE_MAX:
        sg = absmax_scale(gf)
        gq = quantize_int8(gf, sg)
        (plo_h, phi_h), (plo_w, phi_w) = padding
        sh, sw_ = strides
        kh_n, kw_n = k_hw
        n, _, _, cin = xq.shape
        xp = jnp.pad(xq, ((0, 0), (plo_h, phi_h + sh), (plo_w, phi_w + sw_),
                          (0, 0)))
        tiles = []
        for kh in range(kh_n):
            row = []
            for kw in range(kw_n):
                xs = jax.lax.slice(
                    xp, (0, kh, kw, 0),
                    (n, kh + sh * (ho - 1) + 1, kw + sw_ * (wo - 1) + 1, cin),
                    (1, sh, sw_, 1))
                row.append(jax.lax.dot_general(
                    xs, gq, (((0, 1, 2), (0, 1, 2)), ((), ())),
                    preferred_element_type=jnp.int32))
            tiles.append(jnp.stack(row))                   # (kw,I,O)
        dwk = jnp.stack(tiles)                             # (kh,kw,I,O)
        dw = (dwk.astype(jnp.float32) * (sx * sg)).astype(w_dt)
    else:
        # transposed-conv wgrad (dilated x) and tiny-spatial plain
        # wgrads → bf16 conv on the dequantized x̂, CHWN/IHWO layout
        x_hat = (xq.astype(jnp.float32) * sx).astype(jnp.bfloat16)
        pad_rhs = _vjp_rhs_padding(in_hw, k_hw, strides, out_hw, padding,
                                   lhs_dilation, (1, 1))
        dn = jax.lax.conv_dimension_numbers(
            x_hat.shape, g.shape, ("CHWN", "IHWO", "NHWC"))
        dw32 = jax.lax.conv_general_dilated(
            x_hat, g.astype(jnp.bfloat16), window_strides=(1, 1),
            padding=pad_rhs, lhs_dilation=lhs_dilation,
            rhs_dilation=strides, dimension_numbers=dn,
            preferred_element_type=jnp.float32,
        )
        dw = jnp.transpose(dw32, (1, 2, 0, 3)).astype(w_dt)
    return dx, dw


def _int8_conv_bwd(strides, padding, lhs_dilation, res, g):
    return _int8_bwd_core(strides, padding, lhs_dilation, res, g)


int8_conv.defvjp(_int8_conv_fwd, _int8_conv_bwd)


# ---------------------------------------------------------------- delayed
# Delayed (stored-scale) activation quantization — TransformerEngine-style
# amax bookkeeping adapted to convs. The dynamic path above serializes on
# a full absmax reduction over x before the quantize can start (two HBM
# passes over every quantized activation, and a latency chain XLA cannot
# hide). Here the scale comes from the PREVIOUS step (a "quant" flax
# collection threaded through TrainState like batch_stats), so the
# quantize fuses into the producer, and the current amax is measured in
# the SAME pass to update the stored value for the next step. Transient
# under-scaling clips symmetrically at ±127 for one step — the decaying-
# max update (module code) adapts the scale upward immediately after.
# Cotangent (backward) scales stay dynamic: custom_vjp backward passes
# cannot write state, and the cotangent absmax fuses with the g·s_w fold
# anyway.


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def int8_conv_ds(x: jax.Array, w: jax.Array, sx: jax.Array,
                 strides: Tuple[int, int], padding: Pads,
                 lhs_dilation: Tuple[int, int] = (1, 1)):
    """``int8_conv`` with a STORED per-tensor activation scale ``sx``.

    Returns ``(y, amax_x)`` — the conv output and the CURRENT max|x|
    measured in the quantize pass, for the caller's scale update.
    """
    out, _ = _int8_conv_ds_fwd(x, w, sx, strides, padding, lhs_dilation)
    return out


def _int8_conv_ds_fwd(x, w, sx, strides, padding, lhs_dilation):
    sx = jnp.maximum(sx.astype(jnp.float32), 1e-12)
    sw = absmax_scale(w, axis=(0, 1, 2))          # (1,1,1,O) — w is tiny
    xf = x.astype(jnp.float32)
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    amax = jnp.max(jnp.abs(xf))                   # fused into the same pass
    wq = quantize_int8(w, sw)
    y32 = _conv_i32(xq, wq, strides, padding, lhs_dil=lhs_dilation)
    y = y32.astype(jnp.float32) * (sx * sw.reshape(1, 1, 1, -1))
    x_tok = jnp.zeros((0,), x.dtype)
    w_tok = jnp.zeros((0,), w.dtype)
    return (y.astype(x.dtype), amax), (xq, sx, wq, sw, x_tok, w_tok)


def _int8_conv_ds_bwd(strides, padding, lhs_dilation, res, ct):
    g, _ = ct  # the amax output feeds a state update, never a loss
    dx, dw = _int8_bwd_core(strides, padding, lhs_dilation, res, g)
    return dx, dw, jnp.zeros((), jnp.float32)


int8_conv_ds.defvjp(_int8_conv_ds_fwd, _int8_conv_ds_bwd)


# ------------------------------------------------------------- kn2row
# int8 form of the kn2row tap decomposition (ops/conv.py
# kn2row_thin_conv) — the thin-output heads (PatchGAN 512→1) where the
# ONLY large-tensor traffic is the 1×1 tap matmul over x. Per-form
# dispatch table (chained v5e microbenchmarks, the ops/int8.py
# convention):
#
#   contraction                form              dtype   why
#   ---------------------------------------------------------------------
#   fwd    z = x @ w_taps      dot over C_in     int8    C_in wide (512),
#                                                        the one full-rate
#                                                        HBM pass over x —
#                                                        2× MXU
#   wgrad  dw = xᵀ · pz        dot over N·H·W    int8    contraction dim is
#                                                        the whole spatial
#                                                        extent; re-reads x
#                                                        (int8 = half the
#                                                        bytes) at 2× MXU
#   dgrad  dx = pz @ ŵᵀ        dot over k²·O     bf16    contraction dim is
#                                                        k²·O (= 16 for the
#                                                        k4→1 head) — far
#                                                        below one MXU tile;
#                                                        the s8 rate is
#                                                        unrealizable, bf16
#                                                        on the dequantized
#                                                        surrogate keeps the
#                                                        exact-VJP law
#
# The backward is the hand-derived patches-of-dz form (ops/conv.py
# thin_head_conv — pz = im2col(pad(dz, k−1)) holds every shifted dz view
# both cotangents need), generalized to the zero-padded stride-1 case:
# pz spans the PADDED input coordinates, dx crops the ring, dw reads the
# int8-padded xq (zero padding is exact in int8).


def _kn2row_i32(xq, wq, pad):
    """Quantized tap decomposition: int32 tap matmul + int32 shift-adds.
    xq (N,H,W,C) int8, wq (k,k,C,O) int8 → (N,H+2p−k+1,W+2p−k+1,O) int32.
    The k² partial sums accumulate in int32 — rounding once at the dequant
    exactly like the s32 conv accumulator it replaces."""
    kh, kw, c, o = wq.shape
    n, h, w, _ = xq.shape
    ho, wo = h + 2 * pad - kh + 1, w + 2 * pad - kw + 1
    wt = wq.reshape(kh * kw, c, o).transpose(1, 0, 2).reshape(
        c, kh * kw * o)
    z32 = jax.lax.dot_general(
        xq, wt, (((3,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ).reshape(n, h, w, kh * kw, o)
    z32 = jnp.pad(z32, ((0, 0), (pad, pad), (pad, pad), (0, 0), (0, 0)))
    y32 = jnp.zeros((n, ho, wo, o), jnp.int32)
    for t in range(kh * kw):
        dh, dw = divmod(t, kw)
        y32 = y32 + jax.lax.dynamic_slice(
            z32, (0, dh, dw, t, 0), (n, ho, wo, 1, o)
        ).reshape(n, ho, wo, o)
    return y32


def _kn2row_fwd_core(x, w, sx, pad, amax_from_x):
    """Shared forward of the dynamic/delayed int8 kn2row pair. Returns
    ``((y, amax), residuals)``; ``amax_from_x`` measures max|x| in the
    same pass (the delayed-scale update proposal)."""
    sx = jnp.maximum(jnp.asarray(sx, jnp.float32), 1e-12)
    sw = absmax_scale(w, axis=(0, 1, 2))          # (1,1,1,O)
    xf = x.astype(jnp.float32)
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    amax = jnp.max(jnp.abs(xf)) if amax_from_x else jnp.zeros((), jnp.float32)
    wq = quantize_int8(w, sw)
    y32 = _kn2row_i32(xq, wq, pad)
    y = y32.astype(jnp.float32) * (sx * sw.reshape(1, 1, 1, -1))
    x_tok = jnp.zeros((0,), x.dtype)
    w_tok = jnp.zeros((0,), w.dtype)
    return (y.astype(x.dtype), amax), (xq, sx, wq, sw, x_tok, w_tok)


def _int8_kn2row_bwd_core(pad, res, g):
    """Patches-of-dz backward with the per-form dispatch above."""
    xq, sx, wq, sw, x_tok, w_tok = res
    from p2p_tpu.ops.conv import im2col_patches

    k = wq.shape[0]
    o = wq.shape[-1]
    cin = wq.shape[2]
    n, h, w_, _ = xq.shape
    gf = g.astype(jnp.float32)
    # pz[q, (kh',kw',o)] = dz[q − (k−1) + (kh',kw')] over PADDED x coords
    dzp = jnp.pad(gf, ((0, 0), (k - 1, k - 1), (k - 1, k - 1), (0, 0)))
    pz = im2col_patches(dzp.astype(jnp.bfloat16), k)   # (N,H+2p,W+2p,k²·O)
    # ---- dgrad (bf16 — tiny k²·O contraction, dispatch table above) ----
    w_hat = (wq.astype(jnp.float32) * sw).astype(jnp.bfloat16)
    wd = jnp.flip(w_hat, (0, 1)).transpose(0, 1, 3, 2).reshape(
        k * k * o, cin)
    # bf16 by the dispatch table above — the coverage waiver lives at the
    # custom-VJP CALL SITES (jax attributes backward eqns there), e.g.
    # ops/conv.py KN2RowConv
    dxp = jax.lax.dot_general(
        pz, wd, (((3,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dx = jax.lax.slice(
        dxp, (0, pad, pad, 0), (n, pad + h, pad + w_, cin)
    ).astype(x_tok.dtype)
    # ---- wgrad (int8 — the big N·H·W contraction re-reading x) --------
    xpq = jnp.pad(xq, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    spz = absmax_scale(pz)
    pzq = quantize_int8(pz, spz)
    dwm32 = jax.lax.dot_general(
        xpq, pzq, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                          # (C, k²·O) in (kh',kw',o)
    dwm = dwm32.astype(jnp.float32) * (sx * spz)
    dw = jnp.flip(dwm.reshape(cin, k, k, o), (1, 2)).transpose(1, 2, 0, 3)
    return dx, dw.astype(w_tok.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def int8_kn2row_conv(x: jax.Array, w: jax.Array, pad: int):
    """Stride-1 thin-output conv on the int8 kn2row path (dynamic
    per-tensor activation scale). NHWC ⊛ HWIO, zero padding both sides."""
    (y, _), _ = _kn2row_fwd_core(x, w, absmax_scale(x), pad, False)
    return y


def _int8_kn2row_fwd(x, w, pad):
    (y, _), res = _kn2row_fwd_core(x, w, absmax_scale(x), pad, False)
    return y, res


def _int8_kn2row_bwd(pad, res, g):
    return _int8_kn2row_bwd_core(pad, res, g)


int8_kn2row_conv.defvjp(_int8_kn2row_fwd, _int8_kn2row_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def int8_kn2row_conv_ds(x: jax.Array, w: jax.Array, sx: jax.Array,
                        pad: int):
    """``int8_kn2row_conv`` with a STORED activation scale — returns
    ``(y, amax_x)`` like :func:`int8_conv_ds` (same delayed-scale
    contract; the cotangent-side scales stay dynamic)."""
    out, _ = _kn2row_fwd_core(x, w, sx, pad, True)
    return out


def _int8_kn2row_ds_fwd(x, w, sx, pad):
    return _kn2row_fwd_core(x, w, sx, pad, True)


def _int8_kn2row_ds_bwd(pad, res, ct):
    g, _ = ct  # the amax output feeds a state update, never a loss
    dx, dw = _int8_kn2row_bwd_core(pad, res, g)
    return dx, dw, jnp.zeros((), jnp.float32)


int8_kn2row_conv_ds.defvjp(_int8_kn2row_ds_fwd, _int8_kn2row_ds_bwd)


# ----------------------------------------------------- prequantized in
# The consumer half of the quantize-fused epilogue
# (ops/pallas/norm_act.py norm_act_quant): the producer kernel already
# clipped/rounded the activation onto the int8 grid (values in
# [-127,127], carried in the compute dtype so autodiff stays legal — an
# int8-dtype output would surface float0 tangents and sever the chain),
# so the conv's input quantize degenerates to a pure convert that fuses
# into the conv's operand read. The returned input cotangent is w.r.t.
# the DEQUANTIZED surrogate sx·q — the epilogue's straight-through
# backward consumes it as d/dy directly, which composes to exactly the
# unfused ``int8_conv_ds`` VJP law.


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def int8_conv_pq(xi: jax.Array, w: jax.Array, sx: jax.Array,
                 strides: Tuple[int, int], padding: Pads,
                 lhs_dilation: Tuple[int, int] = (1, 1)):
    """``int8_conv_ds`` whose activation arrives ALREADY on the int8 grid
    (integer values in [-127,127] in a float container, scale ``sx``)."""
    y, _ = _int8_conv_pq_fwd(xi, w, sx, strides, padding, lhs_dilation)
    return y


def _int8_conv_pq_fwd(xi, w, sx, strides, padding, lhs_dilation):
    sx = jnp.maximum(jnp.asarray(sx, jnp.float32), 1e-12)
    sw = absmax_scale(w, axis=(0, 1, 2))
    xq = xi.astype(jnp.int8)        # pure convert: values already on-grid
    wq = quantize_int8(w, sw)
    y32 = _conv_i32(xq, wq, strides, padding, lhs_dil=lhs_dilation)
    y = y32.astype(jnp.float32) * (sx * sw.reshape(1, 1, 1, -1))
    x_tok = jnp.zeros((0,), xi.dtype)
    w_tok = jnp.zeros((0,), w.dtype)
    return y.astype(xi.dtype), (xq, sx, wq, sw, x_tok, w_tok)


def _int8_conv_pq_bwd(strides, padding, lhs_dilation, res, g):
    dx, dw = _int8_bwd_core(strides, padding, lhs_dilation, res, g)
    return dx, dw, jnp.zeros((), jnp.float32)


int8_conv_pq.defvjp(_int8_conv_pq_fwd, _int8_conv_pq_bwd)


# Decaying-max amax update: responds upward immediately (next step uses
# the larger measured amax), decays 5%/step when activations shrink so a
# one-off spike doesn't pin the scale forever.
AMAX_DECAY = 0.95


def reshard_amax(amax: jax.Array, old_width: int,
                 new_width: int) -> jax.Array:
    """Closed-form amax resharding law for a TP-width change under
    delayed-int8 state (the elastic ``tp_amax_recalibrate`` migration,
    p2p_tpu.resilience.reshape).

    amax is a MAX statistic, so the law needs no data pass:

    - a **per-tensor** amax (scalar, or any leaf without a leading
      ``old_width`` shard axis — the repo's ``amax_x`` scalars, whose
      ``jnp.max`` is a GLOBAL reduction under GSPMD) is shard-width
      invariant: every shard of the activation quantizes with the same
      global scale — identity;
    - a **per-shard** amax (leading ``[old_width]`` axis) remaps so each
      new shard takes the max over the old shards overlapping its channel
      range: on WIDEN (more, smaller shards) each old shard broadcasts to
      its children (the containing shard's amax is a safe, exact-or-upper
      bound for every sub-range); on NARROW (fewer, bigger shards) each
      new shard maxes over the old shards it absorbs (exact: max of
      maxes). Widen-then-narrow round-trips bitwise
      (``max(a, a) == a`` — pinned in tests/test_int8.py).

    Widths must divide (the mesh resolve already enforces power-of-two
    style factorings); anything else raises with the two widths named.
    """
    amax = jnp.asarray(amax)
    old_width, new_width = int(old_width), int(new_width)
    if old_width == new_width:
        return amax
    if amax.ndim == 0 or amax.shape[0] != old_width:
        return amax  # per-tensor scale: shard-width invariant
    if new_width > old_width:
        if new_width % old_width:
            raise ValueError(
                f"cannot widen amax shards {old_width} -> {new_width}: "
                "widths must divide")
        return jnp.repeat(amax, new_width // old_width, axis=0)
    if old_width % new_width:
        raise ValueError(
            f"cannot narrow amax shards {old_width} -> {new_width}: "
            "widths must divide")
    k = old_width // new_width
    return jnp.max(amax.reshape((new_width, k) + amax.shape[1:]), axis=1)


def amax_update(cur_amax: jax.Array, stored: jax.Array) -> jax.Array:
    """The delayed-scale update law: max(cur, AMAX_DECAY·stored).

    Shared contract between the per-layer ``_delayed_scale`` plumbing below
    and the GPipe quant stacking (parallel/pp.py): because the pipelined
    forward quantizes every microbatch with the FROZEN start-of-step scale,
    the per-microbatch update *proposals* can be max-combined —
    max_m(max(amax_m, d·s)) == max(max_m(amax_m), d·s) == this law on the
    full-batch amax — so the stacked-quant pipeline reproduces the
    unpipelined update bitwise.
    """
    return jnp.maximum(cur_amax, AMAX_DECAY * stored)


def _norm_pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _fused_epilogue_scale(mod: nn.Module, x: jax.Array, ep: Callable):
    """The quantize-fused-epilogue twin of :func:`_delayed_scale`, shared
    by ``QuantConv`` and ``SpectralConv``: own the ``amax_x`` leaf (init
    = the epilogue's measured amax on the init batch — the amax output
    is scale-independent, so any positive probe works), read this step's
    stored scale, run the fused ``(y_raw, sx) -> (q, amax)`` epilogue,
    and store the update proposal when 'quant' is mutable. Returns
    ``(q, sx)`` — feed :func:`int8_conv_pq`; the dequantized tap is
    ``q·sx``."""
    amax_v = mod.variable(
        "quant", "amax_x",
        lambda: ep(x, jnp.ones((), jnp.float32))[1],
    )
    sx = jnp.maximum(amax_v.value, 1e-12) / 127.0
    q, amax = ep(x, sx)
    if mod.is_mutable_collection("quant"):
        amax_v.value = amax_update(amax, amax_v.value)
    return q, sx


def surrogate_tap(q: jax.Array, sx: jax.Array) -> jax.Array:
    """The dequantized feature tap of a fused epilogue: VALUE ``sx·q``
    (what the downstream conv contracts), but with the cotangent passed
    to ``q`` UNSCALED — the fused-epilogue VJP already interprets q's
    cotangent in the surrogate (d/dŷ) frame, and a plain ``q*sx`` would
    multiply it by ``sx`` a second time (≈amax/127, silently
    near-zeroing the feature-matching gradients through the tap)."""
    return q + jax.lax.stop_gradient(q * sx - q)


def _delayed_scale(mod: nn.Module, x: jax.Array):
    """Stored-scale plumbing shared by the Quant* modules: an ``amax_x``
    scalar in the 'quant' collection (initialized from the init batch),
    read as this step's scale. Returns ``(sx, update_fn)``; call
    ``update_fn(cur_amax)`` with the amax the conv measured."""
    amax_v = mod.variable(
        "quant", "amax_x",
        lambda: jnp.max(jnp.abs(x.astype(jnp.float32))),
    )
    sx = jnp.maximum(amax_v.value, 1e-12) / 127.0

    def update(cur_amax):
        if mod.is_mutable_collection("quant"):
            amax_v.value = amax_update(cur_amax, amax_v.value)

    return sx, update


class QuantConv(nn.Module):
    """Drop-in for the repo's ``nn.Conv`` uses, on the int8 MXU path.

    Parameter tree ("kernel" HWIO + optional "bias") matches ``nn.Conv``
    so bf16↔int8 checkpoints interchange. ``padding`` is an int (both
    sides) or explicit ((lo,hi),(lo,hi)). ``delayed`` switches the
    activation scale to the stored-amax path (see int8_conv_ds): the
    'quant' collection must then be threaded by the caller.

    ``epilogue`` (requires ``delayed``) is the quantize-fused input
    epilogue (ISSUE 14): a callable ``(y_raw, sx) -> (q, amax)`` — the
    model binds ``make_norm_act(...)``'s ``quant_scale`` form — applied
    to the RAW previous-conv output so [norm + act + clip/round + amax]
    run as one streaming pass; the conv then consumes the prequantized
    activation via :func:`int8_conv_pq`. The stored scale IS this
    module's own ``amax_x`` (same 'quant' leaf as the unfused path —
    checkpoints interchange; its init measures the epilogue's float
    output on the init batch). ``epilogue_tap=True`` additionally
    returns the dequantized surrogate ``sx·q`` — what the downstream
    conv actually sees — for feature-matching taps.
    """

    features: int
    kernel_size: int = 4
    strides: int = 1
    padding: int = 1
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()
    delayed: bool = False
    epilogue: Optional[Callable] = None
    epilogue_tap: bool = False

    @nn.compact
    def __call__(self, x):
        k = _norm_pair(self.kernel_size)
        kernel = self.param(
            "kernel", self.kernel_init, k + (x.shape[-1], self.features),
            jnp.float32,
        )
        pad = self.padding
        pad = ((pad, pad), (pad, pad)) if isinstance(pad, int) else pad
        dt = self.dtype or jnp.float32
        tap = None
        if self.epilogue is not None:
            if not self.delayed:
                raise ValueError(
                    "QuantConv(epilogue=...) needs delayed=True — the "
                    "fused quantize reads this module's stored amax")
            q, sx = _fused_epilogue_scale(self, x, self.epilogue)
            # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch (_int8_bwd_core): same bf16 backward forms as the int8_conv_ds branch below, by design
            y = int8_conv_pq(q.astype(dt), kernel.astype(dt), sx,
                             _norm_pair(self.strides), pad)
            if self.epilogue_tap:
                tap = surrogate_tap(q.astype(dt), sx).astype(dt)
        elif self.delayed:
            sx, update = _delayed_scale(self, x)
            # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch (_int8_bwd_core): the lhs-dilated stride-2 dgrad and the transposed/big-spatial wgrads measured SLOWER in int8 on v5e — those contractions stay bf16 on the dequantized surrogate while fwd, s1 dgrad and the unrolled wgrad run s8×s8→s32 (module docstring table; backward eqns attribute to this call site)
            y, amax = int8_conv_ds(x.astype(dt), kernel.astype(dt), sx,
                                   _norm_pair(self.strides), pad)
            update(amax)
        else:
            # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch: see the delayed branch above — same _int8_bwd_core bf16 forms by design
            y = int8_conv(x.astype(dt), kernel.astype(dt),
                          _norm_pair(self.strides), pad)
        y = save_conv_out(y)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(y.dtype)
        if self.epilogue_tap:
            return y, tap
        return y


class QuantSubpixelDeconv(nn.Module):
    """``SubpixelDeconv`` (ops/conv.py — ConvTranspose k4 s2 re-expressed
    as conv k2 s1 + shifted depth-to-space) with the inner conv on the
    int8 path. The k2-s1 plain conv is the form where ALL THREE int8
    contractions win on v5e (fwd 2×, dgrad 2×, wgrad dot_general 1.5×),
    unlike the lhs-dilated ConvTranspose forward where int8 loses —
    which is why the int8 U-Net decoder uses this instead of
    ``QuantConvTranspose``. Param tree matches ``SubpixelDeconv``
    (kernel (2,2,C,4F)); the exact weight mapping from a ConvTranspose
    checkpoint is documented there.
    """

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()
    delayed: bool = False

    @nn.compact
    def __call__(self, x):
        out = QuantConv(
            4 * self.features, kernel_size=2, strides=1,
            padding=((1, 1), (1, 1)), use_bias=self.use_bias,
            dtype=self.dtype, kernel_init=self.kernel_init, name="Conv_0",
            delayed=self.delayed,
        )(x)                                    # (N, H+1, W+1, 4F)
        return subpixel_interleave(out, self.features)


class QuantConvTranspose(nn.Module):
    """Drop-in for ``nn.ConvTranspose(k4, s2, 'SAME')`` on the int8 path.

    flax's ConvTranspose lowers to a conv with ``lhs_dilation=strides``
    and an un-flipped kernel; 'SAME' padding for k=4, s=2 is (2,2) per
    spatial dim (lax._conv_transpose_padding). Parameter tree matches
    ``nn.ConvTranspose``.
    """

    features: int
    kernel_size: int = 4
    strides: int = 2
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()
    delayed: bool = False

    @nn.compact
    def __call__(self, x):
        k = _norm_pair(self.kernel_size)
        s = _norm_pair(self.strides)
        kernel = self.param(
            "kernel", self.kernel_init, k + (x.shape[-1], self.features),
            jnp.float32,
        )
        # lax._conv_transpose_padding for 'SAME': total = k + s - 2,
        # lo = k - 1 if s > k - 1 else ceil(total / 2).
        pads = []
        for ki, si in zip(k, s):
            total = ki + si - 2
            lo = ki - 1 if si > ki - 1 else int(np.ceil(total / 2))
            pads.append((lo, total - lo))
        dt = self.dtype or jnp.float32
        if self.delayed:
            sx, update = _delayed_scale(self, x)
            y, amax = int8_conv_ds(x.astype(dt), kernel.astype(dt), sx,
                                   (1, 1), tuple(pads), lhs_dilation=s)
            update(amax)
        else:
            y = int8_conv(x.astype(dt), kernel.astype(dt), (1, 1),
                          tuple(pads), lhs_dilation=s)
        y = save_conv_out(y)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(y.dtype)
        return y
