"""Normalization layers.

The reference uses ``BatchNorm2d`` throughout its live model zoo
(networks.py:433 and others — the InstanceNorm ``get_norm_layer`` at
networks.py:93-102 is dead code), trained at batch size 1, which makes its
"batch" statistics effectively instance statistics with running-stat drift.
The build keeps BatchNorm as the reference-faithful default, and offers
InstanceNorm (pix2pixHD-style) plus a Pallas-fused InstanceNorm for the
1024×512 config.

Statistics are computed in fp32 regardless of the bf16 compute dtype.

Cross-device sync under data parallelism: all layers here compute statistics
with plain ``jnp`` reductions over a *logically global* batch — under
jit+GSPMD the mesh makes those reductions global automatically (XLA inserts
the psum over the ``data`` axis), which IS sync-BN. Under ``shard_map``
regions pass ``axis_name='data'`` to opt in explicitly.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name


def _gamma_init(key, shape, dtype=jnp.float32):
    # Reference BatchNorm affine init: γ ~ N(1, 0.02) (networks.py:144-146).
    return 1.0 + jax.random.normal(key, shape, dtype) * 0.02


@jax.custom_vjp
def dual_moments(xc):
    """Per-channel (Σxc, Σxc²) over all leading axes in ONE variadic
    reduction — f32 accumulation.

    Two separate ``jnp.mean`` reductions profile as one fused kernel that
    still READS the activation twice (534 MB moved for a 268 MB tensor —
    the round-3 BatchNorm_12 'add' kernel). A variadic ``lax.reduce`` with
    the square fused as an elementwise producer is a single pass at the
    HLO level — but the round-4 profile shows XLA's reduce kernel STILL
    reads each operand separately, so ``P2P_PALLAS_BN=1`` routes eligible
    shapes through the hand-fused Pallas kernel
    (ops/pallas/batch_moments.py) that genuinely reads x once. The VJP
    is the same closed form XLA derives for sum/sumsq:
    ``dxc = ds + 2·xc·dss`` (broadcast over channels).
    """
    if os.environ.get("P2P_PALLAS_BN", "0") == "1":
        from p2p_tpu.ops.pallas.batch_moments import (
            eligible_block,
            pallas_dual_moments,
        )

        mb = eligible_block(xc)
        if mb:
            return pallas_dual_moments(
                xc.reshape(-1, xc.shape[-1]), mb)
    xf = xc.astype(jnp.float32)
    dims = tuple(range(xc.ndim - 1))
    return jax.lax.reduce(
        (xf, jnp.square(xf)),
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
        dims,
    )


def _dual_moments_fwd(xc):
    out = dual_moments(xc)
    return out, xc


def _dual_moments_bwd(xc, ct):
    ds, dss = ct
    dxc = ds.astype(jnp.float32) + 2.0 * xc.astype(jnp.float32) * dss
    return (dxc.astype(xc.dtype),)


dual_moments.defvjp(_dual_moments_fwd, _dual_moments_bwd)


class _FastBatchNorm(nn.Module):
    """Hand-written BatchNorm tuned for TPU HBM traffic.

    ``flax.linen.BatchNorm`` materializes a full fp32 copy of the (bf16)
    activation for its statistics and runs a two-pass variance; on the
    256² U-Net step that shows up in the profile as standalone
    ``convert_element_type`` / ``reduce`` kernels re-reading the largest
    decoder activations several times. This version:

    - computes both moments in ONE pass (`mean`, `mean(x²)`) with fp32
      *accumulation* (``jnp.mean(..., dtype=f32)``) so the bf16→f32
      convert fuses into the reduction instead of materializing;
    - folds the normalization into a per-channel affine ``y = x·a + b``
      (a = γ·rsqrt(var+ε), b = β − μ·a), one fusable elementwise pass;
    - keeps flax param/stat names (scale/bias, mean/var) and semantics
      (biased batch variance stored in the running stats).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))
        scale = self.param("scale", _gamma_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        init = self.is_initializing()
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # Shifted one-pass moments: Var(x) = E[(x−c)²] − (μ−c)² for any
            # constant c; with c = the running mean (≈ μ after warm-up) the
            # subtraction is cancellation-safe where the naive E[x²]−E[x]²
            # form loses all precision for high-mean/low-variance channels.
            # Still a single read of x — the shift fuses into the reduces.
            c = jax.lax.stop_gradient(ra_mean.value).astype(x.dtype)
            xc = x - c
            n = x.size // x.shape[-1]
            sum_c, sumsq_c = dual_moments(xc)
            mean_c = sum_c / n
            msq_c = sumsq_c / n
            if self.axis_name is not None:
                mean_c = jax.lax.pmean(mean_c, self.axis_name)
                msq_c = jax.lax.pmean(msq_c, self.axis_name)
            mean = mean_c + c.astype(jnp.float32)  # add back the exact shift
            var = jnp.maximum(msq_c - jnp.square(mean_c), 0.0)
            if not init:
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
                ra_var.value = m * ra_var.value + (1.0 - m) * var

        a = scale * jax.lax.rsqrt(var + self.epsilon)
        b = bias - mean * a
        # Under the conv-residuals-only checkpoint policy (train/step.py),
        # keep the tiny per-channel affine so the backward never re-reduces
        # the full activation to recover the batch statistics.
        a = checkpoint_name(a, "norm_stats")
        b = checkpoint_name(b, "norm_stats")
        # Apply the folded affine in the input dtype: an f32 apply would pin a
        # materialized fp32 copy of the activation (multiple consumers defeat
        # fusion of the convert). Per-channel a/b quantization to bf16 is
        # ~2⁻⁸ relative — noise for GAN training; fp32 inputs are unaffected.
        y = x * a.astype(x.dtype) + b.astype(x.dtype)
        return y.astype(self.dtype or x.dtype)


class BatchNorm(nn.Module):
    """BatchNorm over (N,H,W) in NHWC with running stats in 'batch_stats'.

    Affine init matches the reference: γ ~ N(1, 0.02), β = 0
    (networks.py:144-146). Inner module is pinned to the flax name
    ``BatchNorm_0`` so param/stat pytree paths stay stable.
    """

    use_running_average: bool = False
    momentum: float = 0.9  # flax convention; equals torch momentum=0.1
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        ura = (
            self.use_running_average
            if use_running_average is None
            else use_running_average
        )
        return _FastBatchNorm(
            use_running_average=ura,
            momentum=self.momentum,
            epsilon=self.epsilon,
            axis_name=self.axis_name,
            dtype=self.dtype,
            name="BatchNorm_0",
        )(x)


class InstanceNorm(nn.Module):
    """Per-sample, per-channel normalization over H,W (NHWC).

    Matches torch ``InstanceNorm2d(affine=affine)`` semantics: statistics are
    always per-forward (no running stats), eps inside the sqrt.
    """

    affine: bool = False
    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        mean = checkpoint_name(
            jnp.mean(x32, axis=(1, 2), keepdims=True), "norm_stats"
        )
        var = checkpoint_name(
            jnp.var(x32, axis=(1, 2), keepdims=True), "norm_stats"
        )
        y = (x32 - mean) * jax.lax.rsqrt(var + self.epsilon)
        if self.affine:
            c = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
            y = y * scale + bias
        return y.astype(self.dtype or orig_dtype)


def make_norm_act(kind: str, *, train: bool = True,
                  axis_name: Optional[str] = None, dtype=None):
    """Factory for the post-conv epilogue ``act(norm(y) [+ residual])`` —
    the ONE seam the generator/discriminator blocks call so the
    ``pallas_instance`` kind can fuse the whole chain into the Pallas
    normalize pass (ops/pallas/norm_act.py) while every other kind keeps
    today's exact op order (norm module → residual add → output-masked
    activation). Returns ``apply(y, act="none", slope=0.2, residual=None)``;
    call inside ``@nn.compact`` (the non-fused kinds instantiate their norm
    module per call, so flax auto-naming — and therefore param/stat trees —
    is identical to the unfused ``make_norm`` layout)."""
    if kind == "pallas_instance":
        from p2p_tpu.ops.pallas.instance_norm import (
            pallas_instance_norm_act,
            pallas_instance_norm_act_quant,
        )

        def apply_fused(y, act: str = "none", slope: float = 0.2,
                        residual=None, quant_scale=None):
            if quant_scale is not None:
                # quantize-fused epilogue (ISSUE 14): emit the on-grid
                # activation + its amax proposal from the same two-pass
                # kernel; the caller feeds ops.int8.int8_conv_pq
                if residual is not None:
                    raise ValueError(
                        "quant_scale does not compose with residual "
                        "(no quantized resblock tail in the zoo)")
                return pallas_instance_norm_act_quant(
                    y, quant_scale, act=act, slope=slope)
            out = pallas_instance_norm_act(y, residual=residual, act=act,
                                           slope=slope)
            return out.astype(dtype or y.dtype)

        return apply_fused

    mk = make_norm(kind, train=train, axis_name=axis_name, dtype=dtype)

    def apply_ref(y, act: str = "none", slope: float = 0.2, residual=None,
                  quant_scale=None):
        from p2p_tpu.ops.activations import leaky_relu_y, relu_y

        if quant_scale is not None:
            if kind != "instance" or residual is not None:
                raise ValueError(
                    "quant_scale needs a stateless instance-family norm "
                    f"with no residual (kind={kind!r})")
            # the CPU/lax reference of the quantize-fused epilogue —
            # same custom-VJP STE law as the kernel path
            from p2p_tpu.ops.pallas.norm_act import instance_norm_act_quant

            return instance_norm_act_quant(y, quant_scale, act=act,
                                           slope=slope)
        z = mk()(y)
        if residual is not None:
            z = z + residual
        if act == "relu":
            return relu_y(z)
        if act == "leaky":
            return leaky_relu_y(z, slope)
        return z

    return apply_ref


def make_norm(kind: str, *, train: bool = True, axis_name: Optional[str] = None,
              dtype=None):
    """Factory mapping config ``norm`` strings to layer constructors.

    Returned callables construct a fresh module (use inside @nn.compact).
    """
    if kind == "batch":
        return lambda: BatchNorm(
            use_running_average=not train, axis_name=axis_name, dtype=dtype
        )
    if kind == "instance":
        return lambda: InstanceNorm(dtype=dtype)
    if kind == "pallas_instance":
        from p2p_tpu.ops.pallas.instance_norm import PallasInstanceNorm

        return lambda: PallasInstanceNorm(dtype=dtype)
    if kind == "none":
        return lambda: (lambda x: x)
    raise ValueError(f"unknown norm kind {kind!r}")
