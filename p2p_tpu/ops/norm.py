"""Normalization layers.

The reference uses ``BatchNorm2d`` throughout its live model zoo
(networks.py:433 and others — the InstanceNorm ``get_norm_layer`` at
networks.py:93-102 is dead code), trained at batch size 1, which makes its
"batch" statistics effectively instance statistics with running-stat drift.
The build keeps BatchNorm as the reference-faithful default, and offers
InstanceNorm (pix2pixHD-style) plus a Pallas-fused InstanceNorm for the
1024×512 config.

Statistics are computed in fp32 regardless of the bf16 compute dtype.

Cross-device sync under data parallelism: all layers here compute statistics
with plain ``jnp`` reductions over a *logically global* batch — under
jit+GSPMD the mesh makes those reductions global automatically (XLA inserts
the psum over the ``data`` axis), which IS sync-BN. Under ``shard_map``
regions pass ``axis_name='data'`` to opt in explicitly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def _gamma_init(key, shape, dtype=jnp.float32):
    # Reference BatchNorm affine init: γ ~ N(1, 0.02) (networks.py:144-146).
    return 1.0 + jax.random.normal(key, shape, dtype) * 0.02


class BatchNorm(nn.Module):
    """BatchNorm over (N,H,W) in NHWC with running stats in 'batch_stats'.

    Affine init matches the reference: γ ~ N(1, 0.02), β = 0
    (networks.py:144-146).
    """

    use_running_average: bool = False
    momentum: float = 0.9  # flax convention; equals torch momentum=0.1
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        ura = (
            self.use_running_average
            if use_running_average is None
            else use_running_average
        )
        return nn.BatchNorm(
            use_running_average=ura,
            momentum=self.momentum,
            epsilon=self.epsilon,
            axis_name=self.axis_name,
            dtype=self.dtype,
            scale_init=_gamma_init,
            bias_init=nn.initializers.zeros,
            use_fast_variance=False,
        )(x)


class InstanceNorm(nn.Module):
    """Per-sample, per-channel normalization over H,W (NHWC).

    Matches torch ``InstanceNorm2d(affine=affine)`` semantics: statistics are
    always per-forward (no running stats), eps inside the sqrt.
    """

    affine: bool = False
    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
        var = jnp.var(x32, axis=(1, 2), keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.epsilon)
        if self.affine:
            c = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
            y = y * scale + bias
        return y.astype(self.dtype or orig_dtype)


def make_norm(kind: str, *, train: bool = True, axis_name: Optional[str] = None,
              dtype=None):
    """Factory mapping config ``norm`` strings to layer constructors.

    Returned callables construct a fresh module (use inside @nn.compact).
    """
    if kind == "batch":
        return lambda: BatchNorm(
            use_running_average=not train, axis_name=axis_name, dtype=dtype
        )
    if kind == "instance":
        return lambda: InstanceNorm(dtype=dtype)
    if kind == "pallas_instance":
        from p2p_tpu.ops.pallas.instance_norm import PallasInstanceNorm

        return lambda: PallasInstanceNorm(dtype=dtype)
    if kind == "none":
        return lambda: (lambda x: x)
    raise ValueError(f"unknown norm kind {kind!r}")
