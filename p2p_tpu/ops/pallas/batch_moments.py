"""Single-pass BatchNorm moments (Σx, Σx²) as a Pallas TPU kernel.

Why: ``ops/norm.dual_moments`` lowers the two moments as ONE variadic
``lax.reduce`` — but the round-3/4 profiles show XLA's reduce kernel still
READS each operand separately (534 MB moved for a 268 MB activation on the
round-3 BatchNorm_12 kernel; re-measured unchanged in round 4 after the
variadic rewrite). The reference never had this problem to solve — torch's
cuDNN BatchNorm owns its fused stats pass (networks.py:433 BatchNorm2d);
this kernel is the TPU equivalent of that fusion, done by hand because the
compiler won't.

Shape contract: a 2-D ``(M, C)`` view of the activation (callers flatten
all leading axes). The grid streams M in row blocks; both f32 accumulators
live in the same revisited ``(1, C)`` output block — TPU grids execute
sequentially, so first-visit init + accumulate is race-free (same pattern
as instance_norm_kernel.py). The bf16→f32 convert and the square happen
in-register on the VMEM block: ONE read of x total.

Used by ``ops/norm.dual_moments`` when eligible (TPU backend, no >1-device
mesh in scope, M divisible into VMEM-sized blocks); the XLA path remains
the fallback and the numerics are identical (f32 accumulation in both).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_m_block(m: int, c: int, budget_bytes: int = 2 << 20) -> int:
    """Largest divisor of M whose padded (mb, C) input block fits VMEM.

    Sized against the PADDED tile (minor dims round up to (8, 128) f32 /
    (16, 128) bf16 tiles — see instance_norm_kernel._pick_h_block, which
    learned this the hard way on the 32-channel pix2pixHD preset)."""
    padded_c = -(-c // 128) * 128
    row_bytes = padded_c * 4  # f32 working copy dominates
    max_mb = max(1, budget_bytes // row_bytes)
    best = 1
    for mb in range(min(m, max_mb), 0, -1):
        if m % mb == 0:
            best = mb
            break
    return best


def _moments_kernel(x_ref, s1_ref, s2_ref):
    i = pl.program_id(0)
    xf = x_ref[...].astype(jnp.float32)
    s1 = jnp.sum(xf, axis=0, keepdims=True)
    s2 = jnp.sum(xf * xf, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = s1
        s2_ref[...] = s2

    @pl.when(i > 0)
    def _acc():
        s1_ref[...] += s1
        s2_ref[...] += s2


@functools.partial(jax.jit, static_argnames=("block_m", "interpret"))
def pallas_dual_moments(x2d: jax.Array, block_m: int,
                        interpret: bool = False):
    """(M, C) → ((C,) Σx, (C,) Σx²) in f32, one pass over x.

    ``interpret=True`` runs the kernel in Pallas interpret mode so the
    CPU test suite can pin its numerics against the XLA path."""
    m, c = x2d.shape
    out = jax.ShapeDtypeStruct((1, c), jnp.float32)
    s1, s2 = pl.pallas_call(
        _moments_kernel,
        grid=(m // block_m,),
        in_specs=[pl.BlockSpec((block_m, c), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_shape=[out, out],
        interpret=interpret,
    )(x2d)
    return s1[0], s2[0]


def eligible_block(x: jax.Array) -> int:
    """0 = use the XLA path; otherwise the row-block size to stream with.

    Eligibility: TPU backend, no multi-device mesh in trace scope (a
    pallas_call under GSPMD would force a gather of the sharded
    activation), at least 2 row blocks (otherwise the fusion can't beat
    XLA's single fused kernel), and a big enough tensor that the double
    read is worth saving (small activations are latency-bound either way).
    """
    from p2p_tpu.core.mesh import current_mesh

    try:
        if jax.default_backend() != "tpu":
            return 0
    except Exception:  # pragma: no cover - backend probing never fatal
        return 0
    mesh = current_mesh()
    if mesh is not None and mesh.size > 1:
        return 0
    if x.ndim < 2 or x.size < (1 << 20):
        return 0
    m = x.size // x.shape[-1]
    mb = _pick_m_block(m, x.shape[-1])
    if m // mb < 2 or mb < 256:
        return 0
    return mb
