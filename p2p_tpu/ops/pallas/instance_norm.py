"""Pallas-fused InstanceNorm (TPU).

Target: the pix2pixHD 1024×512 config (BASELINE.json configs[3]), where
instance-norm statistics over 512×1024 spatial extents are HBM-bound and
worth fusing: one pass accumulates per-(sample, channel) sum / sum-of-squares
tiles, a second normalizes — versus XLA's default which materializes the
centered tensor.

``pallas_instance_norm`` dispatches to the kernel on TPU and to a reference
XLA implementation elsewhere (CPU tests run the kernel in interpret mode via
``force_pallas=True``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def _xla_instance_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.var(x32, axis=(1, 2), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale + bias
    return y.astype(x.dtype)


def _xla_instance_norm_act(x, scale, bias, residual, act, slope, eps):
    """The lax reference for the fused epilogue — the CPU/tier-1 fallback
    of :func:`pallas_instance_norm_act` (same op order as the kernel:
    norm → affine → residual add → activation, all in f32).

    This chain is also the fusion-gap lint's flagged site
    (``perf-unfused-norm-chain``, analysis/perf_audit.py): in a program
    whose config says the epilogues fuse, these reference ops appearing
    in the jaxpr mean the dispatch below silently fell back — the lint
    CLI traces the fused program under ``P2P_TPU_FORCE_PALLAS=1`` so a
    regression here (a dispatch-condition typo, a new call site skipping
    :func:`p2p_tpu.ops.norm.make_norm_act`) fails ``lint --strict``
    instead of quietly costing a bench round."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.var(x32, axis=(1, 2), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale + bias
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        from p2p_tpu.ops.activations import relu_y

        y = relu_y(y)
    elif act == "leaky":
        from p2p_tpu.ops.activations import leaky_relu_y

        y = leaky_relu_y(y, slope)
    return y.astype(x.dtype)


def sharded_pallas_instance_norm(
    x: jax.Array,
    scale: Optional[jax.Array],
    bias: Optional[jax.Array],
    eps: float,
    mesh,
    interpret: bool = False,
) -> jax.Array:
    """The Pallas InstanceNorm inside a manual-sharding (shard_map) region.

    GSPMD has no partitioning rule for custom calls: left alone under a
    ``P('data','spatial',...)`` activation sharding it would all-gather the
    full (N,H,W,C) tensor around the ``pallas_call`` — at pix2pixHD's
    1024×512 that silently defeats the spatial shard (VERDICT r1 weak#4).
    Here each device runs the kernel on its local H-shard and only the
    (N,1,1,C) stat tiles cross the ICI via psum.
    """
    from jax.sharding import PartitionSpec as P

    from p2p_tpu.core.mesh import (
        BATCH_AXES,
        SPATIAL_AXIS,
        shard_map_compat as shard_map,
    )
    from p2p_tpu.ops.pallas.instance_norm_kernel import (
        instance_norm_fused_sharded,
    )

    # N splits over (data, fsdp) — core/mesh.batch_sharding; instance
    # stats are per-sample so only the spatial psum crosses devices
    x_spec = P(BATCH_AXES, SPATIAL_AXIS, None, None)
    if scale is None:
        fn = shard_map(
            lambda xl: instance_norm_fused_sharded(
                xl, None, None, eps, SPATIAL_AXIS, interpret),
            mesh=mesh, in_specs=(x_spec,), out_specs=x_spec,
            check_vma=False,  # pallas out_shapes carry no vma info
        )
        return fn(x)
    fn = shard_map(
        lambda xl, s, b: instance_norm_fused_sharded(
            xl, s, b, eps, SPATIAL_AXIS, interpret),
        mesh=mesh, in_specs=(x_spec, P(), P()), out_specs=x_spec,
        check_vma=False,  # pallas out_shapes carry no vma info
    )
    return fn(x, scale, bias)


def _sharding_mesh_for(x: jax.Array):
    """The active mesh when x is shardable over (data×fsdp, spatial),
    else None."""
    from p2p_tpu.core.mesh import BATCH_AXES, SPATIAL_AXIS, current_mesh

    mesh = current_mesh()
    if mesh is None:
        return None
    d = 1
    for a in BATCH_AXES:
        d *= mesh.shape.get(a, 1)
    s = mesh.shape.get(SPATIAL_AXIS, 1)
    if s <= 1:
        return None
    if x.shape[0] % (d or 1) or x.shape[1] % s:
        return None
    return mesh


def pallas_instance_norm(
    x: jax.Array,
    scale: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    eps: float = 1e-5,
    force_pallas: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """InstanceNorm on NHWC. Uses the Pallas kernel on TPU backends; inside
    a spatial-sharded parallel step (core.mesh.mesh_context) it switches to
    the shard_map variant so the activations never get all-gathered."""
    on_tpu = jax.default_backend() in ("tpu", "axon")
    force_pallas = force_pallas or os.environ.get(
        "P2P_TPU_FORCE_PALLAS") == "1"
    if not (on_tpu or force_pallas):
        # off-TPU: XLA norm — fast, and GSPMD partitions it natively (no
        # custom-call all-gather hazard). Fake-mesh CI / the driver dryrun
        # opt into the real shard_map + interpret-mode program via
        # force_pallas=True or P2P_TPU_FORCE_PALLAS=1.
        return _xla_instance_norm(x, scale, bias, eps)
    interp = interpret or not on_tpu
    mesh = _sharding_mesh_for(x)
    if mesh is not None:
        return sharded_pallas_instance_norm(x, scale, bias, eps, mesh, interp)
    from p2p_tpu.ops.pallas.instance_norm_kernel import instance_norm_fused

    return instance_norm_fused(x, scale, bias, eps, interpret=interp)


def sharded_pallas_instance_norm_act(
    x, scale, bias, residual, act, slope, eps, mesh, interpret=False):
    """The fused norm+act(+residual) kernel inside a shard_map region —
    same GSPMD custom-call rationale as :func:`sharded_pallas_instance_norm`
    (the residual shards like ``x``; only stat tiles cross the ICI)."""
    from jax.sharding import PartitionSpec as P

    from p2p_tpu.core.mesh import (
        DATA_AXIS,
        SPATIAL_AXIS,
        shard_map_compat as shard_map,
    )
    from p2p_tpu.ops.pallas.norm_act import instance_norm_act_fused_sharded

    x_spec = P(DATA_AXIS, SPATIAL_AXIS, None, None)
    affine = scale is not None
    has_res = residual is not None
    in_specs = [x_spec] + ([P(), P()] if affine else []) + (
        [x_spec] if has_res else [])
    args = (x,) + ((scale, bias) if affine else ()) + (
        (residual,) if has_res else ())

    def body(*a):
        it = iter(a)
        xl = next(it)
        s = next(it) if affine else None
        b = next(it) if affine else None
        r = next(it) if has_res else None
        return instance_norm_act_fused_sharded(
            xl, s, b, r, act=act, slope=slope, eps=eps,
            axis_name=SPATIAL_AXIS, interpret=interpret)

    fn = shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=x_spec,
        check_vma=False,  # pallas out_shapes carry no vma info
    )
    return fn(*args)


def pallas_instance_norm_act(
    x: jax.Array,
    scale: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    residual: Optional[jax.Array] = None,
    act: str = "none",
    slope: float = 0.2,
    eps: float = 1e-5,
    force_pallas: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """InstanceNorm with the whole post-conv epilogue fused:
    ``act(norm(x)·γ+β [+ residual])`` — the dispatch seam for the fused
    norm+activation chains (docs/PERFORMANCE.md). TPU backends run the
    Pallas kernel (ops/pallas/norm_act.py); inside a spatial-sharded step
    the shard_map variant keeps the custom call on local shards; elsewhere
    the lax reference runs (so CPU tier-1 exercises the same call sites)."""
    on_tpu = jax.default_backend() in ("tpu", "axon")
    force_pallas = force_pallas or os.environ.get(
        "P2P_TPU_FORCE_PALLAS") == "1"
    if not (on_tpu or force_pallas):
        return _xla_instance_norm_act(x, scale, bias, residual, act, slope,
                                      eps)
    interp = interpret or not on_tpu
    mesh = _sharding_mesh_for(x)
    if mesh is not None:
        return sharded_pallas_instance_norm_act(
            x, scale, bias, residual, act, slope, eps, mesh, interp)
    from p2p_tpu.ops.pallas.norm_act import instance_norm_act_fused

    return instance_norm_act_fused(x, scale, bias, residual, act=act,
                                   slope=slope, eps=eps, interpret=interp)


def pallas_instance_norm_act_quant(
    x: jax.Array,
    sx: jax.Array,
    scale: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    act: str = "none",
    slope: float = 0.2,
    eps: float = 1e-5,
    force_pallas: bool = False,
    interpret: bool = False,
):
    """The QUANTIZE-fused epilogue dispatch (ISSUE 14 bandwidth half):
    ``act(norm(x)·γ+β)`` clipped/rounded onto the int8 grid with stored
    scale ``sx`` → ``(q, amax)``, all in one two-pass streaming kernel
    (ops/pallas/norm_act.py ``instance_norm_act_quant``). Same seam
    shape as :func:`pallas_instance_norm_act`: TPU backends (or
    ``P2P_TPU_FORCE_PALLAS=1``) run the Pallas kernel, everywhere else
    the lax reference runs through the SAME custom-VJP STE law — CPU
    tier-1 exercises the identical call sites and backward. Spatially
    sharded shards fall back to the reference (the quant kernel has no
    shard_map variant yet — the D families this epilogue serves are not
    spatial-sharded)."""
    from p2p_tpu.ops.pallas.norm_act import instance_norm_act_quant

    on_tpu = jax.default_backend() in ("tpu", "axon")
    force_pallas = force_pallas or os.environ.get(
        "P2P_TPU_FORCE_PALLAS") == "1"
    use_kernel = (on_tpu or force_pallas) and _sharding_mesh_for(x) is None
    return instance_norm_act_quant(
        x, sx, scale, bias, act=act, slope=slope, eps=eps,
        use_kernel=use_kernel, interpret=interpret or not on_tpu)


class PallasInstanceNorm(nn.Module):
    """Module wrapper matching :class:`p2p_tpu.ops.norm.InstanceNorm`."""

    affine: bool = False
    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        scale = bias = None
        if self.affine:
            c = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        y = pallas_instance_norm(x, scale, bias, self.epsilon)
        return y.astype(self.dtype or x.dtype)
