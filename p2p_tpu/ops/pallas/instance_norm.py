"""Pallas-fused InstanceNorm (TPU).

Target: the pix2pixHD 1024×512 config (BASELINE.json configs[3]), where
instance-norm statistics over 512×1024 spatial extents are HBM-bound and
worth fusing: one pass accumulates per-(sample, channel) sum / sum-of-squares
tiles, a second normalizes — versus XLA's default which materializes the
centered tensor.

``pallas_instance_norm`` dispatches to the kernel on TPU and to a reference
XLA implementation elsewhere (CPU tests run the kernel in interpret mode via
``force_pallas=True``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def _xla_instance_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.var(x32, axis=(1, 2), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale + bias
    return y.astype(x.dtype)


def pallas_instance_norm(
    x: jax.Array,
    scale: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    eps: float = 1e-5,
    force_pallas: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """InstanceNorm on NHWC. Uses the Pallas kernel on TPU backends."""
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if not (on_tpu or force_pallas):
        return _xla_instance_norm(x, scale, bias, eps)
    from p2p_tpu.ops.pallas.instance_norm_kernel import instance_norm_fused

    return instance_norm_fused(x, scale, bias, eps, interpret=interpret or not on_tpu)


class PallasInstanceNorm(nn.Module):
    """Module wrapper matching :class:`p2p_tpu.ops.norm.InstanceNorm`."""

    affine: bool = False
    epsilon: float = 1e-5
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        scale = bias = None
        if self.affine:
            c = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        y = pallas_instance_norm(x, scale, bias, self.epsilon)
        return y.astype(self.dtype or x.dtype)
