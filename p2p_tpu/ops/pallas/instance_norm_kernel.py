"""The fused InstanceNorm Pallas TPU kernel.

Two sequential-grid passes over NHWC data, blocked on H so arbitrarily large
spatial extents stream through VMEM:

1. stats pass — per (sample, H-block): accumulate Σx and Σx² tiles of shape
   (1, 1, 1, C) in fp32, revisiting the same output block across H-blocks
   (TPU grids execute sequentially, so first-visit init + accumulate is
   race-free).
2. normalize pass — per (sample, H-block): y = (x − μ)·rsqrt(σ² + ε)·γ + β
   with μ, σ², γ, β broadcast from (1,1,1,C) tiles.

The tiny μ/σ² computation between passes is plain jnp and fuses away.

One implementation serves both the single-device and the spatially-sharded
case: with ``axis_name`` set (call inside a shard_map whose x spec shards H
over that axis) the (N,1,1,C) stat tiles are psum'd across the axis between
the passes — the activations never cross devices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_h_block(h: int, w: int, c: int, budget_bytes: int = 1024 * 1024) -> int:
    """Largest divisor of H whose (hb, W, C) fp32 block fits the VMEM budget.

    Sized against the PADDED tile: VMEM lays the (w, c) minor dims out in
    (8, 128) tiles, so a narrow channel dim (e.g. the 32-channel local
    enhancer at 1024×512) occupies 128 lanes regardless — ignoring that
    padding overflowed scoped vmem (23.8M > 16M limit) on the pix2pixHD
    preset. The budget covers the fp32 working copy; the bf16 in/out
    blocks and double-buffering ride in the remaining headroom."""
    padded_w = -(-w // 8) * 8
    padded_c = -(-c // 128) * 128
    row_bytes = max(1, padded_w * padded_c * 4)
    max_hb = max(1, budget_bytes // row_bytes)
    for hb in range(min(h, max_hb), 0, -1):
        if h % hb == 0:
            return hb
    return 1


def _stats_kernel(x_ref, s1_ref, s2_ref):
    hb = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    s1 = jnp.sum(x, axis=(0, 1, 2))[None, None, None, :]
    s2 = jnp.sum(x * x, axis=(0, 1, 2))[None, None, None, :]

    @pl.when(hb == 0)
    def _init():
        s1_ref[...] = s1
        s2_ref[...] = s2

    @pl.when(hb != 0)
    def _acc():
        s1_ref[...] += s1
        s2_ref[...] += s2


def _norm_kernel(x_ref, mean_ref, rstd_ref, scale_ref, bias_ref, y_ref):
    x = x_ref[...].astype(jnp.float32)
    y = (x - mean_ref[...]) * rstd_ref[...]
    y = y * scale_ref[...] + bias_ref[...]
    y_ref[...] = y.astype(y_ref.dtype)


def _stats_local(x, interpret):
    """Pass 1 on the (possibly local-shard) array: per-(n,c) Σx, Σx²."""
    n, h, w, c = x.shape
    hb = _pick_h_block(h, w, c)
    x_spec = pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0))
    cvec_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (i, 0, 0, 0))
    return pl.pallas_call(
        _stats_kernel,
        grid=(n, h // hb),
        in_specs=[x_spec],
        out_specs=[cvec_spec, cvec_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((n, 1, 1, c), jnp.float32),
        ],
        interpret=interpret,
    )(x)


def _norm_local(x, mean, rstd, scale, bias, interpret):
    """Pass 2: y = (x − μ)·rstd·γ + β on the (possibly local-shard) array."""
    n, h, w, c = x.shape
    hb = _pick_h_block(h, w, c)
    x_spec = pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0))
    cvec_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (i, 0, 0, 0))
    bcast_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (0, 0, 0, 0))
    if scale is None:
        scale_t = jnp.ones((1, 1, 1, c), jnp.float32)
        bias_t = jnp.zeros((1, 1, 1, c), jnp.float32)
    else:
        scale_t = scale.reshape(1, 1, 1, c).astype(jnp.float32)
        bias_t = bias.reshape(1, 1, 1, c).astype(jnp.float32)
    return pl.pallas_call(
        _norm_kernel,
        grid=(n, h // hb),
        in_specs=[x_spec, cvec_spec, cvec_spec, bcast_spec, bcast_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, mean, rstd, scale_t, bias_t)


def _fwd_impl(x, scale, bias, eps: float, interpret: bool, axis_name=None):
    """Runs the two Pallas passes; returns (y, mean, rstd, count) with
    mean/rstd shaped (N,1,1,C) fp32. ``axis_name`` = spatial-sharded mode
    (see module docstring)."""
    n, h, w, c = x.shape
    s1, s2 = _stats_local(x, interpret)
    if axis_name is None:
        count = jnp.float32(h * w)
    else:
        s1 = jax.lax.psum(s1, axis_name)
        s2 = jax.lax.psum(s2, axis_name)
        count = float(h * w) * jax.lax.psum(
            jnp.ones((), jnp.float32), axis_name)
    mean = s1 / count
    var = jnp.maximum(s2 / count - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    y = _norm_local(x, mean, rstd, scale, bias, interpret)
    return y, mean, rstd, count


# pallas_call has no reverse-mode rule, so the fused forward carries an
# explicit instance-norm VJP (standard normalization backward; the two
# backward reductions are small and XLA-fused — psum'd across the spatial
# axis in sharded mode).
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _in_fused(x, scale, bias, eps, interpret, axis_name):
    y, _, _, _ = _fwd_impl(x, scale, bias, eps, interpret, axis_name)
    return y


def _in_fused_fwd(x, scale, bias, eps, interpret, axis_name):
    y, mean, rstd, count = _fwd_impl(x, scale, bias, eps, interpret, axis_name)
    return y, (x, scale, bias, mean, rstd, count)


def _in_fused_bwd(eps, interpret, axis_name, res, g):
    x, scale, bias, mean, rstd, count = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    xhat = (x32 - mean) * rstd
    gamma = (
        jnp.float32(1.0) if scale is None
        else scale.reshape(1, 1, 1, -1).astype(jnp.float32)
    )
    dxhat = g32 * gamma
    # means over the (possibly sharded) global (H, W) extent
    m1 = jnp.sum(dxhat, axis=(1, 2), keepdims=True)
    m2 = jnp.sum(dxhat * xhat, axis=(1, 2), keepdims=True)
    if axis_name is not None:
        m1 = jax.lax.psum(m1, axis_name)
        m2 = jax.lax.psum(m2, axis_name)
    m1 = m1 / count
    m2 = m2 / count
    dx = (rstd * (dxhat - m1 - xhat * m2)).astype(x.dtype)
    if scale is None:
        dscale = dbias = None
    else:
        # local contributions in sharded mode; shard_map's transpose of
        # the replicated scale/bias in_specs psums these across devices
        dscale = jnp.sum(g32 * xhat, axis=(0, 1, 2)).astype(scale.dtype)
        dbias = jnp.sum(g32, axis=(0, 1, 2)).astype(bias.dtype)
    return dx, dscale, dbias


_in_fused.defvjp(_in_fused_fwd, _in_fused_bwd)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def instance_norm_fused(x, scale=None, bias=None, eps: float = 1e-5,
                        interpret: bool = False):
    return _in_fused(x, scale, bias, eps, interpret, None)


def instance_norm_fused_sharded(x, scale=None, bias=None, eps: float = 1e-5,
                                axis_name: str = "spatial",
                                interpret: bool = False):
    """InstanceNorm over an H-sharded NHWC shard (call inside shard_map)."""
    return _in_fused(x, scale, bias, eps, interpret, axis_name)
