"""Fused InstanceNorm + activation (+ residual add) Pallas TPU kernels.

The round-4/5 profiles put the remaining HD-generator headroom in the
reflect-pad copies and the InstanceNorm stat/normalize passes plus the
elementwise chains that follow them: XLA fuses the norm's second pass with
the activation *sometimes*, but the residual add in the resblock tail pins
a separate full-size read-modify-write, and the activation after the affine
is a third pass whenever the norm output has two consumers. This kernel
family extends ``instance_norm_kernel.py``'s two-pass structure with the
whole post-conv epilogue folded into the normalize pass:

    y = act( (x - mu) * rsqrt(var + eps) * gamma + beta  [+ residual] )

so the conv output is read exactly twice (stats, normalize) and written
once, with the activation and the residual add riding the normalize pass's
VMEM-resident block — the conv's entire epilogue in one streaming pass.

``act`` is one of ``"none" | "relu" | "leaky"`` (LeakyReLU slope for the
discriminator chains). The residual is added BEFORE the activation —
matching both resblock tails in the zoo: the classic ResnetBlock
(``x + norm(conv)``, act="none") and ExpandNetwork's ResidualBlock
(``relu(norm(conv) + x)``).

Backward follows the repo's output-mask idiom (ops/activations.py): relu
and positive-slope leaky-relu preserve sign, so the activation mask comes
from the OUTPUT and no pre-activation tensor is kept. The rest is the
standard instance-norm VJP in XLA (small reductions, fused), exactly like
the act-free kernel. With ``axis_name`` set the stat tiles psum across a
spatial shard_map axis — same contract as ``instance_norm_fused_sharded``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from p2p_tpu.ops.pallas.instance_norm_kernel import _pick_h_block, _stats_local

ACTS = ("none", "relu", "leaky")


def _norm_act_kernel(x_ref, mean_ref, rstd_ref, scale_ref, bias_ref, y_ref,
                     *, act: str, slope: float):
    x = x_ref[...].astype(jnp.float32)
    y = (x - mean_ref[...]) * rstd_ref[...]
    y = y * scale_ref[...] + bias_ref[...]
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "leaky":
        y = jnp.where(y >= 0.0, y, slope * y)
    y_ref[...] = y.astype(y_ref.dtype)


def _norm_act_res_kernel(x_ref, res_ref, mean_ref, rstd_ref, scale_ref,
                         bias_ref, y_ref, *, act: str, slope: float):
    x = x_ref[...].astype(jnp.float32)
    y = (x - mean_ref[...]) * rstd_ref[...]
    y = y * scale_ref[...] + bias_ref[...] + res_ref[...].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "leaky":
        y = jnp.where(y >= 0.0, y, slope * y)
    y_ref[...] = y.astype(y_ref.dtype)


def _norm_act_local(x, residual, mean, rstd, scale, bias, act, slope,
                    interpret):
    """Pass 2 with the fused epilogue on the (possibly local-shard) array."""
    n, h, w, c = x.shape
    hb = _pick_h_block(h, w, c)
    x_spec = pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0))
    cvec_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (i, 0, 0, 0))
    bcast_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (0, 0, 0, 0))
    if scale is None:
        scale_t = jnp.ones((1, 1, 1, c), jnp.float32)
        bias_t = jnp.zeros((1, 1, 1, c), jnp.float32)
    else:
        scale_t = scale.reshape(1, 1, 1, c).astype(jnp.float32)
        bias_t = bias.reshape(1, 1, 1, c).astype(jnp.float32)
    if residual is None:
        kern = functools.partial(_norm_act_kernel, act=act, slope=slope)
        in_specs = [x_spec, cvec_spec, cvec_spec, bcast_spec, bcast_spec]
        args = (x, mean, rstd, scale_t, bias_t)
    else:
        kern = functools.partial(_norm_act_res_kernel, act=act, slope=slope)
        in_specs = [x_spec, x_spec, cvec_spec, cvec_spec, bcast_spec,
                    bcast_spec]
        args = (x, residual, mean, rstd, scale_t, bias_t)
    return pl.pallas_call(
        kern,
        grid=(n, h // hb),
        in_specs=in_specs,
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(*args)


def _fwd_impl(x, scale, bias, residual, act, slope, eps, interpret,
              axis_name):
    n, h, w, c = x.shape
    s1, s2 = _stats_local(x, interpret)
    if axis_name is None:
        count = jnp.float32(h * w)
    else:
        s1 = jax.lax.psum(s1, axis_name)
        s2 = jax.lax.psum(s2, axis_name)
        count = float(h * w) * jax.lax.psum(
            jnp.ones((), jnp.float32), axis_name)
    mean = s1 / count
    var = jnp.maximum(s2 / count - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    y = _norm_act_local(x, residual, mean, rstd, scale, bias, act, slope,
                        interpret)
    return y, mean, rstd, count


# pallas_call has no reverse-mode rule — explicit VJP, like the act-free
# kernel. The activation mask comes from the saved OUTPUT (sign-preserving
# acts only — module docstring); the residual's cotangent is the masked
# upstream cotangent, free of the norm chain.
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _in_act_fused(x, scale, bias, residual, act, slope, eps, interpret,
                  axis_name):
    y, _, _, _ = _fwd_impl(x, scale, bias, residual, act, slope, eps,
                           interpret, axis_name)
    return y


def _in_act_fused_fwd(x, scale, bias, residual, act, slope, eps, interpret,
                      axis_name):
    y, mean, rstd, count = _fwd_impl(x, scale, bias, residual, act, slope,
                                     eps, interpret, axis_name)
    # zero-sized dtype carrier (ops/int8.py idiom): the backward needs the
    # residual's presence + dtype, never its values
    res_tok = None if residual is None else jnp.zeros((0,), residual.dtype)
    return y, (x, scale, bias, res_tok, y, mean, rstd, count)


def _in_act_fused_bwd(act, slope, eps, interpret, axis_name, res, g):
    x, scale, bias, res_tok, y, mean, rstd, count = res
    g32 = g.astype(jnp.float32)
    if act == "relu":
        # grad 0 at y==0 — matches ops/activations.relu_y
        g32 = jnp.where(y > 0, g32, 0.0)
    elif act == "leaky":
        g32 = jnp.where(y >= 0, g32, slope * g32)
    x32 = x.astype(jnp.float32)
    xhat = (x32 - mean) * rstd
    gamma = (
        jnp.float32(1.0) if scale is None
        else scale.reshape(1, 1, 1, -1).astype(jnp.float32)
    )
    dxhat = g32 * gamma
    m1 = jnp.sum(dxhat, axis=(1, 2), keepdims=True)
    m2 = jnp.sum(dxhat * xhat, axis=(1, 2), keepdims=True)
    if axis_name is not None:
        m1 = jax.lax.psum(m1, axis_name)
        m2 = jax.lax.psum(m2, axis_name)
    m1 = m1 / count
    m2 = m2 / count
    dx = (rstd * (dxhat - m1 - xhat * m2)).astype(x.dtype)
    if scale is None:
        dscale = dbias = None
    else:
        dscale = jnp.sum(g32 * xhat, axis=(0, 1, 2)).astype(scale.dtype)
        dbias = jnp.sum(g32, axis=(0, 1, 2)).astype(bias.dtype)
    # the residual bypasses the norm entirely: its cotangent is the
    # act-masked upstream cotangent
    dres = None if res_tok is None else g32.astype(res_tok.dtype)
    return dx, dscale, dbias, dres


_in_act_fused.defvjp(_in_act_fused_fwd, _in_act_fused_bwd)


def _check_act(act: str, slope: float) -> None:
    if act not in ACTS:
        raise ValueError(f"act must be one of {ACTS}, got {act!r}")
    if act == "leaky" and slope <= 0:
        raise ValueError(
            f"leaky needs slope > 0 (got {slope}); the output-based "
            "gradient mask is only valid for sign-preserving activations")


@functools.partial(jax.jit,
                   static_argnames=("act", "slope", "eps", "interpret"))
def instance_norm_act_fused(x, scale=None, bias=None, residual=None,
                            act: str = "none", slope: float = 0.2,
                            eps: float = 1e-5, interpret: bool = False):
    """Fused ``act(instance_norm(x)·γ+β [+ residual])`` on NHWC (TPU)."""
    _check_act(act, slope)
    return _in_act_fused(x, scale, bias, residual, act, slope, eps,
                         interpret, None)


def instance_norm_act_fused_sharded(x, scale=None, bias=None, residual=None,
                                    act: str = "none", slope: float = 0.2,
                                    eps: float = 1e-5,
                                    axis_name: str = "spatial",
                                    interpret: bool = False):
    """The fused epilogue over an H-sharded NHWC shard (inside shard_map);
    the residual must be sharded like ``x``."""
    _check_act(act, slope)
    return _in_act_fused(x, scale, bias, residual, act, slope, eps,
                         interpret, axis_name)


# ----------------------------------------------------- quantize-fused
# ISSUE 14, the bandwidth half: when the conv that CONSUMES a norm+act
# epilogue runs on the delayed-int8 path, the activation's clip/round
# quantize is one more elementwise pass XLA cannot fuse into the
# pallas_call producer — a full-size read+write the newly quantized
# layer would pay on top of the epilogue. This variant folds [normalize
# · affine · activation · clip/round quantize · amax measurement] into
# the SAME two-pass streaming kernel: the conv output is still read
# exactly twice (stats, normalize) and written once — but what is
# written is the activation already on the int8 grid, plus per-block
# amax partials (the delayed-scale update proposal) reduced outside on
# the tiny tile tensor.
#
# The quantized activation is carried in the COMPUTE dtype (bf16/f32)
# holding exact integer values in [-127, 127]: an int8-dtype output
# would surface float0 tangents at the op boundary and sever autodiff —
# the consumer (ops/int8.py ``int8_conv_pq``) converts to int8 in its
# operand read, a pure elementwise cast. The activation value is rounded
# THROUGH the compute dtype before the quantize (y.astype(x.dtype)) so
# the fused path is bitwise-equal to [unfused epilogue → int8_conv_ds].
#
# Backward mirrors the existing delayed-int8 STE law (ops/int8.py): the
# incoming cotangent is w.r.t. the dequantized surrogate sx·q and passes
# straight through clip/round; the activation mask is recomputed from
# the pre-activation (x, mean, rstd and the affine are residuals — the
# quantized output cannot mask: round() kills the sign information near
# zero), then the standard instance-norm VJP. ``sx`` is state (a stored
# amax), so its cotangent is zero, exactly like ``int8_conv_ds``.


def _norm_act_quant_kernel(x_ref, mean_ref, rstd_ref, scale_ref, bias_ref,
                           sx_ref, y_ref, am_ref, *, act: str, slope: float):
    x = x_ref[...].astype(jnp.float32)
    y = (x - mean_ref[...]) * rstd_ref[...]
    y = y * scale_ref[...] + bias_ref[...]
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "leaky":
        y = jnp.where(y >= 0.0, y, slope * y)
    # round through the activation dtype FIRST — bitwise what the
    # unfused [epilogue module → int8_conv_ds] chain quantizes
    yc = y.astype(y_ref.dtype).astype(jnp.float32)
    q = jnp.clip(jnp.round(yc / sx_ref[...]), -127.0, 127.0)
    y_ref[...] = q.astype(y_ref.dtype)
    am_ref[0, 0] = jnp.max(jnp.abs(yc))


def _norm_act_quant_local(x, mean, rstd, scale, bias, sx, act, slope,
                          interpret):
    """Pass 2 with the quantize-fused epilogue: emits the on-grid
    activation (compute dtype) AND the per-block amax partials."""
    n, h, w, c = x.shape
    hb = _pick_h_block(h, w, c)
    x_spec = pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0))
    cvec_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (i, 0, 0, 0))
    bcast_spec = pl.BlockSpec((1, 1, 1, c), lambda i, j: (0, 0, 0, 0))
    am_spec = pl.BlockSpec((1, 1), lambda i, j: (i, j))
    if scale is None:
        scale_t = jnp.ones((1, 1, 1, c), jnp.float32)
        bias_t = jnp.zeros((1, 1, 1, c), jnp.float32)
    else:
        scale_t = scale.reshape(1, 1, 1, c).astype(jnp.float32)
        bias_t = bias.reshape(1, 1, 1, c).astype(jnp.float32)
    sx_t = jnp.asarray(sx, jnp.float32).reshape(1, 1, 1, 1)
    kern = functools.partial(_norm_act_quant_kernel, act=act, slope=slope)
    yq, am = pl.pallas_call(
        kern,
        grid=(n, h // hb),
        in_specs=[x_spec, cvec_spec, cvec_spec, bcast_spec, bcast_spec,
                  pl.BlockSpec((1, 1, 1, 1), lambda i, j: (0, 0, 0, 0))],
        out_specs=[x_spec, am_spec],
        out_shape=[jax.ShapeDtypeStruct(x.shape, x.dtype),
                   jax.ShapeDtypeStruct((n, h // hb), jnp.float32)],
        interpret=interpret,
    )(x, mean, rstd, scale_t, bias_t, sx_t)
    return yq, jnp.max(am)


def _quant_fwd_impl(x, scale, bias, sx, act, slope, eps, use_kernel,
                    interpret):
    sx = jnp.maximum(jnp.asarray(sx, jnp.float32), 1e-12)
    if use_kernel:
        n, h, w, c = x.shape
        s1, s2 = _stats_local(x, interpret)
        count = jnp.float32(h * w)
        mean = s1 / count
        var = jnp.maximum(s2 / count - mean * mean, 0.0)
        rstd = jax.lax.rsqrt(var + eps)
        yq, amax = _norm_act_quant_local(x, mean, rstd, scale, bias, sx,
                                         act, slope, interpret)
        return yq, amax, mean, rstd, count
    # the lax reference — same op order as the unfused CPU chain
    # (instance_norm._xla_instance_norm_act → quantize): jnp moments,
    # normalize, affine, activation, cast to the activation dtype, THEN
    # clip/round — bitwise what [make_norm_act → int8_conv_ds] computes
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.var(x32, axis=(1, 2), keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x32 - mean) * rstd
    if scale is not None:
        y = y * scale.reshape(1, 1, 1, -1) + bias.reshape(1, 1, 1, -1)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "leaky":
        y = jnp.where(y >= 0.0, y, slope * y)
    yc = y.astype(x.dtype).astype(jnp.float32)
    yq = jnp.clip(jnp.round(yc / sx), -127.0, 127.0).astype(x.dtype)
    amax = jnp.max(jnp.abs(yc))
    count = jnp.float32(x.shape[1] * x.shape[2])
    return yq, amax, mean, rstd, count


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _in_act_quant(x, scale, bias, sx, act, slope, eps, use_kernel,
                  interpret):
    yq, amax, _, _, _ = _quant_fwd_impl(x, scale, bias, sx, act, slope,
                                        eps, use_kernel, interpret)
    return yq, amax


def _in_act_quant_fwd(x, scale, bias, sx, act, slope, eps, use_kernel,
                      interpret):
    yq, amax, mean, rstd, count = _quant_fwd_impl(
        x, scale, bias, sx, act, slope, eps, use_kernel, interpret)
    return (yq, amax), (x, scale, bias, mean, rstd, count)


def _in_act_quant_bwd(act, slope, eps, use_kernel, interpret, res, ct):
    g, _ = ct  # the amax output feeds a state update, never a loss
    x, scale, bias, mean, rstd, count = res
    # STE through clip/round: the incoming cotangent is w.r.t. the
    # dequantized surrogate sx·q ≈ y and passes through unchanged — the
    # composition with int8_conv_pq's surrogate-cotangent convention IS
    # the unfused int8_conv_ds VJP law.
    g32 = g.astype(jnp.float32)
    x32 = x.astype(jnp.float32)
    xhat = (x32 - mean) * rstd
    gamma = (
        jnp.float32(1.0) if scale is None
        else scale.reshape(1, 1, 1, -1).astype(jnp.float32)
    )
    beta = (
        jnp.float32(0.0) if bias is None
        else bias.reshape(1, 1, 1, -1).astype(jnp.float32)
    )
    # activation mask from the recomputed PRE-activation (the saved
    # output is quantized — round() erases the sign near zero); for the
    # sign-preserving acts this is the same mask the output-based law
    # (ops/activations.py) computes: y > 0 ⇔ h > 0, y ≥ 0 ⇔ h ≥ 0
    h = xhat * gamma + beta
    if act == "relu":
        g32 = jnp.where(h > 0, g32, 0.0)
    elif act == "leaky":
        g32 = jnp.where(h >= 0, g32, slope * g32)
    dxhat = g32 * gamma
    m1 = jnp.sum(dxhat, axis=(1, 2), keepdims=True) / count
    m2 = jnp.sum(dxhat * xhat, axis=(1, 2), keepdims=True) / count
    dx = (rstd * (dxhat - m1 - xhat * m2)).astype(x.dtype)
    if scale is None:
        dscale = dbias = None
    else:
        dscale = jnp.sum(g32 * xhat, axis=(0, 1, 2)).astype(scale.dtype)
        dbias = jnp.sum(g32, axis=(0, 1, 2)).astype(bias.dtype)
    # sx is state (a stored amax), not a trained parameter
    return dx, dscale, dbias, jnp.zeros((), jnp.float32)


_in_act_quant.defvjp(_in_act_quant_fwd, _in_act_quant_bwd)


def instance_norm_act_quant(x, sx, scale=None, bias=None,
                            act: str = "none", slope: float = 0.2,
                            eps: float = 1e-5, use_kernel: bool = False,
                            interpret: bool = False):
    """Quantize-fused ``act(instance_norm(x)·γ+β)`` → ``(q, amax)``:
    the activation clipped/rounded onto the int8 grid with stored scale
    ``sx`` (values in [-127,127], carried in ``x.dtype``) plus the max
    |activation| measured in the same pass. ``use_kernel`` selects the
    Pallas two-pass kernel (``interpret=True`` off-TPU); otherwise the
    lax reference with the SAME custom-VJP STE law. Feed ``q`` to
    ``ops.int8.int8_conv_pq`` with the same ``sx``."""
    _check_act(act, slope)
    return _in_act_quant(x, scale, bias, sx, act, slope, eps, use_kernel,
                         interpret)
