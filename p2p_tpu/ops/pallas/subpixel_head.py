"""Pallas TPU kernel for the U-Net image head: ConvTranspose(k4,s2) to a
thin channel count, in the subpixel (k2-s1 conv → shifted interleave)
form, with the k² tap matmuls fused in VMEM.

Why a kernel: the image-producing head (128ch @128² → 3ch @256², ~4 ms of
the 256²/bs=128 train step) is HBM-bound — XLA's deconv reads the input at
~390 GB/s forward (≈2.4 reads of x per pass) and its transposed-conv
backward materializes spatial ``reverse`` copies. Every useful formulation
is a couple of (P,C)·(C,4F) matmuls; what costs is the traffic. These
kernels read each operand ONCE per pass and write only the tap tensor; the
shifted depth-to-space stays a cheap jnp pass outside (ops/conv.py
subpixel_interleave).

Three designs were carried to hardware before this one:

- v1 (round 3) folded the tap tensor to ``(H+1, (W+1)·4F)`` for a
  lane-dense accumulator; Mosaic rejects that lane-regrouping cast
  ("infer-vector-layout: unsupported shape cast") — re-probed on the
  round-4 runtime, same error.
- v2 (H-banded, major-dim reshapes only, halo row via a second BlockSpec)
  COMPILED — the first on-hardware run of this kernel family — but
  measured 921 img/s vs 1708 baseline: its per-tap slices shift the
  SUBLANE dim of the full-width activation (W is not 8-aligned), which
  Mosaic lowers to large VPU shuffle chains, and its dW contraction runs
  over the major (position) dim, forcing an in-kernel transpose.

v3 (this file) keeps v2's banding/halo structure and removes both costs:

- all in-kernel widths are padded to multiples of 8, so every reshape is
  a pure relabeling of sublane tiles;
- forward: ONE matmul per band against the channel-major weight matrix
  ``(C, 4·4F)`` produces the tap tensor t; the (dh, dw) shifts land on t
  (4F lanes — 10× smaller than shifting x) as static offset slices + adds
  (in-kernel ``jnp.pad`` is rejected by Mosaic as an offset-mismatched
  concatenate — everything is expressed as slices of a common width);
- dx: the tap-form mirror — ONE matmul ``dz·Wᵀ_all`` into 4·C lanes, then
  the four shifts fold its 128-ALIGNED lane blocks into the band;
- dW is NOT a Pallas kernel: contracting over positions wants positions
  on lanes (an in-kernel transpose — the v2 killer), and XLA's native
  conv weight-gradient already reads x and dz once. ``_bwd`` takes the
  wgrad from ``jax.vjp`` of the plain XLA conv; only its dx/primal paths
  are replaced.

STATUS (round 4, v5e runtime): v3 compiles AND runs — measured
1129.8 img/s as the 256²/bs=128 train-step head vs 1708 for the XLA
deconv head (v2: 921). The remaining cost is structural on this Mosaic
version: the ±1 offset slices of the tap tensors are sublane-shift chains
on multi-MB vectors, executed once per (sample × band) grid step, and the
custom call additionally breaks XLA's fusions around the head (the ReLU
backward and pad ops that normally fuse into the deconv kernels fall out
as standalone passes). Keep the XLA head in production; the kernel stays
behind ``head_pallas`` / ``BENCH_HPAL=1`` for re-measurement on future
runtimes. Interpret-mode equivalence (fwd + both grads vs the XLA conv)
is pinned by tests/test_ops.py.

The halo trick (unchanged from v2): the k2 conv's one-row band overlap is
fed as a SECOND BlockSpec onto the same padded operand — block shape 1 in
the row dim, so the index map addresses the single halo row ``(hb+1)·B``
directly. No overlapping block windows, no manual DMA. Bands are
zero-padded to ``nh·B`` rows; padded rows compute garbage that is sliced
off (forward) or zeros that contribute nothing (backward).

Weight layout matches ``SubpixelDeconv``'s inner conv (HWIO (2,2,C,4F)) so
the module's param tree — and the documented ConvTranspose weight mapping
(tests/test_ops.py) — is unchanged. Tap matmuls and accumulators are f32
(the XLA conv this replaces also accumulates in f32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_band(rows: int, target: int) -> int:
    """Band height ≈ ``target`` rows; whole tensor if it already fits."""
    if rows <= target:
        return rows
    import math

    return math.ceil(rows / math.ceil(rows / target))


def _align8(v: int) -> int:
    return -(-v // 8) * 8


_FWD_BAND = 32
_DX_BAND = 16


def _fwd_kernel(xm_ref, xh_ref, wall_ref, z_ref):
    """One (sample, band): t = x·W_all, then the 4 tap shifts fold t into
    the band's z rows. Shifts are static offset SLICES of the 4F-lane tap
    tensor only (no pads/concats — Mosaic rejects in-kernel pad as an
    offset-mismatched concatenate)."""
    _, bb, wpa, c = xm_ref.shape         # (1, B, WP, C) — WP 8-aligned
    wout = z_ref.shape[2]                # WP - 1
    f4 = z_ref.shape[-1]
    xfull = jnp.concatenate([xm_ref[0], xh_ref[0]], axis=0)   # (B+1, WP, c)
    wall = wall_ref[...].astype(xfull.dtype)                  # (c, 4·f4)
    t = jax.lax.dot(
        xfull.reshape((bb + 1) * wpa, c), wall,
        preferred_element_type=jnp.float32,
    ).reshape(bb + 1, wpa, 4 * f4)
    # z[h, w] = Σ_{dh,dw} t[h+dh, w+dw, (2·dh+dw)·f4 : +f4]
    z_ref[0] = (
        t[0:bb, 0:wout, 0:f4]
        + t[0:bb, 1:wout + 1, f4:2 * f4]
        + t[1:bb + 1, 0:wout, 2 * f4:3 * f4]
        + t[1:bb + 1, 1:wout + 1, 3 * f4:4 * f4]
    )


def _bwd_dx_kernel(dzm_ref, dzh_ref, wtall_ref, dxp_ref):
    """One (sample, band) of dxp — the tap-form mirror of the forward:
    u = dz·Wᵀ_all (one matmul, 4·C output lanes), then the 4 shifts fold
    u's 128-aligned lane blocks into the band. Shifts are offset slices
    of u's sublane dim; lane selection stays tile-aligned."""
    _, bb, wz, f4 = dzm_ref.shape        # (1, B2, WZ, f4)
    _, _, wpx, c = dxp_ref.shape         # (1, B2, WPX, c)
    dzfull = jnp.concatenate([dzm_ref[0], dzh_ref[0]], axis=0)
    wtall = wtall_ref[...]               # (f4, 4·c), f32
    u = jax.lax.dot(
        dzfull.reshape((bb + 1) * wz, f4).astype(jnp.float32), wtall,
        preferred_element_type=jnp.float32,
    ).reshape(bb + 1, wz, 4 * c)
    # dxp[r, s] = Σ_{dh,dw} u[r+1-dh, s+1-dw, (2·dh+dw)·c : +c]
    acc = (
        u[1:1 + bb, 1:1 + wpx, 0:c]
        + u[1:1 + bb, 0:wpx, c:2 * c]
        + u[0:bb, 1:1 + wpx, 2 * c:3 * c]
        + u[0:bb, 0:wpx, 3 * c:4 * c]
    )
    dxp_ref[0] = acc.astype(dxp_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def subpixel_head_conv(x: jax.Array, w: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """The k2-s1 pad-1 conv of the subpixel head on the Pallas path.

    x: (N,H,W,C); w: (2,2,C,4F) HWIO. Returns (N,H+1,W+1,4F) in f32 —
    feed to ``subpixel_interleave`` (cast afterwards if needed).
    """
    z, _ = _fwd(x, w, interpret)
    return z


def _fwd(x, w, interpret):
    n, h, wd, c = x.shape
    f4 = w.shape[-1]
    ho, wo = h + 1, wd + 1
    bb = _pick_band(ho, _FWD_BAND)
    nh = -(-ho // bb)
    wpa = _align8(wd + 2)
    xp = jnp.pad(x, ((0, 0), (1, nh * bb + 1 - (h + 1)),
                     (1, wpa - 1 - wd), (0, 0)))
    # W_all[c, (2·dh+dw)·f4+f] = w[dh, dw, c, f]
    wall = jnp.transpose(w, (2, 0, 1, 3)).reshape(c, 4 * f4)
    wout = wpa - 1
    zf = pl.pallas_call(
        _fwd_kernel,
        grid=(n, nh),
        in_specs=[
            pl.BlockSpec((1, bb, wpa, c), lambda i, hb: (i, hb, 0, 0)),
            pl.BlockSpec((1, 1, wpa, c),
                         lambda i, hb, _bb=bb: (i, (hb + 1) * _bb, 0, 0)),
            pl.BlockSpec((c, 4 * f4), lambda i, hb: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bb, wout, f4), lambda i, hb: (i, hb, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, nh * bb, wout, f4), jnp.float32),
        interpret=interpret,
    )(xp, xp, wall)
    return zf[:, :ho, :wo], (x, w)


def _bwd(interpret, res, dz):
    x, w = res
    n, h, wd, c = x.shape
    f4 = w.shape[-1]
    ho, wo = h + 1, wd + 1
    hp = h + 2
    dzf = dz.astype(jnp.float32)

    # ---- dx: band over the padded-input rows -----------------------------
    b2 = _pick_band(hp, _DX_BAND)
    nh2 = -(-hp // b2)
    wpx = _align8(wd + 2)
    wz = _align8(wpx + 1)
    # dzp2[i, j] = dz[i-1, j-1], rows padded through the last band's halo
    dzp2 = jnp.pad(dzf, ((0, 0), (1, nh2 * b2 + 1 - (ho + 1)),
                         (1, wz - 1 - wo), (0, 0)))
    # Wᵀ_all[f, (2·dh+dw)·c + ch] = w[dh, dw, ch, f]
    wtall = jnp.transpose(w.astype(jnp.float32), (3, 0, 1, 2)).reshape(
        f4, 4 * c)
    dxp = pl.pallas_call(
        _bwd_dx_kernel,
        grid=(n, nh2),
        in_specs=[
            pl.BlockSpec((1, b2, wz, f4), lambda i, hb: (i, hb, 0, 0)),
            pl.BlockSpec((1, 1, wz, f4),
                         lambda i, hb, _b2=b2: (i, (hb + 1) * _b2, 0, 0)),
            pl.BlockSpec((f4, 4 * c), lambda i, hb: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, b2, wpx, c), lambda i, hb: (i, hb, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, nh2 * b2, wpx, c), x.dtype),
        interpret=interpret,
    )(dzp2, dzp2, wtall)
    dx = dxp[:, 1:1 + h, 1:1 + wd, :]

    # ---- dW: XLA's native conv weight-gradient ---------------------------
    # Contracting over positions on the MXU wants positions on lanes — an
    # in-kernel transpose (the v2 performance killer). XLA's wgrad conv
    # reads x and dz once; let it have this contraction.
    def conv_w(w_):
        return jax.lax.conv_general_dilated(
            x, w_, (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    out_aval = jax.eval_shape(conv_w, w)
    # linear_transpose: the wgrad alone, with no dead primal forward
    wvjp = jax.linear_transpose(conv_w, w)
    (dw,) = wvjp(dzf.astype(out_aval.dtype))
    return dx, dw.astype(w.dtype)


subpixel_head_conv.defvjp(_fwd, _bwd)
