"""Pallas TPU kernel for the U-Net image head: ConvTranspose(k4,s2) to a
thin channel count, in the subpixel (k2-s1 conv → shifted interleave)
form, with the k² tap matmuls fused in VMEM.

Why a kernel: the image-producing head (128ch @128² → 3ch @256², ~4 ms of
the 256²/bs=128 train step) is HBM-bound — XLA's deconv reads the input at
~390 GB/s forward and its transposed-conv backward materializes spatial
``reverse`` copies. Every useful formulation is a couple of (P,C)·(C,4F)
matmuls; what costs is the traffic. This kernel reads x ONCE per sample,
accumulates the 4 tap matmuls in VMEM, and writes only the tap tensor;
the shifted depth-to-space stays a cheap jnp pass outside
(ops/conv.py subpixel_interleave).

Layout: the tap tensor keeps 4F (e.g. 12) in the LANE dim only folded
into W — ``(H+1, (W+1)·4F)`` — because a trailing 12-channel dim would
pad to 128 lanes and blow a full-sample f32 accumulator to ~9.5 MB; the
folded layout is lane-dense (0.9 MB), so one sample per grid step fits
scoped VMEM with room for double-buffered inputs. Callers reshape
``(N, H+1, (W+1)·4F) ↔ (N, H+1, W+1, 4F)`` outside (contiguous, free).

Backward: dx re-plays the taps transposed (one write of dx, f32 local
canvas); dW accumulates across the sequential sample grid — race-free
because TPU grids execute in order (same pattern as the InstanceNorm
stats kernel).

Weight layout matches ``SubpixelDeconv``'s inner conv (HWIO (2,2,C,4F)) so
the module's param tree — and the documented ConvTranspose weight mapping
(tests/test_ops.py) — is unchanged. Tap matmuls and the accumulator are
f32 (the XLA conv this replaces also accumulates in f32).

STATUS (round 3, v5e runtime): correct in interpret mode (fwd + both
grads vs the XLA conv, tests/test_ops.py), but the CURRENT Mosaic
compiler rejects the layout with "infer-vector-layout: unsupported
shape cast" — the (H·W, C) ↔ (H, W·4F) folds cross the sublane/lane
tiling at the head's 129-row shape (odd spatial extents), and every
layout that avoids the fold re-inflates the lane-padded accumulator
(4F=12 pads to 128 lanes → ~9.5 MB f32) past the ~16 MB scoped-VMEM
budget alongside double-buffered inputs, or degrades accumulation to
bf16. Gated off the TPU path in ops/conv.py until Mosaic grows the
cast; the XLA deconv head (measured equal-best, BASELINE ledger)
remains the production path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(xp_ref, w_ref, z_ref):
    """One sample: z[h, w·4F] = Σ_taps xp[h+dh, w+dw, :] @ w[dh,dw]."""
    _, hp, wp, c = xp_ref.shape          # (1, H+2, W+2, C)
    _, ho, wf = z_ref.shape              # (1, H+1, (W+1)·4F)
    f4 = w_ref.shape[-1]
    wo = wf // f4
    xp = xp_ref[0]
    w = w_ref[...].astype(xp.dtype)
    acc = jnp.zeros((ho * wo, f4), jnp.float32)
    for dh in range(2):
        for dw in range(2):
            xs = xp[dh:dh + ho, dw:dw + wo, :].reshape(ho * wo, c)
            acc += jax.lax.dot(
                xs, w[dh, dw], preferred_element_type=jnp.float32
            )
    z_ref[0] = acc.reshape(ho, wf)


def _bwd_dx_kernel(dz_ref, w_ref, dxp_ref):
    """One sample: dxp[h+dh, w+dw, :] += dz[h,w,:] @ w[dh,dw]ᵀ."""
    _, ho, wf = dz_ref.shape
    _, hp, wp, c = dxp_ref.shape
    f4 = w_ref.shape[-1]
    wo = wf // f4
    dz = dz_ref[0].reshape(ho * wo, f4)
    w = w_ref[...].astype(jnp.float32)
    acc = jnp.zeros((hp, wp, c), jnp.float32)
    for dh in range(2):
        for dw in range(2):
            part = jax.lax.dot(
                dz, w[dh, dw].T, preferred_element_type=jnp.float32
            ).reshape(ho, wo, c)
            acc = acc.at[dh:dh + ho, dw:dw + wo, :].add(part)
    dxp_ref[0] = acc.astype(dxp_ref.dtype)


def _bwd_dw_kernel(xp_ref, dz_ref, dw_ref):
    """dW[dh,dw] = Σ_samples xpᵀ_shifted · dz, accumulated across the
    sequential sample grid (first-visit init, then +=)."""
    n = pl.program_id(0)
    _, hp, wp, c = xp_ref.shape
    _, ho, wf = dz_ref.shape
    f4 = dw_ref.shape[-1]
    wo = wf // f4
    xp = xp_ref[0]
    dz = dz_ref[0].reshape(ho * wo, f4).astype(jnp.float32)
    parts = []
    for dh in range(2):
        for dw in range(2):
            xs = xp[dh:dh + ho, dw:dw + wo, :].reshape(ho * wo, c)
            parts.append(jax.lax.dot(
                xs.T.astype(jnp.float32), dz,
                preferred_element_type=jnp.float32))
    dw_now = jnp.stack(parts).reshape(2, 2, c, f4)

    @pl.when(n == 0)
    def _init():
        dw_ref[...] = dw_now

    @pl.when(n != 0)
    def _acc():
        dw_ref[...] += dw_now


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def subpixel_head_conv(x: jax.Array, w: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """The k2-s1 pad-1 conv of the subpixel head on the Pallas path.

    x: (N,H,W,C); w: (2,2,C,4F) HWIO. Returns (N,H+1,W+1,4F) in f32 —
    feed to ``subpixel_interleave`` (cast afterwards if needed).
    """
    z, _ = _fwd(x, w, interpret)
    return z


def _fwd(x, w, interpret):
    n, h, wd, c = x.shape
    f4 = w.shape[-1]
    ho, wo = h + 1, wd + 1
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    zf = pl.pallas_call(
        _fwd_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((2, 2, c, f4), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, ho, wo * f4), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ho, wo * f4), jnp.float32),
        interpret=interpret,
    )(xp, w)
    return zf.reshape(n, ho, wo, f4), (x, w)


def _bwd(interpret, res, dz):
    x, w = res
    n, h, wd, c = x.shape
    f4 = w.shape[-1]
    ho, wo = h + 1, wd + 1
    dzf = dz.astype(jnp.float32).reshape(n, ho, wo * f4)
    dxp = pl.pallas_call(
        _bwd_dx_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, ho, wo * f4), lambda i: (i, 0, 0)),
            pl.BlockSpec((2, 2, c, f4), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h + 2, wd + 2, c),
                               lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h + 2, wd + 2, c), x.dtype),
        interpret=interpret,
    )(dzf, w)
    dx = dxp[:, 1:1 + h, 1:1 + wd, :]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    dw = pl.pallas_call(
        _bwd_dw_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, ho, wo * f4), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 2, c, f4), lambda i: (0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, 2, c, f4), jnp.float32),
        interpret=interpret,
    )(xp, dzf)
    return dx, dw.astype(w.dtype)


subpixel_head_conv.defvjp(_fwd, _bwd)
