"""Pixel shuffle / unshuffle (depth↔space) in NHWC.

Reference: ``PixelUnshuffle`` at networks.py:173-200 builds a one-hot conv
kernel and runs a strided grouped conv to do space-to-depth; ``PixelShuffle``
is torch's builtin used inside CompressionNetwork (networks.py:219).

On TPU a conv is the wrong tool for a pure data-movement op — a
reshape+transpose lowers to an XLA transpose the compiler can fuse or even
elide into neighboring layouts. Channel ordering matches torch's
``F.pixel_shuffle``/``F.pixel_unshuffle`` (for weight-porting parity):
unshuffle output channel index is ``c * r^2 + dy * r + dx``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pixel_unshuffle(x: jax.Array, factor: int) -> jax.Array:
    """NHWC space-to-depth: (N,H,W,C) -> (N,H/r,W/r,C*r²)."""
    n, h, w, c = x.shape
    r = factor
    if h % r or w % r:
        raise ValueError(f"spatial dims {(h, w)} not divisible by {r}")
    x = x.reshape(n, h // r, r, w // r, r, c)
    # -> (N, H/r, W/r, c, dy, dx): flattening the last three axes yields
    # channel index c*r² + dy*r + dx, torch's ordering.
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(n, h // r, w // r, c * r * r)


def pixel_shuffle(x: jax.Array, factor: int) -> jax.Array:
    """NHWC depth-to-space: (N,H,W,C*r²) -> (N,H*r,W*r,C). Inverse of
    :func:`pixel_unshuffle` with torch channel ordering."""
    n, h, w, crr = x.shape
    r = factor
    if crr % (r * r):
        raise ValueError(f"channels {crr} not divisible by {r * r}")
    c = crr // (r * r)
    x = x.reshape(n, h, w, c, r, r)
    x = x.transpose(0, 1, 4, 2, 5, 3)  # (N, H, dy, W, dx, C)
    return x.reshape(n, h * r, w * r, c)
