"""Bit-depth quantizer — the core "compression" op of the pipeline.

Reference: ``compress(tensor, bit)`` at generate_dataset.py:29-34 —
``round(clamp(x, 0, 1) * (2^b - 1)) / (2^b - 1)``. It is used offline to
build the ``b/`` dataset halves and *inside* the train loop (train.py:297).

The reference version is non-differentiable (``round`` has zero gradient,
SURVEY Q2), which silently kills learning of the compression pre-filter.
Here the quantizer comes in two flavors:

- :func:`quantize` — exact reference semantics, zero gradient through round.
- :func:`quantize_ste` — straight-through estimator ``custom_vjp``: forward
  identical, backward passes gradients through unchanged *inside* the clamp
  range and zeroes them outside (the clamp's true gradient). This is the
  intended behavior and the default (ModelConfig.quant_ste).

Both are pure elementwise jnp — XLA fuses them into whatever producer or
consumer op is adjacent; no Pallas needed (memory-bound, zero FLOPs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _levels(bits: int) -> float:
    return float(2**bits - 1)


def quantize(x: jax.Array, bits: int = 3) -> jax.Array:
    """Reference-exact quantizer: clamp to [0,1], round to 2^bits-1 levels."""
    n = _levels(bits)
    return jnp.round(jnp.clip(x, 0.0, 1.0) * n) / n


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def quantize_ste(x: jax.Array, bits: int = 3) -> jax.Array:
    """Quantizer with a straight-through gradient estimator."""
    return quantize(x, bits)


def _ste_fwd(x, bits):
    return quantize(x, bits), x


def _ste_bwd(bits, x, g):
    # Straight-through inside the clamp's active range, zero outside —
    # matches d/dx clip(x,0,1) while treating round as identity.
    del bits
    mask = jnp.logical_and(x >= 0.0, x <= 1.0)
    return (jnp.where(mask, g, jnp.zeros_like(g)),)


quantize_ste.defvjp(_ste_fwd, _ste_bwd)


def dequantize_levels(x: jax.Array, bits: int = 3) -> jax.Array:
    """Map quantized [0,1] values to integer level indices (inverse helper)."""
    return jnp.round(x * _levels(bits)).astype(jnp.int32)
