"""Sobel edge magnitude + angular loss (completeness parity).

Both are *dead code* in the reference (call sites commented out —
SURVEY §2.1 #29/#30) but part of its capability surface:

- ``sobelLayer`` (networks.py:852-868): fixed Sobel filters on the first
  channel of a single image, zero padding, magnitude sqrt(Gx²+Gy²).
- ``angular_loss`` (networks.py:870-894): mean angular error in degrees via
  clamped cosine similarity over the channel axis.

The TPU version vectorizes over the batch instead of squeezing it away and
has no device hardcoding (the reference is CUDA-only here, SURVEY Q6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_SOBEL_X = jnp.array([[1, 0, -1], [2, 0, -2], [1, 0, -1]], jnp.float32)
_SOBEL_Y = jnp.array([[1, 2, 1], [0, 0, 0], [-1, -2, -1]], jnp.float32)


def sobel_edges(img: jax.Array) -> jax.Array:
    """Edge magnitude of channel 0. img: NHWC -> (N, H, W, 1)."""
    x = img[..., :1].astype(jnp.float32)
    kx = _SOBEL_X[:, :, None, None]
    ky = _SOBEL_Y[:, :, None, None]
    dn = ("NHWC", "HWIO", "NHWC")
    gx = jax.lax.conv_general_dilated(x, kx, (1, 1), "SAME", dimension_numbers=dn)
    gy = jax.lax.conv_general_dilated(x, ky, (1, 1), "SAME", dimension_numbers=dn)
    # eps under the sqrt: d/dg sqrt(gx²+gy²) is 0/0 = NaN on flat
    # regions (gx=gy=0 — routine for tanh-saturated patches), and this
    # op is live in the train loss behind lambda_sobel. The reference's
    # dead sobelLayer has no eps (networks.py:866) — value change is
    # ≤ sqrt(eps) = 1e-6.
    return jnp.sqrt(gx**2 + gy**2 + 1e-12)


def angular_loss(illum_gt: jax.Array, illum_pred: jax.Array) -> jax.Array:
    """Mean angular error (degrees) between per-pixel channel vectors.

    Cosine similarity over the channel axis (last in NHWC; the reference's
    dim=1 in NCHW), clamped to ±0.99999 before acos as the reference does.
    """
    a = illum_gt.astype(jnp.float32)
    b = illum_pred.astype(jnp.float32)
    dot = jnp.sum(a * b, axis=-1)
    # eps under the sqrt, not just in the quotient: d‖v‖/dv is 0/0 = NaN
    # at v = 0 (an exactly-mid-gray pixel), and this loss is live behind
    # lambda_angular
    na = jnp.sqrt(jnp.sum(a * a, axis=-1) + 1e-12)
    nb = jnp.sqrt(jnp.sum(b * b, axis=-1) + 1e-12)
    cos = dot / jnp.maximum(na * nb, 1e-8)
    cos = jnp.clip(cos, -0.99999, 0.99999)
    return jnp.mean(jnp.arccos(cos)) * 180.0 / jnp.pi
