"""Spectral normalization as a pure-functional transform.

Reference: hand-rolled ``SpectralNorm`` wrapper at networks.py:525-582 —
one power-iteration step per forward over the weight matrix viewed as
(out_channels, -1), with persistent ``u``/``v`` vectors, applied to the two
inner convs of every PatchGAN discriminator (networks.py:767-775).

This is the reference's main stateful-op functionalization hazard
(SURVEY §2.2): under jit there is no hidden buffer mutation, so ``u``/``v``
live in a flax variable collection named ``'spectral'`` that the train step
threads explicitly (mutable during training, frozen at eval). Semantics:

- exactly ONE power-iteration update per *call* while ``'spectral'`` is
  mutable — the reference updates on all three D forwards per step; we pin
  the canonical count to the number of D calls in the step, matching it.
- ``u``/``v`` are stop-gradiented; σ = uᵀWv keeps gradient flow through W
  (torch.nn.utils.spectral_norm semantics, and what the reference's
  autograd graph effectively does).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from p2p_tpu.ops.conv import normal_init, save_conv_out


def _l2norm(x, eps=1e-12):
    return x / (jnp.linalg.norm(x) + eps)


def spectral_normalize(
    w_mat: jax.Array, u: jax.Array, n_iter: int = 1
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One (or more) power-iteration steps on matrix ``w_mat`` (rows, cols).

    Returns (sigma, new_u, new_v). ``u`` is the left singular-vector
    estimate of length ``rows``.
    """
    wm = jax.lax.stop_gradient(w_mat)
    v = None
    for _ in range(n_iter):
        # p2p-lint: disable=jaxpr-f32-leak -- deliberate: the power iteration tracks the TRUE f32 weight (only w/σ is cast to the compute dtype downstream); these are per-layer matvecs, trivial next to the convs they normalize
        v = _l2norm(wm.T @ u)
        # p2p-lint: disable=jaxpr-f32-leak -- deliberate: see the matvec above
        u = _l2norm(wm @ v)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    # p2p-lint: disable=jaxpr-f32-leak -- deliberate: sigma is estimated against the f32 master weight by design
    sigma = u @ w_mat @ v
    return sigma, u, v


class SpectralConv(nn.Module):
    """Conv2d (NHWC, explicit zero padding) with spectral weight norm.

    Power-iteration state lives in the 'spectral' collection; pass
    ``mutable=['spectral']`` (the train step does) to advance it.
    """

    features: int
    kernel_size: int
    stride: int = 1
    padding: int = 0
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = normal_init()
    n_power_iterations: int = 1
    # int8 QAT path (ops/int8.py) for the conv itself. The power
    # iteration runs on the TRUE f32 weight (σ must track the real
    # spectrum); only the normalized kernel w/σ is quantized — the same
    # "quantize the derived weight" order torch QAT uses for weight-norm
    # wrappers.
    int8: bool = False
    # stored-scale activation quantization (ops/int8.py int8_conv_ds);
    # requires the caller to thread the 'quant' collection.
    int8_delayed: bool = False
    # quantize-fused input epilogue (ISSUE 14, ops/int8.py QuantConv
    # docstring): (y_raw, sx) -> (q, amax); requires int8 + int8_delayed.
    # Composes with spectral norm unchanged — the power iteration still
    # tracks the true f32 weight, only w/σ meets the prequantized
    # activation in the s8×s8→s32 contraction.
    epilogue: Optional[Callable] = None
    epilogue_tap: bool = False

    @nn.compact
    def __call__(self, x):
        k = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", self.kernel_init, (k, k, cin, self.features), jnp.float32
        )
        # Matrix view (out_features, k*k*cin) — rows = output channels,
        # mirroring torch's w.view(out, -1).
        w_mat = kernel.transpose(3, 0, 1, 2).reshape(self.features, -1)

        u_var = self.variable(
            "spectral",
            "u",
            lambda: _l2norm(jax.random.normal(self.make_rng("params"), (self.features,))),
        )
        sigma, new_u, _ = spectral_normalize(
            w_mat, u_var.value, self.n_power_iterations
        )
        if self.is_mutable_collection("spectral"):
            u_var.value = new_u
        kernel_sn = (kernel / sigma).astype(self.dtype or x.dtype)

        pad = self.padding
        tap = None
        if self.int8:
            p = ((pad, pad), (pad, pad))
            if self.epilogue is not None:
                if not self.int8_delayed:
                    raise ValueError(
                        "SpectralConv(epilogue=...) needs int8_delayed — "
                        "the fused quantize reads the stored amax")
                from p2p_tpu.ops.int8 import (
                    _fused_epilogue_scale,
                    int8_conv_pq,
                    surrogate_tap,
                )

                q, sx = _fused_epilogue_scale(self, x, self.epilogue)
                # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch (_int8_bwd_core): the lhs-dilated stride-2 dgrad and transposed/big-spatial wgrads stay bf16 by the measured dispatch table (ops/int8.py; backward eqns attribute to this call site)
                y = int8_conv_pq(
                    q.astype(kernel_sn.dtype), kernel_sn, sx,
                    (self.stride, self.stride), p,
                )
                if self.epilogue_tap:
                    tap = surrogate_tap(
                        q.astype(kernel_sn.dtype), sx
                    ).astype(kernel_sn.dtype)
            elif self.int8_delayed:
                from p2p_tpu.ops.int8 import _delayed_scale, int8_conv_ds

                sx, update = _delayed_scale(self, x)
                # p2p-lint: disable=perf-int8-coverage-gap -- 2026-08-04 per-form dispatch (_int8_bwd_core): the lhs-dilated stride-2 dgrad and transposed/big-spatial wgrads stay bf16 by the measured dispatch table (ops/int8.py; backward eqns attribute to this call site)
                y, amax = int8_conv_ds(
                    x.astype(kernel_sn.dtype), kernel_sn, sx,
                    (self.stride, self.stride), p,
                )
                update(amax)
            else:
                from p2p_tpu.ops.int8 import int8_conv

                y = int8_conv(
                    x.astype(kernel_sn.dtype), kernel_sn,
                    (self.stride, self.stride), p,
                )
        else:
            if pad:
                x = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
            y = jax.lax.conv_general_dilated(
                x.astype(kernel_sn.dtype),
                kernel_sn,
                window_strides=(self.stride, self.stride),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias.astype(y.dtype)
        y = save_conv_out(y)
        if self.epilogue_tap:
            return y, tap
        return y
