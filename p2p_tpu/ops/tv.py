"""Total-variation loss. Ref: calc_tv_Loss at train.py:123-126 —
mean |∂x along W| + mean |∂x along H| (anisotropic, L1, mean-reduced)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def total_variation_loss(x: jax.Array) -> jax.Array:
    """Anisotropic TV on NHWC images, fp32 reduction."""
    x = x.astype(jnp.float32)
    dw = jnp.mean(jnp.abs(x[:, :, :-1, :] - x[:, :, 1:, :]))
    dh = jnp.mean(jnp.abs(x[:, :-1, :, :] - x[:, 1:, :, :]))
    return dw + dh
