"""Parallelism strategies over the global device mesh (SURVEY.md §2.4).

- ``dp``       data parallelism (+ mixed data×spatial) via sharding
               annotations on the jitted train step; GSPMD collectives.
- ``tp``       tensor parallelism: Megatron-style channel shards on the
               ResNet trunk's conv pairs over the ``model`` mesh axis.
- ``spatial``  GSPMD spatial sharding of H with explicit shard_map halo
               exchange for the stride-1 conv trunk.
- ``temporal`` sequence parallelism over video frames for the vid2vid
               temporal discriminator.
- ``halo``     the shared nearest-neighbor ppermute halo-exchange primitive.

Not applicable to this model family (documented, per SURVEY §2.4): expert
parallelism (no MoE), ring/Ulysses attention (no attention ops — the
spatial/temporal halo exchange is the conv equivalent). Pipeline parallelism
is out of scope v1; the mesh reserves no axis for it but ``MeshSpec`` is the
single place to add one.
"""

from p2p_tpu.parallel.dp import (
    make_parallel_eval_step,
    make_parallel_train_step,
    replicate_state,
    shard_batch,
)
from p2p_tpu.parallel.halo import halo_exchange, ring_shift
from p2p_tpu.parallel.tp import place_state_tp, tp_sharding_tree
from p2p_tpu.parallel.spatial import (
    check_spatial_divisible,
    conv2d_local,
    make_sharded_conv,
    sharded_conv2d,
    spatial_activation_sharding,
)
from p2p_tpu.parallel.temporal import (
    gather_frames,
    make_sharded_temporal_conv,
    sharded_temporal_conv3d,
    temporal_mean,
)

__all__ = [
    "make_parallel_eval_step",
    "make_parallel_train_step",
    "replicate_state",
    "shard_batch",
    "halo_exchange",
    "place_state_tp",
    "tp_sharding_tree",
    "ring_shift",
    "check_spatial_divisible",
    "conv2d_local",
    "make_sharded_conv",
    "sharded_conv2d",
    "spatial_activation_sharding",
    "gather_frames",
    "make_sharded_temporal_conv",
    "sharded_temporal_conv3d",
    "temporal_mean",
]
