"""Parallelism strategies over the global device mesh (SURVEY.md §2.4).

- ``rules``    THE declarative sharding authority (ISSUE 15): one
               regex-over-named-tree rule table produces the layout of
               the whole TrainState — Megatron TP pair shards over
               ``model``, ZeRO optimizer/EMA(/param) shards over
               ``fsdp``, replicate floor.
- ``dp``       data parallelism (+ mixed data×spatial) via sharding
               annotations on the jitted train step; GSPMD collectives.
- ``tp``       tensor parallelism: Megatron-style channel shards on the
               ResNet trunk's conv pairs over the ``model`` mesh axis
               (the tree builder is a shim over ``rules``).
- ``spatial``  GSPMD spatial sharding of H with explicit shard_map halo
               exchange for the stride-1 conv trunk.
- ``temporal`` sequence parallelism over video frames for the vid2vid
               temporal discriminator.
- ``pp``       pipeline parallelism: GPipe fill/drain over the generator's
               residual trunk on the ``pipe`` mesh axis (stacked stage
               params, neighbor ppermute hand-offs, autodiff backward).
- ``halo``     the shared nearest-neighbor ppermute halo-exchange primitive.

Not applicable to this model family (documented, per SURVEY §2.4): expert
parallelism (no MoE), ring/Ulysses attention (no attention ops — the
spatial/temporal halo exchange is the conv equivalent).
"""

from p2p_tpu.parallel.dp import (
    make_parallel_eval_step,
    make_parallel_train_step,
    replicate_state,
    shard_batch,
)
from p2p_tpu.parallel.halo import halo_exchange, ring_shift
from p2p_tpu.parallel.pp import (
    gpipe_trunk,
    make_expand_block_apply,
    make_resnet_block_apply,
    place_trunk_pp,
    pp_expand_forward,
    pp_generator_forward,
    pp_split_state,
    stack_trunk,
)
from p2p_tpu.parallel.rules import (
    make_fsdp_rules,
    make_tp_rules,
    match_partition_rules,
    state_target_shardings,
    trainstate_rules,
)
from p2p_tpu.parallel.tp import place_state_tp, tp_sharding_tree
from p2p_tpu.parallel.spatial import (
    check_spatial_divisible,
    conv2d_local,
    make_sharded_conv,
    sharded_conv2d,
    spatial_activation_sharding,
)
from p2p_tpu.parallel.temporal import (
    gather_frames,
    make_sharded_temporal_conv,
    sharded_temporal_conv3d,
    temporal_mean,
)

__all__ = [
    "make_parallel_eval_step",
    "make_parallel_train_step",
    "replicate_state",
    "shard_batch",
    "halo_exchange",
    "gpipe_trunk",
    "make_expand_block_apply",
    "make_resnet_block_apply",
    "place_trunk_pp",
    "pp_expand_forward",
    "pp_generator_forward",
    "pp_split_state",
    "stack_trunk",
    "make_fsdp_rules",
    "make_tp_rules",
    "match_partition_rules",
    "state_target_shardings",
    "trainstate_rules",
    "place_state_tp",
    "tp_sharding_tree",
    "ring_shift",
    "check_spatial_divisible",
    "conv2d_local",
    "make_sharded_conv",
    "sharded_conv2d",
    "spatial_activation_sharding",
    "gather_frames",
    "make_sharded_temporal_conv",
    "sharded_temporal_conv3d",
    "temporal_mean",
]
