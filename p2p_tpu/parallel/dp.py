"""Data-parallel (and mixed data×spatial) execution of the train step.

The reference is strictly single-device (SURVEY.md §2.4: no DDP/DataParallel
anywhere; bs=1 at train.py:143,177). Here DP is a *sharding annotation*, not
a code path: the same jitted step from ``p2p_tpu.train.step`` runs over any
``Mesh`` — parameters and optimizer state replicated, batches sharded
``P('data', 'spatial', None, None)`` — and XLA/GSPMD inserts the gradient
all-reduces over ICI.

Sync-BatchNorm falls out for free: the step computes batch-stat means over
the *global* (sharded) batch axis inside jit, so GSPMD lowers those
reductions to cross-replica collectives — exactly the ``pmean``-of-stats
semantics ParallelConfig.sync_batchnorm asks for, with no extra code.

Loss semantics vs the reference: per-example losses are means over the
global batch, so gradients match a single-device run on the same global
batch (tested to fp tolerance in tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh

from p2p_tpu.core.config import Config
from p2p_tpu.core.mesh import (
    batch_sharding,
    mesh_context,
    replicated,
    video_sharding,
)
from p2p_tpu.train.step import build_train_step


def replicate_state(state: Any, mesh: Mesh) -> Any:
    """Place every leaf of the train state replicated over the mesh."""
    return jax.device_put(state, replicated(mesh))


def shard_batch(batch: Dict[str, jax.Array], mesh: Mesh) -> Dict[str, jax.Array]:
    """Place a host batch with N over data (and H over spatial, T over time
    for 5-D video tensors); multi-process assembly handled by
    :func:`p2p_tpu.data.pipeline.place_global`."""
    from p2p_tpu.data.pipeline import place_global

    img = batch_sharding(mesh)
    vid = video_sharding(mesh)
    return place_global(
        batch, lambda v: vid if getattr(v, "ndim", 4) == 5 else img
    )


def make_parallel_train_step(
    cfg: Config,
    mesh: Mesh,
    vgg_params: Optional[Any] = None,
    steps_per_epoch: int = 1,
    train_dtype=None,
    state_sharding: Optional[Any] = None,
):
    """The single-device train step, jitted over ``mesh``.

    Returns ``step(state, batch) -> (state, metrics)`` where ``state`` is
    replicated and ``batch`` is sharded per :func:`shard_batch`. Gradient
    psums, BN stat reductions, and (for spatial>1) conv halo exchanges are
    all GSPMD-inserted.

    ``state_sharding``: optional NamedSharding pytree for the TrainState
    (``parallel.rules.state_target_shardings`` — Megatron TP over
    ``model``, ZeRO moments/EMA over ``fsdp``); defaults to fully
    replicated.
    """
    step = build_train_step(
        cfg, vgg_params, steps_per_epoch, train_dtype, jit=False
    )

    def step_in_mesh(state, batch):
        # mesh visible at trace time: ops needing manual sharding regions
        # (Pallas InstanceNorm) wrap themselves in shard_map over it.
        with mesh_context(mesh):
            return step(state, batch)

    rep = replicated(mesh)
    bsh = batch_sharding(mesh)
    ssh = rep if state_sharding is None else state_sharding
    return jax.jit(
        step_in_mesh,
        in_shardings=(ssh, bsh),
        out_shardings=(ssh, rep),
        donate_argnums=0,
    )


def make_parallel_multi_train_step(
    cfg: Config,
    mesh: Mesh,
    vgg_params: Optional[Any] = None,
    steps_per_epoch: int = 1,
    train_dtype=None,
    state_sharding: Optional[Any] = None,
    unroll: int = 1,
):
    """``build_multi_train_step`` (K steps per dispatch via lax.scan) jitted
    over ``mesh`` with explicit state/batch shardings — the scan-path twin
    of :func:`make_parallel_train_step`, used by the CLI trainer when
    ``scan_steps > 1`` on a TP mesh. Batches carry a leading K axis:
    ``P(None, 'data', 'spatial', None, None)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from p2p_tpu.core.mesh import BATCH_AXES, SPATIAL_AXIS

    inner = build_train_step(
        cfg, vgg_params, steps_per_epoch, train_dtype, jit=False
    )

    def multi_step(state, batches):
        with mesh_context(mesh):
            return jax.lax.scan(inner, state, batches, unroll=unroll)

    rep = replicated(mesh)
    stacked_bsh = NamedSharding(
        mesh, P(None, BATCH_AXES, SPATIAL_AXIS, None, None))
    ssh = rep if state_sharding is None else state_sharding
    return jax.jit(
        multi_step,
        in_shardings=(ssh, stacked_bsh),
        out_shardings=(ssh, rep),
        donate_argnums=0,
    )


def make_parallel_eval_step(cfg: Config, mesh: Mesh, train_dtype=None):
    from p2p_tpu.train.step import build_eval_step

    step = build_eval_step(cfg, train_dtype, jit=False)

    def step_in_mesh(state, batch):
        with mesh_context(mesh):
            return step(state, batch)

    rep = replicated(mesh)
    bsh = batch_sharding(mesh)
    return jax.jit(step_in_mesh, in_shardings=(rep, bsh),
                   out_shardings=(bsh, rep))
