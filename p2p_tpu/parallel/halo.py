"""Generic halo exchange over a named mesh axis.

The reference has no distributed layer (SURVEY.md §2.3); its "long-context"
analogue is large spatial extent / video length (SURVEY.md §5.7). The
primitive both need is the same: each shard of a spatially- or
temporally-split tensor must see ``halo`` rows/frames owned by its mesh
neighbors before a convolution can produce its local slice of the output.

This module implements that exchange with a single bidirectional
``jax.lax.ppermute`` pair — nearest-neighbor traffic that rides the ICI
torus links (the mesh is laid out so ``spatial``/``time`` are the innermost
axes — see ``p2p_tpu.core.mesh.make_mesh``). It is meant to be called
*inside* a ``jax.shard_map`` region, where ``x`` is the local shard.

Edge policy matches the conv padding being reproduced:

- ``"reflect"`` — outermost shards reflect their own rows, reproducing the
  framework's ReflectionPad convs (ref networks.py:395-405) exactly.
- ``"zero"``    — zero padding (PatchGAN convs, temporal conv boundaries).
- ``"wrap"``    — periodic; the raw ppermute ring result.

(shard_map outputs must be shape-uniform across shards, so a VALID-style
"no outer padding" mode is not expressible here — callers wanting VALID
convs slice the edge shards' output instead.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _take(x: jax.Array, start: int, size: int, dim: int) -> jax.Array:
    return lax.slice_in_dim(x, start, start + size, axis=dim)


def halo_exchange(
    x: jax.Array,
    *,
    dim: int,
    halo: int,
    axis_name: str,
    edge_mode: str = "reflect",
) -> jax.Array:
    """Pad the local shard with ``halo`` neighbor rows on both sides of ``dim``.

    Must be called inside ``shard_map`` with ``x`` sharded over ``axis_name``
    along ``dim``. Returns the local shard grown by ``2*halo`` along ``dim``
    (edge shards included — their outer halo is synthesized per
    ``edge_mode``).
    """
    if halo == 0:
        return x
    if x.shape[dim] < halo + 1:
        raise ValueError(
            f"local shard extent {x.shape[dim]} along dim {dim} too small for "
            f"halo {halo} (need at least halo+1 rows per shard)"
        )
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    lo_rows = _take(x, 0, halo, dim)                      # my first rows
    hi_rows = _take(x, x.shape[dim] - halo, halo, dim)    # my last rows

    fwd = [(i, (i + 1) % n) for i in range(n)]            # i sends to i+1
    bwd = [(i, (i - 1) % n) for i in range(n)]            # i sends to i-1
    from_prev = lax.ppermute(hi_rows, axis_name, fwd)     # prev's last rows
    from_next = lax.ppermute(lo_rows, axis_name, bwd)     # next's first rows

    if edge_mode == "wrap":
        lo_halo, hi_halo = from_prev, from_next
    elif edge_mode == "zero":
        zeros = jnp.zeros_like(from_prev)
        lo_halo = jnp.where(idx == 0, zeros, from_prev)
        hi_halo = jnp.where(idx == n - 1, zeros, from_next)
    elif edge_mode == "reflect":
        # Global ReflectionPad(p): top halo of the whole image is rows
        # p..1 reversed — fully owned by shard 0, so synthesized locally.
        lo_reflect = jnp.flip(_take(x, 1, halo, dim), axis=dim)
        hi_reflect = jnp.flip(
            _take(x, x.shape[dim] - 1 - halo, halo, dim), axis=dim
        )
        lo_halo = jnp.where(idx == 0, lo_reflect, from_prev)
        hi_halo = jnp.where(idx == n - 1, hi_reflect, from_next)
    else:
        raise ValueError(f"unknown edge_mode {edge_mode!r}")

    return jnp.concatenate([lo_halo, x, hi_halo], axis=dim)


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Cyclically shift shards around the mesh axis ring (ppermute).

    The building block for ring-style pipelines (the conv-GAN equivalent of
    ring attention's block rotation): after ``axis_size`` shifts every shard
    has seen every block.
    """
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)
