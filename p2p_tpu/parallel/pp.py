"""Pipeline parallelism (GPipe) over the ``pipe`` mesh axis — SURVEY §2.4 PP row.

The reference is single-device (no pipeline anywhere in /root/reference);
SURVEY §2.4 scoped PP "out-of-scope v1, design mesh axes so it can be
added". This module adds it, TPU-native:

- **Stage unit** — the generator's residual trunk: the only depth-regular,
  FLOP-dominant segment in the zoo (9 identical 128-ch blocks in the
  flagship ExpandNetwork, networks.py:472-480; ``n_blocks`` up to 9 in the
  ResNet family). Each of the S pipeline stages owns ``n_blocks/S``
  consecutive blocks; their parameters are *stacked* along a leading stage
  axis and sharded over ``pipe``, so stage weights live only on their
  stage's devices (the point of PP: fit a deeper trunk than one chip's HBM).
- **Schedule** — GPipe fill/drain over M microbatches inside ONE jitted
  ``shard_map``: every tick each stage applies its block stack
  (``lax.scan`` over the stacked block params) and hands its activation to
  the next stage with a neighbor ``ppermute`` (``pipe`` is the innermost
  mesh axis — the shift is one ICI hop). T = M + S − 1 ticks; bubble
  fraction (S−1)/T exactly as GPipe.
- **Backward** — ``jax.grad`` of the same program: the transpose of
  ``ppermute`` is the reverse shift, so autodiff derives the reverse-order
  pipeline schedule with no hand-written VJP.
- **Norm semantics** — microbatching changes *train-mode BatchNorm*
  statistics (per-microbatch instead of per-batch — the GPipe paper's BN
  caveat), so the pipelined trunk applies blocks with frozen (eval)
  BatchNorm stats. InstanceNorm models are unaffected (per-sample stats):
  for the instance-norm family (cityscapes / pix2pixHD — where model scale
  actually motivates PP) the pipelined forward AND gradients are exact vs
  the train-mode unpipelined model; for the BatchNorm flagship they are
  exact vs eval mode. Both pinned in tests/test_pp.py.

Composability: the microbatch batch axis stays sharded over ``data``
(in-spec ``P(None, 'data', ...)``), so PP composes with DP on one mesh —
exercised by the dryrun phase 5 (data=2 × pipe=4) and tests.

Single-chip note: this environment exposes ONE real TPU chip, so PP here is
validated for numerics on the fake CPU mesh and compile-checked via the
driver dryrun, like TP (parallel/tp.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_tpu.core.mesh import DATA_AXIS, PIPE_AXIS

BlockApply = Callable[[Dict[str, Any], jax.Array], jax.Array]


def stack_trunk(variables: Dict[str, Any], n_stages: int,
                prefix: str = "ResidualBlock_") -> Dict[str, Any]:
    """Stack the trunk's per-block variable subtrees into stage-major arrays.

    Returns a tree shaped like one block's variables but with every leaf
    prefixed by ``[S, B]`` axes (S stages × B = n_blocks/S blocks per
    stage); block ``s*B + j`` sits at ``[s, j]``, so scanning j within a
    pipelined stage s applies blocks in the original serial order.
    """
    names = [n for n in variables["params"] if n.startswith(prefix)]
    names.sort(key=lambda n: int(n[len(prefix):]))
    n_blocks = len(names)
    if n_blocks == 0:
        raise ValueError(f"no {prefix}* blocks in variables")
    if n_blocks % n_stages:
        raise ValueError(
            f"{n_blocks} trunk blocks not divisible by {n_stages} stages")
    per = n_blocks // n_stages

    def gather(collection):
        blocks = [collection[n] for n in names]
        flat = jax.tree.map(lambda *leaves: jnp.stack(leaves), *blocks)
        return jax.tree.map(
            lambda a: a.reshape((n_stages, per) + a.shape[1:]), flat)

    stacked = {"params": gather(variables["params"])}
    stats = variables.get("batch_stats", {})
    if names[0] in stats:
        stacked["batch_stats"] = gather(stats)
    return stacked


def place_trunk_pp(stacked: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """Shard the stacked trunk stage-axis over ``pipe`` (each stage's block
    weights live only on that stage's devices)."""
    sh = NamedSharding(mesh, P(PIPE_AXIS))
    return jax.tree.map(lambda a: jax.device_put(a, sh), stacked)


def gpipe_trunk(block_apply: BlockApply, stacked: Dict[str, Any],
                y_mb: jax.Array, mesh: Mesh) -> jax.Array:
    """Run the stacked trunk over ``y_mb`` [M, mb, H, W, C] with the GPipe
    fill/drain schedule on the mesh's ``pipe`` axis.

    ``block_apply(block_vars, y) -> y`` applies ONE residual block given its
    (unstacked) variable subtree. Output has the same shape/sharding as
    ``y_mb`` (mb stays on ``data``); result is replicated over ``pipe``.
    """
    n_stages = mesh.shape[PIPE_AXIS]
    n_micro = int(y_mb.shape[0])
    ticks = n_micro + n_stages - 1
    act_spec = P(None, DATA_AXIS, *([None] * (y_mb.ndim - 2)))
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def shard_fn(st, xmb):
        local = jax.tree.map(lambda a: a[0], st)   # this stage's [B, ...]
        idx = jax.lax.axis_index(PIPE_AXIS)

        def stage(y):
            def body(c, bv):
                return block_apply(bv, c), None
            y, _ = jax.lax.scan(body, y, local)
            return y

        def tick(carry, t):
            act, out = carry
            # stage 0 injects microbatch t (clamped re-feeds during drain
            # are bubble ticks whose output is never written)
            feed = jax.lax.dynamic_index_in_dim(
                xmb, jnp.minimum(t, n_micro - 1), 0, keepdims=False)
            y_out = stage(jnp.where(idx == 0, feed, act))
            # last stage retires microbatch t-(S-1) into its output slot
            o_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            prev = jax.lax.dynamic_index_in_dim(out, o_idx, 0, keepdims=False)
            write = jnp.logical_and(t >= n_stages - 1, idx == n_stages - 1)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y_out, prev), o_idx, 0)
            return (jax.lax.ppermute(y_out, PIPE_AXIS, perm), out), None

        # carries are stage-varying (idx enters tick) — pcast the replicated
        # zeros to the varying type shard_map's vma tracking expects
        zero = jax.lax.pcast(
            jnp.zeros(xmb.shape[1:], xmb.dtype), (DATA_AXIS, PIPE_AXIS),
            to="varying")
        out0 = jax.lax.pcast(jnp.zeros_like(xmb), (PIPE_AXIS,), to="varying")
        (act, out), _ = jax.lax.scan(tick, (zero, out0), jnp.arange(ticks))
        # non-last stages accumulated zeros; the masked psum replicates the
        # last stage's outputs to every pipe shard
        return jax.lax.psum(
            jnp.where(idx == n_stages - 1, out, jnp.zeros_like(out)),
            PIPE_AXIS)

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(PIPE_AXIS), act_spec), out_specs=act_spec,
    )(stacked, y_mb)


# ---------------------------------------------------------------------------
# Flagship wiring: pipelined ExpandNetwork forward
# ---------------------------------------------------------------------------


def make_expand_block_apply(model_cfg, dtype=None) -> BlockApply:
    """Block applier for ExpandNetwork's ``ResidualBlock_i`` trunk
    (frozen-stat norms — see module docstring)."""
    from p2p_tpu.models.expand import ResidualBlock

    if model_cfg.int8 and model_cfg.int8_generator:
        # the int8-delayed trunk carries a 'quant' scale collection that
        # stack_trunk does not stack (and that wants mutation per step)
        raise NotImplementedError(
            "pp v1 does not pipeline the int8 trunk; run int8 configs "
            "unpipelined or stack the 'quant' collection first")
    block = ResidualBlock(
        model_cfg.ngf * 4, norm=model_cfg.norm,
        legacy_layout=model_cfg.legacy_layout, dtype=dtype)

    def apply_one(bvars, y):
        return block.apply(bvars, y, False)

    return apply_one


def make_resnet_block_apply(features: int, norm: str = "instance",
                            legacy_layout: bool = False,
                            dtype=None) -> BlockApply:
    """Block applier for the ResNet family's ``ResnetBlock_i`` trunk
    (models/resnet_gen.py — cityscapes and pix2pixHD's ``global``/G1,
    whose 1024-channel trunk is where PP actually pays). Use with
    ``stack_trunk(variables, n_stages, prefix="ResnetBlock_")`` and
    ``gpipe_trunk``. Instance norm is per-sample, so the pipelined trunk
    is exact vs train mode (module docstring)."""
    from p2p_tpu.models.resnet_gen import ResnetBlock

    block = ResnetBlock(features, norm=norm, legacy_layout=legacy_layout,
                        dtype=dtype)

    def apply_one(bvars, y):
        return block.apply(bvars, y, False)

    return apply_one


def pp_expand_forward(model_cfg, variables: Dict[str, Any], x_mb: jax.Array,
                      mesh: Mesh,
                      stacked: Optional[Dict[str, Any]] = None,
                      dtype=None) -> jax.Array:
    """Full pipelined flagship (ExpandNetwork) forward.

    ``x_mb``: [M, mb, H, W, 3] microbatched input (mb sharded over ``data``).
    Encoder/decoder run replicated over ``pipe`` on the flat batch (they are
    <15% of the FLOPs — networks.py:460-520; pipelining them buys nothing at
    this depth); the residual trunk runs the GPipe schedule. Mirrors
    ExpandNetwork.__call__ (models/expand.py) name-for-name — drift between
    the two is pinned bitwise by tests/test_pp.py.
    """
    if model_cfg.generator != "expand":
        raise NotImplementedError(
            "pp v1 pipelines the ExpandNetwork trunk; for the ResNet family "
            "use gpipe_trunk() directly with a ResnetBlock applier")

    from p2p_tpu.models.expand import ResidualBlock  # noqa: F401  (doc link)
    from p2p_tpu.ops.activations import PReLU, leaky_relu_y, tanh_y
    from p2p_tpu.ops.conv import ConvLayer, UpsampleConvLayer, upsample_nearest
    from p2p_tpu.ops.norm import make_norm
    from p2p_tpu.ops.pixel_shuffle import pixel_unshuffle

    p = variables["params"]
    bs = variables.get("batch_stats", {})
    cfg = model_cfg
    ub = cfg.legacy_layout or cfg.norm == "none"
    mk = make_norm(cfg.norm, train=False, dtype=dtype)

    def norm_at(i, y):
        if cfg.norm == "none":
            return y
        name = f"{type(mk()).__name__}_{i}"
        vs = {}
        if name in p:
            vs["params"] = p[name]
        if name in bs:
            vs["batch_stats"] = bs[name]
        return mk().apply(vs, y)

    def act(y):
        return PReLU().apply({"params": p["PReLU_0"]}, y)

    if stacked is None:
        stacked = stack_trunk(variables, mesh.shape[PIPE_AXIS])

    n_micro, mb = x_mb.shape[0], x_mb.shape[1]

    def flat(t):
        # [M, mb, ...] -> [mb*M, ...] *mb-major*: the data-sharded mb axis
        # stays outermost so GSPMD keeps the encoder/decoder data-parallel
        # (an M-major flatten interleaves the shards and forces XLA to
        # all-gather the full batch onto every device)
        return jnp.swapaxes(t, 0, 1).reshape((mb * n_micro,) + t.shape[2:])

    def unflat(t):
        return jnp.swapaxes(
            t.reshape((mb, n_micro) + t.shape[1:]), 0, 1)

    x = flat(x_mb)

    # --- encoder (replicated over pipe; flat batch) ---
    y = pixel_unshuffle(x, 2)
    y = upsample_nearest(y, 2)
    y = act(norm_at(0, ConvLayer(cfg.ngf, kernel_size=9, use_bias=ub, dtype=dtype)
                    .apply({"params": p["ConvLayer_0"]}, y)))
    y = act(norm_at(1, ConvLayer(cfg.ngf * 2, kernel_size=3, stride=2,
                                 use_bias=ub, dtype=dtype)
                    .apply({"params": p["ConvLayer_1"]}, y)))
    y = act(norm_at(2, ConvLayer(cfg.ngf * 4, kernel_size=3, stride=2,
                                 use_bias=ub, dtype=dtype)
                    .apply({"params": p["ConvLayer_2"]}, y)))

    # --- pipelined residual trunk ---
    residual = y
    y_mb = gpipe_trunk(make_expand_block_apply(cfg, dtype), stacked,
                       unflat(y), mesh)
    y = leaky_relu_y(flat(y_mb) + residual, 0.2)

    # --- decoder ---
    y = act(norm_at(3, UpsampleConvLayer(cfg.ngf * 2, kernel_size=3,
                                         upsample=2, use_bias=ub, dtype=dtype)
                    .apply({"params": p["UpsampleConvLayer_0"]}, y)))
    y = act(norm_at(4, UpsampleConvLayer(cfg.ngf, kernel_size=3, upsample=2,
                                         use_bias=ub, dtype=dtype)
                    .apply({"params": p["UpsampleConvLayer_1"]}, y)))
    y = UpsampleConvLayer(cfg.output_nc, kernel_size=9, use_bias=ub,
                                      dtype=dtype).apply(
        {"params": p["UpsampleConvLayer_2"]}, y)
    y = norm_at(5, y)
    y = tanh_y(y)
    return unflat(y)
