"""Pipeline parallelism (GPipe) over the ``pipe`` mesh axis — SURVEY §2.4 PP row.

The reference is single-device (no pipeline anywhere in /root/reference);
SURVEY §2.4 scoped PP "out-of-scope v1, design mesh axes so it can be
added". This module adds it, TPU-native:

- **Stage unit** — the generator's residual trunk: the only depth-regular,
  FLOP-dominant segment in the zoo (9 identical 128-ch blocks in the
  flagship ExpandNetwork, networks.py:472-480; ``n_blocks`` up to 9 in the
  ResNet family). Each of the S pipeline stages owns ``n_blocks/S``
  consecutive blocks; their parameters are *stacked* along a leading stage
  axis and sharded over ``pipe``, so stage weights live only on their
  stage's devices (the point of PP: fit a deeper trunk than one chip's HBM).
- **Schedule** — GPipe fill/drain over M microbatches inside ONE jitted
  ``shard_map``: every tick each stage applies its block stack
  (``lax.scan`` over the stacked block params) and hands its activation to
  the next stage with a neighbor ``ppermute`` (``pipe`` is the innermost
  mesh axis — the shift is one ICI hop). T = M + S − 1 ticks; bubble
  fraction (S−1)/T exactly as GPipe.
- **Backward** — ``jax.grad`` of the same program: the transpose of
  ``ppermute`` is the reverse shift, so autodiff derives the reverse-order
  pipeline schedule with no hand-written VJP.
- **Norm semantics** — microbatching changes *train-mode BatchNorm*
  statistics (per-microbatch instead of per-batch — the GPipe paper's BN
  caveat), so the pipelined trunk applies blocks with frozen (eval)
  BatchNorm stats. InstanceNorm models are unaffected (per-sample stats):
  for the instance-norm family (cityscapes / pix2pixHD — where model scale
  actually motivates PP) the pipelined forward AND gradients are exact vs
  the train-mode unpipelined model; for the BatchNorm flagship they are
  exact vs eval mode. Both pinned in tests/test_pp.py.

Composability: the microbatch batch axis stays sharded over ``data``
(in-spec ``P(None, 'data', ...)``), so PP composes with DP on one mesh —
exercised by the dryrun phase 5 (data=2 × pipe=4) and tests.

Single-chip note: this environment exposes ONE real TPU chip, so PP here is
validated for numerics on the fake CPU mesh and compile-checked via the
driver dryrun, like TP (parallel/tp.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_tpu.core.mesh import (
    DATA_AXIS,
    PIPE_AXIS,
    pcast_varying,
    shard_map_compat as shard_map,
)

# (block_vars, y) -> y — or -> (y, quant_proposal) for the delayed-int8
# trunk (gpipe_trunk dispatches on the stacked 'quant' collection)
BlockApply = Callable[[Dict[str, Any], jax.Array], Any]


def stack_trunk(variables: Dict[str, Any], n_stages: int,
                prefix: str = "ResidualBlock_") -> Dict[str, Any]:
    """Stack the trunk's per-block variable subtrees into stage-major arrays.

    Returns a tree shaped like one block's variables but with every leaf
    prefixed by ``[S, B]`` axes (S stages × B = n_blocks/S blocks per
    stage); block ``s*B + j`` sits at ``[s, j]``, so scanning j within a
    pipelined stage s applies blocks in the original serial order.
    """
    names = [n for n in variables["params"] if n.startswith(prefix)]
    names.sort(key=lambda n: int(n[len(prefix):]))
    n_blocks = len(names)
    if n_blocks == 0:
        raise ValueError(f"no {prefix}* blocks in variables")
    if n_blocks % n_stages:
        raise ValueError(
            f"{n_blocks} trunk blocks not divisible by {n_stages} stages")
    def gather(collection):
        # ONE stacking law (shared with the init_opt=False opt-moment
        # split): the params-derived block list drives every collection
        return _gather_stack(collection, prefix, n_stages, names=names)

    stacked = {"params": gather(variables["params"])}
    # stage-regular non-param collections ride along: BN running stats and
    # the delayed-int8 'quant' amax scales (both per-block, both [S, B]-
    # stackable — the quant GPipe semantics live in gpipe_trunk)
    for coll in ("batch_stats", "quant"):
        entries = variables.get(coll) or {}
        if names[0] in entries:
            stacked[coll] = gather(entries)
    return stacked


def place_trunk_pp(stacked: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """Shard the stacked trunk stage-axis over ``pipe`` (each stage's block
    weights live only on that stage's devices)."""
    sh = NamedSharding(mesh, P(PIPE_AXIS))
    return jax.tree.map(lambda a: jax.device_put(a, sh), stacked)


def gpipe_trunk(block_apply: BlockApply, stacked: Dict[str, Any],
                y_mb: jax.Array, mesh: Mesh, overlap: bool = False):
    """Run the stacked trunk over ``y_mb`` [M, mb, H, W, C] with the GPipe
    fill/drain schedule on the mesh's ``pipe`` axis.

    ``block_apply(block_vars, y) -> y`` applies ONE residual block given its
    (unstacked) variable subtree. Output has the same shape/sharding as
    ``y_mb`` (mb stays on ``data``); result is replicated over ``pipe``.

    ``overlap=True`` switches to the LATENCY-HIDING schedule: the hand-off
    is double-buffered — each tick issues the ``ppermute`` on the PREVIOUS
    tick's output (a scan-carry value, structurally independent of this
    tick's block compute), so the ICI transfer runs concurrently with the
    stage compute instead of serializing after it. The stage→stage hop then
    takes two ticks (stage ``s`` holds microbatch ``t − 2s`` at tick ``t``)
    and the schedule runs ``M + 2(S−1)`` ticks vs the serial ``M + S − 1``:
    the doubled fill/drain bubble buys ticks of ``max(compute, transfer)``
    instead of ``compute + transfer`` — a win when ``transfer/compute >
    (S−1)/(M+S−1)``. Numerics are IDENTICAL (same blocks on the same
    microbatches; pinned bitwise in tests/test_pp.py), and the
    issued-from-carry property is pinned structurally on the jaxpr.

    When ``stacked`` carries a ``'quant'`` collection (the delayed-int8
    trunk, ops/int8.py), ``block_apply`` must instead return ``(y, quant
    proposal)`` — the block applied with the FROZEN stored scales plus the
    mutated collection it proposes. Every microbatch then quantizes with
    the same start-of-step scale (exactly the unpipelined batch semantics)
    and the per-microbatch proposals are max-combined over the valid ticks
    and psum-maxed over ``data``, which reproduces the unpipelined
    full-batch ``amax_update`` bitwise (ops/int8.py). Returns ``(y_out,
    new_quant_stack)`` in that case, ``y_out`` alone otherwise.
    """
    n_stages = mesh.shape[PIPE_AXIS]
    n_micro = int(y_mb.shape[0])
    # per-stage microbatch lag: 1 tick/hop serial, 2 ticks/hop overlapped
    lag = 2 if overlap else 1
    ticks = n_micro + lag * (n_stages - 1)
    act_spec = P(None, DATA_AXIS, *([None] * (y_mb.ndim - 2)))
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    has_quant = "quant" in stacked

    def shard_fn(st, xmb):
        local = jax.tree.map(lambda a: a[0], st)   # this stage's [B, ...]
        idx = jax.lax.axis_index(PIPE_AXIS)

        def stage(y):
            if has_quant:
                def body(c, bv):
                    return block_apply(bv, c)      # (y', quant proposal)
                return jax.lax.scan(body, y, local)

            def body(c, bv):
                return block_apply(bv, c), None
            y, _ = jax.lax.scan(body, y, local)
            return y, {}

        def retire(out, y_out, t):
            # last stage retires microbatch t-lag·(S-1) into its slot
            o_idx = jnp.clip(t - lag * (n_stages - 1), 0, n_micro - 1)
            prev = jax.lax.dynamic_index_in_dim(out, o_idx, 0,
                                                keepdims=False)
            write = jnp.logical_and(t >= lag * (n_stages - 1),
                                    idx == n_stages - 1)
            return jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y_out, prev), o_idx, 0)

        def acc_quant(qacc, qp, t):
            # amax bookkeeping is carried state, never a loss input —
            # cut it out of the autodiff graph (pmax/psum-max below
            # have no differentiation rule, and none is wanted)
            qp = jax.tree.map(jax.lax.stop_gradient, qp)
            # stage `idx` holds microbatch t-lag·idx at tick t — bubble
            # ticks (fill zeros, drain re-feeds) must not touch amax
            valid = jnp.logical_and(t >= lag * idx,
                                    t - lag * idx <= n_micro - 1)
            return jax.tree.map(
                lambda a, p: jnp.where(valid, jnp.maximum(a, p), a),
                qacc, qp)

        def feed_at(t):
            # stage 0 injects microbatch t (clamped re-feeds during drain
            # are bubble ticks whose output is never written)
            return jax.lax.dynamic_index_in_dim(
                xmb, jnp.minimum(t, n_micro - 1), 0, keepdims=False)

        def tick(carry, t):
            act, out, qacc = carry
            y_out, qp = stage(jnp.where(idx == 0, feed_at(t), act))
            if has_quant:
                qacc = acc_quant(qacc, qp, t)
            out = retire(out, y_out, t)
            return (jax.lax.ppermute(y_out, PIPE_AXIS, perm), out, qacc), None

        def tick_overlap(carry, t):
            recv, y_prev, out, qacc = carry
            # double-buffered hand-off: transfer LAST tick's output now —
            # ``y_prev`` is a scan carry, so this collective has no data
            # dependence on this tick's stage compute and the scheduler is
            # free to run the ICI hop under it (the latency-hiding point;
            # pinned structurally by tests/test_pp.py)
            send = jax.lax.ppermute(y_prev, PIPE_AXIS, perm)
            y_out, qp = stage(jnp.where(idx == 0, feed_at(t), recv))
            if has_quant:
                qacc = acc_quant(qacc, qp, t)
            out = retire(out, y_out, t)
            return (send, y_out, out, qacc), None

        # carries are stage-varying (idx enters tick) — pcast the replicated
        # zeros to the varying type shard_map's vma tracking expects
        zero = pcast_varying(
            jnp.zeros(xmb.shape[1:], xmb.dtype), (DATA_AXIS, PIPE_AXIS))
        out0 = pcast_varying(jnp.zeros_like(xmb), (PIPE_AXIS,))
        # amax proposals are >= 0, so max-accumulation starts from zeros
        q0 = jax.tree.map(
            lambda a: pcast_varying(jnp.zeros_like(a),
                                    (DATA_AXIS, PIPE_AXIS)),
            local.get("quant", {}))
        if overlap:
            zero2 = pcast_varying(
                jnp.zeros(xmb.shape[1:], xmb.dtype), (DATA_AXIS, PIPE_AXIS))
            (_, _, out, qacc), _ = jax.lax.scan(
                tick_overlap, (zero, zero2, out0, q0), jnp.arange(ticks))
        else:
            (_, out, qacc), _ = jax.lax.scan(
                tick, (zero, out0, q0), jnp.arange(ticks))
        # non-last stages accumulated zeros; the masked psum replicates the
        # last stage's outputs to every pipe shard
        y_full = jax.lax.psum(
            jnp.where(idx == n_stages - 1, out, jnp.zeros_like(out)),
            PIPE_AXIS)
        # each data shard saw only its rows — the global amax is the max
        # over the data axis (exact: max of maxes), stage-local otherwise
        q_new = jax.tree.map(
            lambda a: jax.lax.pmax(a, DATA_AXIS)[None], qacc)
        return y_full, q_new

    y_out, q_new = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(PIPE_AXIS), act_spec),
        out_specs=(act_spec, P(PIPE_AXIS)),
    )(stacked, y_mb)
    return (y_out, q_new) if has_quant else y_out


# ---------------------------------------------------------------------------
# Generator wiring: pipelined trunk inside the REAL model module
# ---------------------------------------------------------------------------


def _quant_applier(block):
    """Applier for a delayed-int8 block: frozen stored scales in the
    forward, mutated 'quant' collection returned as the update proposal
    (gpipe_trunk max-combines proposals — the semantics contract is
    ops/int8.py amax_update)."""

    def apply_mut(bvars, y):
        out, mut = block.apply(bvars, y, False, mutable=["quant"])
        return out, mut["quant"]

    return apply_mut


def make_expand_block_apply(model_cfg, dtype=None) -> BlockApply:
    """Block applier for ExpandNetwork's ``ResidualBlock_i`` trunk
    (frozen-stat norms — see module docstring). The int8 trunk (dynamic or
    delayed scales) pipelines too: the delayed form returns ``(y, quant
    proposal)`` pairs for gpipe_trunk's stacked-quant path."""
    from p2p_tpu.models.expand import ResidualBlock

    int8_g = model_cfg.int8 and model_cfg.int8_generator
    block = ResidualBlock(
        model_cfg.ngf * 4, norm=model_cfg.norm, int8=int8_g,
        int8_delayed=model_cfg.int8_delayed,
        legacy_layout=model_cfg.legacy_layout, dtype=dtype)
    if int8_g and model_cfg.int8_delayed:
        return _quant_applier(block)

    def apply_one(bvars, y):
        return block.apply(bvars, y, False)

    return apply_one


def make_resnet_block_apply(features: int, norm: str = "instance",
                            legacy_layout: bool = False, int8: bool = False,
                            int8_delayed: bool = False,
                            dtype=None) -> BlockApply:
    """Block applier for the ResNet family's ``ResnetBlock_i`` trunk
    (models/resnet_gen.py — cityscapes and pix2pixHD's ``global``/G1,
    whose 1024-channel trunk is where PP actually pays). Use with
    ``stack_trunk(variables, n_stages, prefix="ResnetBlock_")`` and
    ``gpipe_trunk``. Instance norm is per-sample, so the pipelined trunk
    is exact vs train mode (module docstring)."""
    from p2p_tpu.models.resnet_gen import ResnetBlock

    block = ResnetBlock(features, norm=norm, int8=int8,
                        int8_delayed=int8_delayed,
                        legacy_layout=legacy_layout, dtype=dtype)
    if int8 and int8_delayed:
        return _quant_applier(block)

    def apply_one(bvars, y):
        return block.apply(bvars, y, False)

    return apply_one


def mb_major_flatten(t: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [mb*M, ...] with the data-sharded mb axis OUTERMOST,
    so GSPMD keeps flat-batch (encoder/decoder) compute data-parallel — an
    M-major flatten interleaves the shards and forces XLA to all-gather the
    full batch onto every device. The ONE definition of the carve order
    (its inverse below; pinned by the no-all-gather HLO test)."""
    n_micro, mb = t.shape[0], t.shape[1]
    return jnp.swapaxes(t, 0, 1).reshape((mb * n_micro,) + t.shape[2:])


def mb_major_unflatten(t: jax.Array, n_micro: int) -> jax.Array:
    """Inverse of :func:`mb_major_flatten`: [mb*M, ...] -> [M, mb, ...]."""
    mb = t.shape[0] // n_micro
    return jnp.swapaxes(t.reshape((mb, n_micro) + t.shape[1:]), 0, 1)


_TRUNK_PREFIX = {"expand": "ResidualBlock_", "resnet": "ResnetBlock_"}


def trunk_prefix(model_cfg) -> str:
    try:
        return _TRUNK_PREFIX[model_cfg.generator]
    except KeyError:
        raise NotImplementedError(
            f"pp pipelines the expand/resnet trunk families, not "
            f"{model_cfg.generator!r} (docs/PARALLELISM.md v2 boundaries)"
        ) from None


def _trunk_block_apply(model_cfg, dtype=None) -> BlockApply:
    if model_cfg.generator == "expand":
        return make_expand_block_apply(model_cfg, dtype)
    # ResnetGenerator via define_G uses its default n_downsampling=2 and
    # no feature cap → the trunk width is ngf * 4
    int8_g = model_cfg.int8 and model_cfg.int8_generator
    return make_resnet_block_apply(
        model_cfg.ngf * 4, norm=model_cfg.norm,
        legacy_layout=model_cfg.legacy_layout, int8=int8_g,
        int8_delayed=model_cfg.int8_delayed, dtype=dtype)


def pp_generator_forward(model_cfg, variables: Dict[str, Any],
                         x_mb: jax.Array, mesh: Mesh,
                         stacked: Optional[Dict[str, Any]] = None,
                         dtype=None, with_quant: bool = False,
                         overlap: bool = False):
    """Full pipelined generator forward (expand / resnet trunk families).

    ``x_mb``: [M, mb, H, W, 3] microbatched input (mb sharded over ``data``).
    Encoder/decoder run replicated over ``pipe`` on the mb-major flat batch
    (they are <15% of the FLOPs — networks.py:460-520; pipelining them buys
    nothing at this depth) through the REAL model module via its
    ``trunk_fn`` hook — no hand-mirrored forward to drift — while the
    residual trunk runs the GPipe schedule. The mb-major flatten keeps the
    data-sharded mb axis outermost so GSPMD keeps the encoder/decoder
    data-parallel (an M-major flatten interleaves the shards and forces
    XLA to all-gather the full batch onto every device — pinned by the HLO
    test in tests/test_pp.py).

    ``with_quant=True`` additionally returns the updated stacked 'quant'
    collection (None when the trunk carries none).
    """
    from p2p_tpu.models.registry import define_G

    prefix = trunk_prefix(model_cfg)
    if stacked is None:
        stacked = stack_trunk(variables, mesh.shape[PIPE_AXIS],
                              prefix=prefix)
    block_apply = _trunk_block_apply(model_cfg, dtype)

    n_micro = int(x_mb.shape[0])
    q_new = None

    def trunk_fn(y):
        nonlocal q_new
        r = gpipe_trunk(block_apply, stacked,
                        mb_major_unflatten(y, n_micro), mesh,
                        overlap=overlap)
        if "quant" in stacked:
            y_mb, q_new = r
        else:
            y_mb = r
        return mb_major_flatten(y_mb)

    g = define_G(model_cfg, dtype=dtype)
    y = g.apply(
        {"params": variables["params"],
         "batch_stats": variables.get("batch_stats", {})},
        mb_major_flatten(x_mb), False, trunk_fn=trunk_fn,
    )
    y = mb_major_unflatten(y, n_micro)
    return (y, q_new) if with_quant else y


def pp_expand_forward(model_cfg, variables: Dict[str, Any], x_mb: jax.Array,
                      mesh: Mesh,
                      stacked: Optional[Dict[str, Any]] = None,
                      dtype=None, overlap: bool = False) -> jax.Array:
    """Pipelined flagship (ExpandNetwork) forward — the expand-only entry
    point kept for compatibility; :func:`pp_generator_forward` is the
    general form (and the one the PP train step uses)."""
    if model_cfg.generator != "expand":
        raise NotImplementedError(
            "pp_expand_forward pipelines the ExpandNetwork trunk; use "
            "pp_generator_forward for the ResNet family")
    return pp_generator_forward(model_cfg, variables, x_mb, mesh,
                                stacked=stacked, dtype=dtype,
                                overlap=overlap)


# ---------------------------------------------------------------------------
# Trainer wiring: TrainState surgery for the PP step (train/step.py
# build_pp_train_step)
# ---------------------------------------------------------------------------


def _trunk_dict_map(tree, prefix: str, fn):
    """Apply ``fn`` to every dict node of ``tree`` that holds trunk-block
    entries (keys starting with ``prefix``), leaving everything else —
    including the optax wrapper scalars (counts, hyperparams) — intact.
    The Adam mu/nu trees mirror the param tree, so ONE traversal rule
    restructures params, batch_stats, quant, and both moments."""
    def is_trunk_dict(x):
        return isinstance(x, dict) and any(
            isinstance(k, str) and k.startswith(prefix) for k in x)

    return jax.tree_util.tree_map(
        lambda n: fn(n) if is_trunk_dict(n) else n,
        tree, is_leaf=is_trunk_dict)


def _trunk_names(tree: Dict[str, Any], prefix: str):
    names = [n for n in tree if n.startswith(prefix)]
    names.sort(key=lambda n: int(n[len(prefix):]))
    return names


def _gather_stack(tree: Dict[str, Any], prefix: str, n_stages: int,
                  names=None):
    """{block_i: subtree} → one-block-shaped subtree with [S, B] leaves —
    THE stacking law: block ``s*B + j`` lands at ``[s, j]``. Used by
    :func:`stack_trunk` (which passes the params-derived ``names`` so a
    collection missing a block fails loudly) and by the init_opt=False
    moment split on any param-mirroring dict."""
    if names is None:
        names = _trunk_names(tree, prefix)
    per = len(names) // n_stages
    blocks = [tree[n] for n in names]
    flat = jax.tree.map(lambda *leaves: jnp.stack(leaves), *blocks)
    return jax.tree.map(
        lambda a: a.reshape((n_stages, per) + a.shape[1:]), flat)


def unstack_trunk(stacked: Dict[str, Any], prefix: str) -> Dict[str, Any]:
    """Inverse of the ``stack_trunk`` gather on ONE collection subtree:
    a one-block-shaped tree with [S, B] leading axes → ``{prefix}{i}``
    per-block subtrees, block ``s*B + j`` read from ``[s, j]`` (the same
    ordering law, so merge-then-split round-trips bitwise)."""
    leaves = jax.tree_util.tree_leaves(stacked)
    if not leaves:
        return {}
    s, b = leaves[0].shape[:2]
    flat = jax.tree.map(lambda a: a.reshape((s * b,) + a.shape[2:]), stacked)
    return {f"{prefix}{i}": jax.tree.map(lambda a: a[i], flat)
            for i in range(s * b)}


def pp_merge_state(state, cfg, steps_per_epoch: int = 1):
    """Inverse of :func:`pp_split_state`: fold the stage-stacked trunk
    (``pp_stages`` + ``opt_s``) back into the flat generator tree.

    The per-block params / batch_stats / quant entries re-enter
    ``params_g``/``batch_stats_g``/``quant_g`` under their original
    ``{prefix}{i}`` names, and ``opt_g`` is rebuilt over the full tree
    with the trunk leaves' Adam moments UNSTACKED from ``opt_s`` (per-leaf
    Adam is independent per leaf, so the merged trajectory is the split
    one — nothing is re-initialized). The elastic pipe-width migration
    (p2p_tpu.resilience.reshape) uses merge → :func:`pp_split_state`
    (``init_opt=False``) to re-express a checkpoint at any new width,
    pipe→no-pipe and no-pipe→pipe included.
    """
    from p2p_tpu.train.state import make_optimizers

    if state.pp_stages is None:
        return state
    prefix = trunk_prefix(cfg.model)
    stacked = state.pp_stages
    params_g = {**state.params_g, **unstack_trunk(stacked["params"], prefix)}
    batch_stats_g = state.batch_stats_g
    if "batch_stats" in stacked:
        batch_stats_g = {**(batch_stats_g or {}),
                         **unstack_trunk(stacked["batch_stats"], prefix)}
    quant_g = state.quant_g
    if "quant" in stacked:
        quant_g = {**(quant_g or {}),
                   **unstack_trunk(stacked["quant"], prefix)}

    # Rebuild the full-tree opt STRUCTURE, then fill every leaf from its
    # source: non-trunk paths (and the wrapper's count/hyperparams
    # scalars) exist verbatim in opt_g; trunk paths strip their block
    # segment and index [s, j] into the stacked opt_s leaf.
    opt_g, _, _ = make_optimizers(cfg, steps_per_epoch)
    template = opt_g.init(params_g)
    rest = {jax.tree_util.keystr(p): leaf for p, leaf
            in jax.tree_util.tree_flatten_with_path(state.opt_g)[0]}
    stacked_opt = {jax.tree_util.keystr(p): leaf for p, leaf
                   in jax.tree_util.tree_flatten_with_path(state.opt_s)[0]}
    s_b = jax.tree_util.tree_leaves(stacked["params"])[0].shape[:2]
    per = int(s_b[1])

    def fill(path, zero):
        key = jax.tree_util.keystr(path)
        if key in rest:
            return rest[key]
        for k in path:
            name = getattr(k, "key", None)
            if isinstance(name, str) and name.startswith(prefix):
                i = int(name[len(prefix):])
                stripped = key.replace(f"['{name}']", "", 1)
                return stacked_opt[stripped][i // per, i % per]
        raise KeyError(f"opt leaf {key} in neither opt_g nor opt_s")

    merged_opt = jax.tree_util.tree_map_with_path(fill, template)
    return state.replace(
        params_g=params_g,
        batch_stats_g=batch_stats_g,
        quant_g=quant_g,
        opt_g=merged_opt,
        pp_stages=None,
        opt_s=None,
    )


def pp_split_state(state, cfg, mesh: Optional[Mesh] = None,
                   steps_per_epoch: int = 1,
                   n_stages: Optional[int] = None,
                   init_opt: bool = True, place: bool = True):
    """Move the generator trunk out of a flat TrainState into the
    pipe-sharded ``pp_stages`` stack with its own optimizer state.

    The trunk's per-block ``params`` / ``batch_stats`` / ``quant`` entries
    leave ``params_g``/``batch_stats_g``/``quant_g`` (stage weights live
    only on their stage's devices — the point of PP); ``opt_s`` gets the
    same optimizer over the stacked stage params. Per-leaf Adam makes the
    split update trajectory identical to the fused one.

    ``init_opt=True`` (training START): ``opt_g``/``opt_s`` are freshly
    initialized — fresh Adam state is zeros either way. ``init_opt=False``
    (the elastic pipe-width migration): the flat state's LIVE optimizer
    moments are carried — the trunk-less remainder stripped in place, the
    trunk moments stacked under the same [S, B] law as the params — so a
    mid-run checkpoint re-expresses at a new width without losing its
    trajectory. ``n_stages`` defaults to the mesh's pipe width;
    ``place=False`` skips the device placement (template building for a
    cross-topology restore needs shapes, not a mesh).
    """
    from p2p_tpu.train.state import make_optimizers

    prefix = trunk_prefix(cfg.model)
    if n_stages is None:
        n_stages = mesh.shape[PIPE_AXIS]
    variables = {"params": state.params_g}
    if state.batch_stats_g:
        variables["batch_stats"] = state.batch_stats_g
    if state.quant_g:
        variables["quant"] = state.quant_g
    stacked = stack_trunk(variables, n_stages, prefix=prefix)
    if place:
        stacked = place_trunk_pp(stacked, mesh)

    def strip(tree):
        if not tree:
            return tree
        return {k: v for k, v in tree.items() if not k.startswith(prefix)}

    params_rest = strip(state.params_g)
    if init_opt:
        # optax transforms are stateless — ONE generator-family optimizer
        # serves both the trunk-less tree and the stage stack
        opt_g, _, _ = make_optimizers(cfg, steps_per_epoch)
        new_opt_g = opt_g.init(params_rest)
        new_opt_s = opt_g.init(stacked["params"])
    else:
        new_opt_g = _trunk_dict_map(state.opt_g, prefix, strip)
        new_opt_s = _trunk_dict_map(
            state.opt_g, prefix,
            lambda t: _gather_stack(t, prefix, n_stages))
    return state.replace(
        params_g=params_rest,
        batch_stats_g=strip(state.batch_stats_g),
        quant_g=(strip(state.quant_g)
                 if state.quant_g is not None else None),
        opt_g=new_opt_g,
        pp_stages=stacked,
        opt_s=new_opt_s,
    )
