"""Rule-driven partition-spec derivation over named state trees.

The seed of the declarative sharding-rule engine (ROADMAP item 3, the
regex-over-named-tree ``match_partition_rules`` pattern of SNIPPETS [1]/
[2]): ONE ordered rule table — ``(regex, PartitionSpec)`` pairs matched
against slash-joined leaf paths — produces the PartitionSpec tree for an
arbitrary pytree (params, optimizer moments, or a whole TrainState; adam's
mu/nu mirror the param paths, so one param rule covers all three).

**Predicate rules** (the item-3 migration mechanism): a rule may carry a
third element, ``predicate(shape) -> bool`` — the rule fires only when its
regex matches AND the predicate accepts the leaf shape. This is exactly
the expressive gap the tp-diff worklist names ``needs-predicate-rule``:
the hand-built TP assignment (parallel/tp.py) gates every shard on
channel width and divisibility, which a bare regex cannot see.
:func:`make_unet_tp_rules` / :func:`make_patchgan_tp_rules` use it to
reproduce ``tp_leaf_spec`` declaratively for the facades (U-Net +
PatchGAN) family — the first family drained from the worklist; the
ResNet/pix2pixHD trunks are the remaining entries.

First consumer: the elastic resharded-resume path (train/loop.py
``plan_elastic_restore``). A relaunch on a different slice derives the
checkpoint's **target shardings for the NEW mesh** from rules instead of
from the dead run's layout — today the table is narrow (replicate
everything; Megatron channel shards via the TP pair rule when the model
axis is real), but the derivation is already the single place a future
FSDP/ZeRO rule-set plugs into.

Scalars (and 1-element leaves) never partition — the universal floor rule
the snippets agree on.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_tpu.core.mesh import MODEL_AXIS

#: ``(regex, PartitionSpec)`` or ``(regex, PartitionSpec, predicate)``
#: entries, first match wins (re.search semantics; a predicate rule only
#: matches when ``predicate(shape)`` is also true).
Rules = Sequence[Tuple]

ShapePredicate = Callable[[Tuple[int, ...]], bool]


def rule_parts(rule) -> Tuple[str, P, Optional[ShapePredicate]]:
    """Normalize a 2- or 3-tuple rule entry to ``(pattern, spec, pred)``."""
    if len(rule) == 2:
        return rule[0], rule[1], None
    pat, spec, pred = rule
    return pat, spec, pred

#: The baseline table: fully-replicated state — correct for DP and for
#: every mesh whose extra axes (spatial/time/pipe) shard activations, not
#: parameters. TP layers its pair rule ON TOP via make_tp_rule.
REPLICATED_RULES: Rules = ((r".*", P()),)


def leaf_path_name(path) -> str:
    """``jax.tree_util`` key path → slash-joined rule-matchable name,
    e.g. ``params_g/down1/conv/kernel``."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            # pinned fallback for unknown key types (a future jax key kind
            # must not silently change every rule-matchable path): the
            # type name is part of the segment, so a rule written against
            # the old ``str(k)`` form fails LOUDLY instead of matching a
            # different leaf. Format pinned by tests/test_elastic.py.
            parts.append(f"<{type(k).__name__}:{k}>")
    return "/".join(parts)


def match_partition_rules(rules: Rules, tree: Any):
    """PartitionSpec pytree for ``tree`` from an ordered rule table.

    Every leaf must match some rule (append a ``(".*", P())`` catch-all
    for replicate-by-default); an unmatched leaf raises — silently
    replicating a leaf the table meant to shard is how layout bugs hide.
    """

    def spec_for(path, leaf):
        name = leaf_path_name(path)
        shape = np.shape(leaf) if not hasattr(leaf, "shape") else leaf.shape
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalars
        for rule in rules:
            pat, ps, pred = rule_parts(rule)
            if re.search(pat, name) is not None \
                    and (pred is None or pred(tuple(shape))):
                return ps
        tried = "; ".join(f"[{i}] {rule_parts(r)[0]!r}"
                          for i, r in enumerate(rules))
        raise ValueError(f"no partition rule matched leaf {name!r} "
                         f"(shape {tuple(shape)}); tried "
                         f"{tried or '<empty table>'} — add a catch-all "
                         f"rule ('.*', P())")

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def state_target_shardings(state: Any, mesh: Mesh,
                           rules: Optional[Rules] = None,
                           tp_min_ch: int = 512):
    """NamedSharding pytree: the restore-target layout of ``state`` on
    ``mesh`` — the elastic resharded-restore's source of truth.

    ``rules=None`` picks the layout the trainers actually run: the
    Megatron TP tree when the mesh has a real model axis (delegating to
    :func:`p2p_tpu.parallel.tp.tp_sharding_tree`, whose pair rule is
    shape-conditional — outside the regex table's reach until rules grow
    predicates), fully replicated otherwise.
    """
    if rules is None:
        if mesh.shape.get(MODEL_AXIS, 1) > 1:
            from p2p_tpu.parallel.tp import tp_sharding_tree

            return tp_sharding_tree(state, mesh, min_ch=tp_min_ch)
        rules = REPLICATED_RULES
    specs = match_partition_rules(rules, state)
    return jax.tree_util.tree_map(lambda ps: NamedSharding(mesh, ps), specs,
                                  is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# Family TP tables — predicate rules reproducing parallel/tp.tp_leaf_spec
# declaratively, family by family (the item-3 worklist drain).
# ---------------------------------------------------------------------------

_OUT_K = P(None, None, None, MODEL_AXIS)   # conv kernel, C_out sharded
_IN_K = P(None, None, MODEL_AXIS, None)    # conv kernel, C_in sharded
_OUT_B = P(MODEL_AXIS)                     # bias riding a sharded C_out


def _gate_out(axis_size: int, min_ch: int) -> ShapePredicate:
    return lambda s: (len(s) == 4 and s[3] >= min_ch
                      and s[3] % axis_size == 0)


def _gate_in(axis_size: int, min_ch: int) -> ShapePredicate:
    return lambda s: (len(s) == 4 and s[2] >= min_ch
                      and s[2] % axis_size == 0)


def _gate_bias(axis_size: int, min_ch: int) -> ShapePredicate:
    return lambda s: (len(s) == 1 and s[0] >= min_ch
                      and s[0] % axis_size == 0)


def _log2_odd(n: int) -> bool:
    # exact power of two with odd exponent — the PatchGAN chain parity key
    return n > 0 and (n & (n - 1)) == 0 and (n.bit_length() - 1) % 2 == 1


def make_unet_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The U-Net generator's Megatron pairs as predicate rules: (down3 →
    down4) and the bottleneck (down5 → up5), kernels only (the U-Net down
    convs carry no bias — BatchNorm absorbs it). Width/divisibility gates
    mirror :func:`p2p_tpu.parallel.tp.tp_leaf_spec` exactly."""
    out, inn = _gate_out(axis_size, min_ch), _gate_in(axis_size, min_ch)
    return (
        (r"down3/kernel$", _OUT_K, out),
        (r"down4/kernel$", _IN_K, inn),
        (r"down5/kernel$", _OUT_K, out),
        (r"up5/kernel$", _IN_K, inn),
    )


def make_patchgan_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The PatchGAN discriminator chains as predicate rules. The conv
    names differ per preset (``_PlainConv_k`` / ``SpectralConv_k``), so
    the rules key on the channel-doubling chain's log2-parity — the same
    shape law ``tp_leaf_spec`` applies: an odd-power C_in in-shards (with
    one psum), an odd-power C_out out-shards, gates replicate the rest.
    The bare in-parity rule (no gate) BLOCKS a gate-failed in-parity
    kernel from falling through to the out rule — precedence mirrors
    ``_tp_spec`` checking C_in first."""
    out, inn = _gate_out(axis_size, min_ch), _gate_in(axis_size, min_ch)
    bias = _gate_bias(axis_size, min_ch)
    return (
        (r"scale\d+/.*/kernel$", _IN_K,
         lambda s: len(s) == 4 and _log2_odd(s[2]) and inn(s)),
        (r"scale\d+/.*/kernel$", P(),
         lambda s: len(s) == 4 and _log2_odd(s[2])),
        (r"scale\d+/.*/kernel$", _OUT_K,
         lambda s: len(s) == 4 and _log2_odd(s[3]) and out(s)),
        (r"scale\d+/.*/bias$", _OUT_B,
         lambda s: len(s) == 1 and _log2_odd(s[0]) and bias(s)),
    )


def make_resnet_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The ResNet-trunk Megatron pairs as predicate rules (ISSUE 13
    satellite — the item-3 worklist drain for the ResNet/pix2pixHD
    families): each residual block's conv pair (``ConvLayer_0`` C_out →
    ``ConvLayer_1`` C_in, one psum per block), the encoder's deepest
    transition (``ConvLayer_3`` → ``ConvLayer_4``) and the decoder's
    (``UpsampleConvLayer_0`` → ``UpsampleConvLayer_1``) — cityscapes at
    the generator root, pix2pixHD under its ``global`` subtree, the
    flagship ExpandNetwork via the ``ResidualBlock`` naming. Kernels
    only: these trunks run norm layers that absorb no bias and their
    convs carry none (a model that grows sharded-width biases shows up
    as a tp-diff gap, which is exactly the worklist's job). The
    ``(?:^|/)`` anchor keeps ``ConvLayer_3`` from matching inside
    ``UpsampleConvLayer_3``-style names."""
    out, inn = _gate_out(axis_size, min_ch), _gate_in(axis_size, min_ch)
    return (
        (r"Res(?:net|idual)Block_\d+/ConvLayer_0/Conv_0/kernel$",
         _OUT_K, out),
        (r"Res(?:net|idual)Block_\d+/ConvLayer_1/Conv_0/kernel$",
         _IN_K, inn),
        (r"(?:^|/)ConvLayer_3/Conv_0/kernel$", _OUT_K, out),
        (r"(?:^|/)ConvLayer_4/Conv_0/kernel$", _IN_K, inn),
        (r"(?:^|/)UpsampleConvLayer_0/Conv_0/kernel$", _OUT_K, out),
        (r"(?:^|/)UpsampleConvLayer_1/Conv_0/kernel$", _IN_K, inn),
    )


def tp_equivalence_rules(cfg, axis_size: int = 2,
                         min_ch: int = 512) -> Optional[Rules]:
    """The declarative table reproducing ``tp_leaf_spec`` for ``cfg``'s
    model family, or None for an unknown family. ALL preset families are
    drained (zero tp-diff gaps, pinned + CI-grepped): the facades family
    (U-Net G + PatchGAN D), and — ISSUE 13 — the ResNet/pix2pixHD/Expand
    trunks plus their multiscale PatchGAN discriminators.

    The trunk rules join the table only when the family's widest trunk
    conv can clear the ``min_ch`` floor (pix2pixHD's global trunk tops
    out at ``16·ngf``, the plain ResNet/Expand trunks at ``4·ngf``) —
    below it every trunk gate is provably never-true and the rules would
    only audit as dead. The audit + tp-diff pins in tests/test_analysis
    verify the width law against the real preset states."""
    gen = cfg.model.generator
    if gen == "unet":
        return (make_unet_tp_rules(axis_size, min_ch)
                + make_patchgan_tp_rules(axis_size, min_ch)
                + ((r".*", P()),))
    if gen in ("resnet", "pix2pixhd", "expand"):
        trunk_top = cfg.model.ngf * (16 if gen == "pix2pixhd" else 4)
        trunk = (make_resnet_tp_rules(axis_size, min_ch)
                 if trunk_top >= min_ch else ())
        return (trunk + make_patchgan_tp_rules(axis_size, min_ch)
                + ((r".*", P()),))
    return None
