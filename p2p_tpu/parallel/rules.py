"""Rule-driven partition-spec derivation over named state trees — THE
sharding authority for the whole TrainState (ROADMAP item 3, closed by
ISSUE 15).

The regex-over-named-tree ``match_partition_rules`` pattern of SNIPPETS
[1]/[2]: ONE ordered rule table matched against slash-joined leaf paths
produces the PartitionSpec tree for an arbitrary pytree (params,
optimizer moments, EMA, or a whole TrainState; adam's mu/nu mirror the
param paths, so one param rule covers all three). Every live layout —
CLI trainer placement, serving-engine placement, the elastic restore
targets, the static memory budget — derives from
:func:`state_target_shardings` over :func:`trainstate_rules`; the old
hand-built TP tree builder in ``parallel/tp.py`` is a thin shim over
these tables (a CI grep gate keeps it that way).

Rule entries, first ``re.search`` match wins:

- ``(regex, PartitionSpec)``;
- ``(regex, PartitionSpec, predicate)`` — **predicate rules**: fires only
  when ``predicate(shape)`` also accepts the leaf shape (the TP tables
  gate every channel shard on width/divisibility, which a bare regex
  cannot see);
- ``(regex, spec_builder)`` where ``spec_builder(shape) -> PartitionSpec``
  — **spec-builder rules** (ISSUE 15): the FSDP table needs a
  per-shape DIMENSION choice (shard a conv kernel's C_out, a bias's only
  dim), which a fixed spec cannot express; the builder keeps the table
  declarative while choosing the partitioned dim per leaf.

Tables:

- :func:`make_tp_rules` — the union of the per-family Megatron TP tables
  (U-Net + ResNet/pix2pixHD/Expand trunks + PatchGAN chains), pinned
  equal to the retired hand-built assignment (zero tp-diff gaps, CI-
  grepped);
- :func:`make_fsdp_rules` — ZeRO-style state sharding over the ``fsdp``
  mesh axis: Adam moments (``opt_g/d/c``) and ``ema_g`` partition along
  the data dimension (ZeRO-1); ``fsdp_params=True`` additionally shards
  ``params_g/d/c`` (ZeRO-3-ish, gather-on-use left to GSPMD via the pjit
  in/out shardings — no hand-written collectives anywhere);
- :func:`trainstate_rules` composes them for a mesh: TP pairs claim
  their leaves first (a TP-sharded moment mirrors its param shard), the
  FSDP rules claim the rest of the optimizer/EMA state, a catch-all
  replicates the remainder.

Scalars (and 1-element leaves) never partition — the universal floor rule
the snippets agree on.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_tpu.core.mesh import FSDP_AXIS, MODEL_AXIS

#: ``(regex, spec_or_builder[, predicate])`` entries, first match wins
#: (re.search semantics; a predicate rule only matches when
#: ``predicate(shape)`` is also true; a callable spec is resolved per
#: leaf as ``spec(shape)``).
Rules = Sequence[Tuple]

ShapePredicate = Callable[[Tuple[int, ...]], bool]
SpecBuilder = Callable[[Tuple[int, ...]], P]
SpecLike = Union[P, SpecBuilder]


def rule_parts(rule) -> Tuple[str, SpecLike, Optional[ShapePredicate]]:
    """Normalize a 2- or 3-tuple rule entry to ``(pattern, spec, pred)``."""
    if len(rule) == 2:
        return rule[0], rule[1], None
    pat, spec, pred = rule
    return pat, spec, pred


def resolve_spec(spec: SpecLike, shape) -> P:
    """A rule's concrete PartitionSpec for one leaf: fixed specs pass
    through, spec builders are called with the leaf shape."""
    return spec(tuple(shape)) if callable(spec) else spec

#: The baseline table: fully-replicated state — correct for DP and for
#: every mesh whose extra axes (spatial/time/pipe) shard activations, not
#: parameters. trainstate_rules layers the TP/FSDP tables ON TOP.
REPLICATED_RULES: Rules = ((r".*", P()),)


def leaf_path_name(path) -> str:
    """``jax.tree_util`` key path → slash-joined rule-matchable name,
    e.g. ``params_g/down1/conv/kernel``."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            # pinned fallback for unknown key types (a future jax key kind
            # must not silently change every rule-matchable path): the
            # type name is part of the segment, so a rule written against
            # the old ``str(k)`` form fails LOUDLY instead of matching a
            # different leaf. Format pinned by tests/test_elastic.py.
            parts.append(f"<{type(k).__name__}:{k}>")
    return "/".join(parts)


def match_partition_rules(rules: Rules, tree: Any):
    """PartitionSpec pytree for ``tree`` from an ordered rule table.

    Every leaf must match some rule (append a ``(".*", P())`` catch-all
    for replicate-by-default); an unmatched leaf raises — silently
    replicating a leaf the table meant to shard is how layout bugs hide.
    """

    def spec_for(path, leaf):
        name = leaf_path_name(path)
        shape = np.shape(leaf) if not hasattr(leaf, "shape") else leaf.shape
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalars
        for rule in rules:
            pat, ps, pred = rule_parts(rule)
            if re.search(pat, name) is not None \
                    and (pred is None or pred(tuple(shape))):
                return resolve_spec(ps, shape)
        tried = "; ".join(f"[{i}] {rule_parts(r)[0]!r}"
                          for i, r in enumerate(rules))
        raise ValueError(f"no partition rule matched leaf {name!r} "
                         f"(shape {tuple(shape)}); tried "
                         f"{tried or '<empty table>'} — add a catch-all "
                         f"rule ('.*', P())")

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def state_target_shardings(state: Any, mesh: Mesh,
                           rules: Optional[Rules] = None,
                           tp_min_ch: int = 512,
                           fsdp_params: bool = False):
    """NamedSharding pytree: THE layout of ``state`` on ``mesh`` — the
    single source of truth for trainer placement, serving placement, and
    the elastic restore targets.

    ``rules=None`` derives the table from the mesh itself via
    :func:`trainstate_rules`: Megatron TP pair shards when the ``model``
    axis is real, ZeRO optimizer/EMA shards when the ``fsdp`` axis is
    real (params too under ``fsdp_params``), replicated otherwise.
    """
    if rules is None:
        rules = trainstate_rules(dict(mesh.shape), tp_min_ch=tp_min_ch,
                                 fsdp_params=fsdp_params)
    specs = match_partition_rules(rules, state)
    return jax.tree_util.tree_map(lambda ps: NamedSharding(mesh, ps), specs,
                                  is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# Family TP tables — predicate rules reproducing parallel/tp.tp_leaf_spec
# declaratively, family by family (the item-3 worklist drain).
# ---------------------------------------------------------------------------

_OUT_K = P(None, None, None, MODEL_AXIS)   # conv kernel, C_out sharded
_IN_K = P(None, None, MODEL_AXIS, None)    # conv kernel, C_in sharded
_OUT_B = P(MODEL_AXIS)                     # bias riding a sharded C_out


def _gate_out(axis_size: int, min_ch: int) -> ShapePredicate:
    return lambda s: (len(s) == 4 and s[3] >= min_ch
                      and s[3] % axis_size == 0)


def _gate_in(axis_size: int, min_ch: int) -> ShapePredicate:
    return lambda s: (len(s) == 4 and s[2] >= min_ch
                      and s[2] % axis_size == 0)


def _gate_bias(axis_size: int, min_ch: int) -> ShapePredicate:
    return lambda s: (len(s) == 1 and s[0] >= min_ch
                      and s[0] % axis_size == 0)


def _log2_odd(n: int) -> bool:
    # exact power of two with odd exponent — the PatchGAN chain parity key
    return n > 0 and (n & (n - 1)) == 0 and (n.bit_length() - 1) % 2 == 1


def make_unet_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The U-Net generator's Megatron pairs as predicate rules: (down3 →
    down4) and the bottleneck (down5 → up5), kernels only (the U-Net down
    convs carry no bias — BatchNorm absorbs it). Width/divisibility gates
    mirror :func:`p2p_tpu.parallel.tp.tp_leaf_spec` exactly."""
    out, inn = _gate_out(axis_size, min_ch), _gate_in(axis_size, min_ch)
    return (
        (r"down3/kernel$", _OUT_K, out),
        (r"down4/kernel$", _IN_K, inn),
        (r"down5/kernel$", _OUT_K, out),
        (r"up5/kernel$", _IN_K, inn),
    )


def make_patchgan_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The PatchGAN discriminator chains as predicate rules. The conv
    names differ per preset (``_PlainConv_k`` / ``SpectralConv_k``), so
    the rules key on the channel-doubling chain's log2-parity — the same
    shape law ``tp_leaf_spec`` applies: an odd-power C_in in-shards (with
    one psum), an odd-power C_out out-shards, gates replicate the rest.
    The bare in-parity rule (no gate) BLOCKS a gate-failed in-parity
    kernel from falling through to the out rule — precedence mirrors
    ``_tp_spec`` checking C_in first."""
    out, inn = _gate_out(axis_size, min_ch), _gate_in(axis_size, min_ch)
    bias = _gate_bias(axis_size, min_ch)
    return (
        (r"scale\d+/.*/kernel$", _IN_K,
         lambda s: len(s) == 4 and _log2_odd(s[2]) and inn(s)),
        (r"scale\d+/.*/kernel$", P(),
         lambda s: len(s) == 4 and _log2_odd(s[2])),
        (r"scale\d+/.*/kernel$", _OUT_K,
         lambda s: len(s) == 4 and _log2_odd(s[3]) and out(s)),
        (r"scale\d+/.*/bias$", _OUT_B,
         lambda s: len(s) == 1 and _log2_odd(s[0]) and bias(s)),
    )


def make_resnet_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The ResNet-trunk Megatron pairs as predicate rules (ISSUE 13
    satellite — the item-3 worklist drain for the ResNet/pix2pixHD
    families): each residual block's conv pair (``ConvLayer_0`` C_out →
    ``ConvLayer_1`` C_in, one psum per block), the encoder's deepest
    transition (``ConvLayer_3`` → ``ConvLayer_4``) and the decoder's
    (``UpsampleConvLayer_0`` → ``UpsampleConvLayer_1``) — cityscapes at
    the generator root, pix2pixHD under its ``global`` subtree, the
    flagship ExpandNetwork via the ``ResidualBlock`` naming. Kernels
    only: these trunks run norm layers that absorb no bias and their
    convs carry none (a model that grows sharded-width biases shows up
    as a tp-diff gap, which is exactly the worklist's job). The
    ``(?:^|/)`` anchor keeps ``ConvLayer_3`` from matching inside
    ``UpsampleConvLayer_3``-style names."""
    out, inn = _gate_out(axis_size, min_ch), _gate_in(axis_size, min_ch)
    return (
        (r"Res(?:net|idual)Block_\d+/ConvLayer_0/Conv_0/kernel$",
         _OUT_K, out),
        (r"Res(?:net|idual)Block_\d+/ConvLayer_1/Conv_0/kernel$",
         _IN_K, inn),
        (r"(?:^|/)ConvLayer_3/Conv_0/kernel$", _OUT_K, out),
        (r"(?:^|/)ConvLayer_4/Conv_0/kernel$", _IN_K, inn),
        (r"(?:^|/)UpsampleConvLayer_0/Conv_0/kernel$", _OUT_K, out),
        (r"(?:^|/)UpsampleConvLayer_1/Conv_0/kernel$", _IN_K, inn),
    )


def tp_equivalence_rules(cfg, axis_size: int = 2,
                         min_ch: int = 512) -> Optional[Rules]:
    """The declarative table reproducing ``tp_leaf_spec`` for ``cfg``'s
    model family, or None for an unknown family. ALL preset families are
    drained (zero tp-diff gaps, pinned + CI-grepped): the facades family
    (U-Net G + PatchGAN D), and — ISSUE 13 — the ResNet/pix2pixHD/Expand
    trunks plus their multiscale PatchGAN discriminators.

    The trunk rules join the table only when the family's widest trunk
    conv can clear the ``min_ch`` floor (pix2pixHD's global trunk tops
    out at ``16·ngf``, the plain ResNet/Expand trunks at ``4·ngf``) —
    below it every trunk gate is provably never-true and the rules would
    only audit as dead. The audit + tp-diff pins in tests/test_analysis
    verify the width law against the real preset states."""
    gen = cfg.model.generator
    if gen == "unet":
        return (make_unet_tp_rules(axis_size, min_ch)
                + make_patchgan_tp_rules(axis_size, min_ch)
                + ((r".*", P()),))
    if gen in ("resnet", "pix2pixhd", "expand"):
        trunk_top = cfg.model.ngf * (16 if gen == "pix2pixhd" else 4)
        trunk = (make_resnet_tp_rules(axis_size, min_ch)
                 if trunk_top >= min_ch else ())
        return (trunk + make_patchgan_tp_rules(axis_size, min_ch)
                + ((r".*", P()),))
    return None


# ---------------------------------------------------------------------------
# The ONE partitioner (ISSUE 15): TP union + FSDP tables + composition.
# ---------------------------------------------------------------------------


def make_tp_rules(axis_size: int = 2, min_ch: int = 512) -> Tuple:
    """The family-agnostic Megatron TP table: the UNION of every drained
    family's predicate rules (the generator naming families are disjoint
    — ``down3`` only exists in the U-Net, ``ConvLayer``/``ResnetBlock``
    only in the ResNet trunks, ``scale\\d+`` only in the PatchGAN Ds — so
    the union reproduces the retired hand-built assignment on ANY state
    tree the repo builds; the per-preset zero-gap pins in
    tests/test_analysis are the proof). No catch-all: this composes
    inside :func:`trainstate_rules`."""
    return (make_unet_tp_rules(axis_size, min_ch)
            + make_resnet_tp_rules(axis_size, min_ch)
            + make_patchgan_tp_rules(axis_size, min_ch))


#: the TrainState fields the FSDP table shards (ZeRO-1: pure per-device
#: replicated memory today — exactly what memory_budget.json quantifies).
#: ``opt_s``/``pp_stages`` are deliberately absent: the PP stage stack
#: shards over the ``pipe`` axis through parallel/pp.py's own machinery,
#: and composing fsdp×pipe layouts is not expressible until a real mesh
#: needs it.
FSDP_STATE_RE = r"^(?:opt_[gdc]|ema_g)(?:/|$)"
FSDP_PARAMS_RE = r"^params_[gdc](?:/|$)"


def fsdp_shard_spec(axis_size: int, axis: str = FSDP_AXIS) -> SpecBuilder:
    """Spec builder: partition the TRAILING divisible dim of a leaf over
    ``axis`` (C_out on a conv kernel, the only dim of a bias/scale),
    replicate when no dim divides — the ZeRO floor that keeps odd-width
    leaves (a 3-channel image-head kernel's C_out) legal without
    per-leaf wiring. Trailing-first keeps the partitioned dim the
    channel dim wherever one exists, mirroring the TP convention."""
    n = int(axis_size)

    def spec(shape: Tuple[int, ...]) -> P:
        for d in range(len(shape) - 1, -1, -1):
            if shape[d] >= n and shape[d] % n == 0:
                entries = [None] * len(shape)
                entries[d] = axis
                return P(*entries)
        return P()

    return spec


def make_fsdp_rules(axis_size: int, fsdp_params: bool = False) -> Tuple:
    """ZeRO-style state sharding over the ``fsdp`` mesh axis as TWO
    spec-builder rules: Adam moments + EMA always (ZeRO-1 — the state
    that is pure replicated HBM today), ``params_*`` behind the
    ``fsdp_params`` knob (ZeRO-3-ish; GSPMD inserts the gather-on-use
    from the pjit in/out shardings). Gradient reduce-scatter (ZeRO-2)
    falls out for free: XLA sees sharded moment outputs and scatters the
    grads feeding them instead of all-reducing."""
    builder = fsdp_shard_spec(axis_size)
    rules: Tuple = ((FSDP_STATE_RE, builder),)
    if fsdp_params:
        rules = ((FSDP_PARAMS_RE, builder),) + rules
    return rules


def trainstate_rules(axis_sizes: Dict[str, int], tp_min_ch: int = 512,
                     fsdp_params: bool = False) -> Rules:
    """THE rule table for a mesh topology (axis-name → size dict; no
    devices needed, so hypothetical meshes audit/budget on one CPU):
    TP pair rules first when the ``model`` axis is real (a TP-claimed
    moment mirrors its param's channel shard), then the FSDP state rules
    when the ``fsdp`` axis is real, then the replicate catch-all."""
    rules: Tuple = ()
    model = int(axis_sizes.get(MODEL_AXIS, 1) or 1)
    if model > 1:
        rules += make_tp_rules(model, tp_min_ch)
    fsdp = int(axis_sizes.get(FSDP_AXIS, 1) or 1)
    if fsdp > 1:
        rules += make_fsdp_rules(fsdp, fsdp_params=fsdp_params)
    return rules + ((r".*", P()),)
