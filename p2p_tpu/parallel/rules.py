"""Rule-driven partition-spec derivation over named state trees.

The seed of the declarative sharding-rule engine (ROADMAP item 3, the
regex-over-named-tree ``match_partition_rules`` pattern of SNIPPETS [1]/
[2]): ONE ordered rule table — ``(regex, PartitionSpec)`` pairs matched
against slash-joined leaf paths — produces the PartitionSpec tree for an
arbitrary pytree (params, optimizer moments, or a whole TrainState; adam's
mu/nu mirror the param paths, so one param rule covers all three).

First consumer: the elastic resharded-resume path (train/loop.py
``plan_elastic_restore``). A relaunch on a different slice derives the
checkpoint's **target shardings for the NEW mesh** from rules instead of
from the dead run's layout — today the table is narrow (replicate
everything; Megatron channel shards via the TP pair rule when the model
axis is real), but the derivation is already the single place a future
FSDP/ZeRO rule-set plugs into.

Scalars (and 1-element leaves) never partition — the universal floor rule
the snippets agree on.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_tpu.core.mesh import MODEL_AXIS

#: (regex, PartitionSpec) pairs, first match wins (re.search semantics).
Rules = Sequence[Tuple[str, P]]

#: The baseline table: fully-replicated state — correct for DP and for
#: every mesh whose extra axes (spatial/time/pipe) shard activations, not
#: parameters. TP layers its pair rule ON TOP via make_tp_rule.
REPLICATED_RULES: Rules = ((r".*", P()),)


def leaf_path_name(path) -> str:
    """``jax.tree_util`` key path → slash-joined rule-matchable name,
    e.g. ``params_g/down1/conv/kernel``."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            # pinned fallback for unknown key types (a future jax key kind
            # must not silently change every rule-matchable path): the
            # type name is part of the segment, so a rule written against
            # the old ``str(k)`` form fails LOUDLY instead of matching a
            # different leaf. Format pinned by tests/test_elastic.py.
            parts.append(f"<{type(k).__name__}:{k}>")
    return "/".join(parts)


def match_partition_rules(rules: Rules, tree: Any):
    """PartitionSpec pytree for ``tree`` from an ordered rule table.

    Every leaf must match some rule (append a ``(".*", P())`` catch-all
    for replicate-by-default); an unmatched leaf raises — silently
    replicating a leaf the table meant to shard is how layout bugs hide.
    """

    def spec_for(path, leaf):
        name = leaf_path_name(path)
        shape = np.shape(leaf) if not hasattr(leaf, "shape") else leaf.shape
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalars
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        tried = "; ".join(f"[{i}] {pat!r}" for i, (pat, _) in enumerate(rules))
        raise ValueError(f"no partition rule matched leaf {name!r} "
                         f"(shape {tuple(shape)}); tried "
                         f"{tried or '<empty table>'} — add a catch-all "
                         f"rule ('.*', P())")

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def state_target_shardings(state: Any, mesh: Mesh,
                           rules: Optional[Rules] = None,
                           tp_min_ch: int = 512):
    """NamedSharding pytree: the restore-target layout of ``state`` on
    ``mesh`` — the elastic resharded-restore's source of truth.

    ``rules=None`` picks the layout the trainers actually run: the
    Megatron TP tree when the mesh has a real model axis (delegating to
    :func:`p2p_tpu.parallel.tp.tp_sharding_tree`, whose pair rule is
    shape-conditional — outside the regex table's reach until rules grow
    predicates), fully replicated otherwise.
    """
    if rules is None:
        if mesh.shape.get(MODEL_AXIS, 1) > 1:
            from p2p_tpu.parallel.tp import tp_sharding_tree

            return tp_sharding_tree(state, mesh, min_ch=tp_min_ch)
        rules = REPLICATED_RULES
    specs = match_partition_rules(rules, state)
    return jax.tree_util.tree_map(lambda ps: NamedSharding(mesh, ps), specs,
                                  is_leaf=lambda x: isinstance(x, P))
