"""GSPMD spatial sharding — large images split along H over the ``spatial``
mesh axis (BASELINE configs[2] Cityscapes 512×256, configs[3] pix2pixHD
1024×512).

Two complementary paths, per the scaling-book recipe ("annotate shardings,
let XLA insert collectives, profile, hand-optimize what's left"):

1. **GSPMD path (default).** Shard the batch ``P('data', 'spatial', None,
   None)`` and ``jit`` the whole train step. XLA's spatial partitioner
   inserts the conv halo exchanges itself — including for the stride-2
   encoder convs where manual index bookkeeping is error-prone. This is the
   production path; ``p2p_tpu.parallel.dp.make_parallel_train_step`` uses it
   for every preset.

2. **shard_map path (hand-optimized).** For the stride-1 ResidualBlock trunk
   (9 × k3 convs at 128ch — the FLOPs bulk of ExpandNetwork/ResnetGenerator,
   ref networks.py:472-480), :func:`sharded_conv2d` does one explicit
   nearest-neighbor ``ppermute`` halo exchange per conv and computes purely
   locally, guaranteeing no accidental resharding. Verified bitwise against
   the unsharded conv in tests/test_parallel.py.

Halo sizing: a stack of stride-1 convs with kernels k_i needs Σ (k_i // 2)
halo rows if exchanged once up front, or k//2 per conv if exchanged per-conv;
:func:`residual_block_sharded` exchanges once per conv (2 rows/block) which
keeps each message at ~W×128×4 bytes — latency-bound but overlappable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from p2p_tpu.core.mesh import SPATIAL_AXIS, shard_map_compat as shard_map
from p2p_tpu.parallel.halo import halo_exchange

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def conv2d_local(
    x: jax.Array,
    kernel: jax.Array,
    *,
    stride: int = 1,
    w_pad_mode: str = "reflect",
) -> jax.Array:
    """Plain local conv, H already halo-padded; W padded locally (unsharded)."""
    pw = kernel.shape[1] // 2
    if pw:
        if w_pad_mode == "reflect":
            x = jnp.pad(x, ((0, 0), (0, 0), (pw, pw), (0, 0)), mode="reflect")
        elif w_pad_mode == "zero":
            x = jnp.pad(x, ((0, 0), (0, 0), (pw, pw), (0, 0)))
        elif w_pad_mode == "wrap":
            x = jnp.pad(x, ((0, 0), (0, 0), (pw, pw), (0, 0)), mode="wrap")
        else:
            raise ValueError(f"unknown w_pad_mode {w_pad_mode!r}")
    dn = lax.conv_dimension_numbers(x.shape, kernel.shape, _DIMNUMS)
    return lax.conv_general_dilated(
        x, kernel, (stride, stride), "VALID", dimension_numbers=dn
    )


def sharded_conv2d(
    x: jax.Array,
    kernel: jax.Array,
    *,
    axis_name: str = SPATIAL_AXIS,
    edge_mode: str = "reflect",
) -> jax.Array:
    """Stride-1 'same' conv on an H-sharded NHWC shard (inside shard_map).

    One bidirectional ppermute of k//2 boundary rows, then a fully local
    VALID conv — the per-shard output rows exactly equal the corresponding
    slice of the unsharded conv output.
    """
    kh = kernel.shape[0]
    halo = kh // 2
    x = halo_exchange(x, dim=1, halo=halo, axis_name=axis_name,
                      edge_mode=edge_mode)
    return conv2d_local(x, kernel, stride=1, w_pad_mode=edge_mode)


def make_sharded_conv(
    mesh: Mesh,
    *,
    axis_name: str = SPATIAL_AXIS,
    edge_mode: str = "reflect",
):
    """Wrap :func:`sharded_conv2d` in shard_map over ``mesh`` for global
    NHWC arrays sharded along H. Returns ``fn(x_global, kernel) -> y_global``.
    """
    spec_x = P(None, axis_name, None, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec_x, P()),
        out_specs=spec_x,
    )
    def _fn(x, kernel):
        return sharded_conv2d(
            x, kernel, axis_name=axis_name, edge_mode=edge_mode
        )

    return _fn


def spatial_activation_sharding(mesh: Mesh) -> NamedSharding:
    """NHWC activations: H over the spatial axis (batch replicated)."""
    return NamedSharding(mesh, P(None, SPATIAL_AXIS, None, None))


def check_spatial_divisible(h: int, mesh: Mesh, n_downsamples: int = 2) -> None:
    """Validate that H stays divisible by the spatial axis through the
    generator's stride-2 encoder (deepest feature map must still split)."""
    n_shards = mesh.shape[SPATIAL_AXIS]
    deepest = h >> n_downsamples
    if deepest % n_shards:
        raise ValueError(
            f"image height {h} → deepest feature height {deepest} is not "
            f"divisible by spatial={n_shards}"
        )
