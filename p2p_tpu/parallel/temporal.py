"""Temporal sequence parallelism — video clips sharded over the ``time``
mesh axis (BASELINE configs[4]: vid2vid 8-frame temporal discriminator).

The reference has no video path at all (SURVEY.md §5.7: no attention, no
sequence dim; this config is a requirement on the new framework). Frames are
the "sequence": an NTHWC clip is sharded ``P('data', 'time', 'spatial',
None, None)``, each device holds T/time_shards frames, and the temporal
discriminator's 3-D convs get their neighbor frames through the same
nearest-neighbor ppermute halo exchange ring attention uses for K/V blocks —
here exchanging *frames* instead of attention blocks.

Primitives:

- :func:`sharded_temporal_conv3d` — k_t×k_h×k_w conv on a T-sharded clip;
  one ppermute of k_t//2 boundary frames, then a local VALID conv.
- :func:`temporal_mean` — psum-mean over the time axis for per-clip losses.
- :func:`make_sharded_temporal_conv` — shard_map wrapper for global arrays.

Used by ``p2p_tpu.models.temporal_d.TemporalDiscriminator`` for its
sequence-parallel path.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from p2p_tpu.core.mesh import TIME_AXIS, shard_map_compat as shard_map
from p2p_tpu.parallel.halo import halo_exchange

_DIMNUMS3D = ("NDHWC", "DHWIO", "NDHWC")


def sharded_temporal_conv3d(
    x: jax.Array,
    kernel: jax.Array,
    *,
    stride_hw: int = 1,
    axis_name: str = TIME_AXIS,
    edge_mode: str = "zero",
) -> jax.Array:
    """'Same'-in-T conv on a local NTHWC shard (inside shard_map).

    ``kernel`` is (kt, kh, kw, Cin, Cout). T gets halo frames from mesh
    neighbors (zero edges, matching torch Conv3d zero padding); H/W are
    zero-padded locally and may be strided.
    """
    kt, kh, kw = kernel.shape[0], kernel.shape[1], kernel.shape[2]
    x = halo_exchange(
        x, dim=1, halo=kt // 2, axis_name=axis_name, edge_mode=edge_mode
    )
    ph, pw = kh // 2, kw // 2
    dn = lax.conv_dimension_numbers(x.shape, kernel.shape, _DIMNUMS3D)
    return lax.conv_general_dilated(
        x,
        kernel,
        (1, stride_hw, stride_hw),
        [(0, 0), (ph, ph), (pw, pw)],
        dimension_numbers=dn,
    )


def temporal_mean(x: jax.Array, axis_name: str = TIME_AXIS) -> jax.Array:
    """Mean of a per-shard scalar over the time axis (inside shard_map)."""
    return lax.pmean(x, axis_name)


def make_sharded_temporal_conv(
    mesh: Mesh,
    *,
    stride_hw: int = 1,
    axis_name: str = TIME_AXIS,
):
    """shard_map wrapper: global NTHWC clip (T sharded) × kernel → global out."""
    spec_x = P(None, axis_name, None, None, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec_x, P()), out_specs=spec_x
    )
    def _fn(x, kernel):
        return sharded_temporal_conv3d(
            x, kernel, stride_hw=stride_hw, axis_name=axis_name
        )

    return _fn


def gather_frames(x: jax.Array, axis_name: str = TIME_AXIS) -> jax.Array:
    """all_gather the full clip onto every time-shard (escape hatch for
    global-T ops, e.g. a clip-level pooling head; O(T) memory)."""
    return lax.all_gather(x, axis_name, axis=1, tiled=True)
