"""Tensor parallelism over the ``model`` mesh axis (SURVEY §2.4 TP row).

The widest compute in the zoo is the ResNet trunk of the pix2pixHD /
cityscapes generators (p2p_tpu.models.resnet_gen / pix2pixhd: stacks of
``ResnetBlock_i = ConvLayer_0 → norm → relu → ConvLayer_1 → norm (+x)``).
TP is expressed the TPU-native way — as *sharding annotations*, not a new
code path: Megatron-style alternating channel shards on each block's conv
pair,

- ``ConvLayer_0`` kernel: C_out over ``model``  → each device computes a
  channel slice of the block's hidden activation;
- ``ConvLayer_1`` kernel: C_in over ``model``   → each device contracts its
  slice; GSPMD inserts ONE psum per block to rebuild the residual.

The norm between the pair is per-channel (InstanceNorm without affine in
these models), so it partitions over the channel shard with no collective.
Everything else (D, losses, optimizer math for non-trunk params) stays
replicated over ``model``.

Use ``norm="instance"`` (XLA) with TP: the Pallas InstanceNorm's manual
sharding region covers the ``spatial`` axis, not channel shards — under TP
the XLA norm partitions natively, the Pallas custom call would force a
channel all-gather.

Single-chip note: this environment exposes ONE real TPU chip, so TP here is
validated for numerics on the fake CPU mesh (tests/test_parallel.py) and
compile-checked via the driver dryrun; multi-chip speedups are expected at
the 1024×512 scale where the 1024-channel trunk convs dominate
(BASELINE configs[3]).
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_tpu.core.mesh import MODEL_AXIS

# ResnetBlock conv-pair leaves, wherever they sit in a pytree (params_g or
# the param-structured optimizer moments mu/nu).
_PAT = re.compile(r"ResnetBlock_\d+'?\]?\['ConvLayer_(\d)'\]\['Conv_0'\]")


def _tp_spec(path_str: str, shape, axis_size: int, min_ch: int):
    m = _PAT.search(path_str)
    if not m:
        return P()
    which = m.group(1)
    if path_str.endswith("['kernel']") and len(shape) == 4:
        if (which == "0" and shape[3] >= min_ch
                and shape[3] % axis_size == 0):
            return P(None, None, None, MODEL_AXIS)      # C_out shard
        if (which == "1" and shape[2] >= min_ch
                and shape[2] % axis_size == 0):
            return P(None, None, MODEL_AXIS, None)      # C_in shard
    if (path_str.endswith("['bias']") and len(shape) == 1 and which == "0"
            and shape[0] >= min_ch and shape[0] % axis_size == 0):
        return P(MODEL_AXIS)                            # rides with C_out
    return P()


def tp_sharding_tree(tree: Any, mesh: Mesh, min_ch: int = 512):
    """NamedSharding pytree for ``tree``: Megatron-style channel shards on
    ResnetBlock conv pairs wider than ``min_ch``, everything else
    replicated. Works on a param tree, an optimizer state (adam's mu/nu
    mirror the param paths), or a whole TrainState."""
    size = mesh.shape.get(MODEL_AXIS, 1)

    def rule(path, leaf):
        ps = jax.tree_util.keystr(path)
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, _tp_spec(ps, shape, size, min_ch))

    return jax.tree_util.tree_map_with_path(rule, tree)


def place_state_tp(state: Any, mesh: Mesh, min_ch: int = 512):
    """device_put the TrainState with TP shardings (replicated elsewhere)."""
    return jax.device_put(state, tp_sharding_tree(state, mesh, min_ch))
