"""Tensor parallelism over the ``model`` mesh axis (SURVEY §2.4 TP row).

The widest compute in the zoo is the ResNet trunk of the pix2pixHD /
cityscapes generators (p2p_tpu.models.resnet_gen / pix2pixhd: stacks of
``ResnetBlock_i = ConvLayer_0 → norm → relu → ConvLayer_1 → norm (+x)``).
TP is expressed the TPU-native way — as *sharding annotations*, not a new
code path: Megatron-style alternating channel shards on each block's conv
pair,

- ``ConvLayer_0`` kernel: C_out over ``model``  → each device computes a
  channel slice of the block's hidden activation;
- ``ConvLayer_1`` kernel: C_in over ``model``   → each device contracts its
  slice; GSPMD inserts ONE psum per block to rebuild the residual.

The norm between the pair is per-channel (InstanceNorm without affine in
these models), so it partitions over the channel shard with no collective.

Round 5 widened the coverage beyond the ResNet trunk (VERDICT r4 #7):
the U-Net's deepest encoder/bottleneck pairs (down3→down4, down5→up5),
the ResNet-family encoder/decoder transitions (ConvLayer_3→4,
UpsampleConvLayer_0→1 — cityscapes at the root and pix2pixHD's
``global`` subtree), and every PatchGAN discriminator scale's
channel-doubling chain (shape-keyed — see ``_D_SCALE`` — so both the
BatchNorm ``_PlainConv`` and the ``SpectralConv`` namings shard). Losses
and the remaining params stay replicated over ``model``; the per-channel
norm/stat vectors between sharded pairs are tiny and GSPMD reshards them
for free.

Use ``norm="instance"`` (XLA) with TP: the Pallas InstanceNorm's manual
sharding region covers the ``spatial`` axis, not channel shards — under TP
the XLA norm partitions natively, the Pallas custom call would force a
channel all-gather.

Round 6 made this a TRAINER capability; ISSUE 15 retired the hand-built
tree builder to a SHIM — the CLI trainer (and serving, and the elastic
restore targets) now derive the whole-TrainState layout from the
declarative tables in ``parallel/rules.py``
(``state_target_shardings``), and :func:`tp_sharding_tree` below just
delegates there. :func:`tp_leaf_spec` remains the REFERENCE assignment
the tables are diffed against (the tp-diff zero-gap CI pin). CLI-TP ==
single-device is pinned per-preset in tests/test_loop.py on top of the
step-level equivalence tests here.

Single-chip note: this environment exposes ONE real TPU chip, so TP here is
validated for numerics on the fake CPU mesh (tests/test_parallel.py) and
compile-checked via the driver dryrun; multi-chip speedups are expected at
the 1024×512 scale where the 1024-channel trunk convs dominate
(BASELINE configs[3]).
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, PartitionSpec as P

from p2p_tpu.core.mesh import MODEL_AXIS

# Residual-trunk conv-pair leaves, wherever they sit in a pytree (params_g
# or the param-structured optimizer moments mu/nu). Covers both trunk
# namings: ``ResnetBlock_i`` (cityscapes / pix2pixHD families,
# models/resnet_gen.py) and ``ResidualBlock_i`` (the flagship
# ExpandNetwork, models/expand.py — networks.py:472-480). The inner
# structure is identical: ConvLayer_0 (C_out shard) → per-channel norm →
# ConvLayer_1 (C_in shard, one psum to rebuild the residual).
_PAT = re.compile(
    r"Res(?:net|idual)Block_\d+'?\]?\['ConvLayer_(\d)'\]\['Conv_0'\]")

# Round-5 extension (VERDICT r4 #7): Megatron pairs beyond the ResNet
# trunk. Named pairs for the generators (stable flax names):
#   U-Net (facades/edges2shoes): (down3 → down4) and the bottleneck
#   (down5 → up5) — the four 512-channel encoder/decoder convs;
#   ResNet-family encoder/decoder (cityscapes at the root, pix2pixHD
#   under ['global']): (ConvLayer_3 → ConvLayer_4) and
#   (UpsampleConvLayer_0 → UpsampleConvLayer_1) — the 512/1024-channel
#   transitions. 'out' shards C_out (device computes a channel slice),
#   'in' shards C_in (device contracts its slice; GSPMD inserts ONE psum
#   per pair). Everything is annotation-only, so ANY assignment stays
#   numerically exact — the pairs are chosen so the activation between
#   the two convs is channel-sharded and needs no collective at all.
_G_PAIR_RULES = [
    (re.compile(r"\['down3'\]"), "out"),
    (re.compile(r"\['down4'\]"), "in"),
    (re.compile(r"\['down5'\]"), "out"),
    (re.compile(r"\['up5'\]"), "in"),
    (re.compile(r"\['ConvLayer_3'\]\['Conv_0'\]"), "out"),
    (re.compile(r"\['ConvLayer_4'\]\['Conv_0'\]"), "in"),
    (re.compile(r"\['UpsampleConvLayer_0'\]\['Conv_0'\]"), "out"),
    (re.compile(r"\['UpsampleConvLayer_1'\]\['Conv_0'\]"), "in"),
]

# Discriminator chains (every PatchGAN scale: stem → ndf→2ndf→4ndf→8ndf →
# head). The conv names differ per preset (_PlainConv_k with BatchNorm,
# SpectralConv_k without) so the rule keys on SHAPE, not name: along a
# channel-doubling chain, log2(C) parity strictly alternates, giving a
# consistent out/in assignment for any ndf — e.g. 64→128 out-shards
# (log2 128 odd), 128→256 in-shards + psum, 256→512 out-shards, and the
# 512→1 head in-shards + psum. The stem's C_in (6) is not a power of two
# and its C_out parity is even → replicated, as is everything the gates
# reject.
_D_SCALE = re.compile(r"\['scale\d+'\]")


def _log2_exact(n: int):
    if n > 0 and (n & (n - 1)) == 0:
        return n.bit_length() - 1
    return None


def _pair_spec(which: str, shape, axis_size: int, min_ch: int,
               is_kernel: bool):
    if is_kernel and len(shape) == 4:
        if (which == "out" and shape[3] >= min_ch
                and shape[3] % axis_size == 0):
            return P(None, None, None, MODEL_AXIS)
        if (which == "in" and shape[2] >= min_ch
                and shape[2] % axis_size == 0):
            return P(None, None, MODEL_AXIS, None)
    if (not is_kernel and which == "out" and len(shape) == 1
            and shape[0] >= min_ch and shape[0] % axis_size == 0):
        return P(MODEL_AXIS)                            # rides with C_out
    return P()


def _tp_spec(path_str: str, shape, axis_size: int, min_ch: int):
    is_kernel = path_str.endswith("['kernel']")
    is_bias = path_str.endswith("['bias']")
    if not (is_kernel or is_bias):
        return P()

    m = _PAT.search(path_str)
    if m:
        which = "out" if m.group(1) == "0" else "in"
        return _pair_spec(which, shape, axis_size, min_ch, is_kernel)

    for pat, which in _G_PAIR_RULES:
        if pat.search(path_str):
            return _pair_spec(which, shape, axis_size, min_ch, is_kernel)

    if _D_SCALE.search(path_str):
        if is_kernel and len(shape) == 4:
            ci, co = shape[2], shape[3]
            l_ci, l_co = _log2_exact(ci), _log2_exact(co)
            if l_ci is not None and l_ci % 2 == 1:
                return _pair_spec("in", shape, axis_size, min_ch, True)
            if l_co is not None and l_co % 2 == 1:
                return _pair_spec("out", shape, axis_size, min_ch, True)
        if is_bias and len(shape) == 1:
            l_co = _log2_exact(shape[0])
            if l_co is not None and l_co % 2 == 1:
                return _pair_spec("out", shape, axis_size, min_ch, False)
    return P()


def tp_leaf_spec(path_str: str, shape, axis_size: int,
                 min_ch: int = 512) -> P:
    """Pure-function view of the TP pair rule for ONE leaf: ``path_str``
    is the ``jax.tree_util.keystr`` path, ``axis_size`` the (possibly
    hypothetical) model-axis width. No mesh, no devices.

    This is the REFERENCE implementation the declarative tables were
    drained against: the sharding auditor's ``tp``-diff mode
    (p2p_tpu/analysis/sharding_audit) diffs it per leaf against
    ``parallel/rules.py``'s tables, and the standing zero-gap CI pin is
    what lets the live layouts run from the tables alone."""
    return _tp_spec(path_str, tuple(shape), axis_size, min_ch)


def tp_sharding_tree(tree: Any, mesh: Mesh, min_ch: int = 512):
    """RETIRED to a shim (ISSUE 15): delegates to the declarative rule
    engine — ``parallel/rules.state_target_shardings`` over
    ``trainstate_rules`` is the one sharding authority now (the zero
    tp-diff gap pins guarantee the tables reproduce the hand-built
    assignment this module used to compute). Kept only so historical
    callers/tests keep meaning "the Megatron TP layout of this tree"."""
    from p2p_tpu.parallel.rules import state_target_shardings

    return state_target_shardings(tree, mesh, tp_min_ch=min_ch)


def place_state_tp(state: Any, mesh: Mesh, min_ch: int = 512):
    """device_put the TrainState with TP shardings (replicated elsewhere)."""
    return jax.device_put(state, tp_sharding_tree(state, mesh, min_ch))
