"""Fault-tolerance subsystem — preemption, retry/backoff, chaos, shedding.

The production stance (docs/RESILIENCE.md): preemption and transient
faults are the COMMON case on preemptible TPU fleets, so recovery is a
first-class layer wired through train, data, serve, and obs rather than
an afterthought per call site. Four pillars:

- :mod:`.preempt` — SIGTERM/SIGINT → flag → step-boundary exact-step
  checkpoint, agreed across hosts; the distinct
  :data:`~p2p_tpu.resilience.preempt.PREEMPTED_EXIT_CODE` (75) means
  "resume me".
- :mod:`.retry` — exponential backoff + full jitter with exception
  classification and deadlines, wrapped around checkpoint I/O and image
  decode.
- :mod:`.chaos` — config/env-driven fault injection (``P2P_CHAOS``) at
  those same seams, so tests, CI, and ``bench.py --chaos`` exercise the
  recovery paths on purpose.
- :mod:`.queue` — serve hardening: bounded request queue with load
  shedding, per-request deadlines, poison-input quarantine.
- :mod:`.health` — self-healing training: divergence sentinel (EWMA +
  robust z-score over the step losses) → bounded recovery ladder (skip →
  LR cooldown → rollback to the last eval-validated checkpoint) →
  :data:`~p2p_tpu.resilience.health.DIVERGED_EXIT_CODE` (76) when the
  ladder is exhausted; plus checkpoint integrity verification and the
  EMA generator (train/checkpoint.py, train/step.py).
- :mod:`.reshape` — restore-time state migration: the elastic
  ``migrate`` verdict's transform chain (batch re-basing from cumulative
  samples, pipe-width trunk restructuring, closed-form TP amax
  re-calibration, opt-in dtype cast), executed by ``elastic_restore``
  from both trainers' ``maybe_resume``.

Everything counts through the PR-1 obs registry: ``preemptions_total``,
``retry_attempts_total``/``retry_exhausted_total``,
``chaos_injected_total``, ``serve_shed_total``,
``serve_deadline_expired_total``, ``serve_quarantined_total``,
``health_spikes_total``/``health_skips_total``/``health_cooldowns_total``/
``health_rollbacks_total``, ``ckpt_corrupt_total``.
"""

from p2p_tpu.resilience.chaos import (
    ChaosMonkey,
    FaultInjected,
    chaos_point,
    get_chaos,
    install as install_chaos,
    parse_spec,
)
from p2p_tpu.resilience.health import (
    DIVERGED_EXIT_CODE,
    DivergenceError,
    DivergenceSentinel,
    RecoveryLadder,
    TrainingHealth,
)
from p2p_tpu.resilience.preempt import (
    PREEMPTED_EXIT_CODE,
    Preempted,
    PreemptionGuard,
)
from p2p_tpu.resilience.queue import BoundedRequestQueue, Quarantine, Request
from p2p_tpu.resilience.retry import (
    CKPT_POLICY,
    DEFAULT_POLICY,
    RetryPolicy,
    retry_call,
    retrying,
)

__all__ = [
    "BoundedRequestQueue",
    "CKPT_POLICY",
    "ChaosMonkey",
    "DEFAULT_POLICY",
    "DIVERGED_EXIT_CODE",
    "DivergenceError",
    "DivergenceSentinel",
    "FaultInjected",
    "RecoveryLadder",
    "TrainingHealth",
    "PREEMPTED_EXIT_CODE",
    "Preempted",
    "PreemptionGuard",
    "Quarantine",
    "Request",
    "RetryPolicy",
    "chaos_point",
    "get_chaos",
    "install_chaos",
    "parse_spec",
    "retry_call",
    "retrying",
]
