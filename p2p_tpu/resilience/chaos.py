"""Fault injection — probabilistic or step-targeted failures at named seams.

Production training stacks treat transient faults (preemptions, flaky
storage, torn uploads) as the common case; the only way to trust the
recovery paths in :mod:`p2p_tpu.resilience` is to fire them on purpose.
This module plants *chaos points* at the seams the retry/backoff layer
wraps — checkpoint save/restore, image decode, serve output writes — and
arms them from a config string or the ``P2P_CHAOS`` environment variable,
so a test, a CI stage, or a ``bench.py --chaos`` run can make those seams
fail on demand.

Spec grammar (comma-separated entries)::

    ckpt_save:0.5        fail seam 'ckpt_save' with probability 0.5
    decode@7             fail seam 'decode' exactly at "step" 7
    ckpt_save:0.5x3      as above, but at most 3 injected faults total
    nan@50x3             fail seam 'nan' at steps 50, 51 and 52
    decode:0.2x1,ckpt_save@12

``seam@N`` compares against the step the seam reports (checkpoint seams
pass the train step); seams with no step concept (decode, serve_write)
fall back to their OWN call count, so ``decode@7`` means "the 7th decode
of this process" — targeted injection works at every seam. A step-
targeted entry's ``xM`` cap widens the target to the RANGE [N, N+M):
``nan@50x3`` fires at steps 50..52 — the shape the recovery-ladder
rehearsals need (one injection per rung). Repeated calls at the same
step (a retry loop) still consume the cap one fault at a time.

Seam names in use: ``ckpt_save``, ``ckpt_restore``, ``decode``,
``serve_write``, ``nan`` (train-loop loss poisoning — the divergence
sentinel's rehearsal hook, train/loop.py), ``ckpt_corrupt`` (simulated
checksum mismatch at restore-verify, train/checkpoint.py). Unknown names
are legal (a chaos point is just a string), so new seams need no
registry changes.

Every injected fault raises :class:`FaultInjected` (classified retryable
by the default :class:`~p2p_tpu.resilience.retry.RetryPolicy`) and bumps
the ``chaos_injected_total{seam=...}`` counter on the obs registry —
injected faults are never silent.

The happy path stays free: :func:`chaos_point` is a no-op returning after
one global check when nothing is armed.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
from typing import Dict, Optional

_ENV_VAR = "P2P_CHAOS"
_ENV_SEED_VAR = "P2P_CHAOS_SEED"


class FaultInjected(RuntimeError):
    """A fault planted by the chaos layer (always retryable)."""

    def __init__(self, seam: str, step: Optional[int] = None):
        self.seam = seam
        self.step = step
        at = f" at step {step}" if step is not None else ""
        super().__init__(f"chaos: injected fault at seam {seam!r}{at}")


@dataclasses.dataclass
class SeamSpec:
    """Arming rule for one seam."""

    prob: float = 0.0                 # per-call failure probability
    at_step: Optional[int] = None     # fire exactly when step == at_step
    max_faults: Optional[int] = None  # stop injecting after this many
    fired: int = 0                    # injected so far (mutable)
    calls: int = 0                    # chaos-point hits (the @N fallback)


_ENTRY_RE = None  # compiled lazily (module import stays re-free)

#: Seams that short-circuit a cross-host agreement protocol and therefore
#: MUST fire on every host at the same step: the ``elastic`` seam converts
#: straight into ``PreemptionGuard.request`` + an immediate stop WITHOUT
#: the allgather cadence (train/loop.py poll_preempt) — that is only safe
#: because a step-pinned ``elastic@N`` fires on every host's Nth dispatch.
#: A probabilistic ``elastic:p`` draws from each process's own RNG stream
#: (whose position depends on that host's other seam traffic), so one host
#: would stop while the rest march into the next agreement collective and
#: hang — the exact bug class the collective-consistency lint exists for
#: (p2p_tpu/analysis/collective_consistency.py).
_STEP_PINNED_SEAMS = frozenset({"elastic"})


def parse_spec(spec: str) -> Dict[str, SeamSpec]:
    """Parse the spec grammar above into ``{seam: SeamSpec}``."""
    import re

    global _ENTRY_RE
    if _ENTRY_RE is None:
        _ENTRY_RE = re.compile(
            r"^(?P<seam>[^:@]+?)"
            r"(?::(?P<prob>[0-9.eE+\-]+)|@(?P<step>\d+))?"
            r"(?:x(?P<cap>\d+))?$"
        )
    out: Dict[str, SeamSpec] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        m = _ENTRY_RE.match(entry)
        if m is None:
            raise ValueError(f"bad chaos entry {entry!r}")
        seam = m.group("seam").strip()
        cap = int(m.group("cap")) if m.group("cap") else None
        if seam in _STEP_PINNED_SEAMS and m.group("step") is None:
            raise ValueError(
                f"chaos seam {seam!r} must be step-pinned (use "
                f"'{seam}@N' or '{seam}@NxM'): a probabilistic spec "
                "fires on a per-host RNG draw, so one host preempts "
                "while the others hang in the next agreement collective "
                f"(bad entry: {entry!r})")
        if m.group("step") is not None:
            out[seam] = SeamSpec(at_step=int(m.group("step")),
                                 max_faults=cap if cap else 1)
        elif m.group("prob") is not None:
            p = float(m.group("prob"))
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"chaos probability out of [0,1]: {entry!r}")
            out[seam] = SeamSpec(prob=p, max_faults=cap)
        else:
            # bare seam name = always fail (prob 1), once unless capped
            out[seam] = SeamSpec(prob=1.0, max_faults=cap if cap else 1)
    if not out:
        raise ValueError(f"empty chaos spec {spec!r}")
    return out


class ChaosMonkey:
    """Armed fault-injection state: seams + a seeded RNG + fired counts."""

    def __init__(self, seams: Dict[str, SeamSpec], seed: int = 0,
                 registry=None):
        self.seams = seams
        self._rng = random.Random(seed)
        self._registry = registry
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0, registry=None) -> "ChaosMonkey":
        return cls(parse_spec(spec), seed=seed, registry=registry)

    def _reg(self):
        if self._registry is None:
            from p2p_tpu.obs import get_registry

            self._registry = get_registry()
        return self._registry

    def counts(self) -> Dict[str, int]:
        return {name: s.fired for name, s in self.seams.items()}

    def maybe_fail(self, seam: str, step: Optional[int] = None) -> None:
        s = self.seams.get(seam)
        if s is None:
            return
        with self._lock:
            s.calls += 1
            if s.max_faults is not None and s.fired >= s.max_faults:
                return
            if s.at_step is not None:
                # seams that report no step (decode, serve_write) target
                # by their own call count, so seam@N works everywhere;
                # the xM cap widens the target to the range [N, N+M) —
                # one injection per step for ladder rehearsals (same-step
                # retries still drain the cap fault by fault)
                at = step if step is not None else s.calls
                span = s.max_faults if s.max_faults is not None else 1
                if not (s.at_step <= at < s.at_step + span):
                    return
            elif not (s.prob > 0.0 and self._rng.random() < s.prob):
                return
            s.fired += 1
        self._reg().counter("chaos_injected_total", seam=seam).inc()
        raise FaultInjected(seam, step)


_active: Optional[ChaosMonkey] = None
_env_checked = False
_lock = threading.Lock()


def install(monkey: Optional[ChaosMonkey]) -> Optional[ChaosMonkey]:
    """Arm ``monkey`` process-wide (None disarms); returns the previous one.
    Also resets the env latch so a later ``P2P_CHAOS`` change can re-arm."""
    global _active, _env_checked
    with _lock:
        prev = _active
        _active = monkey
        _env_checked = monkey is not None
        return prev


def get_chaos() -> Optional[ChaosMonkey]:
    _maybe_arm_from_env()
    return _active


def _maybe_arm_from_env() -> None:
    """One-time check of ``P2P_CHAOS`` — arms the process on first use so
    subprocesses (CLI runs, CI stages) opt in purely through the env."""
    global _active, _env_checked
    if _env_checked:
        return
    with _lock:
        if _env_checked:
            return
        _env_checked = True
        spec = os.environ.get(_ENV_VAR)
        if spec:
            _active = ChaosMonkey.from_spec(
                spec, seed=int(os.environ.get(_ENV_SEED_VAR, "0")))


def chaos_point(seam: str, step: Optional[int] = None) -> None:
    """Mark a fault-injectable seam. No-op unless a :class:`ChaosMonkey`
    is armed (via :func:`install` or ``P2P_CHAOS``); armed, it may raise
    :class:`FaultInjected` per that seam's spec."""
    _maybe_arm_from_env()
    m = _active
    if m is not None:
        m.maybe_fail(seam, step)
