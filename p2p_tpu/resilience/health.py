"""Training health — divergence sentinel, recovery ladder, self-healing.

PR 4 made training survive *external* faults; this module survives the
*internal* ones: LSGAN + feature-matching training is spike-prone, and
before this layer a NaN or a loss explosion simply killed the run — hours
of TPU time lost with no automatic path back to a healthy state. The
protocol is the large-scale-training standard (the spike-skip-and-rollback
recipe of the PaLM/OPT training reports, EMA generator weights from the
ProGAN lineage):

- **Divergence sentinel** (:class:`DivergenceSentinel`): consumes the
  per-step loss metrics the train loop already computes (G/D/C losses,
  plus the ``grad_norm_*`` taps when ``--grad_norms`` is on) and
  classifies each step ``healthy`` / ``spiking`` / ``diverged`` — a spike
  is a robust z-score (median/MAD over the last K healthy steps, EWMA
  recentered) above ``spike_zscore``; non-finite is diverged on sight.
  The loop feeds it one dispatch LATE (the previous dispatch's metrics
  are read while the next one runs) so the happy path never fences.

- **Recovery ladder** (:class:`RecoveryLadder`): bounded escalation —
  rung 1 **skip** (the in-jit guard in ``train/step.py`` already dropped
  a non-finite step's update; the host records it), rung 2 **LR
  cooldown** (scale the G/D/C learning rate by ``cooldown_factor`` for
  ``cooldown_steps`` steps), rung 3 **rollback** to the last
  eval-validated (``mark_good``) checkpoint with a perturbed data-shuffle
  RNG so the same batch order is not replayed. A healthy streak of
  ``reset_after`` steps walks the ladder back down; more than
  ``max_rollbacks`` rollbacks raises :class:`DivergenceError`, which
  ``cli/train.py`` turns into :data:`DIVERGED_EXIT_CODE` (76) — distinct
  from preemption's 75, because "relaunch with identical flags" is
  exactly the WRONG supervisor response to a diverging config.

Every rung counts on the obs registry (``health_spikes_total``,
``health_skips_total``, ``health_cooldowns_total``,
``health_rollbacks_total``) and logs a ``kind="health"`` record, so a
recovered run is auditable after the fact. The ``nan`` chaos seam
(``P2P_CHAOS=nan@50x3`` — fail steps 50..52) rehearses the whole ladder
in tests, CI, and ``bench.py --chaos``.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Dict, Iterable, Optional

# Exit code for "training diverged and the recovery ladder is exhausted".
# 75 (preemption) means "relaunch me"; 76 means "do NOT blindly relaunch —
# the run rolled back max_rollbacks times and diverged again every time".
DIVERGED_EXIT_CODE = 76

HEALTHY = "healthy"
SPIKING = "spiking"
DIVERGED = "diverged"

# Metric keys the sentinel watches when present in a step's metrics.
DEFAULT_WATCH = ("loss_g", "loss_d", "loss_dt", "loss_c",
                 "grad_norm_g", "grad_norm_d")


def poison_nan_observation(step: int,
                           metrics: Dict[str, float]) -> Dict[str, float]:
    """Apply the ``nan`` chaos seam to one step's HOST metrics — the ONE
    poisoning definition shared by the train loop's delayed read and
    ``bench.py``'s sentinel row, so the rehearsal path and the measured
    path cannot drift apart. Returns the (possibly poisoned) metrics."""
    from p2p_tpu.resilience.chaos import FaultInjected, chaos_point

    try:
        chaos_point("nan", step=step)
    except FaultInjected:
        metrics = dict(metrics)
        metrics["loss_g"] = float("nan")
    return metrics


class DivergenceError(RuntimeError):
    """The recovery ladder is exhausted: the run rolled back
    ``max_rollbacks`` times (or had no checkpoint to roll back to) and
    diverged again. Carries the step for the postmortem."""

    def __init__(self, step: int, rollbacks: int, reason: str = ""):
        self.step = int(step)
        self.rollbacks = int(rollbacks)
        msg = (f"training diverged at step {step} after {rollbacks} "
               f"rollback(s); recovery ladder exhausted")
        if reason:
            msg += f" ({reason})"
        super().__init__(msg)


class _RobustWindow:
    """Robust z-score over the last K healthy observations of ONE series.

    Median/MAD over a deque of K values (K is small — tens), recentered
    by an EWMA so a slow level drift (losses decay over training) does
    not read as a spike. Spiking values are EXCLUDED from the window —
    one blowup must not inflate the MAD and mask the next one.
    """

    def __init__(self, window: int, alpha: float):
        self.vals: deque = deque(maxlen=max(4, window))
        self.alpha = alpha
        self.ewma: Optional[float] = None

    def zscore(self, x: float) -> Optional[float]:
        """Robust z of ``x`` against the window; None until warmed up."""
        if len(self.vals) < max(4, self.vals.maxlen // 4):
            return None
        s = sorted(self.vals)
        n = len(s)
        med = (s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2]))
        mad = sorted(abs(v - med) for v in s)[n // 2]
        # 1.4826·MAD ≈ σ for a normal; floor keeps a flat window (MAD=0,
        # e.g. a constant loss) from turning ulp noise into infinite z
        sigma = max(1.4826 * mad, 1e-6 * max(abs(med), 1.0), 1e-12)
        center = med if self.ewma is None else 0.5 * (med + self.ewma)
        return (x - center) / sigma

    def push(self, x: float) -> None:
        self.vals.append(x)
        self.ewma = (x if self.ewma is None
                     else self.ewma + self.alpha * (x - self.ewma))


class DivergenceSentinel:
    """Classify each observed step ``healthy`` / ``spiking`` / ``diverged``
    from windowed loss statistics (EWMA + robust z-score per watched key).
    """

    def __init__(self, window: int = 32, spike_zscore: float = 6.0,
                 ewma_alpha: float = 0.1,
                 watch: Iterable[str] = DEFAULT_WATCH):
        self.window = int(window)
        self.spike_zscore = float(spike_zscore)
        self.watch = tuple(watch)
        self._alpha = float(ewma_alpha)
        self._series: Dict[str, _RobustWindow] = {}

    def reset(self) -> None:
        """Drop all windowed state (after a rollback: the restored regime's
        statistics are the pre-divergence ones, not the blowup's)."""
        self._series.clear()

    def classify(self, metrics: Dict[str, float]) -> str:
        """Classify one step's host metrics and absorb them into the
        windows. ``metrics`` keys outside the watch list are ignored."""
        status = HEALTHY
        worst_key, worst_z = None, 0.0
        for k in self.watch:
            v = metrics.get(k)
            if v is None:
                continue
            v = float(v)
            if not math.isfinite(v):
                self._last = (k, float("inf"))
                return DIVERGED
            w = self._series.get(k)
            if w is None:
                w = self._series[k] = _RobustWindow(self.window, self._alpha)
            z = w.zscore(v)
            if z is not None and abs(z) > self.spike_zscore:
                status = SPIKING
                if abs(z) > abs(worst_z):
                    worst_key, worst_z = k, z
                continue  # spike values stay out of the window
            w.push(v)
        self._last = (worst_key, worst_z)
        return status

    @property
    def last_spike(self):
        """(key, z) of the worst offender in the latest classification."""
        return getattr(self, "_last", (None, 0.0))


class RecoveryLadder:
    """Bounded escalation: skip → cooldown → rollback → give up.

    Pure host-side state machine: :meth:`on_status` maps a sentinel
    classification to an action for the trainer (``None`` / ``"skip"`` /
    ``"cooldown"`` / ``"rollback"``), raising :class:`DivergenceError`
    past the rollback budget. The trainer owns executing the action; the
    ladder owns pacing, counters, and the cooldown's LR multiplier.
    """

    def __init__(self, cooldown_steps: int = 20, cooldown_factor: float = 0.1,
                 max_rollbacks: int = 3, reset_after: int = 16,
                 registry=None, logger=None):
        self.cooldown_steps = int(cooldown_steps)
        self.cooldown_factor = float(cooldown_factor)
        self.max_rollbacks = int(max_rollbacks)
        self.reset_after = int(reset_after)
        self._registry = registry
        self._logger = logger
        self.level = 0            # rungs climbed in the current episode
        self.rollbacks = 0        # lifetime rollbacks performed
        self.healthy_streak = 0
        self._cooldown_left = 0
        self.rollback_pending = False

    def _reg(self):
        if self._registry is None:
            from p2p_tpu.obs import get_registry

            self._registry = get_registry()
        return self._registry

    def _log(self, rec: Dict) -> None:
        if self._logger is not None:
            self._logger.log({"kind": "health", **rec}, force=True)

    @property
    def lr_multiplier(self) -> float:
        """The cooldown's LR factor while active, 1.0 otherwise — the
        trainer folds this into ``TrainState.lr_scale`` alongside the
        plateau controller's scale."""
        return self.cooldown_factor if self._cooldown_left > 0 else 1.0

    def on_status(self, status: str, step: int,
                  detail: Optional[Dict] = None) -> Optional[str]:
        if status == HEALTHY:
            if self._cooldown_left > 0:
                self._cooldown_left -= 1
                if self._cooldown_left == 0:
                    self._log({"event": "cooldown_end", "step": int(step)})
            self.healthy_streak += 1
            if self.level and self.healthy_streak >= self.reset_after:
                self.level = 0
                self._log({"event": "ladder_reset", "step": int(step)})
            return None

        # unhealthy: escalate one rung per event
        self.healthy_streak = 0
        self._reg().counter("health_spikes_total", status=status).inc()
        rec = {"event": status, "step": int(step), "rung": self.level + 1}
        if detail:
            rec.update(detail)
        self.level += 1
        if self.level == 1:
            # rung 1 — skip: a non-finite step's update was already
            # dropped by the in-jit guard; a finite z-spike's single bad
            # update is absorbed. Record, count, carry on.
            self._reg().counter("health_skips_total").inc()
            self._log({**rec, "action": "skip"})
            return "skip"
        if self.level == 2:
            self._cooldown_left = self.cooldown_steps
            self._reg().counter("health_cooldowns_total").inc()
            self._log({**rec, "action": "cooldown",
                       "factor": self.cooldown_factor,
                       "steps": self.cooldown_steps})
            return "cooldown"
        # rung 3 — rollback (the trainer performs it, then calls
        # note_rollback_done); past the budget: give up, distinctly.
        if self.rollbacks >= self.max_rollbacks:
            self._log({**rec, "action": "giveup",
                       "rollbacks": self.rollbacks})
            raise DivergenceError(step, self.rollbacks,
                                  "max_rollbacks exhausted")
        self.rollback_pending = True
        self._log({**rec, "action": "rollback"})
        return "rollback"

    def note_rollback_done(self, step: int, target_step: int) -> None:
        """The trainer restored ``target_step``: count it, re-arm a
        post-rollback cooldown (the restored state re-enters the exact
        regime that diverged — give it a gentler LR runway), and reset
        the episode."""
        self.rollbacks += 1
        self.rollback_pending = False
        self.level = 0
        self.healthy_streak = 0
        self._cooldown_left = self.cooldown_steps
        self._reg().counter("health_rollbacks_total").inc()
        self._log({"event": "rollback_done", "step": int(step),
                   "target_step": int(target_step),
                   "rollbacks": self.rollbacks})


class TrainingHealth:
    """The facade both trainers wire in: sentinel + ladder + bookkeeping.

    ``observe(step, metrics)`` feeds one step's HOST metrics through the
    sentinel and the ladder and returns the ladder's action (or None).
    A non-finite in-jit guard verdict (``metrics["health_ok"] == 0``)
    counts as a skip even when the watched losses were themselves finite.
    """

    def __init__(self, hcfg, registry=None, logger=None):
        self.cfg = hcfg
        self.sentinel = DivergenceSentinel(
            window=hcfg.window, spike_zscore=hcfg.spike_zscore,
            ewma_alpha=hcfg.ewma_alpha)
        self.ladder = RecoveryLadder(
            cooldown_steps=hcfg.cooldown_steps,
            cooldown_factor=hcfg.cooldown_factor,
            max_rollbacks=hcfg.max_rollbacks,
            reset_after=hcfg.reset_after,
            registry=registry, logger=logger)
        self._registry = registry

    @property
    def rollback_pending(self) -> bool:
        return self.ladder.rollback_pending

    @property
    def lr_multiplier(self) -> float:
        return self.ladder.lr_multiplier

    def observe(self, step: int, metrics: Dict[str, float]) -> Optional[str]:
        status = self.sentinel.classify(metrics)
        ok = metrics.get("health_ok")
        if status == HEALTHY and ok is not None and float(ok) == 0.0:
            # the in-jit guard skipped (non-finite grads/losses inside the
            # step) even though the fetched metric values read finite
            status = DIVERGED
        detail = None
        if status != HEALTHY:
            key, z = self.sentinel.last_spike
            if key:
                detail = {"metric": key}
                if math.isfinite(z):  # diverged = non-finite value, no z
                    detail["zscore"] = round(float(z), 3)
        return self.ladder.on_status(status, step, detail)

    def after_rollback(self, step: int, target_step: int) -> None:
        self.sentinel.reset()
        self.ladder.note_rollback_done(step, target_step)

    def summary(self) -> Dict[str, float]:
        reg = self.ladder._reg()
        return {
            "health_spikes_total": reg.total("health_spikes_total"),
            "health_skips_total": reg.total("health_skips_total"),
            "health_cooldowns_total": reg.total("health_cooldowns_total"),
            "health_rollbacks_total": reg.total("health_rollbacks_total"),
            "rollbacks": self.ladder.rollbacks,
        }
