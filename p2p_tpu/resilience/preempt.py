"""Preemption handling — graceful SIGTERM/SIGINT shutdown with exact-step
checkpoint, coordinated across hosts.

Preemptible TPU fleets deliver SIGTERM with a grace window; an unhandled
one kills the process mid-step, losing everything since the last epoch
save — and on multi-host meshes a single dead process hangs every other
host's next collective. The protocol here:

1. :class:`PreemptionGuard` installs SIGTERM/SIGINT handlers that only SET
   A FLAG (plus run registered flush hooks so buffered telemetry survives
   even if the run never reaches an orderly exit). A second signal restores
   the default handler and re-raises it — a wedged run can still be killed.
2. The train loop polls :meth:`PreemptionGuard.should_stop` at step
   boundaries. On multi-host runs the flag is agreed via a tiny allgather
   (any host's signal stops all of them), so every process checkpoints the
   SAME step and nobody hangs in a half-entered collective.
3. The loop saves an exact-step checkpoint (TrainState + data-iterator
   sidecar) and raises :class:`Preempted`; ``cli/train.py`` converts that
   into :data:`PREEMPTED_EXIT_CODE` (75, ``EX_TEMPFAIL`` — "transient
   failure, re-run me"), distinct from crash (1) and success (0), so a
   supervisor can restart exactly the preempted runs.

Obs: ``preemptions_total{signal=...}`` counts delivered signals; the loop
writes a ``kind="preempt"`` record with the step it saved.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, List, Optional

#: Exit code meaning "preempted after a clean checkpoint — resume me".
#: 75 is BSD EX_TEMPFAIL ("temporary failure; user is invited to retry").
PREEMPTED_EXIT_CODE = 75


class Preempted(RuntimeError):
    """Raised by the train loop after a preemption-triggered save."""

    def __init__(self, step: int, signum: Optional[int] = None):
        self.step = step
        self.signum = signum
        name = signal.Signals(signum).name if signum else "request"
        super().__init__(
            f"preempted ({name}): checkpoint saved at step {step}")


class PreemptionGuard:
    """Signal-flag + cross-host agreement for graceful preemption.

    Usable three ways: ``install()`` real signal handlers (the CLI path);
    :meth:`request` programmatically (tests, in-process orchestration); or
    subclass/stub ``should_stop`` entirely. The guard never acts on the
    signal beyond flag + flush hooks — policy lives in the train loop.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, registry=None, sync_every: int = 16):
        self._registry = registry
        self._requested = False
        self._signum: Optional[int] = None
        self._old = {}
        self._installed = False
        self._flush_hooks: List[Callable[[], None]] = []
        self._lock = threading.Lock()
        # multi-host agreement cadence: enter the allgather only every
        # N-th poll (see should_stop) — a per-step host-blocking
        # collective would serialize the dispatch pipeline the train loop
        # protects everywhere else. 16 steps of extra latency before the
        # coordinated stop is noise against a preemption grace window.
        self.sync_every = max(1, int(sync_every))
        self._polls = 0

    # -- wiring ----------------------------------------------------------
    def _reg(self):
        if self._registry is None:
            from p2p_tpu.obs import get_registry

            self._registry = get_registry()
        return self._registry

    def add_flush_hook(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` (e.g. ``registry.flush``) inside the signal handler —
        buffered telemetry survives even a run that dies in its grace
        window. Hooks must be quick and exception-safe-ish; errors are
        swallowed (a broken flush must not eat the preemption flag).
        Locked: the flush helper thread snapshots this list while the
        main thread may still be registering hooks."""
        with self._lock:
            self._flush_hooks.append(fn)

    def install(self) -> "PreemptionGuard":
        """Install SIGTERM/SIGINT handlers (main thread only — signal.signal
        raises elsewhere). Idempotent."""
        if self._installed:
            return self
        for s in self.SIGNALS:
            # p2p-lint: disable=conc-unlocked-shared-mutation -- install/uninstall are main-thread only (signal.signal raises elsewhere), and the handler reading _old runs ON the main thread between bytecodes — one thread, no race
            self._old[s] = signal.signal(s, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the pre-install handlers. Idempotent."""
        if not self._installed:
            return
        for s, old in self._old.items():
            try:
                signal.signal(s, old)
            except (ValueError, TypeError):
                pass
        # p2p-lint: disable=conc-unlocked-shared-mutation -- main-thread only, see install()
        self._old.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- the handler -----------------------------------------------------
    def _handler(self, signum, frame) -> None:
        if self._requested:
            # second delivery: the run is taking too long to reach a step
            # boundary — restore the original disposition and re-deliver so
            # the supervisor's kill actually kills.
            old = self._old.get(signum, signal.SIG_DFL)
            signal.signal(signum, old)
            os.kill(os.getpid(), signum)
            return
        self._signum = signum
        self._requested = True
        # Counter + flush hooks touch registry/sink locks the INTERRUPTED
        # main thread may currently hold (handlers run on the main thread
        # between bytecodes — e.g. mid JSONLSink.write): acquiring them
        # here would self-deadlock the graceful path. A helper thread
        # blocks safely until the main thread releases the lock.
        threading.Thread(
            target=self._signal_side_effects, args=(signum,),
            name="p2p-preempt-flush", daemon=False,
        ).start()

    def _signal_side_effects(self, signum) -> None:
        try:
            self._reg().counter(
                "preemptions_total",
                signal=signal.Signals(signum).name).inc()
        except Exception:
            pass
        with self._lock:
            hooks = list(self._flush_hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:
                pass

    # -- polling ---------------------------------------------------------
    def request(self, signum: Optional[int] = None) -> None:
        """Set the flag programmatically (tests / in-process schedulers)."""
        self._signum = signum
        self._requested = True

    @property
    def requested(self) -> bool:
        return self._requested

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def should_stop(self) -> bool:
        """Poll at a step boundary. Single process: the local flag.
        Multi-process: allgather-any — but only on every ``sync_every``-th
        poll, so the steady-state cost is a counter increment, not a
        per-step host-blocking collective. ALL hosts agree to stop at the
        same step even when only one received the signal; a locally-set
        flag waits (at most sync_every steps) for the next agreement
        point rather than stopping unilaterally. Every process must call
        this the same number of times (the train loops do — one call per
        dispatch, equal batch counts per host), which keeps the
        poll-counter, and therefore the collective schedule, aligned."""
        import jax

        if jax.process_count() == 1:
            return self._requested
        # p2p-lint: disable=conc-unlocked-shared-mutation -- polled from the train loop's dispatch thread only; the signal path never touches the counter
        self._polls += 1
        if self._polls % self.sync_every:
            return False
        import numpy as np
        from jax.experimental import multihost_utils

        # p2p-lint: disable=collective-after-divergent-exit -- the poll counter IS aligned by contract: every host calls should_stop exactly once per dispatch (equal batch counts per host), so the modulo cadence admits/skips the allgather on ALL hosts together
        flags = np.asarray(multihost_utils.process_allgather(
            np.array([1 if self._requested else 0], np.int32)))
        agreed = bool(flags.any())
        if agreed and not self._requested:
            self._requested = True  # peer was signaled: stop here too
        return agreed
