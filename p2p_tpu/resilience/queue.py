"""Serve hardening primitives: bounded queue + load shedding, per-request
deadlines, poison-input quarantine.

A directory-watching frontend (``cli/serve.py``) has three unbounded
failure modes this module bounds:

- **backlog growth** — a traffic burst (or a slow device) grows the
  request queue without limit; by the time old requests dispatch their
  callers are long gone. :class:`BoundedRequestQueue` caps depth and
  SHEDS the newest arrivals once full (``serve_shed_total``): under
  overload, serving *some* requests within deadline beats serving all of
  them too late.
- **deadline blowthrough** — requests that waited longer than the
  per-request deadline are dropped at dispatch time
  (``serve_deadline_expired_total``) instead of burning device time on an
  answer nobody is waiting for.
- **poison inputs** — a permanently-corrupt request file fails decode on
  every attempt; re-enqueueing it forever wedges the server on one bad
  request. After the attempt cap, :class:`Quarantine` MOVES the file into
  a ``failed/`` directory (out of the watched set) and counts it
  (``serve_quarantined_total``) — the 422 of a file-drop RPC.

All counters land on the obs :class:`~p2p_tpu.obs.MetricsRegistry`; the
queue also keeps a ``serve_queue_depth`` gauge so dashboards see pressure
building before shedding starts.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple


@dataclasses.dataclass
class Request:
    """One queued request: a file name for the directory frontend, or a
    name plus an in-memory ``payload`` (the request body bytes) for the
    HTTP frontend (serve/server.py)."""

    name: str
    enqueued_at: float
    attempts: int = 0
    not_before: float = 0.0   # backoff: don't dispatch before this time
    payload: Any = None       # in-memory body; None = decode from disk
    cost: int = 0             # queued payload bytes (byte-budget account)


class BoundedRequestQueue:
    """FIFO with a depth cap (shed-newest), deadlines, and retry re-entry.

    ``tenant`` tags every counter/gauge with ``tenant=<name>`` so the
    multi-model serving process (serve/tenancy.py) attributes shedding,
    deadline expiry and queue pressure PER MODEL instead of reading
    process-global totals; None keeps the untagged metric names.

    ``max_bytes`` additionally bounds the SUM of queued payload bytes
    (``Request.payload``) — the HTTP frontend queues whole request
    bodies, so a count-only cap would admit ``max_depth × body-size``
    of host RAM; an admission that would exceed the budget sheds like
    a depth overflow. The directory frontend queues names only (zero
    cost) and is unaffected.

    Not thread-safe by itself — the directory frontend is single-threaded
    and the HTTP frontend serializes access through
    :class:`p2p_tpu.serve.batcher.ContinuousBatcher`'s condition lock.
    """

    def __init__(
        self,
        max_depth: int,
        deadline_s: Optional[float] = None,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
        tenant: Optional[str] = None,
        max_bytes: Optional[int] = None,
    ):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self.deadline_s = deadline_s
        self.tenant = tenant
        self.max_bytes = max_bytes
        self.queued_bytes = 0
        self._clock = clock
        self._q: deque = deque()
        if registry is None:
            from p2p_tpu.obs import get_registry

            registry = get_registry()
        tags = {"tenant": tenant} if tenant else {}
        self._shed = registry.counter("serve_shed_total", **tags)
        self._expired = registry.counter("serve_deadline_expired_total",
                                         **tags)
        self._depth = registry.gauge("serve_queue_depth", **tags)

    def __len__(self) -> int:
        return len(self._q)

    @property
    def shed_count(self) -> int:
        return int(self._shed.value)

    @property
    def expired_count(self) -> int:
        return int(self._expired.value)

    def offer(self, name: str,
              payload: Any = None) -> Optional[Request]:
        """Enqueue a fresh request; returns the queued :class:`Request`
        (truthy), or None (and counts a shed) when the queue is full —
        under overload the newest arrivals are the ones turned away, they
        waited least."""
        return self.offer_request(Request(name, 0.0, payload=payload))

    def offer_request(self, req: Request) -> Optional[Request]:
        """Enqueue a caller-built request (the HTTP frontend's response-
        carrying subclass); stamps ``enqueued_at`` at admission so the
        deadline clock starts here. Sheds when the depth cap — or the
        payload byte budget — is exceeded, like :meth:`offer`."""
        req.cost = (len(req.payload)
                    if isinstance(req.payload, (bytes, bytearray)) else 0)
        if len(self._q) >= self.max_depth or (
                self.max_bytes is not None
                and self.queued_bytes + req.cost > self.max_bytes):
            self._shed.inc()
            self._depth.set(len(self._q))
            return None
        req.enqueued_at = self._clock()
        self._q.append(req)
        self.queued_bytes += req.cost
        self._depth.set(len(self._q))
        return req

    def oldest_enqueued_at(self) -> Optional[float]:
        """Arrival time of the request at the head of the queue (None
        when empty) — the continuous batcher's linger clock: a forming
        group dispatches once the OLDEST member has waited the linger."""
        return self._q[0].enqueued_at if self._q else None

    def requeue(self, req: Request, delay_s: float = 0.0) -> bool:
        """Re-enter a failed request (attempt accounting is the caller's —
        bump ``req.attempts`` before requeueing). Sheds when full, like
        any arrival; keeps its ORIGINAL enqueue time so the deadline
        covers total time-in-system, not time-since-last-retry."""
        if len(self._q) >= self.max_depth or (
                self.max_bytes is not None
                and self.queued_bytes + req.cost > self.max_bytes):
            self._shed.inc()
            return False
        req.not_before = self._clock() + max(0.0, delay_s)
        self._q.append(req)
        self.queued_bytes += req.cost
        self._depth.set(len(self._q))
        return True

    def take(self, n: int) -> Tuple[List[Request], List[Request]]:
        """Dequeue up to ``n`` dispatchable requests.

        Returns ``(ready, expired)``: expired requests (older than the
        deadline) are counted and handed back for disposal, never
        dispatched. Requests inside a retry-backoff window stay queued
        (they don't block younger requests behind them)."""
        ready: List[Request] = []
        expired: List[Request] = []
        now = self._clock()
        waiting: List[Request] = []
        while self._q and len(ready) < n:
            req = self._q.popleft()
            if self.deadline_s is not None and \
                    now - req.enqueued_at > self.deadline_s:
                self._expired.inc()
                expired.append(req)
            elif req.not_before > now:
                waiting.append(req)   # still backing off; keep for later
            else:
                ready.append(req)
        for req in reversed(waiting):
            self._q.appendleft(req)   # preserve FIFO order among survivors
        for req in ready:
            self.queued_bytes -= req.cost
        for req in expired:
            self.queued_bytes -= req.cost
        self._depth.set(len(self._q))
        return ready, expired

    def flush(self) -> List[Request]:
        """Dequeue EVERYTHING — including requests inside retry-backoff
        windows that :meth:`take` deliberately holds back. The drain-
        timeout path uses this so a stuck-in-backoff straggler is still
        ANSWERED (503) at shutdown instead of abandoned with its handler
        thread."""
        out = list(self._q)
        self._q.clear()
        self.queued_bytes = 0
        self._depth.set(0)
        return out


class Quarantine:
    """Move poison inputs out of the watched directory, with a breadcrumb.

    ``quarantine(path, reason)`` moves the file into ``dir`` (created on
    first use) and writes ``<name>.reason.txt`` beside it naming the final
    error — the operator's triage note. Returns the new path, or None when
    the move itself failed (the file may have vanished; never raises into
    the serve loop)."""

    def __init__(self, directory: str, registry=None,
                 tenant: Optional[str] = None):
        self.directory = directory
        if registry is None:
            from p2p_tpu.obs import get_registry

            registry = get_registry()
        tags = {"tenant": tenant} if tenant else {}
        self._count = registry.counter("serve_quarantined_total", **tags)
        self._registry = registry

    @property
    def count(self) -> int:
        return int(self._count.value)

    def quarantine(self, path: str, reason: str = "") -> Optional[str]:
        dest = os.path.join(self.directory, os.path.basename(path))
        try:
            os.makedirs(self.directory, exist_ok=True)
            # replace-if-exists semantics: a re-poisoned same-name file
            # must still leave the watched dir
            shutil.move(path, dest)
        except OSError:
            return None
        self._count.inc()
        self._registry.record(
            {"kind": "quarantine", "file": dest, "reason": reason[:500]},
            force=True,
        )
        if reason:
            try:
                with open(dest + ".reason.txt", "w") as f:
                    f.write(reason + "\n")
            except OSError:
                pass
        return dest
