"""Restore-time state migration — the ``migrate`` verdict's muscle.

PR 7's elastic relaunch made *compatible* topology deltas (slice size,
process count, data-axis width) a resharded restore; everything else was
a hard ``abort`` (exit 2) a human had to rescue. On a preemptible fleet
the aborted deltas are exactly the ones a supervisor wants to make —
shrink the global batch when half the slice is reclaimed, drop from
pipe=4 to pipe=2, fall back to fewer TP shards — so this module turns
each of them into a lawful, tested transform applied at restore time:

- ``batch_rebase`` — a global-batch change re-derives step/epoch
  position, ``steps_per_epoch``, the LR-schedule basis, and the loader's
  skip arithmetic from the sidecar's cumulative ``samples_seen`` (not
  steps): the consumed-prefix law of ``shard_epoch_indices`` holds in
  SAMPLES, so accounting stays gapless and the plateau/cooldown
  controllers see one consistent timeline.
- ``pp_restructure`` — a pipe-width change merges the stage-stacked
  trunk (``pp_stages`` + ``opt_s``) back to the flat trunk
  (:func:`~p2p_tpu.parallel.pp.pp_merge_state`) and re-splits at the new
  width with optimizer moments preserved; pipe→no-pipe and no-pipe→pipe
  are the degenerate cases.
- ``tp_amax_recalibrate`` — a TP-width change under delayed-int8 amax
  state remaps the stored scales by the closed-form max law
  (:func:`~p2p_tpu.ops.int8.reshard_amax`: amax is a max statistic —
  broadcast on widen, max-of-maxes on narrow; per-tensor scalars are
  width-invariant). ``--recalibrate_steps N`` additionally holds the
  migrated scales FROZEN for the first N dispatches after resume — the
  paranoid path's warmup.
- ``dtype_cast`` — an OPT-IN (``--cast_on_restore``) dtype-policy
  migration: the restore casts into the new template explicitly and
  LOGGED (leaf count + examples, diffed against the save-time integrity
  manifest), optimizer moments follow :data:`MOMENT_MIGRATION`, and the
  integrity manifest is regenerated post-cast so CRC verification stays
  meaningful instead of silently skipping every cast leaf.

Orchestration: ``train/loop.plan_elastic_restore`` (shared by both
trainers' ``maybe_resume``) classifies the delta
(:func:`~p2p_tpu.core.mesh.classify_topology_delta`) and returns an
:class:`ElasticPlan`; :func:`elastic_restore` executes it — template
restructuring, the (possibly resharded) Orbax load, then the
restore-time transform chain. ``batch_rebase`` alone runs later, after
``derive_resume_position``, because it moves the POSITION bookkeeping
(:func:`apply_batch_rebase`). ``--no-elastic`` keeps the strict abort
contract for every delta.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: every transform name ``classify_topology_delta`` may put in a chain —
#: the collective-consistency analyzer's curated list mirrors these (the
#: restore-time transforms run under the same cross-host alignment
#: contract as the restore itself)
RESHAPE_TRANSFORMS = (
    "batch_rebase",
    "pp_restructure",
    "tp_amax_recalibrate",
    "dtype_cast",
)

#: Adam-moment migration policy for a ``dtype_cast`` restore, keyed by
#: (saved moment dtype, current moment dtype) with None meaning the f32
#: default. ``"cast"`` keeps the restored (Orbax-cast) moments —
#: float→float casts preserve the trajectory to storage precision;
#: anything not in the table re-initializes the moments to zeros
#: (``"reinit"``) rather than reinterpreting bytes across numeric
#: families.
MOMENT_MIGRATION = {
    (None, "bfloat16"): "cast",
    ("float32", "bfloat16"): "cast",
    ("bfloat16", None): "cast",
    ("bfloat16", "float32"): "cast",
    ("float16", "float32"): "cast",
    ("float32", "float16"): "cast",
    (None, "float16"): "cast",
    ("float16", None): "cast",
    # None IS float32 (the optimizer default) — identity, never a delta
    # by the classifier's normalization, but the table must agree if a
    # combined dtype_cast (mixed_precision) restore looks the pair up
    (None, "float32"): "cast",
    ("float32", None): "cast",
}


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """One reconciled restore decision: what ``elastic_restore`` executes
    and what the audit records name. ``chain`` is empty for a plain
    reshard."""

    kind: str          # "reshard" | "migrate"
    chain: Tuple[str, ...]
    reason: str
    saved: dict
    current: dict


def _saved_axis(plan: ElasticPlan, axis: str, block: str = "saved") -> int:
    mesh = (getattr(plan, block).get("mesh") or {})
    return int(mesh.get(axis, 1) or 1)


def pp_width_of(state) -> int:
    """Stage count of a (possibly) pipe-split TrainState, 1 when flat.
    Recorded in the sidecar topology block (``pp_stages``) because the
    restore TEMPLATE must match the checkpoint's TREE: the CLI trainer
    runs flat even on a pipe>1 mesh, so the mesh axis alone cannot name
    the stacking."""
    if getattr(state, "pp_stages", None) is None:
        return 1
    leaves = jax.tree_util.tree_leaves(state.pp_stages["params"])
    return int(leaves[0].shape[0])


def _pp_template_at_width(state, cfg, n_stages: int, steps_per_epoch: int):
    """Re-express a TrainState TEMPLATE at ``n_stages`` pipe stages (1 =
    flat) so its tree matches the checkpoint being restored. Shapes and
    structure only — no device placement (the restore lands the leaves
    on the target shardings)."""
    from p2p_tpu.parallel.pp import pp_merge_state, pp_split_state

    if pp_width_of(state) == n_stages:
        return state
    if state.pp_stages is not None:
        state = pp_merge_state(state, cfg, steps_per_epoch)
    if n_stages > 1:
        state = pp_split_state(state, cfg, mesh=None,
                               steps_per_epoch=steps_per_epoch,
                               n_stages=n_stages, init_opt=False,
                               place=False)
    return state


def elastic_restore(tr, step: int, plan: Optional[ElasticPlan]):
    """Execute a reconciled restore for trainer ``tr`` at ``step``.

    ``plan=None`` (same topology / pre-elastic sidecar) is the plain
    exact-step restore. A ``reshard`` plan restores onto rule-derived
    target shardings for the new mesh (PR 7 behavior). A ``migrate``
    plan additionally (a) restructures the restore TEMPLATE to match the
    checkpoint's recorded pipe width, then (b) walks the restored state
    through the plan's transform chain (``batch_rebase`` excepted — it
    moves position bookkeeping and runs from ``maybe_resume`` after
    ``derive_resume_position``). Collective-bearing on >1 process: the
    Orbax cross-topology load is itself a cross-host operation, so call
    sites must be host-uniform (collective_consistency lints this).
    """
    if plan is None:
        return tr.ckpt.restore(tr.state)
    template = tr.state
    if "pp_restructure" in plan.chain:
        # match the checkpoint's TREE, not the mesh axis: the sidecar's
        # pp_stages records the stacking actually saved (the CLI trainer
        # runs flat even on a pipe>1 mesh; absent = pre-PR-11 = flat)
        template = _pp_template_at_width(
            template, tr.cfg, int(plan.saved.get("pp_stages") or 1),
            tr.steps_per_epoch)
    shardings = None
    if tr.mesh is not None:
        from p2p_tpu.parallel.rules import state_target_shardings

        # the ONE partitioner: TP pair shards, ZeRO fsdp shards (an
        # fsdp↔replicated delta lands here as a plain reshard — the
        # Orbax load gathers or scatters the moments/EMA onto the new
        # mesh's rule-derived targets, no transform needed)
        shardings = state_target_shardings(
            template, tr.mesh, tp_min_ch=tr.cfg.parallel.tp_min_ch,
            fsdp_params=tr.cfg.parallel.fsdp_params)
    restored = tr.ckpt.restore(template, shardings=shardings)
    # integrity fallback may have landed on an OLDER intact step — the
    # transforms' audit records (and the dtype cast's regenerated
    # manifest) must name the step actually restored
    if tr.ckpt.last_restored_step is not None:
        step = int(tr.ckpt.last_restored_step)
    for name in plan.chain:
        fn = _RESTORE_TRANSFORMS.get(name)
        if fn is not None:
            restored = fn(tr, int(step), plan, restored)
    return restored


# ------------------------------------------------------------------ (b)
def _pp_restructure(tr, step: int, plan: ElasticPlan, restored):
    """Merge the restored trunk flat, then re-split at the RUN's width —
    optimizer moments ride through both directions (per-leaf Adam makes
    the stacked and flat trajectories identical)."""
    from p2p_tpu.parallel.pp import pp_merge_state, pp_split_state

    s_old = pp_width_of(restored)
    s_new = pp_width_of(tr.state)
    if restored.pp_stages is not None:
        restored = pp_merge_state(restored, tr.cfg, tr.steps_per_epoch)
    if s_new > 1:
        restored = pp_split_state(
            restored, tr.cfg, mesh=tr.mesh,
            steps_per_epoch=tr.steps_per_epoch, n_stages=s_new,
            init_opt=False, place=tr.mesh is not None)
    tr.logger.log(
        {"kind": "pp_restructure", "step": int(step),
         "stages_saved": s_old, "stages_current": s_new},
        force=True,
    )
    return restored


# ------------------------------------------------------------------ (c)
def _tp_amax_recalibrate(tr, step: int, plan: ElasticPlan, restored):
    """Remap every stored amax leaf by the closed-form width law, then
    (``--recalibrate_steps``) arm the frozen-scale warmup window."""
    from p2p_tpu.core.mesh import MODEL_AXIS
    from p2p_tpu.ops.int8 import reshard_amax

    w_old = _saved_axis(plan, MODEL_AXIS, "saved")
    w_new = _saved_axis(plan, MODEL_AXIS, "current")

    def remap(tree):
        if tree is None:
            return None
        return jax.tree_util.tree_map(
            lambda a: reshard_amax(a, w_old, w_new), tree)

    # every amax collection, including the PP-stacked trunk's
    amax_trees = {f: remap(getattr(restored, f))
                  for f in ("quant_g", "quant_d", "quant_c")}
    updates = dict(amax_trees)
    if restored.pp_stages is not None and "quant" in restored.pp_stages:
        amax_trees["pp_quant"] = remap(restored.pp_stages["quant"])
        updates["pp_stages"] = {
            **restored.pp_stages,
            "quant": amax_trees["pp_quant"],
        }
    restored = restored.replace(**updates)
    n_leaves = sum(len(jax.tree_util.tree_leaves(v))
                   for v in amax_trees.values())
    freeze = int(getattr(tr.cfg.train, "recalibrate_steps", 0) or 0)
    tr._quant_freeze_remaining = freeze
    if freeze > 0:
        # snapshot EVERY migrated scale collection HOST-side now (the
        # stacked trunk's included), before the first dispatch donates
        # the restored buffers — hold_frozen_quant re-pins these after
        # every warmup dispatch
        tr._quant_frozen = {
            f: jax.tree_util.tree_map(
                lambda a: np.asarray(jax.device_get(a)), tree)
            for f, tree in amax_trees.items() if tree}
    tr.logger.log(
        {"kind": "tp_amax_recalibrate", "step": int(step),
         "width_saved": w_old, "width_current": w_new,
         "amax_leaves": n_leaves, "recalibrate_steps": freeze},
        force=True,
    )
    return restored


# ------------------------------------------------------------------ (d)
def _moment_roots(opt_state):
    """The mu/nu subtrees of an (inject_hyperparams-wrapped) Adam state —
    matched structurally so both the optax ScaleByAdamState and the
    repo's low-precision twin are covered."""
    roots = []
    for node in jax.tree_util.tree_leaves(
            opt_state, is_leaf=lambda x: hasattr(x, "mu")
            and hasattr(x, "nu")):
        if hasattr(node, "mu") and hasattr(node, "nu"):
            roots.append(node)
    return roots


def _dtype_cast(tr, step: int, plan: ElasticPlan, restored):
    """Make the policy cast explicit: diff the restored leaves' dtypes
    against the save-time integrity manifest (the record of what was on
    disk), log the cast, apply the moment-migration policy, and
    regenerate the manifest so CRC verification names THIS state."""
    manifest = tr.ckpt.integrity_manifest(int(step))
    cast_paths = []
    if manifest:
        recorded = manifest.get("leaves", {})
        for path, leaf in jax.tree_util.tree_flatten_with_path(restored)[0]:
            key = jax.tree_util.keystr(path)
            rec = recorded.get(key)
            if rec is not None and rec["dtype"] != str(
                    np.dtype(getattr(leaf, "dtype", np.float32))):
                cast_paths.append(key)
    policy = "cast"
    saved_mdt = plan.saved.get("moment_dtype")
    cur_mdt = plan.current.get("moment_dtype")
    if saved_mdt != cur_mdt:
        policy = MOMENT_MIGRATION.get((saved_mdt, cur_mdt), "reinit")
        if policy == "reinit":
            reinit = {}
            for f in ("opt_g", "opt_d", "opt_c", "opt_s"):
                opt = getattr(restored, f)
                if opt is None:
                    continue
                zero_roots = {id(r) for r in _moment_roots(opt)}

                def z(node):
                    if id(node) in zero_roots:
                        return node._replace(
                            mu=jax.tree_util.tree_map(
                                jnp.zeros_like, node.mu),
                            nu=jax.tree_util.tree_map(
                                jnp.zeros_like, node.nu))
                    return node

                reinit[f] = jax.tree_util.tree_map(
                    z, opt, is_leaf=lambda x: id(x) in zero_roots)
            restored = restored.replace(**reinit)
    tr.logger.log(
        {"kind": "dtype_migration", "step": int(step),
         "mixed_precision": [plan.saved.get("mixed_precision"),
                             plan.current.get("mixed_precision")],
         "moment_dtype": [saved_mdt, cur_mdt],
         "moment_policy": policy,
         "cast_leaves": len(cast_paths),
         "examples": cast_paths[:5]},
        force=True,
    )
    print(f"dtype migration (--cast_on_restore): {len(cast_paths)} "
          f"leaf(s) cast on restore of step {step}; moment policy "
          f"'{policy}' — regenerating the integrity manifest", flush=True)
    # the on-disk manifest names the PRE-cast bytes; regenerate it from
    # the post-cast state so the next restore verifies CRCs instead of
    # skipping every dtype-changed leaf
    tr.ckpt.rewrite_integrity(int(step), restored,
                              note="dtype_cast migration")
    return restored


_RESTORE_TRANSFORMS = {
    "pp_restructure": _pp_restructure,
    "tp_amax_recalibrate": _tp_amax_recalibrate,
    "dtype_cast": _dtype_cast,
}


# ------------------------------------------------------------------ (a)
def rebase_step_counters(state, new_step: int):
    """Set ``state.step`` and every optimizer ``count`` scalar (the
    inject_hyperparams wrapper's and Adam's — both drive the LR schedule
    and bias correction) to ``new_step``: after a batch re-base the ONE
    step basis is samples/new_batch, and a counter left on the old basis
    would feed the schedule a stale epoch."""
    updates = {"step": jnp.asarray(new_step, state.step.dtype)}
    for f in ("opt_g", "opt_d", "opt_c", "opt_s"):
        opt = getattr(state, f, None)
        if opt is None:
            continue

        def fix(path, leaf):
            last = path[-1] if path else None
            name = getattr(last, "name", getattr(last, "key", None))
            if name == "count":
                return jnp.asarray(new_step, leaf.dtype)
            return leaf

        updates[f] = jax.tree_util.tree_map_with_path(fix, opt)
    return state.replace(**updates)


def apply_batch_rebase(tr, step: int, aux, plan: ElasticPlan,
                       done: int, mid: int) -> Tuple[int, int]:
    """Re-derive the resume position from SAMPLES for a global-batch
    change; returns ``(done_epochs, rebased_step)``.

    The dead run consumed ``epoch_samples_done`` samples of the current
    epoch's permutation (a multiple of the OLD batch); the relaunch skips
    exactly that flat prefix (``skip_samples`` — sample-granular, so an
    old-batch prefix not divisible by the new batch still tiles
    gaplessly) and the step/optimizer counters rebase to
    ``done·spe_new + ceil(epoch_samples/B_new)``: the partially-consumed
    slot is charged to the first post-resume batch, which keeps every
    later epoch boundary exactly on ``step % spe_new == 0`` — the LR
    schedule, the plateau controller, and ``--epoch_count`` renorm all
    read one consistent timeline. Must run AFTER
    ``derive_resume_position`` (which set the sample bookkeeping from
    the sidecar, or its counted fallback).
    """
    b_old = int(plan.saved.get("global_batch")
                or tr.cfg.data.batch_size)
    b_new = int(tr.cfg.data.batch_size)
    spe_new = tr.steps_per_epoch
    if aux is None or (aux.get("samples_seen") is None
                       and aux.get("batches_done") is None):
        # NO position record at all (no sidecar, or one naming neither
        # samples nor batches): reconstruct the old epoch geometry from
        # the saved batch — the step×batch fallback of last resort. A
        # sidecar that DOES carry batches_done already drove
        # derive_sample_position (es = batches_done × saved batch) and
        # is the ground truth — re-deriving from the CURRENT dataset
        # length would drift if the dataset changed under the checkpoint.
        spe_old = max(1, len(tr.train_ds) // b_old)
        done, mid = divmod(int(step), spe_old)
        tr._samples_seen = int(step) * b_old
        tr._epoch_samples_done = mid * b_old
    es = int(tr._epoch_samples_done)
    new_step = done * spe_new + -(-es // b_new)
    tr.state = rebase_step_counters(tr.state, new_step)
    tr._resume_skip_samples = es
    tr._resume_skip = es // b_new
    tr.logger.log(
        {"kind": "batch_rebase", "step": int(step),
         "rebased_step": int(new_step),
         "batch_saved": b_old, "batch_current": b_new,
         "samples_seen": int(tr._samples_seen),
         "epoch_samples_done": es,
         "steps_per_epoch": spe_new},
        force=True,
    )
    print(f"batch re-base: global batch {b_old} -> {b_new}; step "
          f"{step} -> {new_step} (samples_seen={tr._samples_seen}, "
          f"epoch prefix {es} samples re-skipped sample-exact)",
          flush=True)
    return done, int(new_step)


def arm_quant_init_warmup(tr, step: int) -> None:
    """ISSUE 14 forward-compat: the restore just INITIALIZED quant amax
    leaves a pre-drain checkpoint did not carry
    (``CheckpointManager.last_restore_initialized_quant`` — new QuantConv
    sites, the kn2row head, a whole ``quant_c``). Log the graft and arm
    the ``--recalibrate_steps`` frozen-scale warmup over the CURRENT
    (mixed restored+initialized) collections, reusing the
    ``tp_amax_recalibrate`` freeze machinery: the init-batch scales are
    exactly how a fresh run starts, and the warmup keeps every scale
    pinned while the new sites' first real amax measurements land."""
    initialized = list(
        getattr(tr.ckpt, "last_restore_initialized_quant", []) or [])
    if not initialized:
        return
    freeze = int(getattr(tr.cfg.train, "recalibrate_steps", 0) or 0)
    tr.logger.log(
        {"kind": "quant_init", "step": int(step),
         "initialized_leaves": len(initialized),
         "paths": initialized[:16],
         "recalibrate_steps": freeze},
        force=True,
    )
    if freeze <= 0:
        return
    amax_trees = {f: getattr(tr.state, f, None)
                  for f in ("quant_g", "quant_d", "quant_c")}
    pp_stages = getattr(tr.state, "pp_stages", None)
    if isinstance(pp_stages, dict) and "quant" in pp_stages:
        amax_trees["pp_quant"] = pp_stages["quant"]
    tr._quant_freeze_remaining = freeze
    tr._quant_frozen = {
        f: jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), tree)
        for f, tree in amax_trees.items() if tree}


def hold_frozen_quant(tr) -> None:
    """The ``--recalibrate_steps`` warmup: while the window is open,
    re-pin the quant collections to their migrated values after each
    dispatch (the scales are per-layer scalars — the copy is noise), so
    every warmup step quantizes with the recalibrated FROZEN scales
    while the rest of the state trains normally. Freeze granularity is
    the dispatch (``scan_steps`` steps per tick on the scan path)."""
    n = int(getattr(tr, "_quant_freeze_remaining", 0) or 0)
    if n <= 0:
        return
    frozen = getattr(tr, "_quant_frozen", None)
    if not frozen:
        tr._quant_freeze_remaining = 0
        return
    pins = {f: jax.tree_util.tree_map(jnp.asarray, v)
            for f, v in frozen.items() if f != "pp_quant"}
    if "pp_quant" in frozen and tr.state.pp_stages is not None:
        pins["pp_stages"] = {
            **tr.state.pp_stages,
            "quant": jax.tree_util.tree_map(jnp.asarray,
                                            frozen["pp_quant"]),
        }
    tr.state = tr.state.replace(**pins)
    tr._quant_freeze_remaining = n - 1
    if tr._quant_freeze_remaining == 0:
        tr._quant_frozen = None
        tr.logger.log({"kind": "recalibrate_done",
                       "step": int(tr._host_step)}, force=True)
