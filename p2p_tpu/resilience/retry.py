"""Retry with exponential backoff + jitter — the transient-fault primitive.

Checkpoint save/restore and image decode sit on storage that fails
transiently in production (NFS blips, objects mid-upload, files still
being copied into a watched directory). The policy here is the standard
one (MegaScale / Pathways stacks, AWS architecture guidance): classify
the exception, back off exponentially with *full jitter* so a fleet of
retrying hosts doesn't synchronize into thundering herds, give up on a
deadline or an attempt cap, and count everything through the obs
registry:

- ``retry_attempts_total{seam=...}`` — re-attempts performed (not first
  tries);
- ``retry_exhausted_total{seam=...}`` — calls that failed permanently.

:class:`~p2p_tpu.resilience.chaos.FaultInjected` is always classified
retryable — the chaos layer exists to exercise exactly this path.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple, Type

from p2p_tpu.resilience.chaos import FaultInjected

# Transient by default: OS/filesystem errors (includes PIL's
# UnidentifiedImageError for half-copied request files), timeouts, and
# injected chaos faults. ValueError/TypeError/etc. stay fatal — retrying
# a programming error just hides it for max_attempts * backoff seconds.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    OSError, TimeoutError, FaultInjected,
)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape + give-up rules for one seam."""

    max_attempts: int = 4           # total tries (1 first try + 3 retries)
    base_delay: float = 0.05        # seconds before the first retry
    max_delay: float = 2.0          # per-retry backoff cap
    jitter: bool = True             # full jitter: delay ~ U(0, backoff]
    deadline: Optional[float] = None  # total wall-clock budget (seconds)
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable)

    def backoff(self, attempt: int, rng: Optional[random.Random] = None
                ) -> float:
        """Delay before retry number ``attempt`` (1-based)."""
        raw = min(self.max_delay, self.base_delay * (2.0 ** (attempt - 1)))
        if not self.jitter:
            return raw
        r = rng.random() if rng is not None else random.random()
        return raw * (0.5 + 0.5 * r)  # U(raw/2, raw]: jittered, never 0


DEFAULT_POLICY = RetryPolicy()

# Checkpoint I/O tolerates longer waits — a blipping FS usually recovers
# within seconds. (Serve-side decode deliberately does NOT use a blocking
# retry_call: the dispatch loop must never sleep, so its backoff lives in
# the request queue's re-enqueue windows — cli/serve.py — counted on the
# same retry_attempts_total{seam=decode} counter.)
CKPT_POLICY = RetryPolicy(max_attempts=4, base_delay=0.2, max_delay=5.0)


def retry_call(
    fn: Callable,
    *args,
    policy: RetryPolicy = DEFAULT_POLICY,
    seam: str = "op",
    registry=None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying retryable failures.

    Retries up to ``policy.max_attempts`` total tries with exponential
    backoff + jitter, stopping early when ``policy.deadline`` seconds have
    elapsed since the first try. Non-retryable exceptions propagate
    immediately; the final retryable failure is re-raised unchanged (with
    ``retry_exhausted_total`` bumped).
    """
    if registry is None:
        from p2p_tpu.obs import get_registry

        registry = get_registry()
    t0 = clock()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except BaseException as exc:  # noqa: BLE001 — classified below
            if not policy.is_retryable(exc):
                raise
            delay = policy.backoff(attempt, rng)
            out_of_attempts = attempt >= policy.max_attempts
            out_of_time = (policy.deadline is not None
                           and clock() - t0 + delay > policy.deadline)
            if out_of_attempts or out_of_time:
                registry.counter("retry_exhausted_total", seam=seam).inc()
                raise
            registry.counter("retry_attempts_total", seam=seam).inc()
            registry.record(
                {"kind": "retry", "seam": seam, "attempt": attempt,
                 "delay_sec": round(delay, 4), "error": repr(exc)},
            )
            sleep(delay)


def retrying(policy: RetryPolicy = DEFAULT_POLICY, seam: str = "op",
             **retry_kw):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, seam=seam,
                              **retry_kw, **kwargs)

        return wrapped

    return deco
