"""High-throughput inference engine (the serving half of the north star).

- :class:`.engine.InferenceEngine` — AOT-compiled, bucket-batched generator
  serving with params-only restore, pipelined host I/O, bf16 / frozen-int8
  dtype policies and optional tensor-parallel sharding;
- :func:`.engine.engine_from_checkpoint` — template + subtree restore +
  engine in one call (the cli/infer.py and cli/serve.py construction path);
- :mod:`.io` — bucket padding/chunking and the threaded image writer.

See docs/SERVING.md.
"""

from p2p_tpu.serve.engine import (
    InferenceEngine,
    ServeStats,
    engine_from_checkpoint,
)
from p2p_tpu.serve.io import (
    AsyncImageWriter,
    chunk_batch,
    pad_batch,
    pick_bucket,
)

__all__ = [
    "AsyncImageWriter",
    "InferenceEngine",
    "ServeStats",
    "chunk_batch",
    "engine_from_checkpoint",
    "pad_batch",
    "pick_bucket",
]
