"""High-throughput inference serving (the serving half of the north star).

- :class:`.engine.InferenceEngine` — AOT-compiled, bucket-batched generator
  serving with params-only restore, pipelined host I/O, bf16 / frozen-int8
  dtype policies, optional tensor-parallel sharding, and zero-downtime
  weight hot-swap (:meth:`.engine.InferenceEngine.swap_state`);
- :func:`.engine.engine_from_checkpoint` — template + subtree restore +
  engine in one call (the cli/infer.py and cli/serve.py construction path);
- :mod:`.frontend` — the shared dispatch/decode-retry/quarantine loop
  behind the directory and HTTP frontends, with bucket-occupancy
  accounting;
- :mod:`.batcher` — continuous cross-request batching (thread-safe
  admission, bucket-aware group formation, linger-when-under-full);
- :mod:`.tenancy` — the multi-model registry: N checkpoints resident in
  one process, each hot-swappable under traffic;
- :mod:`.server` — the stdlib HTTP frontend (``POST /v1/{model}/translate``,
  ``/healthz``, Prometheus ``/metrics``, ``POST /admin/reload``) with
  PreemptionGuard-style graceful drain;
- :mod:`.io` — bucket padding/chunking, the threaded image writer, and
  PNG-bytes response encoding.

See docs/SERVING.md.
"""

from p2p_tpu.serve.batcher import ContinuousBatcher
from p2p_tpu.serve.engine import (
    InferenceEngine,
    ServeStats,
    engine_from_checkpoint,
)
from p2p_tpu.serve.frontend import DispatchLoop, default_buckets
from p2p_tpu.serve.io import (
    AsyncImageWriter,
    chunk_batch,
    encode_png,
    pad_batch,
    pick_bucket,
)
from p2p_tpu.serve.tenancy import (
    HotSwapRejected,
    ModelRegistry,
    Tenant,
    checkpoint_dir,
)

__all__ = [
    "AsyncImageWriter",
    "ContinuousBatcher",
    "DispatchLoop",
    "HotSwapRejected",
    "InferenceEngine",
    "ModelRegistry",
    "ServeStats",
    "Tenant",
    "checkpoint_dir",
    "chunk_batch",
    "default_buckets",
    "encode_png",
    "engine_from_checkpoint",
    "pad_batch",
    "pick_bucket",
]
