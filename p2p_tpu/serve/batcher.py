"""Continuous cross-request batching — iteration-level scheduling at
image-batch granularity (the Orca/vLLM insight applied to a GAN image
service).

The directory frontend groups whatever one directory scan returned; under
concurrent network traffic that policy leaves buckets half-empty or
requests waiting a full poll interval. :class:`ContinuousBatcher` instead
admits requests the moment they arrive (N producer threads — the HTTP
handler pool — feed one :class:`~p2p_tpu.resilience.queue.
BoundedRequestQueue` through a condition lock) and forms a group every
dispatch tick:

- **loaded** (queue >= group_cap): a full largest-bucket group, NOW —
  under sustained traffic every dispatch runs at occupancy 1.0;
- **under-full**: linger up to ``linger_s`` measured from the OLDEST
  queued request, admitting stragglers into the forming group;
- **linger expired**: dispatch the largest FULL bucket that fits the
  queue depth (the remainder follows immediately in a smaller bucket at
  full occupancy) — only a depth below the smallest bucket ever pads.

The batcher is the single synchronization point between producers and
the per-tenant dispatch thread: every queue operation happens inside its
condition, so the underlying queue keeps its simple single-thread
implementation. Shed/deadline/backoff semantics are entirely the
queue's; occupancy accounting is the dispatch loop's
(:mod:`p2p_tpu.serve.frontend`).
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

from p2p_tpu.resilience.queue import BoundedRequestQueue, Request


class ContinuousBatcher:
    """Thread-safe admission + bucket-aware group formation over a
    bounded request queue. One consumer (the tenant's dispatch thread)
    calls :meth:`next_group`/:meth:`take`; any number of producers call
    :meth:`submit`/:meth:`submit_request`."""

    def __init__(
        self,
        queue: BoundedRequestQueue,
        buckets: Sequence[int],
        group_cap: Optional[int] = None,
        linger_s: float = 0.05,
        clock=time.monotonic,
    ):
        self.queue = queue
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad buckets {self.buckets}")
        cap = self.buckets[-1]
        self.group_cap = min(int(group_cap), cap) if group_cap else cap
        self.linger_s = max(0.0, float(linger_s))
        self._clock = clock
        self._cond = threading.Condition()
        self._closed = False

    # ------------------------------------------------------------ produce
    def submit(self, name: str, payload: Any = None) -> Optional[Request]:
        """Admit a fresh request; None = shed (queue full) or closed
        (draining) — the HTTP handler maps those to 429/503."""
        with self._cond:
            if self._closed:
                return None
            req = self.queue.offer(name, payload=payload)
            if req is not None:
                self._cond.notify()
            return req

    def submit_request(self, req: Request) -> Optional[Request]:
        """Admit a caller-built request (the HTTP frontend's response-
        carrying subclass); same shed/closed contract as :meth:`submit`."""
        with self._cond:
            if self._closed:
                return None
            out = self.queue.offer_request(req)
            if out is not None:
                self._cond.notify()
            return out

    def requeue(self, req: Request, delay_s: float = 0.0) -> bool:
        """Decode-retry re-entry (DispatchLoop calls this through the
        queue surface); locked against concurrent producers."""
        with self._cond:
            ok = self.queue.requeue(req, delay_s)
            if ok:
                self._cond.notify()
            return ok

    # ------------------------------------------------------------ consume
    def take(self, n: int) -> Tuple[List[Request], List[Request]]:
        """Locked pass-through of the queue's take — the drain path."""
        with self._cond:
            return self.queue.take(n)

    def flush(self) -> List[Request]:
        """Locked pass-through of the queue's flush — the drain-timeout
        path's answer-everything escape (backoff windows included)."""
        with self._cond:
            return self.queue.flush()

    def __len__(self) -> int:
        with self._cond:
            return len(self.queue)

    def close(self) -> None:
        """Stop admitting (drain mode): submits return None, blocked
        :meth:`next_group` calls wake and fall through to immediate
        takes so the dispatch thread can finish the backlog."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def _group_size(self, now: float) -> Tuple[int, Optional[float]]:
        """(size, wait): size > 0 = dispatch that many now; else wait is
        how long until the pending linger expires (None = queue empty,
        wait for an arrival). Called under the condition."""
        n = len(self.queue)
        if n == 0:
            return 0, None
        if n >= self.group_cap:
            return self.group_cap, None
        oldest = self.queue.oldest_enqueued_at()
        waited = now - (oldest if oldest is not None else now)
        if waited >= self.linger_s:
            full = [b for b in self.buckets if b <= n]
            return (full[-1] if full else n), None
        return 0, self.linger_s - waited

    def next_group(self, timeout: float = 0.1
                   ) -> Tuple[List[Request], List[Request]]:
        """Block until a group is ready (or ``timeout``); returns
        ``(ready, expired)`` — both possibly empty. Requests held inside
        retry-backoff windows never busy-spin the consumer: when the
        queue looks dispatchable but ``take`` comes back empty, the wait
        resumes instead of looping hot."""
        deadline = self._clock() + max(0.0, timeout)
        with self._cond:
            while not self._closed:
                now = self._clock()
                size, linger_wait = self._group_size(now)
                if size > 0:
                    ready, expired = self.queue.take(size)
                    if ready or expired:
                        return ready, expired
                    # everything apparently-ready sits in a backoff
                    # window — wait a beat rather than spin on take()
                    linger_wait = max(self.linger_s, 0.01)
                remaining = deadline - now
                if remaining <= 0:
                    return [], []
                wait = (remaining if linger_wait is None
                        else min(remaining, linger_wait))
                self._cond.wait(max(wait, 1e-3))
            # closed: hand back whatever is immediately dispatchable so
            # the drain loop can run the backlog down and exit
            return self.queue.take(self.group_cap)
